"""Kernel-parity tests: Pallas flash attention vs the jnp reference
(the methodology of reference tests/unit/test_cuda_forward.py /
test_cuda_backward.py — same inputs, compare within tolerance). Runs the
kernels through the Pallas interpreter on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.attention import (attention,
                                                     xla_attention)
from deepspeed_tpu.ops.transformer.flash_attention import flash_attention


def _make_qkv(rng, b, s, h, d, dtype=jnp.float32):
    shape = (b, s, h, d)
    q = jnp.asarray(rng.standard_normal(shape), dtype)
    k = jnp.asarray(rng.standard_normal(shape), dtype)
    v = jnp.asarray(rng.standard_normal(shape), dtype)
    return q, k, v


GRID = [
    # (batch, seq, heads, head_dim, causal)
    (2, 128, 2, 64, False),
    (2, 128, 2, 64, True),
    (1, 256, 4, 64, True),
    (2, 128, 2, 128, True),
]


class TestFlashForward:
    @pytest.mark.parametrize("b,s,h,d,causal", GRID)
    def test_matches_reference(self, b, s, h, d, causal):
        rng = np.random.default_rng(0)
        q, k, v = _make_qkv(rng, b, s, h, d)
        ref = xla_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_bf16(self):
        rng = np.random.default_rng(0)
        q, k, v = _make_qkv(rng, 2, 128, 2, 64, jnp.bfloat16)
        ref = xla_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   atol=2e-2, rtol=2e-2)


class TestCrossLength:
    """sq != sk: causal must be bottom-right aligned like the xla reference
    (a decode query block attending a longer KV cache)."""

    @pytest.mark.parametrize("sq,sk", [(128, 256), (128, 384)])
    def test_causal_kv_cache_alignment(self, sq, sk):
        rng = np.random.default_rng(2)
        q = jnp.asarray(rng.standard_normal((2, sq, 2, 64)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((2, sk, 2, 64)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((2, sk, 2, 64)), jnp.float32)
        ref = xla_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_causal_kv_cache_grads(self):
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.standard_normal((1, 128, 2, 64)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 256, 2, 64)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 256, 2, 64)), jnp.float32)
        gf = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, interpret=True) ** 2), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(xla_attention(
            q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b, n in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{n}")


class TestKvMask:
    """Key-padding mask parity (the BERT attention_mask path): masked keys
    must contribute to neither the normaliser nor the output, matching the
    xla reference's where-on-logits semantics."""

    def _mask(self, rng, b, s):
        lengths = rng.integers(1, s + 1, (b,))
        return jnp.asarray(np.arange(s)[None, :] < lengths[:, None],
                           jnp.int32)

    @pytest.mark.parametrize("b,s,h,d,causal", GRID)
    def test_forward(self, b, s, h, d, causal):
        rng = np.random.default_rng(4)
        q, k, v = _make_qkv(rng, b, s, h, d)
        km = self._mask(rng, b, s)
        ref = xla_attention(q, k, v, causal=causal,
                            mask=km[:, None, None, :])
        out = flash_attention(q, k, v, causal=causal, kv_mask=km,
                              interpret=True)
        # Padded QUERY rows may differ (flash never sees query masks; the
        # model multiplies them out downstream) — compare valid rows only.
        valid = np.asarray(km, bool)
        np.testing.assert_allclose(np.asarray(out)[valid],
                                   np.asarray(ref)[valid],
                                   atol=2e-5, rtol=2e-5)

    def test_all_ones_mask_matches_unmasked(self):
        rng = np.random.default_rng(5)
        q, k, v = _make_qkv(rng, 2, 128, 2, 64)
        km = jnp.ones((2, 128), jnp.int32)
        out_m = flash_attention(q, k, v, kv_mask=km, interpret=True)
        out = flash_attention(q, k, v, interpret=True)
        np.testing.assert_allclose(np.asarray(out_m), np.asarray(out),
                                   atol=1e-6, rtol=1e-6)

    def test_grads(self):
        rng = np.random.default_rng(6)
        b, s, h, d = 2, 128, 2, 64
        q, k, v = _make_qkv(rng, b, s, h, d)
        km = self._mask(rng, b, s)
        valid = np.asarray(km, bool)
        # Zero the cotangent on padded query rows so both sides see the
        # same upstream gradient on rows the model would keep.
        w = jnp.asarray(valid, jnp.float32)[:, :, None, None]

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, kv_mask=km, interpret=True)
            return jnp.sum((o * w) ** 2)

        def loss_ref(q, k, v):
            o = xla_attention(q, k, v, mask=km[:, None, None, :])
            return jnp.sum((o * w) ** 2)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name} mismatch")


class TestDispatchMask:
    def test_pallas_accepts_padding_mask_forms(self):
        from deepspeed_tpu.ops.transformer.attention import _as_kv_mask
        m2 = jnp.ones((2, 128))
        assert _as_kv_mask(m2, 2, 128) is m2
        m4 = jnp.ones((2, 1, 1, 128))
        assert _as_kv_mask(m4, 2, 128).shape == (2, 128)
        full = jnp.ones((2, 4, 128, 128))
        assert _as_kv_mask(full, 2, 128) is None


class TestFlashBackward:
    @pytest.mark.parametrize("b,s,h,d,causal", GRID)
    def test_grads_match_reference(self, b, s, h, d, causal):
        rng = np.random.default_rng(1)
        q, k, v = _make_qkv(rng, b, s, h, d)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           interpret=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(xla_attention(q, k, v, causal=causal) ** 2)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name} mismatch")


def test_auto_dispatch_shapes_always_run():
    """Regression: every shape _pallas_ok admits must execute — the tuned
    512/1024 block defaults must self-fit to 128-multiple sequences that
    are not multiples of the block (e.g. 768)."""
    import numpy as np

    from deepspeed_tpu.ops.transformer.flash_attention import flash_attention

    rng = np.random.default_rng(0)
    for seq in (128, 256, 640, 768, 1152):
        q = jnp.asarray(rng.standard_normal((1, seq, 2, 64)), jnp.float32)
        out = flash_attention(q, q, q, causal=True, interpret=True)
        assert out.shape == q.shape
        assert np.isfinite(np.asarray(out)).all()


class TestInKernelDropout:
    """In-kernel attention dropout (reference dropout_kernels.cu,
    ds_transformer_cuda.cpp:168-190). The keep-mask comes from a
    counter-based hash shared between the kernels and this oracle, so
    parity is exact — fwd AND bwd regenerate the identical mask."""

    RATE = 0.3

    def _qkv(self, rng, b=2, s=256, h=2, d=64):
        mk = lambda: jnp.asarray(rng.standard_normal((b, s, h, d)),
                                 jnp.float32)
        return mk(), mk(), mk()

    def _oracle(self, q, k, v, seed, rate, causal, kv_mask=None):
        """Dense attention applying the SAME hash-derived keep mask the
        kernel uses, post-softmax."""
        from deepspeed_tpu.ops.transformer.flash_attention import \
            dropout_keep_mask

        b, s, h, d = q.shape
        sk = k.shape[1]
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) / (d ** 0.5)
        neg = jnp.finfo(jnp.float32).min
        if causal:
            cm = jnp.tril(jnp.ones((s, sk), jnp.bool_), k=sk - s)
            logits = jnp.where(cm[None, None], logits, neg)
        if kv_mask is not None:
            logits = jnp.where(kv_mask[:, None, None, :].astype(bool),
                               logits, neg)
        p = jax.nn.softmax(logits, axis=-1)
        rows = jax.lax.broadcasted_iota(jnp.int32, (s, sk), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (s, sk), 1)
        bh = (jnp.arange(b)[:, None] * h + jnp.arange(h)[None, :])
        keep = jax.vmap(jax.vmap(
            lambda i: dropout_keep_mask(seed, i, rows, cols, rate)))(bh)
        p = jnp.where(keep, p / (1.0 - rate), 0.0)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    def _flash(self, q, k, v, seed_key, causal, kv_mask=None):
        return flash_attention(q, k, v, causal=causal, kv_mask=kv_mask,
                               dropout_rate=self.RATE, dropout_rng=seed_key,
                               interpret=True)

    def _seed_of(self, key):
        kd = jax.random.key_data(key).astype(jnp.uint32).reshape(-1)
        return (kd[0] ^ (kd[-1] << 1)).astype(jnp.int32)

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_oracle(self, causal):
        rng = np.random.default_rng(0)
        q, k, v = self._qkv(rng)
        key = jax.random.PRNGKey(5)
        out = self._flash(q, k, v, key, causal)
        ref = self._oracle(q, k, v, self._seed_of(key), self.RATE, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_forward_with_kv_mask(self):
        rng = np.random.default_rng(1)
        q, k, v = self._qkv(rng)
        mask = np.ones((2, 256), np.int32)
        mask[:, 200:] = 0
        mask = jnp.asarray(mask)
        key = jax.random.PRNGKey(6)
        out = self._flash(q, k, v, key, False, kv_mask=mask)
        ref = self._oracle(q, k, v, self._seed_of(key), self.RATE, False,
                           kv_mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_oracle(self, causal):
        rng = np.random.default_rng(2)
        q, k, v = self._qkv(rng)
        key = jax.random.PRNGKey(7)
        seed = self._seed_of(key)

        def loss_flash(q, k, v):
            o = self._flash(q, k, v, key, causal)
            w = jnp.arange(o.size, dtype=jnp.float32).reshape(o.shape)
            return jnp.sum(o * w) / o.size

        def loss_ref(q, k, v):
            o = self._oracle(q, k, v, seed, self.RATE, causal)
            w = jnp.arange(o.size, dtype=jnp.float32).reshape(o.shape)
            return jnp.sum(o * w) / o.size

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name}")

    def test_seed_determinism_and_variation(self):
        rng = np.random.default_rng(3)
        q, k, v = self._qkv(rng)
        k1, k2 = jax.random.PRNGKey(1), jax.random.PRNGKey(2)
        a = self._flash(q, k, v, k1, False)
        b = self._flash(q, k, v, k1, False)
        c = self._flash(q, k, v, k2, False)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.allclose(np.asarray(a), np.asarray(c))

    def test_keep_fraction(self):
        from deepspeed_tpu.ops.transformer.flash_attention import \
            dropout_keep_mask

        rows = jax.lax.broadcasted_iota(jnp.int32, (512, 512), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (512, 512), 1)
        keep = dropout_keep_mask(jnp.int32(123), 3, rows, cols, 0.3)
        frac = float(jnp.mean(keep.astype(jnp.float32)))
        assert abs(frac - 0.7) < 0.01, frac

    def test_dispatch_routes_dropout_to_pallas(self):
        """attention(impl='pallas') with dropout must run the kernel (the
        round-2 gap: it raised and auto fell back to xla everywhere)."""
        from deepspeed_tpu.ops.transformer.attention import attention

        rng = np.random.default_rng(4)
        q, k, v = self._qkv(rng, s=512)
        out = attention(q, k, v, causal=True, dropout_rate=0.1,
                        dropout_rng=jax.random.PRNGKey(0),
                        deterministic=False, impl="pallas")
        assert np.isfinite(np.asarray(out)).all()


class TestDispatchBlockQuality:
    def test_gate_admits_all_128_multiples(self):
        """Round-4 re-measurement (tools/probe_pad_dispatch.py): the flash
        kernel wins at EVERY 128-multiple length >= 512 including the
        degraded-block ones (640/896), dropout on and off — the r3 XLA
        fallback is gone. Short sequences still stay on XLA."""
        from deepspeed_tpu.ops.transformer import attention as att

        for s in (512, 640, 768, 896, 1024, 1152, 1536, 2048):
            q = jnp.zeros((2, s, 4, 64), jnp.bfloat16)
            assert att._pallas_ok(q, q, None, None), s
            assert att._pallas_ok(q, q, None, None, dropout_active=True), s
        q = jnp.zeros((2, 256, 4, 64), jnp.bfloat16)
        assert not att._pallas_ok(q, q, None, None)   # below the crossover
        q = jnp.zeros((2, 576, 4, 64), jnp.bfloat16)
        assert not att._pallas_ok(q, q, None, None)   # not a 128 multiple


class TestPaddedDispatch:
    """impl='pallas_pad' (round-3 VERDICT task 8): odd 128-multiple
    self-attention lengths run the flash kernel on 512-padded sequences
    with the tail masked — numerics must match xla exactly (pad queries
    sliced, pad keys masked)."""

    @pytest.mark.parametrize("seq", [640, 896])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_xla(self, seq, causal):
        rng = np.random.default_rng(0)
        shape = (2, seq, 4, 64)
        q = jnp.asarray(rng.standard_normal(shape), jnp.float32) * 0.1
        k = jnp.asarray(rng.standard_normal(shape), jnp.float32) * 0.1
        v = jnp.asarray(rng.standard_normal(shape), jnp.float32) * 0.1
        ref = attention(q, k, v, causal=causal, impl="xla")
        pad = attention(q, k, v, causal=causal, impl="pallas_pad")
        np.testing.assert_allclose(np.asarray(pad), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_matches_xla_with_key_mask(self):
        rng = np.random.default_rng(1)
        shape = (2, 640, 4, 64)
        q = jnp.asarray(rng.standard_normal(shape), jnp.float32) * 0.1
        k = jnp.asarray(rng.standard_normal(shape), jnp.float32) * 0.1
        v = jnp.asarray(rng.standard_normal(shape), jnp.float32) * 0.1
        mask = np.ones((2, 640), np.int32)
        mask[:, 600:] = 0
        ref = attention(q, k, v, mask=jnp.asarray(mask), impl="xla")
        pad = attention(q, k, v, mask=jnp.asarray(mask), impl="pallas_pad")
        np.testing.assert_allclose(np.asarray(pad), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_gradients_match_xla(self):
        rng = np.random.default_rng(2)
        shape = (1, 640, 2, 64)
        q = jnp.asarray(rng.standard_normal(shape), jnp.float32) * 0.1
        k = jnp.asarray(rng.standard_normal(shape), jnp.float32) * 0.1
        v = jnp.asarray(rng.standard_normal(shape), jnp.float32) * 0.1

        def loss(impl):
            return lambda q, k, v: jnp.sum(
                attention(q, k, v, causal=True, impl=impl) ** 2)

        g_ref = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
        g_pad = jax.grad(loss("pallas_pad"), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_pad, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5, rtol=3e-5)

    def test_dropout_runs_and_is_seeded(self):
        rng = np.random.default_rng(3)
        shape = (1, 640, 2, 64)
        q = jnp.asarray(rng.standard_normal(shape), jnp.float32) * 0.1
        key = jax.random.PRNGKey(7)
        out1 = attention(q, q, q, causal=True, impl="pallas_pad",
                         dropout_rate=0.1, dropout_rng=key,
                         deterministic=False)
        out2 = attention(q, q, q, causal=True, impl="pallas_pad",
                         dropout_rate=0.1, dropout_rng=key,
                         deterministic=False)
        assert np.all(np.isfinite(np.asarray(out1, np.float32)))
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
