"""Telemetry subsystem tests (telemetry/; docs/OBSERVABILITY.md): registry
sinks + tags + histogram percentiles, Chrome trace-event schema, the
recompile detector's compile/hit/retrace accounting, engine span emission
(backward + dataloader included), the zero-sync contract of disabled
telemetry, and tools/trace_report.py."""

import importlib.util
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.telemetry import (InMemorySink, JSONLSink, MetricsRegistry,
                                     RECOMPILE_COUNTER, RecompileDetector,
                                     StepTracer, build_telemetry)

from simple_model import mlp_loss_fn, mlp_params, random_batch, random_batches

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _base_config(**extra):
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 0}}
    cfg.update(extra)
    return cfg


def _engine(config_extra=None, world=8):
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(),
        config=_base_config(**(config_extra or {})),
        mesh=build_mesh(data=world))
    return engine


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------
class TestRegistry:
    def test_jsonl_round_trip_with_tags(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        reg = MetricsRegistry([JSONLSink(path)])
        reg.counter("requests").inc(step=1, route="train")
        reg.counter("requests").inc(2, step=2, route="eval")
        reg.gauge("hbm").set(123.0, step=2, device=0)
        reg.histogram("lat").observe(0.5, step=3)
        reg.flush()
        rows = [json.loads(l) for l in open(path)]
        by_tag = {}
        for r in rows:
            by_tag.setdefault(r["tag"], []).append(r)
        # counter rows carry the RUNNING TOTAL and per-call tags
        assert [r["value"] for r in by_tag["requests"]] == [1.0, 3.0]
        assert by_tag["requests"][0]["route"] == "train"
        assert by_tag["requests"][1]["route"] == "eval"
        assert by_tag["requests"][0]["kind"] == "counter"
        assert by_tag["hbm"][0] == {"tag": "hbm", "value": 123.0, "step": 2,
                                    "kind": "gauge", "device": 0}
        assert by_tag["lat"][0]["kind"] == "histogram"

    def test_histogram_percentiles(self):
        reg = MetricsRegistry([InMemorySink()])
        h = reg.histogram("t")
        for v in range(1, 101):
            h.observe(float(v))
        assert h.percentile(50) == pytest.approx(50.5)
        assert h.percentile(0) == 1.0
        assert h.percentile(100) == 100.0
        assert h.percentile(99) == pytest.approx(99.01)
        p50, p99 = h.percentiles((50, 99))
        assert (p50, p99) == (pytest.approx(50.5), pytest.approx(99.01))
        assert h.count == 100

    def test_in_memory_sink_and_default_step(self):
        reg = MetricsRegistry()
        mem = reg.add_sink(InMemorySink())
        reg.set_step(7)
        reg.gauge("g").set(1.0)
        assert mem.rows == [{"kind": "gauge", "tag": "g", "value": 1.0,
                             "step": 7}]

    def test_no_sinks_is_noop_and_broken_sink_is_contained(self):
        reg = MetricsRegistry()
        reg.counter("c").inc()  # no sinks: must not raise

        class Broken(InMemorySink):
            def emit(self, *a, **k):
                raise RuntimeError("boom")

        reg.add_sink(Broken())
        reg.counter("c").inc()  # contained, not raised

    def test_monitor_compat_add_scalar(self):
        reg = MetricsRegistry()
        mem = reg.add_sink(InMemorySink())
        reg.add_scalar("Train/Samples/train_loss", 0.5, 3)
        assert mem.rows[0]["tag"] == "Train/Samples/train_loss"
        assert mem.rows[0]["step"] == 3


# ---------------------------------------------------------------------------
# Step tracer — Chrome trace-event schema
# ---------------------------------------------------------------------------
class TestTracer:
    def test_chrome_trace_schema(self, tmp_path):
        path = str(tmp_path / "trace.json")
        tr = StepTracer(path=path, sync_spans=False)
        with tr.span("outer", step=1):
            with tr.span("inner"):
                pass
        tr.instant("marker", fn="f")
        tr.counter("recompiles", 2)
        tr.save()
        doc = json.load(open(path))
        assert isinstance(doc["traceEvents"], list)
        assert doc["displayTimeUnit"] == "ms"
        phases = {}
        for ev in doc["traceEvents"]:
            assert isinstance(ev["name"], str)
            assert ev["ph"] in ("X", "i", "C", "M")
            assert isinstance(ev["pid"], int)
            assert isinstance(ev["tid"], int)
            if ev["ph"] != "M":
                assert isinstance(ev["ts"], (int, float))
            if ev["ph"] == "X":
                assert ev["dur"] >= 0
            if ev["ph"] == "i":
                assert ev["s"] == "t"
            phases.setdefault(ev["ph"], []).append(ev)
        assert {e["name"] for e in phases["X"]} == {"outer", "inner"}
        outer = next(e for e in phases["X"] if e["name"] == "outer")
        inner = next(e for e in phases["X"] if e["name"] == "inner")
        # nesting: inner is contained within outer
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1
        assert outer["args"] == {"step": 1}
        assert phases["C"][0]["args"] == {"value": 2.0}

    def test_disabled_tracer_is_noop(self, tmp_path):
        tr = StepTracer(path=None)
        with tr.span("x") as sp:
            pass
        assert sp.duration == 0.0
        assert tr.save() is None
        assert tr.events == []

    def test_bounded_ring_and_dirty_skip(self, tmp_path):
        path = str(tmp_path / "t.json")
        tr = StepTracer(path=path, sync_spans=False, max_events=8)
        for i in range(20):
            with tr.span(f"s{i}"):
                pass
        assert len(tr.events) == 8          # oldest evicted, RAM bounded
        assert tr.dropped_events == 13      # 20 spans + 1 meta - 8 kept
        tr.save()
        doc = json.load(open(path))
        assert doc["metadata"]["dropped_events"] == 13
        assert {e["name"] for e in doc["traceEvents"]} == {
            f"s{i}" for i in range(12, 20)}  # the recent window survives
        # no new events since last save: save() must not rewrite
        before = os.path.getmtime(path)
        os.utime(path, (before - 100, before - 100))
        tr.save()
        assert os.path.getmtime(path) == before - 100

    def test_span_handle_duration(self, tmp_path):
        tr = StepTracer(path=str(tmp_path / "t.json"), sync_spans=False)
        with tr.span("s") as sp:
            pass
        assert sp.duration >= 0.0

    def test_sync_gating(self, monkeypatch, tmp_path):
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        # disabled tracer: zero syncs even with sync_spans requested
        tr = StepTracer(path=None, sync_spans=True)
        with tr.span("a"):
            pass
        assert calls["n"] == 0
        # enabled + sync_spans: a barrier on each span boundary
        tr = StepTracer(path=str(tmp_path / "t.json"), sync_spans=True)
        with tr.span("a"):
            pass
        assert calls["n"] == 2
        # enabled + sync off: still zero
        calls["n"] = 0
        tr = StepTracer(path=str(tmp_path / "t2.json"), sync_spans=False)
        with tr.span("a"):
            pass
        assert calls["n"] == 0


# ---------------------------------------------------------------------------
# Recompile detector
# ---------------------------------------------------------------------------
class TestRecompileDetector:
    def _batch(self, bs=4, dtype=np.float32):
        return {"x": np.zeros((bs, 8), dtype)}

    @staticmethod
    def _capture_warnings(monkeypatch):
        """The deepspeed_tpu logger doesn't propagate to root (caplog can't
        see it) — intercept warning() on the recompile module directly."""
        from deepspeed_tpu.telemetry import recompile as rc_mod
        msgs = []
        monkeypatch.setattr(
            rc_mod.logger, "warning",
            lambda fmt, *a, **k: msgs.append(fmt % a if a else fmt))
        return msgs

    def test_steady_state_is_silent(self, monkeypatch):
        msgs = self._capture_warnings(monkeypatch)
        det = RecompileDetector()
        assert det.check("step", self._batch()) == "compile"
        for _ in range(5):
            assert det.check("step", self._batch()) == "hit"
        assert not msgs
        assert det.stats["step"] == {"compiles": 1, "retraces": 0}

    def test_shape_change_fires(self, monkeypatch):
        msgs = self._capture_warnings(monkeypatch)
        reg = MetricsRegistry()
        mem = reg.add_sink(InMemorySink())
        tr = StepTracer(enabled=True, sync_spans=False)
        det = RecompileDetector(registry=reg, tracer=tr)
        det.check("step", self._batch(bs=4))
        assert det.check("step", self._batch(bs=3), step=7) == "retrace"
        assert msgs and "RECOMPILATION" in msgs[0] and "step" in msgs[0]
        assert "(4, 8)" in msgs[0] and "(3, 8)" in msgs[0]  # names the leaf
        assert det.stats["step"] == {"compiles": 2, "retraces": 1}
        assert mem.values(RECOMPILE_COUNTER) == [1.0]
        assert any(e["name"] == "recompile" for e in tr.events)

    def test_dtype_change_fires(self):
        det = RecompileDetector(warn=False)
        det.check("step", self._batch())
        assert det.check("step", self._batch(dtype=np.float64)) == "retrace"

    def test_revisited_signature_is_a_hit(self):
        # jit keeps old entries in its cache: bouncing between two shapes
        # retraces once per NEW shape, not per switch
        det = RecompileDetector(warn=False)
        det.check("step", self._batch(bs=4))
        assert det.check("step", self._batch(bs=3)) == "retrace"
        assert det.check("step", self._batch(bs=4)) == "hit"
        assert det.check("step", self._batch(bs=3)) == "hit"
        assert det.retraces("step") == 1

    def test_disabled_detector(self):
        det = RecompileDetector(enabled=False)
        assert det.check("step", self._batch()) == "hit"
        assert det.check("step", self._batch(bs=1)) == "hit"
        assert det.stats == {}

    def test_static_string_keys_by_value(self):
        det = RecompileDetector(warn=False)
        det.check("gen", {"static": "max_new_tokens=4"})
        assert det.check("gen", {"static": "max_new_tokens=8"}) == "retrace"


# ---------------------------------------------------------------------------
# Engine integration — the acceptance-criteria run
# ---------------------------------------------------------------------------
class TestEngineTelemetry:
    def _gpt_engine(self, tmp_path, seq=16):
        from deepspeed_tpu.models import make_gpt
        model, cfg = make_gpt("tiny", num_layers=2, dropout_rate=0.0,
                              dtype=jnp.float32)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (8, seq), dtype=np.int32)
        params = model.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)},
                            {"input_ids": ids})["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params, mesh=build_mesh(data=8),
            config={
                "train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "telemetry": {"enabled": True, "dir": str(tmp_path)},
                "resilience": {"enabled": True, "checkpoint": {
                    "dir": str(tmp_path / "ckpt"), "interval": 2}},
            })
        return engine, cfg

    def test_gpt_run_trace_spans_recompiles_and_report(self, eight_devices,
                                                       tmp_path):
        """The ISSUE acceptance run: 2-layer GPT on CPU, telemetry on —
        >= 6 distinct span names (incl. backward and dataloader), exactly
        the expected first-step compile, a flagged injected retrace, and a
        trace_report breakdown."""
        engine, cfg = self._gpt_engine(tmp_path)
        rng = np.random.default_rng(1)

        def batch(bs=8, seq=16):
            return {"input_ids": rng.integers(0, cfg.vocab_size, (bs, seq),
                                              dtype=np.int32)}

        # reference-style loop: forward / backward / step
        for _ in range(3):
            loss = engine.forward(batch())
            engine.backward(loss)
            engine.step()
        # fused loop
        for _ in range(2):
            engine.train_batch({"input_ids": batch()["input_ids"][None]})
        det = engine.telemetry.recompile
        assert det.stats["engine.micro_step"] == {"compiles": 1,
                                                  "retraces": 0}
        assert det.stats["engine.train_step"] == {"compiles": 1,
                                                  "retraces": 0}
        # injected shape change: the detector must flag the retrace
        engine.train_batch(
            {"input_ids": batch(bs=8, seq=8)["input_ids"][None]})
        assert det.stats["engine.train_step"] == {"compiles": 2,
                                                  "retraces": 1}
        if engine.ckpt_manager is not None:
            engine.ckpt_manager.wait()
        engine.telemetry.flush()

        trace_path = tmp_path / "trace.json"
        doc = json.load(open(trace_path))
        names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
        assert {"dataloader", "forward", "backward", "optimizer_step",
                "train_step", "ckpt_snapshot", "ckpt_write"} <= names
        assert len(names) >= 6
        # retrace marker landed in the trace too
        assert any(e["name"] == "recompile" for e in doc["traceEvents"]
                   if e.get("ph") == "i")

        # metrics jsonl got the registry fan-out
        rows = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
        tags = {r["tag"] for r in rows}
        assert "Train/Samples/train_loss" in tags
        assert RECOMPILE_COUNTER in tags
        assert "ckpt/write_latency_sec" in tags

        # trace_report renders a breakdown naming the spans
        report = _load_trace_report()
        summary = report.summarize(report.load_events(str(trace_path)))
        text = report.render(summary)
        assert "dataloader" in text and "ckpt_write" in text
        span_names = {r["name"] for r in summary["spans"]}
        assert len(span_names) >= 6

    def test_disabled_telemetry_zero_syncs(self, monkeypatch):
        """Acceptance: a 20-step loop with telemetry disabled performs ZERO
        telemetry-originated block_until_ready calls."""
        engine = _engine()  # default config: telemetry off, breakdown off
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=1, batch_size=16)
        engine.train_batch(batches)  # compile outside the counted window

        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        for _ in range(20):
            engine.train_batch(batches)
        assert calls["n"] == 0
        assert engine.telemetry.enabled is False
        assert engine.telemetry.tracer.enabled is False
        # goodput rides telemetry: off => None facade, zero added hooks
        # (tests/test_goodput.py asserts the enabled path adds zero syncs)
        assert engine.goodput is None

    def test_enabled_telemetry_does_sync(self, monkeypatch, tmp_path):
        engine = _engine({"telemetry": {"enabled": True,
                                        "dir": str(tmp_path)}})
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=1, batch_size=16)
        engine.train_batch(batches)
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        engine.train_batch(batches)
        assert calls["n"] > 0  # sync'd span boundaries

    def test_wall_clock_breakdown_records_new_timers(self):
        engine = _engine({"wall_clock_breakdown": True})
        rng = np.random.default_rng(0)
        batch = random_batch(rng, batch_size=16)
        loss = engine.forward(batch)
        engine.backward(loss)
        engine.step()
        for name in ("dataloader", "forward", "backward", "step"):
            assert engine.timers.has_timer(name), name
            assert engine.timers(name).count >= 1, name

    def test_legacy_tensorboard_block_rides_registry(self, tmp_path):
        """tensorboard-only config (telemetry absent): scalars still land
        via the registry's tensorboard sink — the unified facade."""
        engine = _engine({"tensorboard": {"enabled": True,
                                          "output_path": str(tmp_path),
                                          "job_name": "job1"}})
        rng = np.random.default_rng(0)
        for _ in range(2):
            engine.train_batch(random_batches(rng, gas=1, batch_size=16))
        assert engine.telemetry.enabled is False
        assert engine.telemetry.registry.sinks  # the tensorboard sink
        files = os.listdir(tmp_path / "job1")
        assert files
        if "scalars.jsonl" in files:
            rows = [json.loads(l)
                    for l in open(tmp_path / "job1" / "scalars.jsonl")]
            assert "Train/Samples/train_loss" in {r["tag"] for r in rows}


class TestPipelineTelemetry:
    def test_bubble_gauges(self, eight_devices, tmp_path):
        from deepspeed_tpu.config.config import DeepSpeedTPUConfig
        from deepspeed_tpu.models.gpt import GPTConfig
        from deepspeed_tpu.parallel.pipe import PipelineEngine, gpt_pipe_model
        from deepspeed_tpu.utils.jax_compat import NATIVE_SHARD_MAP
        if not NATIVE_SHARD_MAP:
            pytest.skip("stages > 1 needs a jax with native shard_map")

        cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=32,
                        num_layers=4, num_heads=2, dropout_rate=0.0,
                        dtype=jnp.float32)
        ds = DeepSpeedTPUConfig({
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "telemetry": {"enabled": True, "dir": str(tmp_path),
                          "metrics": {"sinks": ["memory"]}},
        })
        engine = PipelineEngine(gpt_pipe_model(cfg), ds,
                                mesh=build_mesh(data=4, pipe=2))
        rng = np.random.default_rng(0)
        batches = {"input_ids": rng.integers(0, cfg.vocab_size, (4, 8, 16),
                                             dtype=np.int32)}
        engine.train_batch(batches)
        mem = engine.telemetry.registry.sinks[0]
        assert isinstance(mem, InMemorySink)
        # 2 stages, 4 microbatches: bubble = (S-1)/(M+S-1) = 1/5
        assert mem.values("pipe/bubble_fraction") == [pytest.approx(0.2)]
        assert mem.values("pipe/bubble_time_sec")[0] > 0
        assert "pipe_step" in engine.telemetry.tracer.span_names()


# ---------------------------------------------------------------------------
# Satellites: timer + monitor fixes
# ---------------------------------------------------------------------------
class TestTimerSatellites:
    def test_avg_samples_per_sec_before_warmup_is_zero(self):
        from deepspeed_tpu.utils.timer import ThroughputTimer
        t = ThroughputTimer(batch_size=4, start_step=2, sync=False)
        assert t.avg_samples_per_sec() == 0.0  # not the old float("-1")
        t.start(); t.stop()
        assert t.avg_samples_per_sec() == 0.0
        for _ in range(4):
            t.start(); t.stop()
        assert t.avg_samples_per_sec() > 0.0

    def test_dead_init_timer_removed(self):
        from deepspeed_tpu.utils.timer import ThroughputTimer
        t = ThroughputTimer(batch_size=4)
        assert not hasattr(t, "_init_timer")
        assert not hasattr(t, "initialized")

    def test_wallclock_sync_gated_with_force_escape(self, monkeypatch):
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        timers = timer_mod.SynchronizedWallClockTimer(enabled=False)
        timers("t").start(); timers("t").stop()
        assert calls["n"] == 0
        timers("t").start(force_sync=True)
        timers("t").stop(force_sync=True)
        assert calls["n"] == 2
        on = timer_mod.SynchronizedWallClockTimer(enabled=True)
        on("t").start(); on("t").stop()
        assert calls["n"] == 4

    def test_throughput_timer_sync_flag(self, monkeypatch):
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        t = timer_mod.ThroughputTimer(batch_size=1, start_step=0, sync=False)
        t.start(); t.stop()
        assert calls["n"] == 0
        t = timer_mod.ThroughputTimer(batch_size=1, start_step=0, sync=True)
        t.start(); t.stop()
        assert calls["n"] == 2


class TestMonitorSatellites:
    def test_metrics_jsonl_extra_kwargs(self, tmp_path):
        from deepspeed_tpu.utils.monitor import MetricsJSONL
        m = MetricsJSONL(str(tmp_path / "m.jsonl"))
        m.add_scalar("t", 1.0, 0, attempt=2, kind="counter")
        m.flush()
        rows = m.read("t")
        assert rows == [{"tag": "t", "value": 1.0, "step": 0, "attempt": 2,
                         "kind": "counter"}]
        m.close()

    def test_tensorboard_fallback_flush_and_extra(self, tmp_path,
                                                  monkeypatch):
        # force the JSONL fallback path regardless of torch availability
        monkeypatch.setitem(sys.modules, "torch.utils.tensorboard", None)
        from deepspeed_tpu.utils.monitor import TensorboardMonitor
        mon = TensorboardMonitor(str(tmp_path), job_name="j")
        assert mon._writer is None and mon._jsonl is not None
        mon.add_scalar("a", 1.5, 3, source="test")
        mon.flush()  # must flush the fallback sink (the satellite fix)
        rows = [json.loads(l)
                for l in open(tmp_path / "j" / "scalars.jsonl")]
        assert rows == [{"tag": "a", "value": 1.5, "step": 3,
                         "source": "test"}]
        mon.close()


# ---------------------------------------------------------------------------
# tools/trace_report.py
# ---------------------------------------------------------------------------
def _load_trace_report():
    path = os.path.join(REPO, "tools", "trace_report.py")
    spec = importlib.util.spec_from_file_location("trace_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestTraceReport:
    def test_selftest_cli(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
             "--selftest"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "selftest ok" in proc.stdout

    def test_report_on_tracer_output(self, tmp_path):
        path = str(tmp_path / "t.json")
        tr = StepTracer(path=path, sync_spans=False)
        for _ in range(4):
            with tr.span("forward"):
                pass
            with tr.span("optimizer_step"):
                pass
        tr.counter("telemetry/recompiles", 1)
        tr.save()
        report = _load_trace_report()
        summary = report.summarize(report.load_events(path))
        by = {r["name"]: r for r in summary["spans"]}
        assert by["forward"]["count"] == 4
        assert summary["counters"]["telemetry/recompiles"] == 1.0
        assert abs(sum(r["share"] for r in summary["spans"]) - 1.0) < 1e-6
        text = report.render(summary, sort="count")
        assert "forward" in text

    def test_bare_array_trace_accepted(self, tmp_path):
        p = tmp_path / "bare.json"
        p.write_text(json.dumps([
            {"name": "s", "ph": "X", "pid": 1, "tid": 1, "ts": 0,
             "dur": 10.0}]))
        report = _load_trace_report()
        summary = report.summarize(report.load_events(str(p)))
        assert summary["spans"][0]["name"] == "s"
