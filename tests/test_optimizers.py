"""Optimizer numerics vs torch reference (reference tests/unit/test_adamw.py,
test_cpu_adam.py methodology: identical weights/grads, compare updates)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.adam.fused_adam import FusedAdam, FusedAdamW
from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb

torch = pytest.importorskip("torch")


def _tree_from(arrs):
    return {f"p{i}": jnp.asarray(a) for i, a in enumerate(arrs)}


def _run_jax_adam(params_np, grads_np, steps, **kw):
    opt = FusedAdam(**kw)
    params = _tree_from(params_np)
    state = opt.init(params)
    grads = _tree_from(grads_np)
    for _ in range(steps):
        params, state = opt.update(grads, state, params)
    return [np.asarray(params[f"p{i}"]) for i in range(len(params_np))]


def _run_torch(params_np, grads_np, steps, opt_cls, **kw):
    tp = [torch.nn.Parameter(torch.tensor(a)) for a in params_np]
    opt = opt_cls(tp, **kw)
    for _ in range(steps):
        for p, g in zip(tp, grads_np):
            p.grad = torch.tensor(g)
        opt.step()
    return [p.detach().numpy() for p in tp]


@pytest.mark.parametrize("steps", [1, 10])
def test_adamw_matches_torch(steps, rng):
    params = [rng.standard_normal((4, 8)).astype(np.float32),
              rng.standard_normal((16,)).astype(np.float32)]
    grads = [rng.standard_normal(p.shape).astype(np.float32) * 0.1 for p in params]
    ours = _run_jax_adam(params, grads, steps, lr=1e-2, weight_decay=0.01,
                         adamw_mode=True)
    ref = _run_torch(params, grads, steps, torch.optim.AdamW, lr=1e-2,
                     weight_decay=0.01)
    for a, b in zip(ours, ref):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("steps", [1, 10])
def test_adam_l2_matches_torch(steps, rng):
    params = [rng.standard_normal((8, 8)).astype(np.float32)]
    grads = [rng.standard_normal(p.shape).astype(np.float32) * 0.1 for p in params]
    ours = _run_jax_adam(params, grads, steps, lr=1e-2, weight_decay=0.01,
                         adamw_mode=False)
    ref = _run_torch(params, grads, steps, torch.optim.Adam, lr=1e-2,
                     weight_decay=0.01)
    for a, b in zip(ours, ref):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_adam_no_bias_correction_differs(rng):
    p = [rng.standard_normal((4,)).astype(np.float32)]
    g = [np.ones((4,), np.float32)]
    with_bc = _run_jax_adam(p, g, 1, lr=1e-2, bias_correction=True)
    without = _run_jax_adam(p, g, 1, lr=1e-2, bias_correction=False)
    assert not np.allclose(with_bc[0], without[0])


def test_lamb_trust_ratio_bounds(rng):
    opt = FusedLamb(lr=1e-2, max_coeff=10.0, min_coeff=0.01)
    params = {"w": jnp.asarray(rng.standard_normal((8, 8)).astype(np.float32))}
    state = opt.init(params)
    grads = {"w": jnp.asarray(rng.standard_normal((8, 8)).astype(np.float32))}
    new_params, state = opt.update(grads, state, params)
    # the step moved the weights, and not absurdly far
    delta = np.abs(np.asarray(new_params["w"] - params["w"])).max()
    assert 0 < delta < 1.0


def test_lamb_decreases_quadratic(rng):
    opt = FusedLamb(lr=0.1)
    params = {"w": jnp.asarray(rng.standard_normal((16,)).astype(np.float32))}
    state = opt.init(params)

    def loss(p):
        return jnp.sum(jnp.square(p["w"]))

    l0 = float(loss(params))
    for _ in range(20):
        grads = jax.grad(loss)(params)
        params, state = opt.update(grads, state, params)
    assert float(loss(params)) < l0


def test_amsgrad_rejected():
    with pytest.raises(NotImplementedError):
        FusedAdam(amsgrad=True)
