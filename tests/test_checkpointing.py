"""Checkpoint save/load (reference tests/unit/test_checkpointing.py): tag +
latest semantics, optimizer-state round trip, client state, consolidation."""

import os

import numpy as np
import pytest
import jax

from deepspeed_tpu import initialize
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.runtime.checkpointing import consolidate_to_fp32

from simple_model import mlp_params, mlp_loss_fn, random_batch


def _make_engine(zero_stage=0, seed=0):
    mesh = build_mesh(data=8)
    engine, _, _, _ = initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(),
        config={"train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "scheduler": {"type": "WarmupLR",
                              "params": {"warmup_max_lr": 0.1,
                                         "warmup_num_steps": 100}},
                "zero_optimization": {"stage": zero_stage}},
        mesh=mesh, rng_seed=seed)
    return engine


def _train(engine, rng, steps=3):
    for _ in range(steps):
        b = random_batch(rng, batch_size=16)
        engine.forward(b)
        engine.backward(None)
        engine.step()


def _params_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(a)),
                    jax.tree_util.tree_leaves(jax.device_get(b))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=0, atol=0)


@pytest.mark.parametrize("stage", [0, 2])
def test_checkpoint_roundtrip(tmp_path, rng, stage):
    e1 = _make_engine(zero_stage=stage)
    _train(e1, rng)
    path = e1.save_checkpoint(str(tmp_path), client_state={"epoch": 7})
    assert os.path.isdir(path)
    assert open(os.path.join(tmp_path, "latest")).read().strip() == \
        os.path.basename(path)

    e2 = _make_engine(zero_stage=stage)
    load_path, client = e2.load_checkpoint(str(tmp_path))
    assert load_path == path
    assert client["epoch"] == 7
    assert e2.global_steps == e1.global_steps
    _params_equal(e1.state.params, e2.state.params)
    _params_equal(e1.state.opt_state.exp_avg, e2.state.opt_state.exp_avg)
    assert e2.lr_scheduler.get_lr() == e1.lr_scheduler.get_lr()

    # training continues identically from the restore
    rng2a = np.random.default_rng(42)
    rng2b = np.random.default_rng(42)
    _train(e1, rng2a, steps=2)
    _train(e2, rng2b, steps=2)
    _params_equal(e1.state.params, e2.state.params)


def test_checkpoint_cross_stage_restore(tmp_path, rng):
    """A stage-2 sharded save restores into a stage-0 replicated engine —
    the dp-resharding / elastic checkpoint property (stage2.py:1921)."""
    e1 = _make_engine(zero_stage=2)
    _train(e1, rng)
    e1.save_checkpoint(str(tmp_path))
    e2 = _make_engine(zero_stage=0)
    e2.load_checkpoint(str(tmp_path))
    _params_equal(e1.state.params, e2.state.params)


def test_load_without_optimizer_states(tmp_path, rng):
    e1 = _make_engine()
    _train(e1, rng)
    e1.save_checkpoint(str(tmp_path))
    e2 = _make_engine()
    fresh_moments = jax.device_get(e2.state.opt_state.exp_avg)
    e2.load_checkpoint(str(tmp_path), load_optimizer_states=False)
    _params_equal(e1.state.params, e2.state.params)
    _params_equal(e2.state.opt_state.exp_avg, fresh_moments)


def test_explicit_tag(tmp_path, rng):
    e1 = _make_engine()
    _train(e1, rng, steps=1)
    e1.save_checkpoint(str(tmp_path), tag="alpha")
    _train(e1, rng, steps=1)
    e1.save_checkpoint(str(tmp_path), tag="beta")
    e2 = _make_engine()
    path, _ = e2.load_checkpoint(str(tmp_path), tag="alpha")
    assert path.endswith("alpha")
    assert e2.global_steps == 1


def test_consolidate_to_fp32(tmp_path, rng):
    """zero_to_fp32 equivalent: offline merge of a sharded checkpoint."""
    e1 = _make_engine(zero_stage=2)
    _train(e1, rng)
    e1.save_checkpoint(str(tmp_path))
    flat = consolidate_to_fp32(str(tmp_path))
    ref = jax.device_get(e1.state.params)
    got = flat["head.w"]
    np.testing.assert_allclose(got, np.asarray(ref["head"]["w"]), rtol=0, atol=0)
    assert all(v.dtype == np.float32 for v in flat.values())


class TestPipelineRepartition:
    """Checkpoint trained at one pipeline depth reloads at another
    (round-3 VERDICT task 5; reference saves per-layer files for this,
    pipe/module.py:517-585 — here the stacked-blocks tree IS per-layer
    addressable on its leading dim, so pp-resize is an orbax reshard)."""

    def _engine(self, stages, eight_devices=None):
        import jax.numpy as jnp

        from deepspeed_tpu.config.config import DeepSpeedTPUConfig
        from deepspeed_tpu.models.gpt import GPTConfig
        from deepspeed_tpu.parallel.mesh import build_mesh
        from deepspeed_tpu.parallel.pipe import (PipelineEngine,
                                                 gpt_pipe_model)

        cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=32,
                        num_layers=4, num_heads=2, dropout_rate=0.0,
                        dtype=jnp.float32)
        pm = gpt_pipe_model(cfg)
        mesh = build_mesh(data=8 // stages, pipe=stages)
        ds = DeepSpeedTPUConfig({
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
        })
        return PipelineEngine(pm, ds, mesh=mesh), cfg

    @pytest.mark.parametrize("pp_to", [1, 4])
    def test_pp2_reloads_at_other_depths(self, eight_devices, tmp_path,
                                         pp_to):
        import numpy as np

        e2, cfg = self._engine(2)
        rng = np.random.default_rng(0)
        batches = {"input_ids": rng.integers(0, cfg.vocab_size, (4, 8, 16),
                                             dtype=np.int32)}
        for _ in range(3):
            e2.train_batch(batches)
        e2.save_checkpoint(str(tmp_path), client_state={"pp": 2})
        ref_eval = float(e2.eval_batch(batches))

        e_new, _ = self._engine(pp_to)
        _, client = e_new.load_checkpoint(str(tmp_path))
        assert client["pp"] == 2
        assert e_new.global_steps == e2.global_steps
        # params bit-equal through the reshard
        for a, b in zip(jax.tree_util.tree_leaves(e2.state.params),
                        jax.tree_util.tree_leaves(e_new.state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_allclose(float(e_new.eval_batch(batches)),
                                   ref_eval, rtol=1e-6)
        # training continues identically at the new depth (one step)
        l2 = float(e2.train_batch(batches))
        ln = float(e_new.train_batch(batches))
        np.testing.assert_allclose(ln, l2, rtol=2e-4, atol=2e-4)
