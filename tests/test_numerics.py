"""Numerics observatory tests (telemetry/numerics.py; docs/OBSERVABILITY.md
"Numerics observatory"): per-layer-group stats correctness vs hand-computed
gradients, dtype saturation/underflow counters, the roundtrip_error
property suite (comm/quantize.py satellite), DCN int8 quantization-error
bounds on a 2-slice mesh, the zero-overhead off-contract (engine.numerics
None, zero device syncs, bit-identical lowered step vs a numerics-less
config), the single-flush-fetch on-contract, spike verdicts naming the
poisoned layer group (instant + crashdump), offload/pipe tier coverage,
the serving int8 KV error gauge, the fleet grad-norm field, the
get_global_grad_norm no-retrace satellite, and tools/numerics_report.py."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.quantize import (quantize_blockwise, roundtrip_error,
                                         roundtrip_error_parts)
from deepspeed_tpu.config.config import ConfigError, DeepSpeedTPUConfig
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.telemetry.numerics import (GRAD_SQ, N_GROUP_STATS,
                                              OTHER_GROUP, SATURATED,
                                              UNDERFLOWED, UPDATE_SQ,
                                              WEIGHT_SQ, NumericsPlan)

from simple_model import mlp_loss_fn, mlp_params, random_batches

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tel(tmp_path, numerics=None, sinks=("memory",), **extra_tel):
    tel = {"enabled": True, "dir": str(tmp_path),
           "trace": {"enabled": False},
           "metrics": {"sinks": list(sinks)}, **extra_tel}
    if numerics is not None:
        tel["numerics"] = numerics
    return tel


def _engine(config_extra=None, mesh=None, params=None):
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn,
        params=params if params is not None else mlp_params(),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "steps_per_print": 1,
                **(config_extra or {})},
        mesh=mesh if mesh is not None else build_mesh(data=8))
    return engine


def _rows(engine, tag):
    return [r for r in engine.telemetry.registry.sinks[0].rows
            if r["tag"] == tag]


# ---------------------------------------------------------------------------
# Plan grouping
# ---------------------------------------------------------------------------
class TestPlanGrouping:
    def test_top_level_groups(self):
        plan = NumericsPlan(mlp_params())
        assert plan.group_names == ["head", "layer_0", "layer_1"]
        assert len(plan.leaf_group) == len(
            jax.tree_util.tree_leaves(mlp_params()))

    def test_group_cap_collapses_tail_into_other(self):
        params = {f"k{i:02d}": np.zeros((2,), np.float32) for i in range(9)}
        plan = NumericsPlan(params, max_groups=4)
        assert len(plan.group_names) == 4
        assert plan.group_names[-1] == OTHER_GROUP
        # 3 named + 6 collapsed
        other_idx = plan.group_names.index(OTHER_GROUP)
        assert plan.leaf_group.count(other_idx) == 6

    def test_config_validation(self):
        with pytest.raises(ConfigError):
            DeepSpeedTPUConfig({
                "train_micro_batch_size_per_gpu": 1,
                "telemetry": {"enabled": True, "dir": "/tmp",
                              "numerics": {"enabled": True,
                                           "max_groups": 0}}})


# ---------------------------------------------------------------------------
# roundtrip_error (comm/quantize.py satellite): property tests
# ---------------------------------------------------------------------------
class TestRoundtripError:
    def test_zero_blocks_exact(self):
        rel, mab = roundtrip_error(jnp.zeros((4, 256)), 8, 256)
        assert float(rel) == 0.0 and float(mab) == 0.0

    def test_error_bounded_by_half_scale(self):
        rng = np.random.default_rng(0)
        for block in (256, 1024):
            x = jnp.asarray(rng.standard_normal((4, 2048)), jnp.float32)
            rel, mab = roundtrip_error(x, 8, block)
            # RTNE: per-element error <= scale/2 where scale = absmax/127
            # per block; bound by the largest block's scale.
            blocks = np.asarray(x).reshape(4, 2048 // block, block)
            scale = np.abs(blocks).max(axis=-1) / 127.0
            assert float(mab) <= scale.max() * 0.5 * (1 + 1e-3)
            assert 0 < float(rel) < 0.05

    def test_nan_transparent(self):
        x = jnp.ones((256,)).at[3].set(jnp.nan)
        rel, mab = roundtrip_error(x, 8, 256)
        assert not np.isfinite(float(rel))
        assert not np.isfinite(float(mab))

    def test_bf16_tier_and_fp32_passthrough(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((1024,)), jnp.float32)
        rel16, _ = roundtrip_error(x, 16, 256)
        assert 0 < float(rel16) < 0.01         # bf16: ~2^-9 relative
        rel32, mab32 = roundtrip_error(x, 32, 256)
        assert float(rel32) == 0.0 and float(mab32) == 0.0

    def test_parts_compose_to_rel(self):
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((512,)), jnp.float32)
        esq, rsq, mab = roundtrip_error_parts(x, 8, 256)
        rel, mab2 = roundtrip_error(x, 8, 256)
        np.testing.assert_allclose(
            float(rel), np.sqrt(float(esq) / float(rsq)), rtol=1e-6)
        assert float(mab) == float(mab2)

    def test_roundtrip_matches_quantize_blockwise(self):
        """The helper measures the SAME transform the wire applies."""
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((2, 512)), jnp.float32)
        q, s = quantize_blockwise(x, 256)
        from deepspeed_tpu.comm.quantize import dequantize_blockwise
        dq = dequantize_blockwise(q, s, 256)
        rel, _ = roundtrip_error(x, 8, 256)
        manual = np.linalg.norm(np.asarray(dq - x)) / np.linalg.norm(
            np.asarray(x))
        np.testing.assert_allclose(float(rel), manual, rtol=1e-5)


# ---------------------------------------------------------------------------
# In-program statistics: correctness vs hand-computed grads
# ---------------------------------------------------------------------------
class TestInProgramStats:
    @pytest.mark.parametrize("stage", [0, 2])
    def test_group_stats_match_reference(self, eight_devices, tmp_path,
                                         stage):
        params0 = mlp_params()
        engine = _engine({"telemetry": _tel(tmp_path,
                                            numerics={"enabled": True}),
                          "zero_optimization": {"stage": stage}},
                         params=params0)
        assert engine.numerics is not None
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        engine.train_batch(batches)

        # Reference: one micro-batch, no dropout -> grads independent of
        # rng; gas=1, fp32 (no loss scale).
        batch0 = jax.tree_util.tree_map(lambda x: x[0], batches)
        ref_grads = jax.grad(
            lambda p: mlp_loss_fn(p, batch0, None))(params0)
        for group in engine.numerics.plan.group_names:
            got = [r for r in _rows(engine, "numerics/grad_norm")
                   if r["group"] == group][-1]["value"]
            want = float(np.sqrt(sum(
                float(jnp.sum(g.astype(jnp.float32) ** 2))
                for k, g in ref_grads.items() if k == group
                for g in jax.tree_util.tree_leaves(g))))
            np.testing.assert_allclose(got, want, rtol=1e-4)
            w = [r for r in _rows(engine, "numerics/weight_norm")
                 if r["group"] == group][-1]["value"]
            want_w = float(np.sqrt(sum(
                float(np.sum(np.square(np.asarray(l, np.float64))))
                for l in jax.tree_util.tree_leaves(params0[group]))))
            np.testing.assert_allclose(w, want_w, rtol=1e-4)
            u = [r for r in _rows(engine, "numerics/update_ratio")
                 if r["group"] == group][-1]["value"]
            assert 0 < u < 1.0          # Adam step with lr 1e-2
        # global norm = sqrt(sum of group squares)
        gg = _rows(engine, "numerics/global_grad_norm")[-1]["value"]
        want_g = float(np.sqrt(sum(
            float(jnp.sum(g.astype(jnp.float32) ** 2))
            for g in jax.tree_util.tree_leaves(ref_grads))))
        np.testing.assert_allclose(gg, want_g, rtol=1e-4)

    def test_saturation_and_underflow_counters(self):
        """Direct plan unit: fp16 compute dtype. 1e5 saturates (fp16 max
        65504), 1e-9 underflows to zero, 1.0 survives."""
        params = {"a": jnp.ones((3,), jnp.float32)}
        plan = NumericsPlan(params, compute_dtype=jnp.float16)
        grads = {"a": jnp.asarray([1e5, 1e-9, 1.0], jnp.float32)}
        stats = np.asarray(jax.jit(plan.group_stats)(grads, params))
        assert stats.shape == (1, N_GROUP_STATS)
        assert stats[0, SATURATED] == 1
        assert stats[0, UNDERFLOWED] == 1
        np.testing.assert_allclose(stats[0, GRAD_SQ],
                                   1e10 + 1e-18 + 1.0, rtol=1e-6)
        np.testing.assert_allclose(stats[0, WEIGHT_SQ], 3.0, rtol=1e-6)
        assert stats[0, UPDATE_SQ] == 0.0      # no new_params handed over

    def test_fp32_run_has_zero_counters(self, eight_devices, tmp_path):
        engine = _engine({"telemetry": _tel(tmp_path,
                                            numerics={"enabled": True})})
        engine.train_batch(random_batches(np.random.default_rng(0), gas=1,
                                          batch_size=16))
        for tag in ("numerics/saturation_count",
                    "numerics/underflow_count"):
            assert all(r["value"] == 0 for r in _rows(engine, tag))

    def test_micro_step_api_path(self, eight_devices, tmp_path):
        """forward/backward/step (the non-fused _apply_step path) feeds
        the same aux."""
        engine = _engine({"telemetry": _tel(tmp_path,
                                            numerics={"enabled": True})})
        rng = np.random.default_rng(0)
        batch = {k: v[0] for k, v in random_batches(rng, gas=1,
                                                    batch_size=16).items()}
        loss = engine.forward(batch)
        engine.backward(loss)
        engine.step()
        assert _rows(engine, "numerics/grad_norm")
        assert _rows(engine, "numerics/update_ratio")


# ---------------------------------------------------------------------------
# Off-contract: None facade, zero syncs, bit-identical lowered step
# ---------------------------------------------------------------------------
class TestOffContract:
    def test_disabled_numerics_is_none_no_tags_zero_syncs(
            self, eight_devices, tmp_path, monkeypatch):
        engine = _engine({"telemetry": _tel(tmp_path)})   # numerics absent
        assert engine.numerics is None
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        engine.train_batch(batches)               # compile outside window
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        for _ in range(5):
            engine.train_batch(batches)
        assert calls["n"] == 0
        mem = engine.telemetry.registry.sinks[0]
        assert not {t for t in mem.tags() if t.startswith("numerics/")}
        # telemetry fully off => None too
        engine2 = _engine()
        assert engine2.numerics is None

    def test_lowered_step_bit_identical_when_off(self, eight_devices,
                                                 tmp_path):
        """numerics {"enabled": false} and a numerics-less telemetry
        block (and no telemetry at all) must lower to the SAME step
        text; enabled must differ (the stats really are in-program —
        otherwise this whole contract is vacuous)."""
        batches_np = random_batches(np.random.default_rng(0), gas=1,
                                    batch_size=16)
        texts = {}
        for name, extra in (
                ("absent", {"telemetry": _tel(tmp_path / "a")}),
                ("disabled", {"telemetry": _tel(
                    tmp_path / "b", numerics={"enabled": False})}),
                ("no_telemetry", {}),
                ("enabled", {"telemetry": _tel(
                    tmp_path / "c", numerics={"enabled": True})})):
            engine = _engine(extra)
            placed = engine.put_batch(batches_np, leading_gas_dim=True)
            texts[name] = engine._train_step.lower(
                engine.state, placed, jnp.float32(1e-2)).as_text()
        assert texts["absent"] == texts["disabled"] == texts["no_telemetry"]
        assert texts["enabled"] != texts["absent"]

    def test_lowered_step_bit_identical_when_off_hierarchical(
            self, eight_devices, tmp_path):
        """Same contract on the int8 2-slice grad-sync path: numerics
        off must not perturb the DCN stage's lowering."""
        texts = {}
        for name, numerics in (("absent", None),
                               ("disabled", {"enabled": False})):
            engine = _engine(
                {"gradient_accumulation_steps": 2,
                 "zero_optimization": {"stage": 2},
                 "comm": {"hierarchical": "on", "quant_block_size": 256},
                 "telemetry": _tel(tmp_path / name, numerics=numerics)},
                mesh=build_mesh(slices=2))
            batches = random_batches(np.random.default_rng(0), gas=2,
                                     batch_size=16)
            placed = engine.put_batch(batches, leading_gas_dim=True)
            texts[name] = engine._train_step.lower(
                engine.state, placed, jnp.float32(1e-2)).as_text()
        assert texts["absent"] == texts["disabled"]


# ---------------------------------------------------------------------------
# On-contract: zero step-path syncs, ONE fetch per flush boundary
# ---------------------------------------------------------------------------
class TestOnContract:
    def test_single_fetch_at_flush_boundary(self, eight_devices, tmp_path,
                                            monkeypatch):
        engine = _engine({"steps_per_print": 3,
                          "telemetry": _tel(tmp_path,
                                            numerics={"enabled": True})})
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        engine.train_batch(batches)               # compile + first flush
        from deepspeed_tpu.utils import timer as timer_mod
        syncs = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: syncs.__setitem__("n", syncs["n"] + 1))
        fetches = {"n": 0}
        real_fetch = engine.numerics._fetch

        def counting_fetch():
            fetches["n"] += 1
            return real_fetch()

        monkeypatch.setattr(engine.numerics, "_fetch", counting_fetch)
        for _ in range(6):                        # steps 2..7
            engine.train_batch(batches)
        # flush boundaries at steps 3 and 6 -> exactly two fetches, no
        # timer syncs anywhere on the step path.
        assert fetches["n"] == 2, fetches
        assert syncs["n"] == 0


# ---------------------------------------------------------------------------
# DCN int8 quantization error (the acceptance bound)
# ---------------------------------------------------------------------------
class TestDcnQuantError:
    def test_int8_two_slice_bounded(self, eight_devices, tmp_path):
        engine = _engine(
            {"gradient_accumulation_steps": 2,
             "zero_optimization": {"stage": 2},
             "comm": {"hierarchical": "on", "quant_block_size": 256},
             "telemetry": _tel(tmp_path, numerics={"enabled": True})},
            mesh=build_mesh(slices=2))
        assert engine.grad_sync_plan.measure_quant
        rng = np.random.default_rng(0)
        for _ in range(2):
            engine.train_batch(random_batches(rng, gas=2, batch_size=16))
        rel = _rows(engine, "numerics/dcn_quant_rel_err")
        assert rel, "dcn_quant_rel_err not emitted"
        # emitted, nonzero, bounded: rel-L2 < 1e-1 at block 256
        assert all(0 < r["value"] < 1e-1 for r in rel), rel
        mab = _rows(engine, "numerics/dcn_quant_max_abs_err")
        assert mab and all(0 < r["value"] < 1.0 for r in mab)
        assert all(r["bucket"] in range(
            engine.grad_sync_plan.num_buckets) for r in rel)

    def test_fp32_passthrough_measures_nothing(self, eight_devices,
                                               tmp_path):
        engine = _engine(
            {"gradient_accumulation_steps": 2,
             "comm": {"hierarchical": "on", "dcn_quant_bits": 32},
             "telemetry": _tel(tmp_path, numerics={"enabled": True})},
            mesh=build_mesh(slices=2))
        assert not engine.grad_sync_plan.measure_quant
        engine.train_batch(random_batches(np.random.default_rng(0), gas=2,
                                          batch_size=16))
        assert not _rows(engine, "numerics/dcn_quant_rel_err")
        assert _rows(engine, "numerics/grad_norm")    # stats still ride


# ---------------------------------------------------------------------------
# Spike verdicts name the poisoned layer group (instant + crashdump)
# ---------------------------------------------------------------------------
class TestSpikeNaming:
    def test_nan_poisoned_run_names_group(self, eight_devices, tmp_path):
        dumps = tmp_path / "dumps"
        engine = _engine({
            "steps_per_print": 100,
            "resilience": {"fault_injection": {"nan_loss_at_step": 3}},
            "guardrails": {
                "enabled": True,
                "detector": {"zscore_threshold": 1e9, "warmup_steps": 1},
                "rollback": {"snapshot_interval": 1,
                             "consecutive_spikes": 1, "skip_batches": 0},
                "watchdog": {"crashdump_dir": str(dumps)}},
            "telemetry": {**_tel(tmp_path, numerics={"enabled": True}),
                          "trace": {"enabled": True,
                                    "sync_spans": False}}})
        rng = np.random.default_rng(1)
        stream = [random_batches(rng, gas=1, batch_size=16)
                  for _ in range(8)]
        i = 0
        while engine.global_steps < 5:
            engine.train_batch(stream[i % len(stream)])
            i += 1
        names = engine.numerics.plan.group_names
        spikes = [e for e in engine.telemetry.tracer.events
                  if e.get("name") == "guardrails_spike"]
        assert spikes, "no spike instant"
        worst = spikes[0]["args"]["worst_group"]
        assert worst in names, (worst, names)
        spike_dirs = [d for d in os.listdir(dumps)
                      if d.startswith("spike_step")]
        assert spike_dirs, os.listdir(dumps)
        info = json.load(open(dumps / spike_dirs[0] / "info.json"))
        assert info["worst_group"] == worst
        assert info["reason"] == "nonfinite"
        table = {g["group"]: g for g in info["groups"]}
        assert set(table) == set(names)
        # NaN batch poisons every group's grads; the table says so
        assert not table[worst]["finite"]

    def test_dump_budget_bounds_disk(self, eight_devices, tmp_path):
        dumps = tmp_path / "dumps"
        engine = _engine({
            "steps_per_print": 100,
            "resilience": {"fault_injection": {"nan_loss_at_step": 2,
                                               "nan_loss_steps": 6}},
            "guardrails": {
                "enabled": True,
                "detector": {"zscore_threshold": 1e9, "warmup_steps": 1},
                "rollback": {"enabled": False},
                "watchdog": {"crashdump_dir": str(dumps)}},
            "telemetry": _tel(tmp_path, numerics={"enabled": True,
                                                  "max_spike_dumps": 2})})
        rng = np.random.default_rng(1)
        stream = [random_batches(rng, gas=1, batch_size=16)
                  for _ in range(8)]
        for i in range(8):
            engine.train_batch(stream[i % len(stream)])
        spike_dirs = [d for d in os.listdir(dumps)
                      if d.startswith("spike_step")]
        assert len(spike_dirs) == 2, spike_dirs


# ---------------------------------------------------------------------------
# Offload + pipe tiers
# ---------------------------------------------------------------------------
class TestOtherTiers:
    def test_offload_grad_stats_update_zero(self, eight_devices, tmp_path):
        engine = _engine({
            "zero_optimization": {"stage": 2,
                                  "offload_optimizer": {"device": "cpu"}},
            "telemetry": _tel(tmp_path, numerics={"enabled": True})})
        rng = np.random.default_rng(0)
        for _ in range(2):
            engine.train_batch(random_batches(rng, gas=1, batch_size=16))
        gn = _rows(engine, "numerics/grad_norm")
        assert gn and all(r["value"] > 0 for r in gn)
        # host-side optimizer: update norms reported as 0 by contract
        assert all(r["value"] == 0
                   for r in _rows(engine, "numerics/update_ratio"))

    def test_pipe_engine_stats(self, eight_devices, tmp_path):
        from deepspeed_tpu.models.gpt import GPTConfig
        from deepspeed_tpu.parallel.pipe import (PipelineEngine,
                                                 gpt_pipe_model)

        cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=32,
                        num_layers=2, num_heads=2, dropout_rate=0.0,
                        dtype=jnp.float32)
        ds = DeepSpeedTPUConfig({
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "steps_per_print": 1,
            "telemetry": _tel(tmp_path, numerics={"enabled": True})})
        pipe = PipelineEngine(gpt_pipe_model(cfg), ds,
                              mesh=build_mesh(data=8, pipe=1))
        assert pipe.numerics is not None
        rng = np.random.default_rng(0)
        pipe.train_batch({"input_ids": rng.integers(
            0, 128, (2, 8, 16), dtype=np.int32)})
        gn = _rows(pipe, "numerics/grad_norm")
        groups = {r["group"] for r in gn}
        assert "blocks" in groups and gn
        assert all(r["value"] > 0 for r in gn)

    def test_onebit_logs_and_disables(self, eight_devices, tmp_path):
        engine = _engine({
            "optimizer": {"type": "OneBitAdam",
                          "params": {"lr": 1e-3, "freeze_step": 100}},
            "zero_optimization": {"stage": 0},
            "telemetry": _tel(tmp_path, numerics={"enabled": True})})
        assert engine.numerics is None            # documented unavailability
        engine.train_batch(random_batches(np.random.default_rng(0), gas=1,
                                          batch_size=16))


# ---------------------------------------------------------------------------
# Serving int8 KV error gauge
# ---------------------------------------------------------------------------
class TestServingKV:
    def test_int8_kv_prefill_emits_bounded_error(self):
        from deepspeed_tpu.config.config import ServingConfig
        from deepspeed_tpu.models import make_gpt
        from deepspeed_tpu.serving import ServeEngine
        from deepspeed_tpu.telemetry import (InMemorySink, MetricsRegistry,
                                             RecompileDetector, StepTracer,
                                             Telemetry)

        model, _cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=64,
                               dtype=jnp.float32)
        params = model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(1)},
            {"input_ids": np.zeros((1, 8), np.int32)})["params"]
        reg = MetricsRegistry()
        sink = reg.add_sink(InMemorySink())
        tel = Telemetry(reg, StepTracer(enabled=False),
                        RecompileDetector(enabled=False))
        eng = deepspeed_tpu.init_inference(model, params=params,
                                           dtype=jnp.float32)
        srv = ServeEngine(eng, config=ServingConfig(
            max_batch_size=2, kv_block_size=4, kv_num_blocks=64,
            max_model_len=48, int8_kv_cache=True), telemetry=tel,
            measure_kv_quant_error=True)
        srv.submit([1, 2, 3, 4, 5], max_new_tokens=3)
        srv.run_until_complete()
        rel = [r for r in sink.rows
               if r["tag"] == "numerics/kv_quant_rel_err"]
        assert rel and all(0 <= r["value"] < 0.2 for r in rel), rel
        assert [r for r in sink.rows
                if r["tag"] == "numerics/kv_quant_max_abs_err"]

    def test_int8_without_numerics_opt_in_measures_nothing(self):
        """Telemetry-only serving deployments must not pay the
        per-prefill measure: without the numerics opt-in no error
        gauge is emitted and no measure program is ever built."""
        from deepspeed_tpu.config.config import ServingConfig
        from deepspeed_tpu.models import make_gpt
        from deepspeed_tpu.serving import ServeEngine
        from deepspeed_tpu.telemetry import (InMemorySink, MetricsRegistry,
                                             RecompileDetector, StepTracer,
                                             Telemetry)

        model, _cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=64,
                               dtype=jnp.float32)
        params = model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(1)},
            {"input_ids": np.zeros((1, 8), np.int32)})["params"]
        reg = MetricsRegistry()
        sink = reg.add_sink(InMemorySink())
        tel = Telemetry(reg, StepTracer(enabled=False),
                        RecompileDetector(enabled=False))
        eng = deepspeed_tpu.init_inference(model, params=params,
                                           dtype=jnp.float32)
        srv = ServeEngine(eng, config=ServingConfig(
            max_batch_size=2, kv_block_size=4, kv_num_blocks=64,
            max_model_len=48, int8_kv_cache=True), telemetry=tel)
        srv.submit([1, 2, 3], max_new_tokens=2)
        srv.run_until_complete()
        assert not srv._measure_kv and not srv._kv_err_jit
        assert not [r for r in sink.rows
                    if r["tag"].startswith("numerics/")]

    def test_fp_kv_emits_nothing(self):
        from deepspeed_tpu.config.config import ServingConfig
        from deepspeed_tpu.models import make_gpt
        from deepspeed_tpu.serving import ServeEngine
        from deepspeed_tpu.telemetry import (InMemorySink, MetricsRegistry,
                                             RecompileDetector, StepTracer,
                                             Telemetry)

        model, _cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=64,
                               dtype=jnp.float32)
        params = model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(1)},
            {"input_ids": np.zeros((1, 8), np.int32)})["params"]
        reg = MetricsRegistry()
        sink = reg.add_sink(InMemorySink())
        tel = Telemetry(reg, StepTracer(enabled=False),
                        RecompileDetector(enabled=False))
        eng = deepspeed_tpu.init_inference(model, params=params,
                                           dtype=jnp.float32)
        srv = ServeEngine(eng, config=ServingConfig(
            max_batch_size=2, kv_block_size=4, kv_num_blocks=64,
            max_model_len=48, int8_kv_cache=False), telemetry=tel)
        srv.submit([1, 2, 3], max_new_tokens=2)
        srv.run_until_complete()
        assert not [r for r in sink.rows
                    if r["tag"].startswith("numerics/")]


# ---------------------------------------------------------------------------
# Fleet grad-norm field
# ---------------------------------------------------------------------------
class TestFleetGradNorm:
    def test_fleet_vector_carries_grad_norm(self, eight_devices, tmp_path):
        engine = _engine({"telemetry": {
            **_tel(tmp_path, numerics={"enabled": True}),
            "fleet": {"enabled": True, "min_window": 1}}})
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        for _ in range(2):
            engine.train_batch(batches)
        mem = engine.telemetry.registry.sinks[0]
        gauge = engine.telemetry.registry.gauge(
            "numerics/global_grad_norm").value
        vals = mem.values("fleet/grad_norm_max")
        assert vals and vals[-1] > 0
        np.testing.assert_allclose(vals[-1], gauge, rtol=1e-6)

    def test_numerics_off_reports_zero(self, eight_devices, tmp_path):
        engine = _engine({"telemetry": {
            **_tel(tmp_path),
            "fleet": {"enabled": True, "min_window": 1}}})
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        for _ in range(2):
            engine.train_batch(batches)
        mem = engine.telemetry.registry.sinks[0]
        vals = mem.values("fleet/grad_norm_max")
        assert vals and vals[-1] == 0.0


# ---------------------------------------------------------------------------
# Satellite: get_global_grad_norm no longer re-jits per call
# ---------------------------------------------------------------------------
class TestGlobalNormNoRetrace:
    def test_single_trace_across_calls(self, eight_devices, tmp_path,
                                       monkeypatch):
        import deepspeed_tpu.runtime.engine as eng_mod
        from deepspeed_tpu.runtime.utils import global_norm

        engine = _engine({"telemetry": _tel(tmp_path)})
        engine.train_batch(random_batches(np.random.default_rng(0), gas=1,
                                          batch_size=16))
        traces = {"n": 0}

        def counted(tree):
            traces["n"] += 1
            return global_norm(tree)

        monkeypatch.setattr(eng_mod, "_GLOBAL_NORM_JIT", jax.jit(counted))
        for _ in range(5):
            engine.get_global_grad_norm()
        # ONE trace for five calls (the old inline jax.jit(global_norm)
        # built a fresh wrapper — and re-traced — per invocation) ...
        assert traces["n"] == 1, traces
        # ... and the recompile detector agrees: one expected compile,
        # zero retraces under the engine.global_norm name.
        rec = engine.telemetry.recompile
        assert rec.compiles("engine.global_norm") == 1
        assert rec.retraces("engine.global_norm") == 0


# ---------------------------------------------------------------------------
# Report tool
# ---------------------------------------------------------------------------
class TestNumericsReport:
    def test_selftest_cli(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "numerics_report.py"),
             "--selftest"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "selftest ok" in proc.stdout

    def test_renders_engine_written_run_dir(self, eight_devices, tmp_path):
        """End to end: a numerics-on engine writes metrics.jsonl; the
        stdlib report renders per-group rows from it."""
        engine = _engine({"telemetry": _tel(tmp_path,
                                            numerics={"enabled": True},
                                            sinks=("jsonl",))})
        rng = np.random.default_rng(0)
        for _ in range(3):
            engine.train_batch(random_batches(rng, gas=1, batch_size=16))
        engine.telemetry.flush()
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "numerics_report.py"),
             str(tmp_path)],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        for group in engine.numerics.plan.group_names:
            assert group in proc.stdout
        assert "global grad norm" in proc.stdout


# ---------------------------------------------------------------------------
# Bench environment records the block
# ---------------------------------------------------------------------------
class TestBenchEnvironment:
    def test_bench_source_records_numerics_off(self):
        with open(os.path.join(REPO, "bench.py")) as f:
            src = f.read()
        assert '"numerics": "off"' in src
