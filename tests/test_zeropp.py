"""ZeRO++ weight path (ISSUE 12): qwZ quantized weight all-gather +
hpZ hierarchical secondary partition + sharded optimizer apply
(arXiv 2306.10209, weight-update sharding arXiv 2004.13336;
docs/PERFORMANCE.md "ZeRO++ weight path").

The acceptance ladder on the virtual 2-slice mesh:

- a default-off ``zeropp`` block lowers a **bit-identical** step vs a
  zeropp-less config (the PR 4 off-identity contract);
- the explicit gather round-trips within the blockwise-int8 bound at
  blocks {256, 1024} and the fp32 passthrough (hpZ alone) is EXACT —
  an all-gather is not a reduction, so the hpZ tier is an equality
  rung, not a tolerance one;
- int8 stays within rtol 2e-2 of the implicit path over a tiny-GPT
  trajectory (mirroring test_dcn's DCN-grad tolerance);
- with hpZ on, the jitted fwd/bwd contains ZERO cross-slice (dcn-axis)
  param collectives — jaxpr-asserted — while the global primary
  (hpz off) gathers over (dcn, data) and shards the optimizer apply
  over the full world;
- the memory ledger charges the hpZ secondary replica and the capacity
  planner projects it;
- the new numerics gauge keeps the zero-overhead contract.
"""

import os
import re
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import build_mesh

INT8 = {"quantized_weights": "int8", "quant_block_size": 256, "hpz": "on"}


def mlp_loss_fn(params, batch, rng):
    h = jnp.tanh(batch["x"] @ params["w1"])
    pred = h @ params["w2"]
    return jnp.mean((pred - batch["y"]) ** 2)


def mlp_params():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    return {"w1": jax.random.normal(k1, (16, 64)) * 0.1,
            "w2": jax.random.normal(k2, (64, 8)) * 0.1}


def make_batches(rng, gas, bs):
    return {"x": rng.standard_normal((gas, bs, 16)).astype(np.float32),
            "y": rng.standard_normal((gas, bs, 8)).astype(np.float32)}


def build(mesh, zeropp=None, stage=3, comm=None, config_extra=None,
          **init_kwargs):
    zcfg = {"stage": stage, "stage3_param_persistence_threshold": 0}
    if zeropp is not None:
        zcfg["zeropp"] = zeropp
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": zcfg,
    }
    if comm is not None:
        config["comm"] = comm
    if config_extra:
        config.update(config_extra)
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(), mesh=mesh, config=config,
        **init_kwargs)
    return engine


def make_gpt_engine(zeropp, telemetry=None):
    from deepspeed_tpu.models import make_gpt

    model, cfg = make_gpt("tiny", num_layers=2, dropout_rate=0.0,
                          dtype=jnp.float32)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (8, 16), dtype=np.int32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": ids})["params"]
    zcfg = {"stage": 3, "stage3_param_persistence_threshold": 0}
    if zeropp:
        zcfg["zeropp"] = zeropp
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": zcfg,
    }
    if telemetry:
        config["telemetry"] = telemetry
        config["steps_per_print"] = 1
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=params, mesh=build_mesh(slices=2),
        config=config)
    return engine, cfg


def _collective_blocks(txt):
    """Every collective primitive's param block in a jaxpr string."""
    return re.findall(
        r"(?:all_gather|all_to_all|psum2?|ppermute)\[(.*?)\]", txt, re.S)


class TestOffIdentity:
    def test_default_off_bit_identical_lowered_step(self, eight_devices):
        """An explicitly-inert zeropp block ({off, off}) produces a
        jaxpr string-identical to a zeropp-less stage-3 config, with no
        explicit collectives at all (the implicit path has none)."""
        rng = np.random.default_rng(0)
        batches = make_batches(rng, 2, 16)
        base = build(build_mesh(slices=2))
        off = build(build_mesh(slices=2),
                    zeropp={"quantized_weights": "off", "hpz": "off"})
        assert base.param_gather_plan is None
        assert off.param_gather_plan is None
        pb = base.put_batch(batches, leading_gas_dim=True)
        jx_base = str(base._train_step.trace(
            base.state, pb, jnp.float32(1e-2)).jaxpr)
        jx_off = str(off._train_step.trace(
            off.state, pb, jnp.float32(1e-2)).jaxpr)
        assert jx_base == jx_off
        assert "all_gather" not in jx_off

    def test_specs_unchanged_when_off(self, eight_devices):
        base = build(build_mesh(slices=2))
        off = build(build_mesh(slices=2),
                    zeropp={"quantized_weights": "off", "hpz": "off"})
        assert base.param_specs == off.param_specs
        assert base.opt_specs == off.opt_specs


class TestQwZRoundtrip:
    """The gather itself, against ground truth: int8 bounded by the
    blockwise-RTNE error, fp32 passthrough exact."""

    @pytest.mark.parametrize("block", [256, 1024])
    def test_int8_gather_roundtrip_bounded(self, eight_devices, block):
        eng = build(build_mesh(slices=2),
                    zeropp={"quantized_weights": "int8",
                            "quant_block_size": block, "hpz": "on"})
        plan = eng.param_gather_plan
        assert plan is not None and plan.bits == 8
        with eng.mesh:
            full, _ = jax.jit(lambda p: plan.gather(p))(eng.state.params)
        ref = jax.device_get(eng.state.params)
        out = jax.device_get(full)
        for k in ref:
            amax = np.abs(ref[k]).max()
            err = np.abs(out[k] - ref[k]).max()
            # Symmetric int8 RTNE: per-element error <= blockmax/254 <=
            # leafmax/254 (blocks are shard-local flat runs).
            assert err <= amax / 254 + 1e-7, (k, err, amax)

    def test_param_qerr_counts_each_unique_shard_once(self, eight_devices):
        """Mixed tree under the hpz=off global primary: a (data,)-only
        fallback leaf is dcn-replicated inside the manual region, and
        the psum over {dcn, data} would count its error parts dcn times
        — the plan must pre-divide by the replication factor so the
        emitted rel-L2 equals the unweighted round-trip error over every
        UNIQUE shard, exactly once each."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from deepspeed_tpu.comm.grad_sync import ParamGatherPlan
        from deepspeed_tpu.comm.quantize import (rel_from_parts,
                                                 roundtrip_error_parts)
        from deepspeed_tpu.runtime.zero.config import ZeroConfig

        mesh = build_mesh(slices=2)          # dcn2 x data4
        rng = np.random.default_rng(3)
        a = rng.standard_normal((8, 4)).astype(np.float32)
        b = rng.standard_normal((12, 4)).astype(np.float32)
        specs = {"a": P(("dcn", "data"), None),     # 8 unique shards
                 "b": P("data", None)}              # 4, dcn-replicated
        params = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                  for k, v in (("a", a), ("b", b))}
        zpp = ZeroConfig.from_dict({"stage": 3, "zeropp": {
            "quantized_weights": "int8", "hpz": "off"}}).zeropp
        plan = ParamGatherPlan(zpp, mesh, param_template=params,
                               param_specs=specs, measure_quant_error=True)
        with mesh:
            full, qerr = jax.jit(lambda p: plan.gather(p))(params)
        qerr = np.asarray(jax.device_get(qerr))
        np.testing.assert_allclose(np.asarray(full["a"]), a, atol=0.05)
        np.testing.assert_allclose(np.asarray(full["b"]), b, atol=0.05)

        def parts(x, shards):
            es = rs = ms = 0.0
            for s in np.split(x, shards):        # shard-local flat runs
                flat = s.reshape(-1)
                pad = (-len(flat)) % 256
                flat = np.concatenate([flat, np.zeros(pad, np.float32)])
                e, r, m = (float(v) for v in roundtrip_error_parts(
                    jnp.asarray(flat), 8, 256))
                es, rs, ms = es + e, rs + r, max(ms, m)
            return es, rs, ms

        ea, ra, ma = parts(a, 8)
        eb, rb, mb = parts(b, 4)                 # once per UNIQUE shard
        want = float(rel_from_parts(jnp.float32(ea + eb),
                                    jnp.float32(ra + rb)))
        np.testing.assert_allclose(qerr[0], want, rtol=1e-5)
        np.testing.assert_allclose(qerr[1], max(ma, mb), rtol=1e-5)

    def test_fp32_passthrough_gather_exact(self, eight_devices):
        eng = build(build_mesh(slices=2), zeropp={"hpz": "on"})
        plan = eng.param_gather_plan
        assert plan is not None and plan.bits == 32
        with eng.mesh:
            full, qerr = jax.jit(lambda p: plan.gather(p))(
                eng.state.params)
        assert qerr is None          # nothing lossy to measure
        ref = jax.device_get(eng.state.params)
        out = jax.device_get(full)
        for k in ref:
            np.testing.assert_array_equal(out[k], ref[k])


class TestParityLadder:
    def test_fp32_passthrough_tracks_plain_stage3_exactly(
            self, eight_devices):
        """hpZ alone (fp32 wire): the gather is lossless and elementwise
        — the trajectory must EQUAL plain stage-3 to float tolerance
        (tighter than the grad-sync ulp rung: no reduction reordering
        is involved in an all-gather)."""
        rng = np.random.default_rng(1)
        batches = [make_batches(rng, 2, 16) for _ in range(5)]
        plain = build(build_mesh(slices=2))
        hpz = build(build_mesh(slices=2), zeropp={"hpz": "on"})
        for b in batches:
            lp = float(plain.train_batch({k: v.copy() for k, v in b.items()}))
            lh = float(hpz.train_batch({k: v.copy() for k, v in b.items()}))
            np.testing.assert_allclose(lh, lp, rtol=1e-6, atol=1e-7)

    @pytest.mark.parametrize("zeropp,tol", [
        (dict(INT8), 2e-2),
        ({"quantized_weights": "bf16", "hpz": "off"}, 5e-3),
    ])
    def test_quantized_rungs_track_plain(self, eight_devices, zeropp, tol):
        """int8 intra-slice and bf16 global-primary both stay within
        tolerance of the implicit path (the global rung also exercises
        the (dcn, data) stitch order — a misordered reconstruction
        explodes immediately)."""
        rng = np.random.default_rng(2)
        batches = [make_batches(rng, 2, 16) for _ in range(4)]
        plain = build(build_mesh(slices=2))
        on = build(build_mesh(slices=2), zeropp=zeropp)
        for b in batches:
            lp = float(plain.train_batch({k: v.copy() for k, v in b.items()}))
            lh = float(on.train_batch({k: v.copy() for k, v in b.items()}))
            assert np.isfinite(lh)
            np.testing.assert_allclose(lh, lp, rtol=tol, atol=tol)

    def test_int8_gpt_trajectory(self, eight_devices):
        """Short tiny-GPT trajectory: qwZ-int8 stays within rtol 2e-2 of
        the implicit stage-3 path and still trains (mirrors
        test_dcn.test_int8_gpt_trajectory's DCN-grad rung)."""
        plain, cfg = make_gpt_engine(None)
        on, _ = make_gpt_engine(dict(INT8))
        rng = np.random.default_rng(3)
        ids = rng.integers(0, cfg.vocab_size, (2, 16, 16), dtype=np.int32)
        losses_p, losses_on = [], []
        for _ in range(5):
            losses_p.append(float(plain.train_batch(
                {"input_ids": ids.copy()})))
            losses_on.append(float(on.train_batch(
                {"input_ids": ids.copy()})))
        losses_p, losses_on = np.array(losses_p), np.array(losses_on)
        assert np.isfinite(losses_on).all()
        np.testing.assert_allclose(losses_on, losses_p, rtol=2e-2)
        assert losses_on[-1] < losses_on[0]      # still trains

    def test_zeropp_is_fused_only(self, eight_devices):
        """An active zeropp block disables the per-microbatch program
        (the explicit gather is a collective — one per optimizer step,
        like the hierarchical/1-bit/offload tiers): forward()/backward()
        stash-and-fuse, and the trajectory matches train_batch exactly."""
        eng = build(build_mesh(slices=2), zeropp=dict(INT8))
        assert eng._micro_step is None and eng._apply_step is None
        rng = np.random.default_rng(9)
        b = make_batches(rng, 2, 16)
        micros = [{k: v[i] for k, v in b.items()} for i in range(2)]
        for _ in range(2):
            for m in micros:
                eng.forward(m)
                eng.backward()
            eng.step()
        ref = build(build_mesh(slices=2), zeropp=dict(INT8))
        for _ in range(2):
            ref.train_batch(b)
        for a, c in zip(jax.tree_util.tree_leaves(eng.state.params),
                        jax.tree_util.tree_leaves(ref.state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))

    def test_composes_with_hierarchical_grad_sync(self, eight_devices):
        """qwZ + the hierarchical int8 grad sync: both lossy hops in one
        step, trajectory still within tolerance of the fully-implicit
        path."""
        rng = np.random.default_rng(4)
        plain = build(build_mesh(slices=2))
        both = build(build_mesh(slices=2), zeropp=dict(INT8),
                     comm={"hierarchical": "on", "dcn_quant_bits": 8,
                           "quant_block_size": 256})
        assert both.grad_sync_plan is not None
        assert both.param_gather_plan is not None
        for b in [make_batches(rng, 2, 16) for _ in range(3)]:
            lp = float(plain.train_batch({k: v.copy() for k, v in b.items()}))
            lh = float(both.train_batch({k: v.copy() for k, v in b.items()}))
            assert np.isfinite(lh)
            np.testing.assert_allclose(lh, lp, rtol=3e-2, atol=3e-2)


class TestHpZPlacement:
    def test_hpz_zero_cross_slice_param_collectives(self, eight_devices):
        """THE hpZ claim, at the jaxpr level: with hpz on the traced
        train_step's collectives never name the dcn axis — the explicit
        int8 param gather (all_gather of i8 codes) rides data only."""
        on = build(build_mesh(slices=2), zeropp=dict(INT8))
        rng = np.random.default_rng(5)
        pb = on.put_batch(make_batches(rng, 2, 16), leading_gas_dim=True)
        txt = str(on._train_step.trace(
            on.state, pb, jnp.float32(1e-2)).jaxpr)
        ags = re.findall(r"all_gather\[(.*?)\]", txt, re.S)
        assert ags, "no explicit param gather in the hpZ jaxpr"
        blocks = _collective_blocks(txt)
        assert blocks and not any("dcn" in b for b in blocks), \
            [b[:120] for b in blocks if "dcn" in b][:1]
        assert "i8[" in txt, "no int8 wire arrays in the step"

    def test_global_primary_gathers_over_dcn(self, eight_devices):
        """hpz off (block active): the primary partition spans
        (dcn, data) — master/opt shard 8-way, the gather's collectives
        name dcn, and the sharded optimizer apply updates 1/(dcn*data)
        shards."""
        from jax.sharding import PartitionSpec as P

        glob = build(build_mesh(slices=2),
                     zeropp={"quantized_weights": "int8",
                             "quant_block_size": 256, "hpz": "off"})
        assert glob.param_specs["w1"] == P(None, ("dcn", "data"))
        assert glob.opt_specs["w1"] == P(None, ("dcn", "data"))
        m = glob.state.opt_state.exp_avg["w1"]
        shard_elems = int(np.prod(m.sharding.shard_shape(m.shape)))
        assert shard_elems == 16 * 64 // 8, shard_elems
        rng = np.random.default_rng(6)
        pb = glob.put_batch(make_batches(rng, 2, 16), leading_gas_dim=True)
        txt = str(glob._train_step.trace(
            glob.state, pb, jnp.float32(1e-2)).jaxpr)
        ags = re.findall(r"all_gather\[(.*?)\]", txt, re.S)
        assert ags and any("dcn" in a for a in ags)

    def test_global_primary_falls_back_to_data_axis(self, eight_devices):
        """hpz off: a leaf whose dims divide data (4) but not dcn*data
        (8) must fall back to the intra-slice (data,) partition — NEVER
        to full replication (plain stage 3 sharded it over data, and the
        maximal-HBM-savings mode can't do worse); the moments follow the
        same fallback, and the gather plan still gathers the leaf (over
        data only, like an hpZ leaf) instead of calling it persistent."""
        from jax.sharding import PartitionSpec as P

        from deepspeed_tpu.comm.grad_sync import ParamGatherPlan
        from deepspeed_tpu.runtime.zero.config import ZeroConfig
        from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner

        mesh = build_mesh(slices=2)          # dcn2 x data4
        zpp = {"zeropp": {"quantized_weights": "int8", "hpz": "off"}}
        plain = ZeroPartitioner(mesh, ZeroConfig.from_dict(
            {"stage": 3, "stage3_param_persistence_threshold": 0}))
        glob = ZeroPartitioner(mesh, ZeroConfig.from_dict(
            {"stage": 3, "stage3_param_persistence_threshold": 0, **zpp}))
        assert plain.param_spec((12, 3)) == P("data", None)
        assert glob.param_spec((12, 3)) == P("data", None)
        assert glob.opt_state_spec((12, 3)) == P("data", None)
        # dcn*data-divisible dims still take the global primary.
        assert glob.param_spec((16, 3)) == P(("dcn", "data"), None)
        plan = ParamGatherPlan(
            ZeroConfig.from_dict({"stage": 3, **zpp}).zeropp, mesh,
            param_template={"w": jnp.zeros((12 // 4, 3))},
            param_specs={"w": glob.param_spec((12, 3))})
        assert [a for _, _, a in plan.gathered] == [("data",)]

    def test_hpz_keeps_intra_slice_partition(self, eight_devices):
        """hpz on: master/opt shard over data only (4-way — the
        dcn-replicated secondary layout the ledger charges)."""
        on = build(build_mesh(slices=2), zeropp=dict(INT8))
        m = on.state.opt_state.exp_avg["w1"]
        shard_elems = int(np.prod(m.sharding.shard_shape(m.shape)))
        assert shard_elems == 16 * 64 // 4, shard_elems

    def test_modeled_param_bytes_ladder(self, eight_devices):
        """hpZ: dcn param bytes structurally 0; int8: >= 3.5x modeled
        compression; global: dcn share = (dcn-1)/dcn of the payload."""
        hpz = build(build_mesh(slices=2), zeropp=dict(INT8))
        m = hpz.param_gather_plan.modeled_bytes()
        assert m["bytes_dcn_params"] == 0
        assert m["bytes_ici_params"] > 0
        assert m["compression_ratio"] >= 3.5
        assert m["fallback_elems"] == 0      # plain MLP: everything gathers
        glob = build(build_mesh(slices=2),
                     zeropp={"quantized_weights": "int8",
                             "quant_block_size": 256, "hpz": "off"})
        g = glob.param_gather_plan.modeled_bytes()
        assert g["bytes_dcn_params"] > 0
        assert g["bytes_dcn_params"] == g["bytes_ici_params"]  # dcn=2


class TestAccounting:
    def test_ledger_charges_secondary_replica(self, eight_devices,
                                              tmp_path):
        """memory/ledger_secondary_bytes = (1 - 1/dcn) x the per-device
        fp32 state of the dcn-shardable (gathered) leaves under hpZ,
        recorded in the ledger AND projected by plan_capacity
        (hpz_secondary_bytes); 0 for the global primary and for
        zeropp-less engines."""
        from deepspeed_tpu.telemetry.registry import InMemorySink

        on = build(build_mesh(slices=2), zeropp=dict(INT8),
                   config_extra={"telemetry": {
                       "enabled": True, "dir": str(tmp_path),
                       "memory": {"enabled": True}}})
        led = on.memory.last_ledger
        assert led["secondary"]["hpz"]
        ratio = led["full"]["optimizer_bytes"] / led["full"]["master_bytes"]
        shard_master = (16 * 64 + 64 * 8) // 4 * 4   # data=4 shards, fp32
        expect = int(shard_master * (1 + ratio) / 2)
        assert led["secondary"]["replica_bytes"] == expect > 0
        # The gathered compute tree is FULL per device (the explicit
        # all-gather replicates it) — a pure-fp32 run books that copy.
        assert led["per_device"]["compute_params_bytes"] \
            == (16 * 64 + 64 * 8) * 4
        # Not double-counted into the device model-state sum.
        assert led["per_device"]["model_state_bytes"] == sum(
            v for k, v in led["per_device"].items()
            if k != "model_state_bytes")
        assert on.memory.last_plan["hpz_secondary_bytes"] == expect
        assert (on.memory.last_plan["hpz_global_primary_savings_bytes"]
                == expect)
        mem = on.telemetry.registry.add_sink(InMemorySink())
        on.memory._emit_ledger(led)
        rows = {r["tag"]: r["value"] for r in mem.rows}
        assert rows["memory/ledger_secondary_bytes"] == expect

        off = build(build_mesh(slices=2),
                    config_extra={"telemetry": {
                        "enabled": True, "dir": str(tmp_path / "off"),
                        "memory": {"enabled": True}}})
        assert off.memory.last_ledger["secondary"]["replica_bytes"] == 0

        # The fp32-passthrough hpZ tier has the identical dcn-replicated
        # placement — the charge is a placement property, independent of
        # the wire dtype.
        fp32 = build(build_mesh(slices=2), zeropp={"hpz": "on"},
                     config_extra={"telemetry": {
                         "enabled": True, "dir": str(tmp_path / "fp32"),
                         "memory": {"enabled": True}}})
        assert (fp32.memory.last_ledger["secondary"]["replica_bytes"]
                == expect)

    def test_secondary_charge_excludes_non_dcn_shardable_leaves(
            self, eight_devices, tmp_path):
        """A leaf whose dims divide data but not dcn x data falls back
        to the SAME (data,) partition under the global primary, so
        flipping hpz off saves nothing on it — the ledger's secondary
        charge must scale by the dcn-shardable fraction, not bill the
        whole fp32 state."""
        from deepspeed_tpu.telemetry.memory import model_state_ledger

        def loss(p, b, r):
            h = jnp.tanh(b["x"] @ p["w1"])
            reg = 1e-6 * jnp.sum(p["wx"] ** 2)
            return jnp.mean((h @ p["w2"] - b["y"]) ** 2) + reg

        k = jax.random.split(jax.random.PRNGKey(0), 3)
        params = {"w1": jax.random.normal(k[0], (16, 64)) * 0.1,
                  "w2": jax.random.normal(k[1], (64, 8)) * 0.1,
                  # 12 % 4 == 0 but 12 % 8 != 0: (data,)-fallback leaf.
                  "wx": jax.random.normal(k[2], (12, 12)) * 0.1}
        engine, _, _, _ = deepspeed_tpu.initialize(
            loss_fn=loss, params=params, mesh=build_mesh(slices=2),
            config={
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {
                    "stage": 3, "stage3_param_persistence_threshold": 0,
                    "zeropp": dict(INT8)}})
        led = model_state_ledger(engine)
        ratio = led["full"]["optimizer_bytes"] / led["full"]["master_bytes"]
        shard_master = (16 * 64 + 64 * 8) // 4 * 4  # wx's elems excluded
        expect = int(shard_master * (1 + ratio) / 2)
        assert led["secondary"]["replica_bytes"] == expect > 0

        # A base spec that already pins the data axis (the TiledLinear
        # shape) early-returns under the global primary too — flipping
        # hpz off gains nothing on that leaf, so it leaves the charge.
        from jax.sharding import PartitionSpec as P

        from deepspeed_tpu.config.config import DeepSpeedTPUConfig
        from deepspeed_tpu.runtime.engine import TPUEngine

        pinned = TPUEngine(
            loss_fn=mlp_loss_fn, params=mlp_params(),
            config=DeepSpeedTPUConfig({
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {
                    "stage": 3, "stage3_param_persistence_threshold": 0,
                    "zeropp": dict(INT8)}}),
            mesh=build_mesh(slices=2),
            param_partition_specs={"w1": P(None, "data"), "w2": None})
        led = model_state_ledger(pinned)
        ratio = led["full"]["optimizer_bytes"] / led["full"]["master_bytes"]
        shard_master = (64 * 8) // 4 * 4        # w1 base-pinned: excluded
        assert led["secondary"]["replica_bytes"] \
            == int(shard_master * (1 + ratio) / 2) > 0

    def test_secondary_charge_counts_tp_fallback_leaves(
            self, eight_devices):
        """A TP-sharded leaf rides the implicit gather path (fallback),
        but its free dim still carries the primary placement — a global
        (hpz off) primary would spread it over dcn, so the hpZ secondary
        charge must bill its shard bytes like a gathered leaf's."""
        from jax.sharding import PartitionSpec as P

        from deepspeed_tpu.config.config import DeepSpeedTPUConfig
        from deepspeed_tpu.runtime.engine import TPUEngine
        from deepspeed_tpu.telemetry.memory import model_state_ledger

        engine = TPUEngine(
            loss_fn=mlp_loss_fn, params=mlp_params(),
            config=DeepSpeedTPUConfig({
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {
                    "stage": 3, "stage3_param_persistence_threshold": 0,
                    "zeropp": dict(INT8)}}),
            mesh=build_mesh(slices=2, model=2),
            param_partition_specs={"w1": P(None, "model"), "w2": None})
        plan = engine.param_gather_plan
        assert plan.fallback_elems == 16 * 64          # w1: TP fallback
        assert [s for s, _, _ in plan.fallback_leaves()] == [(16, 64)]
        led = model_state_ledger(engine)
        ratio = led["full"]["optimizer_bytes"] / led["full"]["master_bytes"]
        # dcn=2 x data=2 x model=2: w2 gathered over (data,), w1 sharded
        # over (data, model) — BOTH dcn-shardable under the global
        # primary, both billed at their per-device shard elems.
        shard_master = (64 * 8 // 2 + 16 * 64 // 4) * 4
        assert led["secondary"]["replica_bytes"] \
            == int(shard_master * (1 + ratio) / 2) > 0

    def test_comm_param_gauges_and_numerics_gauge(self, eight_devices,
                                                  tmp_path):
        """comm/bytes_dcn_params + comm/bytes_ici_params land each step;
        with telemetry.numerics on, numerics/param_quant_rel_err /
        _max_abs_err land at the flush and measure < 1e-1."""
        from deepspeed_tpu.telemetry.registry import InMemorySink

        on = build(build_mesh(slices=2), zeropp=dict(INT8),
                   config_extra={"steps_per_print": 1,
                                 "telemetry": {
                                     "enabled": True, "dir": str(tmp_path),
                                     "numerics": {"enabled": True}}})
        rng = np.random.default_rng(7)
        on.train_batch(make_batches(rng, 2, 16))
        mem = on.telemetry.registry.add_sink(InMemorySink())
        on.train_batch(make_batches(rng, 2, 16))
        tags = {r["tag"] for r in mem.rows}
        assert {"comm/bytes_dcn_params", "comm/bytes_ici_params",
                "numerics/param_quant_rel_err",
                "numerics/param_quant_max_abs_err"} <= tags
        rel = [r["value"] for r in mem.rows
               if r["tag"] == "numerics/param_quant_rel_err"]
        assert rel and all(0 < v < 1e-1 for v in rel), rel

    def test_zero_overhead_numerics_contract(self, eight_devices,
                                             tmp_path):
        """The new gauge keeps the observatory contract: a qwZ engine
        with telemetry on but numerics OFF lowers the identical step as
        one with telemetry absent (no measurement ops ride along), and
        its plan does not measure."""
        rng = np.random.default_rng(8)
        batches = make_batches(rng, 2, 16)
        bare = build(build_mesh(slices=2), zeropp=dict(INT8))
        tel = build(build_mesh(slices=2), zeropp=dict(INT8),
                    config_extra={"telemetry": {"enabled": True,
                                                "dir": str(tmp_path)}})
        assert not bare.param_gather_plan.measure_quant
        assert not tel.param_gather_plan.measure_quant
        pb = bare.put_batch(batches, leading_gas_dim=True)
        jx_bare = str(bare._train_step.trace(
            bare.state, pb, jnp.float32(1e-2)).jaxpr)
        jx_tel = str(tel._train_step.trace(
            tel.state, pb, jnp.float32(1e-2)).jaxpr)
        assert jx_bare == jx_tel

    def test_param_hop_in_modeled_exposed_frac(self, eight_devices,
                                               tmp_path):
        """zeropp WITHOUT the hierarchical sync still emits the modeled
        comm/exposed_frac, fed by the param gather's wire time (it runs
        before the fused fwd/bwd, fully exposed) — previously the gauge
        only existed with a grad-sync plan, so the device-time
        observatory's measured-vs-modeled divergence warning fired by
        construction whenever qwZ rode alone."""
        from deepspeed_tpu.telemetry.registry import InMemorySink

        on = build(build_mesh(slices=2), zeropp=dict(INT8),
                   config_extra={"steps_per_print": 1,
                                 "telemetry": {
                                     "enabled": True,
                                     "dir": str(tmp_path)}})
        assert on.grad_sync_plan is None      # the param hop is alone
        rng = np.random.default_rng(10)
        on.train_batch(make_batches(rng, 2, 16))
        mem = on.telemetry.registry.add_sink(InMemorySink())
        on.train_batch(make_batches(rng, 2, 16))
        vals = [r["value"] for r in mem.rows
                if r["tag"] == "comm/exposed_frac"]
        assert vals and all(0 < v <= 1 for v in vals), vals

    def test_eval_skips_explicit_gather(self, eight_devices):
        """eval_batch — and the reference API's forward() probe loss
        that rides it — stays on the IMPLICIT full-precision path: the
        probe runs once per microbatch, so the explicit gather there
        would cost gas extra collectives per optimizer step outside the
        one-gather-per-step comm/bytes_*_params model. The qwZ engine's
        eval jaxpr carries no int8 wire arrays and equals plain
        stage-3's exactly."""
        on = build(build_mesh(slices=2), zeropp=dict(INT8))
        plain = build(build_mesh(slices=2))
        rng = np.random.default_rng(11)
        b = make_batches(rng, 2, 16)
        micro = {k: v[0] for k, v in b.items()}
        jx_on = str(on._eval_step.trace(on.state, micro).jaxpr)
        assert "i8[" not in jx_on, "eval must not run the quantized gather"
        jx_plain = str(plain._eval_step.trace(plain.state, micro).jaxpr)
        assert jx_on == jx_plain


class TestConfigValidation:
    def test_requires_stage_ge_2(self, eight_devices):
        from deepspeed_tpu.config.config import ConfigError

        with pytest.raises(ConfigError, match="stage >= 2"):
            build(build_mesh(slices=2), zeropp={"hpz": "on"}, stage=1)

    def test_rejects_onebit(self, eight_devices):
        from deepspeed_tpu.config.config import ConfigError

        config = {
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "OneBitAdam",
                          "params": {"lr": 1e-2, "freeze_step": 2}},
            "zero_optimization": {"stage": 0,
                                  "zeropp": {"quantized_weights": "int8"}},
        }
        with pytest.raises(ConfigError, match="1-bit"):
            deepspeed_tpu.initialize(
                loss_fn=mlp_loss_fn, params=mlp_params(),
                mesh=build_mesh(slices=2), config=config)

    def test_rejects_offload_param(self, eight_devices):
        """The zeropp x offload_param combination must fail loudly with
        the secondary-replica rationale AT CONFIG PARSE — before
        initialize()'s offload tier ever touches the model (its
        block-structured conversion would otherwise crash first with an
        unrelated error)."""
        from deepspeed_tpu.config.config import (ConfigError,
                                                 DeepSpeedTPUConfig)

        with pytest.raises(ConfigError, match="offload_param"):
            DeepSpeedTPUConfig({
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {
                    "stage": 3,
                    "zeropp": {"hpz": "on"},
                    "offload_param": {"device": "cpu"},
                    "offload_optimizer": {"device": "cpu"}}})

    def test_rejects_offload_optimizer(self, eight_devices):
        from deepspeed_tpu.config.config import ConfigError

        with pytest.raises(ConfigError, match="offload_optimizer"):
            build(build_mesh(slices=2),
                  config_extra={"zero_optimization": {
                      "stage": 2,
                      "zeropp": {"quantized_weights": "int8"},
                      "offload_optimizer": {"device": "cpu"}}})

    def test_rejects_host_implied_offload(self, eight_devices):
        """'cpuadam' implies the host tier at ENGINE level (no explicit
        offload_optimizer block for the config-parse wall to see) — the
        engine must still refuse: the offload builders never run the
        explicit gather, so an active plan would emit modeled comm
        gauges and the ledger charge for traffic that does not exist."""
        from deepspeed_tpu.config.config import ConfigError

        config = {
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "cpuadam", "params": {"lr": 1e-2}},
            "zero_optimization": {
                "stage": 2,
                "zeropp": {"quantized_weights": "int8", "hpz": "on"}},
        }
        with pytest.raises(ConfigError, match="host"):
            deepspeed_tpu.initialize(
                loss_fn=mlp_loss_fn, params=mlp_params(),
                mesh=build_mesh(slices=2), config=config)

    def test_rejects_bad_values(self, eight_devices):
        for bad, match in (({"quantized_weights": "int4"},
                            "quantized_weights"),
                           ({"hpz": "maybe"}, "hpz"),
                           ({"quant_block_size": 0}, "quant_block_size"),
                           ({"nope": 1}, "unknown")):
            with pytest.raises(ValueError, match=match):
                build(build_mesh(slices=2), zeropp=bad)

    def test_stage2_gets_param_partition(self, eight_devices):
        """qwZ at stage 2: the implicit post-apply param all-gather
        becomes the explicit partition + gather (params shard like
        stage 3 once the block is active)."""
        from jax.sharding import PartitionSpec as P

        s2 = build(build_mesh(slices=2), zeropp=dict(INT8), stage=2)
        assert s2.param_specs["w1"] == P(None, "data")
        assert s2.param_gather_plan is not None
        rng = np.random.default_rng(9)
        plain = build(build_mesh(slices=2), stage=2)
        for b in [make_batches(rng, 2, 16) for _ in range(3)]:
            lp = float(plain.train_batch({k: v.copy() for k, v in b.items()}))
            lh = float(s2.train_batch({k: v.copy() for k, v in b.items()}))
            assert np.isfinite(lh)
            np.testing.assert_allclose(lh, lp, rtol=2e-2, atol=2e-2)


class TestProbeCLI:
    def test_probe_zeropp_selftest_cli(self):
        """The acceptance probe (ISSUE 12 satellite): modeled-bytes
        ladder off/hpZ/qwZ-int8, trains-under-each-tier, and the
        measured param_quant_rel_err gate — in tier-1 via the CLI it
        ships as."""
        repo = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)  # the tool forces its own 8-device flag
        proc = subprocess.run(
            [sys.executable,
             os.path.join(repo, "tools", "probe_zeropp.py"), "--selftest"],
            capture_output=True, text=True, env=env, timeout=540)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert '"pass": true' in proc.stdout
        assert '"hpz_dcn_param_bytes": 0' in proc.stdout
        assert "param_quant_rel_err" in proc.stdout
