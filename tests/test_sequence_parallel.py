"""Sequence-parallel attention parity: ring attention and Ulysses must match
the dense xla reference on the virtual 8-device mesh (values AND gradients) —
a capability the reference lacks entirely (SURVEY.md §2.4 CP row)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.attention import xla_attention
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.parallel.sequence import ring_attention, ulysses_attention


def _qkv(rng, b=2, s=64, h=4, d=16):
    return tuple(jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
                 for _ in range(3))


@pytest.fixture(params=[2, 4])
def seq_mesh(request, eight_devices):
    n = request.param
    return build_mesh(data=8 // n, sequence=n), n


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, seq_mesh, causal):
        mesh, n = seq_mesh
        rng = np.random.default_rng(0)
        q, k, v = _qkv(rng)
        ref = xla_attention(q, k, v, causal=causal)
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, causal=causal))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_grads_match_dense(self, seq_mesh):
        mesh, n = seq_mesh
        rng = np.random.default_rng(1)
        q, k, v = _qkv(rng)

        g_ring = jax.jit(jax.grad(lambda q, k, v: jnp.sum(ring_attention(
            q, k, v, mesh=mesh, causal=True) ** 2), argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(lambda q, k, v: jnp.sum(xla_attention(
            q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_ring, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name}")

    def test_indivisible_seq_raises(self, eight_devices):
        mesh = build_mesh(data=2, sequence=4)
        rng = np.random.default_rng(0)
        q, k, v = _qkv(rng, s=30)
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q, k, v, mesh=mesh)

    def test_single_rank_fallback(self, eight_devices):
        mesh = build_mesh(data=8, sequence=1)
        rng = np.random.default_rng(0)
        q, k, v = _qkv(rng)
        out = ring_attention(q, k, v, mesh=mesh, causal=True)
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6, rtol=1e-6)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, seq_mesh, causal):
        mesh, n = seq_mesh
        rng = np.random.default_rng(0)
        q, k, v = _qkv(rng)
        ref = xla_attention(q, k, v, causal=causal)
        out = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, mesh=mesh, causal=causal))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_grads_match_dense(self, seq_mesh):
        mesh, n = seq_mesh
        rng = np.random.default_rng(1)
        q, k, v = _qkv(rng)
        g_u = jax.jit(jax.grad(lambda q, k, v: jnp.sum(ulysses_attention(
            q, k, v, mesh=mesh, causal=True) ** 2), argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(lambda q, k, v: jnp.sum(xla_attention(
            q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_u, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name}")

    def test_indivisible_heads_raises(self, eight_devices):
        mesh = build_mesh(data=2, sequence=4)
        rng = np.random.default_rng(0)
        q, k, v = _qkv(rng, h=3)
        with pytest.raises(ValueError, match="heads"):
            ulysses_attention(q, k, v, mesh=mesh)


class TestModelIntegration:
    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_gpt_trains_with_sp_attention(self, eight_devices, impl):
        """GPT with attention_impl='ring'/'ulysses' trains end-to-end on a
        data x sequence mesh through the normal engine path."""
        import deepspeed_tpu
        from jax.sharding import PartitionSpec
        from deepspeed_tpu.models import make_gpt

        from deepspeed_tpu.parallel.mesh import set_default_mesh

        mesh = build_mesh(data=2, sequence=4)
        set_default_mesh(mesh)   # ops need the mesh before engine exists
        model, cfg = make_gpt("tiny", attention_impl=impl, num_heads=4,
                              dtype=jnp.float32)
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(0, cfg.vocab_size, (4, 64),
                                           dtype=np.int32)}
        params = model.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)}, batch)["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params, mesh=mesh,
            batch_spec=PartitionSpec("data"),
            config={"train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 1}})
        losses = []
        for _ in range(10):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0] - 0.3, losses


class TestLongContext:
    def test_ring_long_sequence(self, eight_devices):
        """Longer-than-dense-friendly sequence through the ring path."""
        mesh = build_mesh(data=1, sequence=8)
        rng = np.random.default_rng(2)
        q, k, v = _qkv(rng, b=1, s=1024, h=2, d=16)
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, causal=True))(q, k, v)
        ref = xla_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
