"""External-model injection policies (round-2 VERDICT task 8).

HF-Flax GPT-2/BERT weights convert onto the in-tree families and serve
through init_inference — logits parity against the HF forward, and TP=2
sharded generation matches single-device. Reference:
module_inject/replace_policy.py:43-239, replace_module.py:11-88.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import build_mesh

transformers = pytest.importorskip("transformers")


def tiny_hf_gpt2():
    from transformers import FlaxGPT2LMHeadModel, GPT2Config

    cfg = GPT2Config(vocab_size=128, n_positions=64, n_embd=32, n_layer=2,
                     n_head=2, resid_pdrop=0.0, embd_pdrop=0.0,
                     attn_pdrop=0.0)
    return FlaxGPT2LMHeadModel(cfg, seed=0)


def tiny_hf_bert():
    from transformers import BertConfig, FlaxBertForMaskedLM

    cfg = BertConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=2, intermediate_size=128,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    return FlaxBertForMaskedLM(cfg, seed=0)


class TestGPT2Injection:
    def test_logits_parity_with_hf(self):
        hf = tiny_hf_gpt2()
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, 128, (2, 16), dtype=np.int32))
        hf_logits = np.asarray(hf(ids).logits)

        eng = deepspeed_tpu.init_inference(hf, dtype=jnp.float32)
        ours = np.asarray(eng.forward({"input_ids": ids})["logits"])
        np.testing.assert_allclose(ours, hf_logits, atol=2e-4, rtol=2e-4)

    def test_tp2_generation_matches_single_device(self, eight_devices):
        hf = tiny_hf_gpt2()
        ids = jnp.asarray(np.random.default_rng(1).integers(
            0, 128, (2, 8), dtype=np.int32))
        e1 = deepspeed_tpu.init_inference(hf, dtype=jnp.float32)
        out1 = np.asarray(e1.generate(ids, max_new_tokens=6))
        e2 = deepspeed_tpu.init_inference(hf, dtype=jnp.float32, mp_size=2,
                                          mesh=build_mesh(
                                              model=2, data=4))
        out2 = np.asarray(e2.generate(ids, max_new_tokens=6))
        np.testing.assert_array_equal(out1, out2)

    def test_injection_disabled_requires_intree_contract(self):
        """replace_with_kernel_inject=False keeps the HF module as-is —
        our engine can't drive it (no dict-batch contract) and says so."""
        hf = tiny_hf_gpt2()
        eng = deepspeed_tpu.init_inference(
            hf, dtype=jnp.float32, replace_with_kernel_inject=False,
            params=hf.params)
        with pytest.raises(Exception):
            eng.forward({"input_ids": jnp.zeros((1, 8), jnp.int32)})


class TestBertInjection:
    def test_mlm_logits_parity_with_hf(self):
        hf = tiny_hf_bert()
        rng = np.random.default_rng(2)
        ids = jnp.asarray(rng.integers(0, 128, (2, 16), dtype=np.int32))
        am = jnp.ones((2, 16), jnp.int32)
        hf_logits = np.asarray(hf(ids, attention_mask=am).logits)

        eng = deepspeed_tpu.init_inference(hf, dtype=jnp.float32)
        ours = np.asarray(eng.forward(
            {"input_ids": ids, "attention_mask": am})["logits"])
        # HF BERT uses exact (erf) gelu; the in-tree family uses the tanh
        # approximation — O(1e-3) activation differences compound slightly.
        np.testing.assert_allclose(ours, hf_logits, atol=0.05, rtol=0.05)

    def test_explicit_policy_class(self):
        from deepspeed_tpu.module_inject import HFBertPolicy

        hf = tiny_hf_bert()
        eng = deepspeed_tpu.init_inference(hf, dtype=jnp.float32,
                                           injection_policy=HFBertPolicy)
        from deepspeed_tpu.models.bert import BertModel

        assert isinstance(eng.module, BertModel)


class TestGPTNeoInjection:
    def _tiny(self, window=64):
        from transformers import FlaxGPTNeoForCausalLM, GPTNeoConfig

        cfg = GPTNeoConfig(vocab_size=128, max_position_embeddings=64,
                           hidden_size=32, num_layers=2, num_heads=2,
                           attention_types=[[["global", "local"], 1]],
                           window_size=window, resid_dropout=0.0,
                           embed_dropout=0.0, attention_dropout=0.0)
        return FlaxGPTNeoForCausalLM(cfg, seed=0)

    def test_logits_parity_with_hf(self):
        """GPT-Neo converts exactly (unscaled attention, Dense layouts)
        while the sequence fits the local window."""
        hf = self._tiny()
        ids = jnp.asarray(np.random.default_rng(3).integers(
            0, 128, (2, 16), dtype=np.int32))
        hf_logits = np.asarray(hf(ids).logits)
        eng = deepspeed_tpu.init_inference(hf, dtype=jnp.float32)
        assert eng.module.cfg.attention_scale == 1.0
        ours = np.asarray(eng.forward({"input_ids": ids})["logits"])
        np.testing.assert_allclose(ours, hf_logits, atol=2e-4, rtol=2e-4)

    def test_window_clamps_max_seq(self):
        hf = self._tiny(window=32)
        eng = deepspeed_tpu.init_inference(hf, dtype=jnp.float32)
        assert eng.module.cfg.max_seq_len == 32
        out = eng.generate(jnp.zeros((1, 8), jnp.int32), max_new_tokens=4)
        assert out.shape == (1, 12)


class TestMegatronPolicy:
    """Megatron injection + MP-checkpoint import (round-3 VERDICT task 7;
    reference MegatronLayerPolicy replace_policy.py:146 + megatron sd
    loader state_dict_factory.py:199 + revert replace_module.py:310)."""

    def _gpt_and_params(self, seed=0):
        from deepspeed_tpu.models.gpt import make_gpt

        model, cfg = make_gpt("tiny", vocab_size=256, max_seq_len=32,
                              hidden_size=32, num_layers=2, num_heads=4,
                              dropout_rate=0.0, dtype=jnp.float32)
        batch = {"input_ids": np.zeros((2, 16), np.int32)}
        params = model.init({"params": jax.random.PRNGKey(seed),
                             "dropout": jax.random.PRNGKey(1)},
                            batch)["params"]
        return model, cfg, params

    def test_revert_convert_roundtrip_bit_equal(self):
        from deepspeed_tpu.module_inject.megatron import MegatronLayerPolicy

        model, cfg, params = self._gpt_and_params()
        sd = MegatronLayerPolicy.revert(params, cfg.num_heads)
        model2, params2 = MegatronLayerPolicy.convert(
            sd, cfg.num_heads, max_seq_len=cfg.max_seq_len,
            layer_norm_epsilon=cfg.layer_norm_epsilon)
        assert model2.cfg.num_layers == cfg.num_layers
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            params, params2)

    def test_version0_interleaving_roundtrip(self):
        from deepspeed_tpu.module_inject.megatron import (
            MegatronLayerPolicy, convert_megatron_checkpoint)

        model, cfg, params = self._gpt_and_params(1)
        sd_v0 = MegatronLayerPolicy.revert(params, cfg.num_heads, version=0)
        # v0 rows are per-head interleaved -> differs from the v1 layout
        sd_v1 = MegatronLayerPolicy.revert(params, cfg.num_heads, version=1)
        k0 = "layers.0.attention.query_key_value.weight"
        k1 = "layers.0.self_attention.query_key_value.weight"
        assert not np.array_equal(sd_v0[k0], sd_v1[k1])
        _, params2 = convert_megatron_checkpoint(
            sd_v0, cfg.num_heads, max_seq_len=cfg.max_seq_len, version=0)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            params, params2)

    def test_two_way_shards_merge_and_serve_at_mp1_and_mp4(
            self, eight_devices):
        """Synthetic 2-way Megatron checkpoint -> merged params -> logits
        at mp=1 and mp=4 match (the VERDICT's done criterion)."""
        import deepspeed_tpu
        from deepspeed_tpu.module_inject.megatron import (
            MegatronLayerPolicy, convert_megatron_checkpoint,
            split_megatron_state_dict)

        model, cfg, params = self._gpt_and_params(2)
        full_sd = MegatronLayerPolicy.revert(params, cfg.num_heads)
        shards = split_megatron_state_dict(full_sd, 2)
        assert shards[0]["layers.0.self_attention.query_key_value.weight"]\
            .shape[0] == 3 * cfg.hidden_size // 2
        model2, merged = convert_megatron_checkpoint(
            shards, cfg.num_heads, max_seq_len=cfg.max_seq_len,
            dtype=jnp.float32)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            params, merged)

        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (2, 16), dtype=np.int32)
        outs = {}
        for mp in (1, 4):
            eng = deepspeed_tpu.init_inference(
                model2, params=merged, mp_size=mp, dtype=jnp.float32)
            out = eng.module.apply({"params": eng.params},
                                   {"input_ids": ids}, deterministic=True)
            outs[mp] = np.asarray(out["logits"], np.float32)
        np.testing.assert_allclose(outs[1], outs[4], atol=2e-4, rtol=2e-4)
