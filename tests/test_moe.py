"""MoE layer tests: routing correctness, capacity, aux loss, expert
parallelism on the virtual mesh, end-to-end training."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding

import deepspeed_tpu
from deepspeed_tpu.moe import MoE, MoEConfig, moe_partition_rules
from deepspeed_tpu.models.partition import build_specs
from deepspeed_tpu.parallel.mesh import build_mesh


def make_moe(e=4, k=1, d=16, **kw):
    cfg = MoEConfig(hidden_size=d, num_experts=e, k=k, dtype=jnp.float32,
                    **kw)
    layer = MoE(cfg)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 8, d)), jnp.float32)
    params = layer.init({"params": jax.random.PRNGKey(0)}, x)["params"]
    return layer, params, x, cfg


class TestRouting:
    def test_output_shape_and_finite(self):
        layer, params, x, _ = make_moe()
        y, aux = layer.apply({"params": params}, x)
        assert y.shape == x.shape
        assert np.isfinite(np.asarray(y)).all()
        assert float(aux) > 0

    def test_top1_each_token_single_expert(self):
        layer, params, x, cfg = make_moe(e=4, k=1, capacity_factor=4.0)
        # inspect internals: rebuild dispatch from the router output
        from deepspeed_tpu.moe.layer import _topk_dispatch

        logits = x.reshape(-1, cfg.hidden_size).astype(jnp.float32) @ \
            params["router"]["kernel"]
        gates = jax.nn.softmax(logits, axis=-1)
        dispatch, combine, _ = _topk_dispatch(jnp.asarray(gates), 1, 16)
        per_token = np.asarray(dispatch).sum(axis=(1, 2))
        np.testing.assert_array_equal(per_token, np.ones_like(per_token))
        # top-1 combine weight is the RAW gate prob (Switch: y = p*E(x)) —
        # normalizing would zero the router's task-loss gradient
        top_prob = np.max(np.asarray(gates), axis=-1)
        np.testing.assert_allclose(np.asarray(combine).sum(axis=(1, 2)),
                                   top_prob, atol=1e-5)

    def test_router_gets_task_gradient_at_k1(self):
        layer, params, x, _ = make_moe(e=4, k=1, capacity_factor=4.0)

        def task_loss(p):
            y, _aux = layer.apply({"params": p}, x)
            return jnp.mean(y ** 2)

        g = jax.grad(task_loss)(params)["router"]["kernel"]
        assert float(jnp.abs(g).max()) > 1e-6, \
            "router must learn from the task loss, not only aux"

    def test_top2_routes_two_experts(self):
        from deepspeed_tpu.moe.layer import _topk_dispatch

        gates = jax.nn.softmax(jnp.asarray(
            np.random.default_rng(0).standard_normal((16, 4))), axis=-1)
        dispatch, combine, _ = _topk_dispatch(gates, 2, 16)
        per_token = np.asarray(dispatch).sum(axis=(1, 2))
        np.testing.assert_array_equal(per_token, np.full(16, 2.0))
        np.testing.assert_allclose(np.asarray(combine).sum(axis=(1, 2)),
                                   1.0, atol=1e-5)

    def test_capacity_drops_overflow(self):
        from deepspeed_tpu.moe.layer import _topk_dispatch

        # All tokens prefer expert 0; capacity 2 keeps only 2.
        gates = jnp.asarray(np.tile([[0.97, 0.01, 0.01, 0.01]], (8, 1)),
                            jnp.float32)
        dispatch, _, _ = _topk_dispatch(gates, 1, 2)
        assert float(np.asarray(dispatch).sum()) == 2.0

    def test_balanced_aux_loss_is_one(self):
        from deepspeed_tpu.moe.layer import _topk_dispatch

        # Perfectly uniform gates -> aux = E * sum(1/E * 1/E) = 1.
        gates = jnp.full((16, 4), 0.25, jnp.float32)
        _, _, aux = _topk_dispatch(gates, 1, 16)
        assert float(aux) == pytest.approx(1.0, rel=1e-5)


class TestExpertParallel:
    def test_sharded_experts_match_replicated(self, eight_devices):
        layer, params, x, _ = make_moe(e=8, capacity_factor=8.0)
        y_ref, _ = layer.apply({"params": params}, x)

        mesh = build_mesh(expert=4, data=2)
        specs = build_specs(params, moe_partition_rules(),
                            mesh_axes=dict(mesh.shape))
        sharded = jax.tree_util.tree_map(
            lambda p, sp: jax.device_put(p, NamedSharding(mesh, sp)),
            params, specs)
        w = sharded["experts_in"]
        assert w.sharding.shard_shape(w.shape)[0] == 2  # 8 experts / 4
        with mesh:
            y_sh, _ = jax.jit(
                lambda p, x: layer.apply({"params": p}, x))(sharded, x)
        np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_ref),
                                   rtol=1e-4, atol=1e-5)

    def test_moe_model_trains_with_engine(self, eight_devices, rng):
        import flax.linen as nn

        class TinyMoEModel(nn.Module):
            @nn.compact
            def __call__(self, batch, deterministic=False):
                x = batch["x"]
                y, aux = MoE(MoEConfig(hidden_size=16, num_experts=4,
                                       dtype=jnp.float32))(
                    x, deterministic=deterministic)
                loss = jnp.mean((y - batch["t"]) ** 2) + 0.01 * aux
                return {"loss": loss}

        model = TinyMoEModel()
        x = rng.standard_normal((2, 8, 8, 16)).astype(np.float32)
        t = rng.standard_normal((2, 8, 8, 16)).astype(np.float32)
        params = model.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)},
                            {"x": x[0], "t": t[0]})["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params,
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                    "zero_optimization": {"stage": 2}})
        first = float(engine.train_batch({"x": x, "t": t}))
        for _ in range(10):
            last = float(engine.train_batch({"x": x, "t": t}))
        assert last < first


class TestScatterDispatch:
    """Slot-scatter dispatch (round-2 VERDICT weak #4 / task 10b): parity
    with the GShard einsum oracle, and dispatch memory linear in T (no
    [T, E, C] intermediate)."""

    @pytest.mark.parametrize("k", [1, 2])
    def test_parity_with_einsum(self, k):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((2, 16, 32)), jnp.float32)
        outs = {}
        for disp in ("scatter", "einsum"):
            cfg = MoEConfig(hidden_size=32, num_experts=4, k=k,
                            capacity_factor=2.0, dtype=jnp.float32,
                            dispatch=disp)
            layer = MoE(cfg)
            params = layer.init({"params": jax.random.PRNGKey(0)}, x)["params"]
            y, aux = layer.apply({"params": params}, x)
            outs[disp] = (np.asarray(y), float(aux))
        np.testing.assert_allclose(outs["scatter"][0], outs["einsum"][0],
                                   atol=1e-5, rtol=1e-5)
        assert outs["scatter"][1] == outs["einsum"][1]

    def test_grad_parity_with_einsum(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
        grads = {}
        for disp in ("scatter", "einsum"):
            cfg = MoEConfig(hidden_size=16, num_experts=4, k=2,
                            capacity_factor=2.0, dtype=jnp.float32,
                            dispatch=disp)
            layer = MoE(cfg)
            params = layer.init({"params": jax.random.PRNGKey(0)}, x)["params"]

            def loss(p):
                y, aux = layer.apply({"params": p}, x)
                return jnp.mean(y ** 2) + 0.01 * aux

            grads[disp] = jax.grad(loss)(params)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
            grads["scatter"], grads["einsum"])

    def test_no_tec_intermediate(self):
        """The traced scatter path must contain no array of size
        T*E*C (the one-hot product the einsum path materializes)."""
        t, e, d = 64, 8, 16
        # small expert_intermediate so the legitimate [E, C, d_ff] FFN
        # intermediate stays well below T*E*C
        cfg = MoEConfig(hidden_size=d, num_experts=e, k=1,
                        capacity_factor=2.0, dtype=jnp.float32,
                        dispatch="scatter", expert_intermediate=16)
        capacity = max(cfg.min_capacity, int(np.ceil(t / e * 2.0)))
        layer = MoE(cfg)
        x = jnp.zeros((1, t, d))
        params = layer.init({"params": jax.random.PRNGKey(0)}, x)["params"]
        jaxpr = jax.make_jaxpr(
            lambda p: layer.apply({"params": p}, x)[0])(params)

        def all_avals(jx, out):
            for eqn in jx.eqns:
                for v in eqn.outvars:
                    out.append(v.aval)
                for val in eqn.params.values():
                    inner = getattr(val, "jaxpr", None)
                    if inner is None and type(val).__name__ == "Jaxpr":
                        inner = val
                    if inner is not None:
                        all_avals(inner, out)
            return out

        tec = t * e * capacity
        sizes = [int(np.prod(a.shape)) for a in all_avals(jaxpr.jaxpr, [])
                 if hasattr(a, "shape")]
        assert not any(s >= tec for s in sizes), sorted(sizes)[-4:]


class TestMoEGPT:
    """MoE wired into the in-tree GPT family (round-2 VERDICT weak #4:
    'no in-tree model family wires MoE into a full LM')."""

    def _model(self):
        from deepspeed_tpu.models import make_gpt

        return make_gpt("tiny", vocab_size=256, max_seq_len=64,
                        hidden_size=32, num_layers=4, num_heads=2,
                        dropout_rate=0.0, dtype=jnp.float32,
                        moe_experts=4, moe_k=1, moe_layer_freq=2)

    def test_trains_end_to_end_with_expert_parallelism(self, eight_devices):
        from deepspeed_tpu.models import build_specs
        from deepspeed_tpu.models.gpt import gpt_partition_rules

        model, cfg = self._model()
        mesh = build_mesh(data=4, expert=2)
        rng = np.random.default_rng(0)
        batches = {"input_ids": rng.integers(0, 256, (2, 8, 32),
                                             dtype=np.int32)}
        one = {"input_ids": batches["input_ids"][0]}
        params = model.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)}, one)["params"]
        # every 2nd block carries experts
        assert "moe" in params["h_1"] and "moe" in params["h_3"]
        assert "c_fc" in params["h_0"] and "moe" not in params["h_0"]
        specs = build_specs(params, gpt_partition_rules(),
                            mesh_axes=dict(mesh.shape))
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params, mesh=mesh,
            param_partition_specs=specs,
            config={
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1},
            })
        # expert params sharded over the expert axis
        w = engine.state.params["h_1"]["moe"]["experts_in"]
        assert w.sharding.shard_shape(w.shape)[0] == w.shape[0] // 2
        losses = [float(engine.train_batch(batches)) for _ in range(6)]
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]

    def test_generation_with_moe_blocks(self, eight_devices):
        """KV-cache decode runs through MoE blocks (aux discarded)."""
        model, cfg = self._model()
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, 256, (2, 8), dtype=np.int32))
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)},
                               {"input_ids": ids})
        eng = deepspeed_tpu.init_inference(
            model, params=variables["params"], dtype=jnp.float32)
        out = eng.generate(ids, max_new_tokens=4)
        assert out.shape == (2, 12)


class TestAllToAllDispatch:
    """Explicit all-to-all dispatch (moe/dispatch.py): exact parity with
    the einsum oracle on a sharded mesh — keep regime, drop regime and
    gradients — plus the shape walls."""

    def _outs(self, disp, mesh, k=1, capacity_factor=2.0, shape=(2, 16, 32),
              grad=False):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal(shape), jnp.float32)
        cfg = MoEConfig(hidden_size=shape[-1], num_experts=4, k=k,
                        capacity_factor=capacity_factor, dtype=jnp.float32,
                        dispatch=disp,
                        mesh=mesh if disp == "alltoall" else None)
        layer = MoE(cfg)
        params = layer.init({"params": jax.random.PRNGKey(0)}, x)["params"]
        if grad:
            def loss(p):
                y, aux = layer.apply({"params": p}, x)
                return jnp.mean(y ** 2) + 0.01 * aux
            return jax.grad(loss)(params)
        y, aux = jax.jit(
            lambda p: layer.apply({"params": p}, x))(params)
        return np.asarray(y), float(aux)

    @pytest.mark.parametrize("k", [1, 2])
    def test_parity_with_einsum(self, eight_devices, k):
        mesh = build_mesh(data=2, expert=4)
        y_ref, aux_ref = self._outs("einsum", mesh, k=k)
        y_a2a, aux_a2a = self._outs("alltoall", mesh, k=k)
        np.testing.assert_allclose(y_a2a, y_ref, atol=1e-5, rtol=1e-5)
        # routing (and thus aux) is shared math, but jit fuses the two
        # programs differently — allow fp roundoff on the scalar
        np.testing.assert_allclose(aux_a2a, aux_ref, rtol=1e-6)

    def test_parity_in_drop_regime(self, eight_devices):
        """capacity_factor=1.0 forces real drops — the explicit path
        must drop EXACTLY the oracle's tokens (global queue positions)."""
        mesh = build_mesh(data=2, expert=4)
        y_ref, _ = self._outs("einsum", mesh, capacity_factor=1.0,
                              shape=(4, 16, 32))
        y_a2a, _ = self._outs("alltoall", mesh, capacity_factor=1.0,
                              shape=(4, 16, 32))
        np.testing.assert_allclose(y_a2a, y_ref, atol=1e-5, rtol=1e-5)

    def test_grad_parity_with_einsum(self, eight_devices):
        mesh = build_mesh(data=2, expert=4)
        g_ref = self._outs("einsum", mesh, k=2, grad=True)
        g_a2a = self._outs("alltoall", mesh, k=2, grad=True)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
            g_a2a, g_ref)

    def test_expert_divisibility_wall(self, eight_devices):
        from deepspeed_tpu.moe.dispatch import alltoall_dispatch
        mesh = build_mesh(data=2, expert=4)
        with pytest.raises(ValueError, match="must divide"):
            alltoall_dispatch(
                jnp.zeros((16, 8)), [], jnp.zeros((6, 8, 16)),
                jnp.zeros((6, 16, 8)), capacity=4, dtype=jnp.float32,
                mesh=mesh)

    def test_token_divisibility_wall(self, eight_devices):
        from deepspeed_tpu.moe.dispatch import alltoall_dispatch
        mesh = build_mesh(data=2, expert=4)
        with pytest.raises(ValueError, match="dispatch grid"):
            alltoall_dispatch(
                jnp.zeros((12, 8)), [], jnp.zeros((4, 8, 16)),
                jnp.zeros((4, 16, 8)), capacity=4, dtype=jnp.float32,
                mesh=mesh)

    def test_modeled_bytes(self, eight_devices):
        from deepspeed_tpu.moe.dispatch import modeled_dispatch_bytes_ici
        mesh = build_mesh(data=2, expert=4)
        got = modeled_dispatch_bytes_ici(num_experts=8, capacity=16,
                                         hidden=32, dtype=jnp.float32,
                                         mesh=mesh)
        ec = 8 * 16
        per_cell = (2 * ec * 32 + ec) * 4 * 3 / 4
        assert got == int(8 * per_cell)
        # unsharded expert axis => the exchange is local, nothing modeled
        assert modeled_dispatch_bytes_ici(
            num_experts=8, capacity=16, hidden=32, dtype=jnp.float32,
            mesh=build_mesh(data=8)) == 0


class TestEvalCapacityAndJitter:
    """Config knobs that change routing between train and eval
    (MoEConfig.eval_capacity_factor, router_jitter)."""

    def test_eval_capacity_factor_applies_on_eval_path(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((2, 32, 16)), jnp.float32)
        cfg = MoEConfig(hidden_size=16, num_experts=4, k=1,
                        capacity_factor=0.25, eval_capacity_factor=4.0,
                        min_capacity=1, dtype=jnp.float32, stats=True)
        layer = MoE(cfg)
        params = layer.init({"params": jax.random.PRNGKey(0)}, x)["params"]
        _, _, train_stats = layer.apply({"params": params}, x,
                                        deterministic=False,
                                        rngs={"dropout": jax.random.PRNGKey(2)})
        _, _, eval_stats = layer.apply({"params": params}, x,
                                       deterministic=True)
        assert float(train_stats["capacity_overflow_frac"]) > 0.5
        assert float(eval_stats["capacity_overflow_frac"]) == 0.0

    def test_router_jitter_train_only(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((2, 16, 16)), jnp.float32)
        cfg = MoEConfig(hidden_size=16, num_experts=4, k=1,
                        capacity_factor=2.0, router_jitter=0.5,
                        dtype=jnp.float32)
        layer = MoE(cfg)
        params = layer.init({"params": jax.random.PRNGKey(0)}, x)["params"]
        # train: jitter perturbs routing, different rngs => different y
        y1, _ = layer.apply({"params": params}, x, deterministic=False,
                            rngs={"dropout": jax.random.PRNGKey(1)})
        y2, _ = layer.apply({"params": params}, x, deterministic=False,
                            rngs={"dropout": jax.random.PRNGKey(7)})
        assert float(jnp.abs(y1 - y2).max()) > 0
        # eval: jitter OFF — deterministic, and identical to a
        # jitter-free config's eval output
        e1 = layer.apply({"params": params}, x, deterministic=True)[0]
        quiet = MoE(MoEConfig(hidden_size=16, num_experts=4, k=1,
                              capacity_factor=2.0, router_jitter=0.0,
                              dtype=jnp.float32))
        e2 = quiet.apply({"params": params}, x, deterministic=True)[0]
        np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))


def _moe_gpt_engine(mesh, config, moe_overrides=None, seq=16):
    """MoE GPT engine through the config `moe` block: params are built
    from a model already carrying the shape-affecting moe fields, the
    `moe` surgery injects capacity/dispatch/mesh/stats."""
    from deepspeed_tpu.models import build_specs, make_gpt
    from deepspeed_tpu.models.gpt import gpt_partition_rules

    kw = dict(vocab_size=256, max_seq_len=seq, hidden_size=32,
              num_layers=2, num_heads=4, dropout_rate=0.0,
              dtype=jnp.float32, moe_experts=4, moe_k=1,
              moe_layer_freq=2)
    kw.update(moe_overrides or {})
    model, cfg = make_gpt("tiny", **kw)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (8, seq), dtype=np.int32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": ids})["params"]
    specs = build_specs(params, gpt_partition_rules(),
                        mesh_axes=dict(mesh.shape))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=params, mesh=mesh,
        param_partition_specs=specs, config=config)
    batches = {"input_ids": rng.integers(0, 256, (1, 8, seq),
                                         dtype=np.int32)}
    return engine, batches


class TestExpertZeroCompose:
    """Expert axis >= 2 composed with every ZeRO stage, through the
    config `moe` block (docs/MOE.md 'Composition')."""

    @pytest.mark.parametrize("stage", [0, 1, 2, 3])
    def test_trains_each_stage(self, eight_devices, stage):
        engine, batches = _moe_gpt_engine(
            build_mesh(data=4, expert=2),
            {"train_micro_batch_size_per_gpu": 2,
             "gradient_accumulation_steps": 1,
             "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
             "zero_optimization": {"stage": stage},
             "moe": {"enabled": True, "num_experts": 4, "k": 1,
                     "dispatch": "alltoall"}})
        w = engine.state.params["h_1"]["moe"]["experts_in"]
        assert w.sharding.shard_shape(w.shape)[0] == 2  # 4 experts / 2
        losses = [float(engine.train_batch(batches)) for _ in range(3)]
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]

    def test_eight_experts_alltoall_zero2(self, eight_devices):
        """The ISSUE 16 acceptance rung verbatim: an 8-expert MoE GPT
        on the 8-device mesh, expert axis >= 2, ZeRO-2, all-to-all
        dispatch — trains with finite decreasing loss."""
        engine, batches = _moe_gpt_engine(
            build_mesh(data=2, expert=4),
            {"train_micro_batch_size_per_gpu": 4,
             "gradient_accumulation_steps": 1,
             "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
             "zero_optimization": {"stage": 2},
             "moe": {"enabled": True, "num_experts": 8, "k": 1,
                     "dispatch": "alltoall"}},
            moe_overrides={"moe_experts": 8})
        w = engine.state.params["h_1"]["moe"]["experts_in"]
        assert w.sharding.shard_shape(w.shape)[0] == 2  # 8 experts / 4
        losses = [float(engine.train_batch(batches)) for _ in range(3)]
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]

    def test_expert_params_never_cross_dcn(self, eight_devices):
        """hpZ-style placement: on a 2-slice mesh, expert params stay
        intra-slice — no spec may name the dcn axis, so GSPMD has no
        license to move them over the cross-slice link."""
        engine, batches = _moe_gpt_engine(
            build_mesh(slices=2, data=-1, expert=2),
            {"train_micro_batch_size_per_gpu": 4,
             "gradient_accumulation_steps": 1,
             "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
             "zero_optimization": {"stage": 3},
             "moe": {"enabled": True, "num_experts": 4, "k": 1,
                     "dispatch": "scatter"}})
        for blk in ("h_1",):
            for leaf in ("experts_in", "experts_out"):
                spec = engine.state.params[blk]["moe"][leaf].sharding.spec
                flat = [a for part in spec if part is not None
                        for a in ((part,) if isinstance(part, str)
                                  else tuple(part))]
                assert "dcn" not in flat, (leaf, spec)
                assert "expert" in flat, (leaf, spec)
        loss = float(engine.train_batch(batches))
        assert np.isfinite(loss)


class TestMoEObservability:
    """moe/* gauge family + per-expert numerics groups, emitted by a
    real engine run (telemetry/moe.py, telemetry/numerics.py)."""

    def test_gauges_and_expert_groups_emit(self, eight_devices, tmp_path):
        from deepspeed_tpu.telemetry.moe import MOE_METRIC_TAGS
        from deepspeed_tpu.telemetry.registry import InMemorySink

        engine, batches = _moe_gpt_engine(
            build_mesh(data=4, expert=2),
            {"train_micro_batch_size_per_gpu": 2,
             "gradient_accumulation_steps": 1,
             "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
             "zero_optimization": {"stage": 1},
             "moe": {"enabled": True, "num_experts": 4, "k": 1,
                     "dispatch": "alltoall"},
             "telemetry": {"enabled": True, "dir": str(tmp_path),
                           "numerics": {"enabled": True}},
             "steps_per_print": 1})
        sink = engine.telemetry.registry.add_sink(InMemorySink())
        for _ in range(2):
            engine.train_batch(batches)
        tags = {r["tag"] for r in sink.rows}
        assert MOE_METRIC_TAGS <= tags, MOE_METRIC_TAGS - tags
        # every gauge value is finite and overflow is a fraction
        for r in sink.rows:
            if r["tag"] in MOE_METRIC_TAGS:
                assert np.isfinite(r["value"])
            if r["tag"] == "moe/capacity_overflow_frac":
                assert 0.0 <= r["value"] <= 1.0
        groups = {r.get("group") for r in sink.rows if r.get("group")}
        for i in range(4):
            assert f"moe_expert_{i}" in groups, groups

    def test_monitor_gated_on_config(self):
        from deepspeed_tpu.config.config import DeepSpeedTPUConfig
        from deepspeed_tpu.telemetry.moe import build_moe_monitor

        base = {"train_batch_size": 8, "mesh": {"expert": 2}}
        on = DeepSpeedTPUConfig(
            {**base, "moe": {"enabled": True, "num_experts": 4},
             "telemetry": {"enabled": True}}, world_size=8)
        assert build_moe_monitor(on) is not None
        no_moe = DeepSpeedTPUConfig(
            {**base, "telemetry": {"enabled": True}}, world_size=8)
        assert build_moe_monitor(no_moe) is None
        no_tel = DeepSpeedTPUConfig(
            {**base, "moe": {"enabled": True, "num_experts": 4}},
            world_size=8)
        assert build_moe_monitor(no_tel) is None


class TestMoEOffContract:
    """Zero-overhead-off: no `moe` config block => the lowered train
    step is bit-identical to an explicit `enabled: false` block, and the
    engine carries no monitor."""

    def _lowered(self, eight_devices_mesh_unused, extra):
        from deepspeed_tpu.models import make_gpt

        model, _ = make_gpt("tiny", vocab_size=256, max_seq_len=16,
                            hidden_size=32, num_layers=2, num_heads=4,
                            dropout_rate=0.0, dtype=jnp.float32)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 256, (8, 16), dtype=np.int32)
        params = model.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)},
                            {"input_ids": ids})["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params,
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 1,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 1}, **extra})
        batches = {"input_ids": ids[None, ...]}
        text = engine._train_step.lower(
            engine.state, batches, jnp.float32(1e-3)).as_text()
        return engine, text

    def test_absent_equals_disabled_bit_identical(self, eight_devices):
        eng_a, absent = self._lowered(eight_devices, {})
        eng_d, disabled = self._lowered(
            eight_devices, {"moe": {"enabled": False}})
        assert absent == disabled
        assert eng_a.moe_monitor is None and eng_d.moe_monitor is None

    def test_enabled_moe_changes_the_step(self, eight_devices, tmp_path):
        """The gauge plumbing is config-gated: the same MoE model lowers
        a different step once the `moe` block + telemetry are on (the
        moe aux rides the scan carry)."""
        mesh = build_mesh(data=4, expert=2)
        base = {"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1}}
        texts = {}
        for name, extra in (
                ("off", {}),
                ("on", {"moe": {"enabled": True, "num_experts": 4, "k": 1,
                                "dispatch": "scatter"},
                        "telemetry": {"enabled": True,
                                      "dir": str(tmp_path)}})):
            engine, batches = _moe_gpt_engine(mesh, {**base, **extra})
            texts[name] = engine._train_step.lower(
                engine.state, batches, jnp.float32(1e-3)).as_text()
        assert texts["off"] != texts["on"]


class TestProbeMoECLI:
    @pytest.mark.parametrize("probe", ["probe_moe.py"])
    def test_selftest_passes(self, probe):
        import subprocess
        import sys as _sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [_sys.executable, os.path.join(repo, "tools", probe),
             "--selftest"],
            capture_output=True, text=True, env=env, timeout=540)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert '"pass": true' in proc.stdout
