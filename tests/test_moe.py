"""MoE layer tests: routing correctness, capacity, aux loss, expert
parallelism on the virtual mesh, end-to-end training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding

import deepspeed_tpu
from deepspeed_tpu.moe import MoE, MoEConfig, moe_partition_rules
from deepspeed_tpu.models.partition import build_specs
from deepspeed_tpu.parallel.mesh import build_mesh


def make_moe(e=4, k=1, d=16, **kw):
    cfg = MoEConfig(hidden_size=d, num_experts=e, k=k, dtype=jnp.float32,
                    **kw)
    layer = MoE(cfg)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 8, d)), jnp.float32)
    params = layer.init({"params": jax.random.PRNGKey(0)}, x)["params"]
    return layer, params, x, cfg


class TestRouting:
    def test_output_shape_and_finite(self):
        layer, params, x, _ = make_moe()
        y, aux = layer.apply({"params": params}, x)
        assert y.shape == x.shape
        assert np.isfinite(np.asarray(y)).all()
        assert float(aux) > 0

    def test_top1_each_token_single_expert(self):
        layer, params, x, cfg = make_moe(e=4, k=1, capacity_factor=4.0)
        # inspect internals: rebuild dispatch from the router output
        from deepspeed_tpu.moe.layer import _topk_dispatch

        logits = x.reshape(-1, cfg.hidden_size).astype(jnp.float32) @ \
            params["router"]["kernel"]
        gates = jax.nn.softmax(logits, axis=-1)
        dispatch, combine, _ = _topk_dispatch(jnp.asarray(gates), 1, 16)
        per_token = np.asarray(dispatch).sum(axis=(1, 2))
        np.testing.assert_array_equal(per_token, np.ones_like(per_token))
        # top-1 combine weight is the RAW gate prob (Switch: y = p*E(x)) —
        # normalizing would zero the router's task-loss gradient
        top_prob = np.max(np.asarray(gates), axis=-1)
        np.testing.assert_allclose(np.asarray(combine).sum(axis=(1, 2)),
                                   top_prob, atol=1e-5)

    def test_router_gets_task_gradient_at_k1(self):
        layer, params, x, _ = make_moe(e=4, k=1, capacity_factor=4.0)

        def task_loss(p):
            y, _aux = layer.apply({"params": p}, x)
            return jnp.mean(y ** 2)

        g = jax.grad(task_loss)(params)["router"]["kernel"]
        assert float(jnp.abs(g).max()) > 1e-6, \
            "router must learn from the task loss, not only aux"

    def test_top2_routes_two_experts(self):
        from deepspeed_tpu.moe.layer import _topk_dispatch

        gates = jax.nn.softmax(jnp.asarray(
            np.random.default_rng(0).standard_normal((16, 4))), axis=-1)
        dispatch, combine, _ = _topk_dispatch(gates, 2, 16)
        per_token = np.asarray(dispatch).sum(axis=(1, 2))
        np.testing.assert_array_equal(per_token, np.full(16, 2.0))
        np.testing.assert_allclose(np.asarray(combine).sum(axis=(1, 2)),
                                   1.0, atol=1e-5)

    def test_capacity_drops_overflow(self):
        from deepspeed_tpu.moe.layer import _topk_dispatch

        # All tokens prefer expert 0; capacity 2 keeps only 2.
        gates = jnp.asarray(np.tile([[0.97, 0.01, 0.01, 0.01]], (8, 1)),
                            jnp.float32)
        dispatch, _, _ = _topk_dispatch(gates, 1, 2)
        assert float(np.asarray(dispatch).sum()) == 2.0

    def test_balanced_aux_loss_is_one(self):
        from deepspeed_tpu.moe.layer import _topk_dispatch

        # Perfectly uniform gates -> aux = E * sum(1/E * 1/E) = 1.
        gates = jnp.full((16, 4), 0.25, jnp.float32)
        _, _, aux = _topk_dispatch(gates, 1, 16)
        assert float(aux) == pytest.approx(1.0, rel=1e-5)


class TestExpertParallel:
    def test_sharded_experts_match_replicated(self, eight_devices):
        layer, params, x, _ = make_moe(e=8, capacity_factor=8.0)
        y_ref, _ = layer.apply({"params": params}, x)

        mesh = build_mesh(expert=4, data=2)
        specs = build_specs(params, moe_partition_rules(),
                            mesh_axes=dict(mesh.shape))
        sharded = jax.tree_util.tree_map(
            lambda p, sp: jax.device_put(p, NamedSharding(mesh, sp)),
            params, specs)
        w = sharded["experts_in"]
        assert w.sharding.shard_shape(w.shape)[0] == 2  # 8 experts / 4
        with mesh:
            y_sh, _ = jax.jit(
                lambda p, x: layer.apply({"params": p}, x))(sharded, x)
        np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_ref),
                                   rtol=1e-4, atol=1e-5)

    def test_moe_model_trains_with_engine(self, eight_devices, rng):
        import flax.linen as nn

        class TinyMoEModel(nn.Module):
            @nn.compact
            def __call__(self, batch, deterministic=False):
                x = batch["x"]
                y, aux = MoE(MoEConfig(hidden_size=16, num_experts=4,
                                       dtype=jnp.float32))(
                    x, deterministic=deterministic)
                loss = jnp.mean((y - batch["t"]) ** 2) + 0.01 * aux
                return {"loss": loss}

        model = TinyMoEModel()
        x = rng.standard_normal((2, 8, 8, 16)).astype(np.float32)
        t = rng.standard_normal((2, 8, 8, 16)).astype(np.float32)
        params = model.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)},
                            {"x": x[0], "t": t[0]})["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params,
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                    "zero_optimization": {"stage": 2}})
        first = float(engine.train_batch({"x": x, "t": t}))
        for _ in range(10):
            last = float(engine.train_batch({"x": x, "t": t}))
        assert last < first
