"""Ragged chunked-prefill tests — the mixed decode+prefill kernel and
its admission mode (docs/SERVING.md "Chunked prefill admission").

The acceptance gates:

- the Pallas kernel (interpret path) is **parity-exact within fp32
  rounding** against a per-token gather+mask reference — mixed ragged
  batches, chunk boundaries mid-block, all-decode and all-prefill
  degenerate batches, scrambled block tables, pad rows on the scratch
  table row — and within RTNE tolerance for int8 pools (dequantized
  in-kernel with the whole-heads scale-block layout);
- chunked admission is **token-identical** to the bucketed oracle on a
  mixed continuous-batching trace, composing with int8 KV, the prefix
  cache, speculative decoding and resilience fault replay;
- the mixed program compiles exactly ONCE (recompile-detector-proven)
  while the bucketed engine builds O(buckets) prefill programs;
- chunked off ⇒ zero overhead: the engine builds no mixed state, emits
  no chunked tags, and config validation rejects the combinations the
  token-identity contract cannot honor.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import ConfigError, ServingConfig
from deepspeed_tpu.models import make_gpt
from deepspeed_tpu.ops.transformer.chunked_prefill import (
    chunked_prefill_attention, chunked_prefill_ok)
from deepspeed_tpu.serving import ServeEngine
from deepspeed_tpu.serving.kv_cache import _quant_tokens
from deepspeed_tpu.telemetry import (InMemorySink, MetricsRegistry,
                                     RecompileDetector, StepTracer,
                                     Telemetry)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# kernel parity (interpret mode)
# ---------------------------------------------------------------------------

def _reference(q, k_pool, v_pool, table, pos, block_size, scale):
    """Per-token gather + causal-mask attention over the paged pools."""
    t, h, d = q.shape
    wb = table.shape[1]
    out = np.zeros((t, h, d), np.float32)
    kp = np.asarray(k_pool, np.float32)
    vp = np.asarray(v_pool, np.float32)
    for i in range(t):
        ks = kp[table[i]].reshape(wb * block_size, h, d)
        vs = vp[table[i]].reshape(wb * block_size, h, d)
        kpos = np.arange(wb * block_size)
        mask = kpos <= pos[i]
        for hh in range(h):
            s = (q[i, hh].astype(np.float32) @ ks[:, hh].T) * scale
            s = np.where(mask, s, -1e30)
            p = np.exp(s - s.max())
            p /= p.sum()
            out[i, hh] = p @ vs[:, hh]
    return out


def _pools(rng, nblocks, block_size, h, d, dtype=np.float32):
    k = rng.standard_normal((nblocks, block_size, h, d)).astype(dtype)
    v = rng.standard_normal((nblocks, block_size, h, d)).astype(dtype)
    return k, v


class TestChunkedPrefillKernel:
    @pytest.mark.parametrize("pos", [
        # mixed: decode rows (deep pos) + prefill chunk rows (ragged)
        [11, 3, 0, 1, 2, 5, 6, 7],
        # chunk boundary mid-block (block_size 4: positions 5..8 span it)
        [5, 6, 7, 8, 9, 10, 11, 12],
        # all-decode
        [9, 14, 3, 7, 12, 5, 8, 10],
        # all-prefill from zero
        [0, 1, 2, 3, 4, 5, 6, 7],
    ])
    def test_parity_fp(self, rng, pos):
        bs, h, d, wb = 4, 2, 128, 4
        t = len(pos)
        k, v = _pools(rng, 16, bs, h, d)
        q = rng.standard_normal((t, h, d)).astype(np.float32)
        # scrambled, per-row-distinct tables
        table = np.stack([rng.permutation(np.arange(1, 16))[:wb]
                          for _ in range(t)]).astype(np.int32)
        pos = np.asarray(pos, np.int32)
        got = chunked_prefill_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), None, None,
            jnp.asarray(table), jnp.asarray(pos), block_size=bs)
        ref = _reference(q, k, v, table, pos, bs, d ** -0.5)
        np.testing.assert_allclose(np.asarray(got), ref, atol=2e-5)

    def test_parity_int8(self, rng):
        bs, h, d, wb, t = 4, 2, 128, 4, 6
        kf, vf = _pools(rng, 16, bs, h, d)
        kq, ks = _quant_tokens(jnp.asarray(kf))
        vq, vs = _quant_tokens(jnp.asarray(vf))
        q = rng.standard_normal((t, h, d)).astype(np.float32)
        table = np.stack([rng.permutation(np.arange(1, 16))[:wb]
                          for _ in range(t)]).astype(np.int32)
        pos = np.asarray([0, 5, 9, 2, 13, 7], np.int32)
        got = chunked_prefill_attention(
            jnp.asarray(q), kq, vq, ks, vs,
            jnp.asarray(table), jnp.asarray(pos), block_size=bs)
        # int8 reference: dequantize the pools, then exact attention
        kd = np.asarray(kq, np.float32) * np.asarray(ks)[:, :, :, None]
        vd = np.asarray(vq, np.float32) * np.asarray(vs)[:, :, :, None]
        ref = _reference(q, kd, vd, table, pos, bs, d ** -0.5)
        np.testing.assert_allclose(np.asarray(got), ref, atol=2e-5)

    def test_pad_rows_attend_scratch_only(self, rng):
        """A pad row (all-zeros table, pos 0) sees exactly pool block 0
        position 0 — well-defined output, no NaN."""
        bs, h, d = 4, 2, 128
        k, v = _pools(rng, 8, bs, h, d)
        q = rng.standard_normal((2, h, d)).astype(np.float32)
        table = np.zeros((2, 2), np.int32)
        pos = np.zeros((2,), np.int32)
        got = np.asarray(chunked_prefill_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), None, None,
            jnp.asarray(table), jnp.asarray(pos), block_size=bs))
        assert np.isfinite(got).all()
        np.testing.assert_allclose(got[0, 0], k[0, 0, 0] * 0 + v[0, 0, 0],
                                   atol=2e-5)

    def test_geometry_gate(self):
        assert chunked_prefill_ok(128, 8)
        assert not chunked_prefill_ok(64, 8)     # lane-tiling miss
        assert not chunked_prefill_ok(128, 6)    # sublane-tiling miss


# ---------------------------------------------------------------------------
# engine-level: token identity, one compile, composition
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def gpt_setup():
    model, cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=64,
                          dtype=jnp.float32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    return model, cfg, params


def _serve(model, params, telemetry=None, fault=None, **overrides):
    scfg = ServingConfig(**{
        "max_batch_size": 2, "kv_block_size": 4, "kv_num_blocks": 64,
        "max_model_len": 48, **overrides})
    eng = deepspeed_tpu.init_inference(model, params=params,
                                       dtype=jnp.float32)
    kw = {}
    if fault is not None:
        from deepspeed_tpu.resilience import FaultPlan
        kw["fault_plan"] = FaultPlan.resolve(fault)
    return ServeEngine(eng, config=scfg, telemetry=telemetry, **kw)


def _mem_telemetry():
    reg = MetricsRegistry()
    sink = reg.add_sink(InMemorySink())
    tracer = StepTracer(path=None, enabled=False)
    return Telemetry(reg, tracer, RecompileDetector(enabled=False)), sink


TRACE = [(5, 12), (9, 3), (3, 10), (12, 4), (7, 8)]


def _run_trace(srv, cfg, seed=7):
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, cfg.vocab_size, (t,)).tolist()
               for t, _ in TRACE]
    rids = [srv.submit(p, n) for p, (_, n) in zip(prompts, TRACE)]
    res = srv.run_until_complete(timeout_sec=120.0)
    return prompts, [res[r]["tokens"] for r in rids]


class TestChunkedAdmission:
    @pytest.fixture(scope="class")
    def oracle(self, gpt_setup):
        model, cfg, params = gpt_setup
        _, toks = _run_trace(_serve(model, params), cfg)
        return toks

    @pytest.mark.parametrize("overrides", [
        {},                                      # plain
        {"chunked_token_budget": 2},             # minimum legal budget
        {"int8_kv_cache": True},
        {"prefix_cache": True},
        {"spec_decode": True, "spec_k": 2},
    ], ids=["plain", "tiny-budget", "int8", "prefix", "spec"])
    def test_token_identity(self, gpt_setup, oracle, overrides):
        model, cfg, params = gpt_setup
        base = oracle
        if overrides.get("int8_kv_cache"):
            # int8 quantization error shifts both paths the same way —
            # compare against an int8 bucketed oracle, not the fp one.
            _, base = _run_trace(_serve(model, params, int8_kv_cache=True),
                                 cfg)
        srv = _serve(model, params, chunked_prefill=True,
                     **{"chunked_token_budget": 16, **overrides})
        _, got = _run_trace(srv, cfg)
        assert got == base

    def test_one_compile_and_no_bucketed_programs(self, gpt_setup):
        model, cfg, params = gpt_setup
        srv = _serve(model, params, chunked_prefill=True,
                     chunked_token_budget=16)
        _run_trace(srv, cfg)
        det = srv.engine.recompile_detector
        assert det.compiles("serving.mixed_step") == 1
        assert det.retraces("serving.mixed_step") == 0
        assert len(srv._prefill_jit) == 0
        assert len(srv._tail_prefill_jit) == 0
        assert len(srv._decode_jits) == 0
        # vs the bucketed engine, which pays per-bucket programs
        bsrv = _serve(model, params)
        _run_trace(bsrv, cfg)
        assert len(bsrv._prefill_jit) + len(bsrv._tail_prefill_jit) >= 2

    def test_resilience_replay_token_identity(self, gpt_setup, oracle):
        """A persistent decode fault under chunked admission heals via
        rebuild + replay through the SAME mixed program and finishes
        token-identical to the fault-free bucketed run."""
        model, cfg, params = gpt_setup
        srv = _serve(model, params, chunked_prefill=True,
                     chunked_token_budget=16, resilience=True,
                     resil_retry_base_sec=0.01,
                     fault={"serve_decode_fault_at_step": 3,
                            "serve_decode_fault_count": 3})
        _, got = _run_trace(srv, cfg)
        assert got == oracle
        assert srv._resil.counters["recoveries"] >= 1

    def test_chunked_metrics_emitted(self, gpt_setup):
        model, cfg, params = gpt_setup
        tel, sink = _mem_telemetry()
        srv = _serve(model, params, telemetry=tel, chunked_prefill=True,
                     chunked_token_budget=16)
        _run_trace(srv, cfg)
        srv.telemetry.flush()
        tags = sink.tags()
        assert "serving/chunked_tokens_per_step" in tags
        assert "serving/prefill_chunks_in_flight" in tags


class TestChunkedOffContract:
    def test_off_engine_builds_no_mixed_state(self, gpt_setup):
        model, cfg, params = gpt_setup
        tel, sink = _mem_telemetry()
        srv = _serve(model, params, telemetry=tel)
        _run_trace(srv, cfg)
        assert srv._chunked is False and srv._mixed_jit is None
        srv.telemetry.flush()
        assert not (sink.tags() & {"serving/chunked_tokens_per_step",
                                   "serving/prefill_chunks_in_flight"})

    def test_config_validation(self):
        with pytest.raises(ConfigError, match="token_budget"):
            ServingConfig.from_dict({
                "max_batch_size": 8,
                "chunked_prefill": {"enabled": True, "token_budget": 4}})
        with pytest.raises(ConfigError, match="temperature"):
            ServingConfig.from_dict({
                "temperature": 0.7,
                "chunked_prefill": {"enabled": True}})
        with pytest.raises(ConfigError, match="unknown"):
            ServingConfig.from_dict({
                "chunked_prefill": {"enabled": True, "bogus": 1}})
        # present block defaults to enabled (the PR 15 convention)
        cfg = ServingConfig.from_dict({"chunked_prefill": {}})
        assert cfg.chunked_prefill is True
        assert ServingConfig.from_dict({}).chunked_prefill is False


class TestProbeChunkedPrefillCLI:
    def test_selftest_passes(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "probe_chunked_prefill.py"),
             "--selftest"],
            capture_output=True, text=True, env=env, timeout=540)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "selftest ok" in proc.stdout
