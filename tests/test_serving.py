"""Serving engine tests — paged KV cache, continuous batching, SLO telemetry.

The acceptance gates of the serving tier (docs/SERVING.md):

- the paged cache is **block-table-exact** against a contiguous cache and
  the int8 pools round-trip within RTNE tolerance;
- an e2e mixed trace completes with outputs **token-identical** to
  one-shot ``generate()``, finished slots are backfilled mid-run, and the
  measured ``serving/batch_occupancy`` beats static batching on the same
  trace;
- preemption under KV pressure evicts the youngest sequence and the
  request still completes correctly;
- steady state compiles the decode program exactly once;
- serving telemetry honors the zero-overhead-when-disabled contract
  (same device-sync count off vs on-but-disabled, like
  telemetry/guardrails/goodput).
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import ConfigError, ServingConfig
from deepspeed_tpu.models import make_gpt
from deepspeed_tpu.models.gpt import init_kv_cache
from deepspeed_tpu.serving import (BlockPool, PagedLayerCache, ServeEngine,
                                   init_paged_pools, pack_prefill)
from deepspeed_tpu.telemetry import (InMemorySink, MetricsRegistry,
                                     RecompileDetector, StepTracer,
                                     Telemetry)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def gpt_setup():
    # fp32 like test_inference.py: the parity oracle is one-shot
    # generate(), and bf16 argmax tie-flips between the (numerically
    # different but equally valid) paged and contiguous paths are noise.
    model, cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=64,
                          dtype=jnp.float32)
    rng = np.random.default_rng(0)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    return model, cfg, params


def _serve(model, params, telemetry=None, **overrides):
    scfg = ServingConfig(**{
        "max_batch_size": 2, "kv_block_size": 4, "kv_num_blocks": 64,
        "max_model_len": 48, **overrides})
    eng = deepspeed_tpu.init_inference(model, params=params,
                                       dtype=jnp.float32)
    return ServeEngine(eng, config=scfg, telemetry=telemetry)


def _mem_telemetry(trace_path=None, sync_spans=False):
    reg = MetricsRegistry()
    sink = reg.add_sink(InMemorySink())
    tracer = StepTracer(path=trace_path, enabled=trace_path is not None,
                        sync_spans=sync_spans)
    return Telemetry(reg, tracer, RecompileDetector(enabled=False)), sink


# ---------------------------------------------------------------------------
# Block pool
# ---------------------------------------------------------------------------

class TestBlockPool:
    def test_alloc_release_roundtrip(self):
        pool = BlockPool(8)
        assert pool.capacity == 7
        a = pool.alloc(3)
        b = pool.alloc(4)
        assert len(a) == 3 and len(b) == 4
        assert BlockPool.SCRATCH not in a + b        # block 0 never granted
        assert pool.alloc(1) is None                  # exhausted, no partial
        pool.release(a)
        assert pool.free_blocks == 3
        assert pool.used_blocks == 4

    def test_double_free_and_scratch_guard(self):
        pool = BlockPool(4)
        a = pool.alloc(2)
        pool.release(a)
        with pytest.raises(ValueError, match="double free"):
            pool.release([a[0]])
        with pytest.raises(ValueError, match="scratch"):
            pool.release([0])

    def test_too_small(self):
        with pytest.raises(ValueError, match=">= 2"):
            BlockPool(1)


# ---------------------------------------------------------------------------
# Paged cache numerics
# ---------------------------------------------------------------------------

class TestPagedCache:
    def _packed(self, cfg, params, model, ids, int8, bs=4, nb=16):
        """Prefill ``ids`` [1, T] through the contiguous cache and pack
        into pool blocks [3, 7, ...]; returns (pools, k_stack, blocks)."""
        t = ids.shape[1]
        cache = init_kv_cache(cfg, 1, t, dtype=jnp.float32)
        out = model.apply({"params": params}, {"input_ids": ids},
                          deterministic=True, cache=cache, pos=0)
        k_stack = jnp.stack([c[0][0] for c in out["cache"]])
        v_stack = jnp.stack([c[1][0] for c in out["cache"]])
        pools = init_paged_pools(cfg, nb, bs, int8=int8, dtype=jnp.float32)
        blocks = jnp.asarray([3, 7], jnp.int32)       # non-contiguous
        pools = pack_prefill(pools, blocks, k_stack, v_stack)
        return pools, k_stack, v_stack, blocks

    def test_block_table_exact_vs_contiguous(self, gpt_setup):
        """The acceptance gate: gather through a (deliberately scrambled)
        block table reconstructs the contiguous cache EXACTLY."""
        model, cfg, params = gpt_setup
        rng = np.random.default_rng(1)
        ids = rng.integers(0, cfg.vocab_size, (1, 8), dtype=np.int32)
        pools, k_stack, v_stack, _ = self._packed(cfg, params, model, ids,
                                                  int8=False)
        bt = np.zeros((1, 8), np.int32)
        bt[0, :2] = [3, 7]
        lc = PagedLayerCache(*pools[0], jnp.asarray(bt),
                             jnp.asarray([8], jnp.int32), 4, "float32")
        got_k = np.asarray(lc._gather(lc.k, lc.k_scale))[0, :8]
        got_v = np.asarray(lc._gather(lc.v, lc.v_scale))[0, :8]
        np.testing.assert_array_equal(got_k, np.asarray(k_stack[0]))
        np.testing.assert_array_equal(got_v, np.asarray(v_stack[0]))

    def test_int8_pools_roundtrip_tolerance(self, gpt_setup):
        """int8 pools dequantize within the RTNE bound: per-(token, head)
        absmax / 127 (the comm/quantize.py contract)."""
        model, cfg, params = gpt_setup
        rng = np.random.default_rng(1)
        ids = rng.integers(0, cfg.vocab_size, (1, 8), dtype=np.int32)
        pools, k_stack, _, _ = self._packed(cfg, params, model, ids,
                                            int8=True)
        bt = np.zeros((1, 8), np.int32)
        bt[0, :2] = [3, 7]
        lc = PagedLayerCache(*pools[0], jnp.asarray(bt),
                             jnp.asarray([8], jnp.int32), 4, "float32")
        got = np.asarray(lc._gather(lc.k, lc.k_scale))[0, :8]
        want = np.asarray(k_stack[0])
        bound = np.abs(want).max(axis=-1, keepdims=True) / 127.0 + 1e-7
        assert (np.abs(got - want) <= bound).all()

    def test_update_writes_at_per_row_positions(self, gpt_setup):
        """Two rows at DIFFERENT positions write through their own block
        tables and the validity mask exposes exactly pos+1 keys."""
        model, cfg, params = gpt_setup
        pools = init_paged_pools(cfg, 16, 4, int8=False, dtype=jnp.float32)
        bt = jnp.asarray([[1, 2, 0, 0], [5, 6, 7, 0]], jnp.int32)
        pos = jnp.asarray([2, 6], jnp.int32)
        lc = PagedLayerCache(*pools[0], bt, pos, 4, "float32")
        k_new = jnp.arange(2 * cfg.num_heads * cfg.head_dim,
                           dtype=jnp.float32).reshape(
            2, 1, cfg.num_heads, cfg.head_dim) + 1.0
        new, kk, vv, mask = lc.update(k_new, k_new * 2)
        kk = np.asarray(kk)
        np.testing.assert_array_equal(kk[0, 2], np.asarray(k_new[0, 0]))
        np.testing.assert_array_equal(kk[1, 6], np.asarray(k_new[1, 0]))
        m = np.asarray(mask)[:, 0, 0]                 # [B, L]
        assert m[0].sum() == 3 and m[1].sum() == 7    # kpos <= pos
        # row 0's write landed in block 1 offset 2 of the pool
        np.testing.assert_array_equal(np.asarray(new.k[1, 2]),
                                      np.asarray(k_new[0, 0]))


# ---------------------------------------------------------------------------
# Continuous batching end-to-end
# ---------------------------------------------------------------------------

class TestContinuousBatching:
    # (prompt_len, max_new_tokens) — mixed lengths, arrivals staggered so
    # later requests must backfill freed slots mid-run.
    TRACE = [(5, 12), (9, 3), (3, 10), (12, 4), (7, 8)]
    SUBMIT_AT = [0, 0, 2, 4, 4]        # engine step at which to submit

    @staticmethod
    def _static_occupancy(trace, slots):
        """Static batching on the same trace: batches of ``slots`` formed
        in order, each draining to its LONGEST member before the next
        starts. Returns busy-slot fraction."""
        steps = busy = 0
        for i in range(0, len(trace), slots):
            batch = [n for _, n in trace[i:i + slots]]
            steps += max(batch)
            busy += sum(batch)
        return busy / (slots * steps)

    def _run_trace(self, srv, cfg, rng=None):
        rng = rng or np.random.default_rng(7)
        prompts = [rng.integers(0, cfg.vocab_size, (t,)).tolist()
                   for t, _ in self.TRACE]
        rids, pending = [None] * len(self.TRACE), set(range(len(self.TRACE)))
        step = 0
        while pending or not srv.idle():
            for i in sorted(pending):
                if self.SUBMIT_AT[i] <= step:
                    rids[i] = srv.submit(prompts[i], self.TRACE[i][1])
                    pending.discard(i)
            srv.step()
            step += 1
            assert step < 200
        return prompts, rids

    def test_e2e_matches_generate_and_beats_static(self, gpt_setup):
        model, cfg, params = gpt_setup
        tel, sink = _mem_telemetry()
        srv = _serve(model, params, telemetry=tel)
        prompts, rids = self._run_trace(srv, cfg)

        # every request completed
        assert sorted(srv.results) == sorted(rids)
        # outputs are token-identical to one-shot generate()
        for i, (rid, prompt) in enumerate(zip(rids, prompts)):
            n = self.TRACE[i][1]
            want = np.asarray(srv.engine.generate(
                np.asarray([prompt], np.int32), max_new_tokens=n))[0]
            assert srv.results[rid]["tokens"] == want.tolist(), i
        # finished slots were backfilled mid-run: some slot served
        # multiple requests
        assert max(srv.stats["slot_assignments"].values()) >= 2
        # measured occupancy beats static batching on the same trace
        occ = sink.values("serving/batch_occupancy")
        occ = [o for o in occ if o > 0]
        measured = sum(occ) / len(occ)
        static = self._static_occupancy(self.TRACE, srv.scfg.max_batch_size)
        assert measured > static + 0.05, (measured, static)
        # the registry saw every SLO surface
        tags = sink.tags()
        assert {"serving/ttft_ms", "serving/batch_occupancy",
                "serving/kv_blocks_in_use", "serving/queue_depth",
                "serving/tokens_per_sec",
                "serving/requests_completed"} <= tags

    def test_decode_compiles_exactly_once(self, gpt_setup):
        model, cfg, params = gpt_setup
        srv = _serve(model, params)
        self._run_trace(srv, cfg)
        det = srv.engine.recompile_detector
        assert det.compiles("serving.decode_step") == 1
        assert det.retraces("serving.decode_step") == 0
        # prefill: one compile per bucket, no retraces under any name
        pre = [f for f in det.stats if f.startswith("serving.prefill_b")]
        assert pre, det.stats
        for f in pre:
            assert det.compiles(f) == 1 and det.retraces(f) == 0

    def test_int8_kv_matches_fp_within_tolerance(self, gpt_setup):
        """Same trace, fp vs int8 KV pools: greedy outputs identical and
        per-step decode logits within quantization tolerance."""
        model, cfg, params = gpt_setup
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, cfg.vocab_size, (6,)).tolist()

        def run(int8):
            srv = _serve(model, params, int8_kv_cache=int8)
            srv.capture_logits = True
            rid = srv.submit(prompt, 8)
            logits = []
            while not srv.idle():
                info = srv.step()
                if "logits" in info:
                    for slot, r in info["slots"].items():
                        if r == rid:
                            logits.append(info["logits"][slot])
            return srv.results[rid]["tokens"], logits

        fp_toks, fp_logits = run(False)
        q_toks, q_logits = run(True)
        assert q_toks == fp_toks
        assert len(fp_logits) == len(q_logits) >= 7
        for a, b in zip(fp_logits, q_logits):
            rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
            assert rel < 0.12, rel

    def test_preemption_under_kv_pressure(self, gpt_setup):
        """A pool too small for both sequences forces the YOUNGEST out
        (the oldest is never starved); the evicted request restarts from
        its prompt, still finishes correctly, and contributes exactly ONE
        TTFT observation despite prefilling twice."""
        model, cfg, params = gpt_setup
        rng = np.random.default_rng(5)
        tel, sink = _mem_telemetry()
        # capacity 11 blocks of 4 = 44 positions; two sequences needing
        # (8 prompt-bucket + 16 gen) ~ 6 blocks each fit only briefly
        srv = _serve(model, params, telemetry=tel, kv_num_blocks=12,
                     max_model_len=32)
        p0 = rng.integers(0, cfg.vocab_size, (7,)).tolist()
        p1 = rng.integers(0, cfg.vocab_size, (6,)).tolist()
        r0 = srv.submit(p0, 24)
        r1 = srv.submit(p1, 20)
        res = srv.run_until_complete()
        # exactly ONE eviction: after it, the victim's re-admission is
        # gated on full-lifetime free blocks, so the admit/prefill/evict
        # cycle cannot thrash
        assert srv.sched.preempted_total == 1
        for rid, p, n in ((r0, p0, 24), (r1, p1, 20)):
            want = np.asarray(srv.engine.generate(
                np.asarray([p], np.int32), max_new_tokens=n))[0]
            assert res[rid]["tokens"] == want.tolist()
        # youngest-first: the FIRST-admitted request ran straight through
        assert res[r0]["finish_step"] < res[r1]["finish_step"]
        assert sink.values("serving/preempted_seqs")[-1] >= 1
        # one TTFT observation per request, not per prefill attempt
        assert len(sink.values("serving/ttft_ms")) == 2

    def test_oldest_never_preempted_when_grower_is_youngest(self, gpt_setup):
        """The documented invariant directly: when the YOUNGEST sequence
        itself needs a block from a dry pool, IT is evicted — never the
        older sequence."""
        model, cfg, params = gpt_setup
        srv = _serve(model, params, kv_num_blocks=12, max_model_len=32)
        rng = np.random.default_rng(19)
        p = rng.integers(0, cfg.vocab_size, (6,)).tolist()
        r0 = srv.submit(p, 20)
        srv.step()                              # admit + prefill r0 alone
        r1 = srv.submit(p, 20)
        seen_r0 = set()
        while not srv.idle():
            srv.step()
            if srv.sched.running:
                seen_r0 |= {s.request.rid for s in srv.sched.active}
                # r0 must never leave the running set until it finishes
                if r0 not in srv.results:
                    assert any(s.request.rid == r0
                               for s in srv.sched.active)
        assert srv.sched.preempted_total >= 1
        assert srv.results[r0]["finish_step"] <= srv.results[r1]["finish_step"]

    def test_eos_stops_early(self, gpt_setup):
        """EOS: run once unstopped to learn a token the model will emit,
        then resubmit with that token as EOS and assert early stop."""
        model, cfg, params = gpt_setup
        rng = np.random.default_rng(9)
        prompt = rng.integers(0, cfg.vocab_size, (5,)).tolist()
        srv = _serve(model, params)
        rid = srv.submit(prompt, 10)
        full = srv.run_until_complete()[rid]["tokens"]
        eos = full[len(prompt) + 4]          # 5th generated token
        srv2 = _serve(model, params)
        rid2 = srv2.submit(prompt, 10, eos_token_id=eos)
        got = srv2.run_until_complete()[rid2]["tokens"]
        assert got == full[:len(prompt) + 5]

    def test_submit_validation(self, gpt_setup):
        model, cfg, params = gpt_setup
        srv = _serve(model, params)
        with pytest.raises(ValueError, match="empty prompt"):
            srv.submit([], 4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            srv.submit([1, 2], 0)
        with pytest.raises(ValueError, match="max_model_len"):
            srv.submit(list(range(40)), 20)
        tiny = _serve(model, params, kv_num_blocks=4, max_model_len=32)
        with pytest.raises(ValueError, match="never be admitted"):
            tiny.submit(list(range(10)), 16)   # needs 7 blocks, pool has 3

    def test_boundary_request_fills_pool_exactly(self, gpt_setup):
        """The last sampled token writes no KV: a request whose highest
        write position lands exactly on the pool boundary is admitted
        and completes (off-by-one regression guard)."""
        model, cfg, params = gpt_setup
        # capacity 5 blocks of 4 = 20 positions; prompt 4 + 17 new tokens
        # writes positions 0..19 — exactly 5 blocks
        srv = _serve(model, params, kv_num_blocks=6, max_model_len=21)
        rng = np.random.default_rng(23)
        p = rng.integers(0, cfg.vocab_size, (4,)).tolist()
        rid = srv.submit(p, 17)
        res = srv.run_until_complete()
        want = np.asarray(srv.engine.generate(
            np.asarray([p], np.int32), max_new_tokens=17))[0]
        assert res[rid]["tokens"] == want.tolist()

    def test_paged_cache_rejects_chunk_mask(self, gpt_setup):
        """A [B, S] attention_mask is meaningless against a paged cache's
        per-row positions — the model must refuse it, not splice it at
        key position 0."""
        model, cfg, params = gpt_setup
        from deepspeed_tpu.serving.kv_cache import init_paged_pools
        pools = init_paged_pools(cfg, 8, 4, dtype=jnp.float32)
        bt = jnp.zeros((1, 4), jnp.int32).at[0, 0].set(1)
        cache = tuple(
            PagedLayerCache(*pools[i], bt, jnp.asarray([1], jnp.int32),
                            4, "float32")
            for i in range(cfg.num_layers))
        with pytest.raises(ValueError, match="key-validity"):
            model.apply({"params": params},
                        {"input_ids": jnp.zeros((1, 1), jnp.int32),
                         "attention_mask": jnp.ones((1, 1), jnp.int32)},
                        deterministic=True, cache=cache, pos=None)

    def test_serve_forever_drains_and_returns(self, gpt_setup):
        model, cfg, params = gpt_setup
        srv = _serve(model, params)
        rng = np.random.default_rng(11)
        rid = srv.submit(rng.integers(0, cfg.vocab_size, (4,)).tolist(), 5)
        srv.serve_forever()                   # returns once idle
        assert rid in srv.results

    def test_init_serving_api(self, gpt_setup, tmp_path):
        model, cfg, params = gpt_setup
        srv = deepspeed_tpu.init_serving(
            model, params=params, dtype=jnp.float32,
            config={"serving": {"max_batch_size": 2, "kv_block_size": 4,
                                "kv_num_blocks": 32, "max_model_len": 32},
                    "telemetry": {"enabled": True, "dir": str(tmp_path)}})
        rng = np.random.default_rng(13)
        rid = srv.submit(rng.integers(0, cfg.vocab_size, (5,)).tolist(), 4)
        srv.run_until_complete()
        srv.close()
        assert rid in srv.results
        # metrics JSONL landed in the telemetry dir with serving rows
        mpath = os.path.join(str(tmp_path), "metrics.jsonl")
        assert os.path.exists(mpath)
        with open(mpath) as f:
            assert any('"serving/' in line for line in f)


# ---------------------------------------------------------------------------
# Config validation
# ---------------------------------------------------------------------------

class TestServingConfig:
    def test_defaults_parse(self):
        cfg = ServingConfig.from_dict(None)
        assert cfg.max_batch_size == 8 and cfg.kv_block_size == 16

    @pytest.mark.parametrize("block,match", [
        ({"max_batch_size": 0}, "max_batch_size"),
        ({"kv_block_size": 0}, "kv_block_size"),
        ({"kv_num_blocks": 1}, "kv_num_blocks"),
        ({"max_prefills_per_step": 0}, "max_prefills"),
        ({"temperature": -1}, "temperature"),
        ({"top_k": -1}, "top_k"),
    ])
    def test_rejects_bad_values(self, block, match):
        with pytest.raises(ConfigError, match=match):
            ServingConfig.from_dict(block)

    def test_rides_the_main_config(self):
        from deepspeed_tpu.config.config import DeepSpeedTPUConfig
        cfg = DeepSpeedTPUConfig(
            {"train_micro_batch_size_per_gpu": 1,
             "serving": {"max_batch_size": 3}}, world_size=1)
        assert cfg.serving.max_batch_size == 3

    def test_non_gpt_module_rejected(self):
        import flax.linen as nn

        class Plain(nn.Module):
            @nn.compact
            def __call__(self, batch, deterministic=True):
                return {"logits": nn.Dense(4)(batch["x"])}

        eng = deepspeed_tpu.init_inference(
            Plain(), example_batch={"x": np.zeros((1, 4), np.float32)})
        with pytest.raises(ValueError, match="cache-capable"):
            ServeEngine(eng)


# ---------------------------------------------------------------------------
# Telemetry contract
# ---------------------------------------------------------------------------

class TestServingTelemetry:
    def _drive(self, srv, cfg, n=3):
        rng = np.random.default_rng(17)
        for i in range(n):
            srv.submit(rng.integers(0, cfg.vocab_size, (4 + i,)).tolist(),
                       4 + i)
        srv.run_until_complete()

    @pytest.mark.parametrize("mode", ["off", "disabled"])
    def test_zero_device_syncs_when_off_or_disabled(self, gpt_setup,
                                                    monkeypatch, mode):
        """The zero-overhead contract, tested like telemetry/guardrails/
        goodput: with no telemetry AND with a present-but-disabled
        facade, the serving loop performs ZERO device syncs."""
        model, cfg, params = gpt_setup
        from deepspeed_tpu.telemetry import null_telemetry
        tel = None if mode == "off" else null_telemetry()
        srv = _serve(model, params, telemetry=tel)
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        self._drive(srv, cfg)
        assert calls["n"] == 0
        # and nothing was emitted anywhere
        assert not srv.telemetry.enabled
        assert srv.telemetry.registry.sinks == []

    def test_spans_land_in_the_shared_timeline(self, gpt_setup, tmp_path):
        """prefill/decode_step spans are recorded by the run's StepTracer
        and render through tools/trace_report.py — the same Perfetto view
        as training."""
        model, cfg, params = gpt_setup
        trace = str(tmp_path / "trace.json")
        tel, _ = _mem_telemetry(trace_path=trace)
        srv = _serve(model, params, telemetry=tel)
        self._drive(srv, cfg, n=2)
        names = tel.tracer.span_names()
        assert {"prefill", "decode_step"} <= names
        tel.tracer.save()
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
             trace], capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        assert "prefill" in proc.stdout and "decode_step" in proc.stdout

    def test_generate_span_through_engine_tracer(self, gpt_setup, tmp_path):
        """The one-shot engine's dispatches are bracketed too when a
        tracer is wired (satellite: spans in the inference path)."""
        model, cfg, params = gpt_setup
        tracer = StepTracer(path=str(tmp_path / "t.json"), enabled=True,
                            sync_spans=False)
        eng = deepspeed_tpu.init_inference(model, params=params,
                                           dtype=jnp.float32, tracer=tracer)
        ids = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (1, 5), dtype=np.int32)
        eng.generate(ids, max_new_tokens=2)
        eng.forward({"input_ids": ids})
        assert {"generate", "inference_forward"} <= tracer.span_names()

    def test_report_renders_a_real_run(self, gpt_setup, tmp_path):
        """serving_report over a real engine's JSONL (not just the
        selftest's synthetic rows)."""
        model, cfg, params = gpt_setup
        srv = deepspeed_tpu.init_serving(
            model, params=params, dtype=jnp.float32,
            config={"serving": {"max_batch_size": 2, "kv_block_size": 4,
                                "kv_num_blocks": 32, "max_model_len": 32},
                    "telemetry": {"enabled": True, "dir": str(tmp_path),
                                  "trace": {"enabled": False}}})
        self._drive(srv, cfg)
        srv.close()
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "serving_report.py"),
             str(tmp_path)], capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        assert "TTFT" in proc.stdout and "occupancy" in proc.stdout
        assert "completed       3 requests" in proc.stdout

    def test_selftest_cli(self):
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "serving_report.py"),
             "--selftest"], capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr + proc.stdout
        assert "selftest ok" in proc.stdout
