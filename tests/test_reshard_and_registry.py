"""MP checkpoint resharding, MPI launcher commands, op registry."""

import sys
from collections import OrderedDict

import jax
import numpy as np
import pytest

from deepspeed_tpu.runtime.state_dict_factory import (gpt_mp_rules,
                                                      merge_mp_checkpoints,
                                                      reshard_mp_checkpoint,
                                                      split_mp_checkpoint)


def full_tree(d=8, heads_dim=None):
    rng = np.random.default_rng(0)
    return {
        "h_0": {
            "c_attn": {"kernel": rng.standard_normal((d, 3 * d)).astype(np.float32),
                       "bias": rng.standard_normal((3 * d,)).astype(np.float32)},
            "c_fc": {"kernel": rng.standard_normal((d, 4 * d)).astype(np.float32),
                     "bias": rng.standard_normal((4 * d,)).astype(np.float32)},
            "c_proj": {"kernel": rng.standard_normal((d, d)).astype(np.float32),
                       "bias": rng.standard_normal((d,)).astype(np.float32)},
            "ln_1": {"scale": np.ones((d,), np.float32)},
        },
        "wte": rng.standard_normal((32, d)).astype(np.float32),
    }


def trees_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestMpReshard:
    def test_split_merge_roundtrip(self):
        full = full_tree()
        for mp in (2, 4):
            shards = split_mp_checkpoint(full, mp)
            assert len(shards) == mp
            trees_equal(merge_mp_checkpoints(shards), full)

    def test_qkv_slices_are_per_rank_interleaved(self):
        """Each rank's c_attn shard must hold its q|k|v thirds — the
        property a naive concat would break (reference qkv merge)."""
        full = full_tree(d=8)
        shards = split_mp_checkpoint(full, 2)
        k = full["h_0"]["c_attn"]["kernel"]
        q_part, k_part, v_part = np.split(k, 3, axis=1)
        want_rank0 = np.concatenate(
            [q_part[:, :4], k_part[:, :4], v_part[:, :4]], axis=1)
        np.testing.assert_array_equal(
            shards[0]["h_0"]["c_attn"]["kernel"], want_rank0)

    def test_reshard_4_to_2_matches_direct_split(self):
        full = full_tree()
        four = split_mp_checkpoint(full, 4)
        two_direct = split_mp_checkpoint(full, 2)
        two_resharded = reshard_mp_checkpoint(four, 2)
        for a, b in zip(two_direct, two_resharded):
            trees_equal(a, b)

    def test_replicated_mismatch_rejected(self):
        full = full_tree()
        shards = split_mp_checkpoint(full, 2)
        shards[1]["h_0"]["ln_1"]["scale"] = np.zeros((4,), np.float32)
        with pytest.raises(ValueError, match="replicated leaf"):
            merge_mp_checkpoints(shards)

    def test_indivisible_rejected(self):
        full = full_tree()
        with pytest.raises(ValueError, match="not divisible"):
            split_mp_checkpoint(full, 3)


class TestMpiLauncher:
    def _args(self, launcher):
        from deepspeed_tpu.launcher.runner import parse_args

        return parse_args(["--launcher", launcher, "--master_addr", "h0",
                           "train.py", "--flag"])

    def test_openmpi_command(self):
        from deepspeed_tpu.launcher.runner import build_mpi_command

        active = OrderedDict([("h0", [0]), ("h1", [0])])
        cmd = build_mpi_command(active, self._args("openmpi"),
                                {"JAX_X": "1"})
        assert cmd[0] == "mpirun"
        assert cmd[cmd.index("-np") + 1] == "2"
        assert "--host" in cmd and "h0:1,h1:1" in cmd
        assert "-x" in cmd and "JAX_X=1" in cmd
        assert "--node_rank=-1" in cmd
        assert "train.py" in cmd and "--flag" in cmd

    def test_mpich_command(self):
        from deepspeed_tpu.launcher.runner import build_mpi_command

        active = OrderedDict([("h0", [0]), ("h1", [0])])
        cmd = build_mpi_command(active, self._args("mpich"), {"JAX_X": "1"})
        assert "-hosts" in cmd and "h0,h1" in cmd
        assert "-genv" in cmd

    def test_mvapich_command(self):
        """Reference MVAPICHRunner (multinode_runner.py:141): hydra mpirun
        with a hostfile and MV2_* env (CUDA knobs dropped on TPU)."""
        from deepspeed_tpu.launcher.runner import build_mpi_command

        active = OrderedDict([("h0", [0]), ("h1", [0])])
        cmd = build_mpi_command(active, self._args("mvapich"),
                                {"JAX_X": "1"})
        assert cmd[0] == "mpirun"
        assert "-hostfile" in cmd and "-ppn" in cmd
        assert "-env" in cmd
        i = cmd.index("-hostfile")
        hosts = open(cmd[i + 1]).read().split()
        assert hosts == ["h0", "h1"]
        flat = " ".join(cmd)
        assert "MV2_SMP_USE_CMA" in flat and "MV2_USE_CUDA" not in flat

    def test_mpi_rank_from_env(self, monkeypatch):
        from deepspeed_tpu.launcher.launch import mpi_rank

        monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "3")
        assert mpi_rank() == 3
        monkeypatch.delenv("OMPI_COMM_WORLD_RANK")
        with pytest.raises(RuntimeError, match="MPI environment"):
            mpi_rank()


class TestOpRegistry:
    def test_list_and_load(self):
        from deepspeed_tpu.ops.registry import get_op, list_ops

        ops = list_ops()
        assert {"fused_adam", "flash_attention", "xla_attention",
                "onebit_adam", "moq_quantizer"} <= set(ops)
        adam_cls = get_op("fused_adam")
        from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
        assert adam_cls is FusedAdam

    def test_kind_filter_and_availability(self):
        from deepspeed_tpu.ops.registry import list_ops

        opts = list_ops(kind="optimizer")
        assert all(s.kind == "optimizer" for s in opts.values())
        flash = list_ops()["flash_attention"]
        assert flash.requires_tpu and flash.pallas
        assert flash.available() == (jax.devices()[0].platform == "tpu")

    def test_unknown_op_raises(self):
        from deepspeed_tpu.ops.registry import get_op

        with pytest.raises(KeyError, match="unknown op"):
            get_op("fused_frobnicator")

    def test_env_report_lists_ops(self, capsys):
        from deepspeed_tpu.env_report import main

        main()
        out = capsys.readouterr().out
        assert "op registry" in out and "fused_adam" in out

    def test_duplicate_registration_rejected(self):
        from deepspeed_tpu.ops.registry import register_op

        with pytest.raises(ValueError, match="already registered"):
            register_op("fused_adam", "optimizer", lambda: None)
