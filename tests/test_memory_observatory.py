"""Memory observatory tests (telemetry/memory.py; docs/OBSERVABILITY.md
"Memory observatory"): the model-state ledger cross-checked against
``compiled.memory_analysis()`` across ZeRO stages 0-3 (MLP + the test
GPT config) and the offload tier, capacity-planner over/under-HBM
verdicts, simulated RESOURCE_EXHAUSTED -> crashdump + supervisor
``cause=oom`` (unit and child-process e2e, asserting NO restart), the
zero-overhead disabled contract (attribute None, zero device syncs,
bit-identical step jaxpr — the fleet/goodput contract shape), per-step
headroom gauges + low-headroom instant, the all-device
``see_memory_usage``/timer satellites, the watchdog ``memory.json``
artifact, the fleet headroom field, and tools/memory_report.py."""

import importlib.util
import json
import os
import subprocess
import sys
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import ConfigError, DeepSpeedTPUConfig
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.telemetry.goodput import classify_exit
from deepspeed_tpu.telemetry.memory import (MEMORY_METRIC_TAGS,
                                            collect_memory_snapshot,
                                            is_resource_exhausted,
                                            model_state_ledger,
                                            plan_capacity,
                                            render_plan_table)

from simple_model import mlp_loss_fn, mlp_params, random_batches

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

OOM_MSG = "RESOURCE_EXHAUSTED: Out of memory allocating 2147483648 bytes"


def _load_tool(name):
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _tel_cfg(tmp_path, memory=None, sinks=("memory",), trace=False):
    tel = {"enabled": True, "dir": str(tmp_path),
           "trace": {"enabled": trace},
           "metrics": {"sinks": list(sinks)}}
    if memory is not None:
        tel["memory"] = memory
    return {"telemetry": tel, "steps_per_print": 1}


def _engine(config_extra=None, mesh=None):
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                **(config_extra or {})},
        mesh=mesh if mesh is not None else build_mesh(data=8))
    return engine


def _batch_bytes_per_device(batches, n_dev=8):
    """Per-device bytes of a (gas-leading) batch whose dim-1 divides the
    data axis — the term the XLA argument cross-check adds on top of the
    ledger's model state."""
    return sum(np.asarray(v).nbytes for v in
               jax.tree_util.tree_leaves(batches)) // n_dev


def _ledger_args_bytes(ledger):
    """The ledger components that are ARGUMENTS of the step executable
    (the compute-dtype cast is an in-program temp, not an argument)."""
    per = ledger["per_device"]
    return (per["master_bytes"] + per["optimizer_bytes"]
            + per["grads_bytes"] + per["scalars_bytes"])


def _crosscheck(engine, batches, n_dev=8, rtol=0.02):
    """The acceptance gate: ledger-predicted argument bytes must match
    compiled.memory_analysis() within the stated tolerance (2%)."""
    xla = engine.memory.last_xla
    assert xla is not None and xla["argument_bytes"] > 0
    expected = (_ledger_args_bytes(engine.memory.last_ledger)
                + _batch_bytes_per_device(batches, n_dev)
                + 4)                                    # the lr scalar
    assert abs(xla["argument_bytes"] - expected) <= max(
        512, rtol * xla["argument_bytes"]), (
        f"ledger {expected} vs xla {xla['argument_bytes']} "
        f"(ledger={engine.memory.last_ledger})")


# ---------------------------------------------------------------------------
# Ledger vs compiled.memory_analysis() — ZeRO stages 0-3 + offload
# ---------------------------------------------------------------------------
class TestLedgerCrossCheck:
    @pytest.mark.parametrize("stage", [0, 1, 2, 3])
    def test_mlp_stage_sweep(self, eight_devices, tmp_path, stage):
        zero = {"stage": stage}
        if stage == 3:
            # The tiny MLP sits below the stage-3 persistence threshold —
            # lower it so the sweep exercises real param sharding.
            zero["stage3_param_persistence_threshold"] = 0
        engine = _engine({**_tel_cfg(tmp_path, memory={"enabled": True}),
                          "zero_optimization": zero})
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        engine.train_batch(batches)
        ledger = engine.memory.last_ledger
        assert ledger["zero_stage"] == stage
        assert ledger["per_device"]["master_bytes"] > 0
        if stage >= 1:
            # sharding must actually shrink the per-device moments
            assert (ledger["per_device"]["optimizer_bytes"]
                    < ledger["full"]["optimizer_bytes"])
        _crosscheck(engine, batches)
        # the ledger gauges landed in the sink
        mem = engine.telemetry.registry.sinks[0]
        for tag in ("memory/ledger_master_bytes",
                    "memory/ledger_optimizer_bytes",
                    "memory/ledger_grads_bytes",
                    "memory/ledger_device_bytes"):
            assert mem.values(tag), tag
        for f in ("argument", "temp", "output", "alias"):
            assert mem.values(f"memory/xla_{f}_bytes"), f

    @pytest.mark.parametrize("stage", [0, 1, 2, 3])
    def test_gpt_stage_sweep(self, eight_devices, tmp_path, stage):
        """The acceptance config: the in-tree test GPT across every ZeRO
        stage, ledger vs XLA within the stated 2%."""
        from deepspeed_tpu.models import make_gpt
        model, cfg = make_gpt("tiny", num_layers=2, dropout_rate=0.0,
                              dtype=jnp.float32)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (8, 16), dtype=np.int32)
        params = model.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)},
                            {"input_ids": ids})["params"]
        zero = {"stage": stage}
        if stage == 3:
            zero["stage3_param_persistence_threshold"] = 0
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params, mesh=build_mesh(data=8),
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": zero,
                    **_tel_cfg(tmp_path, memory={"enabled": True})})
        batches = {"input_ids": ids[None]}
        engine.train_batch(batches)
        _crosscheck(engine, batches)

    def test_mixed_precision_counts_compute_copy(self, eight_devices,
                                                 tmp_path):
        """bf16: the in-step compute cast is live model state (counted in
        the ledger) but NOT a program argument (excluded from the
        cross-check) — both facts asserted."""
        engine = _engine({**_tel_cfg(tmp_path, memory={"enabled": True}),
                          "zero_optimization": {"stage": 2},
                          "bf16": {"enabled": True}})
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        engine.train_batch(batches)
        ledger = engine.memory.last_ledger
        per = ledger["per_device"]
        assert per["compute_params_bytes"] > 0
        # bf16 copy is half the fp32 master
        assert per["compute_params_bytes"] == per["master_bytes"] // 2
        _crosscheck(engine, batches)

    def test_offload_ledger_host_tiers(self, eight_devices, tmp_path):
        engine = _engine({
            **_tel_cfg(tmp_path, memory={"enabled": True}),
            "zero_optimization": {
                "stage": 2, "offload_optimizer": {"device": "cpu"}}})
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        engine.train_batch(batches)
        ledger = engine.memory.last_ledger
        assert ledger["offload_optimizer"] == "cpu"
        # master + moments live host-side; device keeps the grads scan
        # accumulator (ZeRO-sharded) + compute params
        assert ledger["per_device"]["master_bytes"] == 0
        assert ledger["host"]["master_bytes"] > 0
        assert ledger["host"]["optimizer_bytes"] > 0
        assert ledger["per_device"]["grads_bytes"] > 0
        assert ledger["per_device"]["compute_params_bytes"] > 0
        # the offload tier attributes its device-side micro-scan
        assert engine.memory.last_xla is not None
        assert engine.memory.last_xla["argument_bytes"] > 0
        mem = engine.telemetry.registry.sinks[0]
        assert mem.values("memory/ledger_host_bytes")[-1] > 0


# ---------------------------------------------------------------------------
# Capacity planner
# ---------------------------------------------------------------------------
class TestPlanner:
    GB = 1024**3

    def test_stage_arithmetic_and_verdicts(self):
        plan = plan_capacity(
            compute_params_bytes=2 * self.GB, grads_bytes=2 * self.GB,
            master_optim_bytes=12 * self.GB, num_shards=8,
            hbm_limit_bytes=8 * self.GB, chosen_stage=0,
            total_params=int(1e9))
        rows = {(r["stage"], r["offload"]): r for r in plan["rows"]}
        assert rows[(0, False)]["model_state_bytes"] == 16 * self.GB
        assert rows[(0, False)]["verdict"] == "over"
        assert rows[(0, False)]["chosen"]
        assert rows[(1, False)]["model_state_bytes"] == int(5.5 * self.GB)
        assert rows[(2, False)]["model_state_bytes"] == int(
            (2 + 14 / 8) * self.GB)
        assert rows[(3, False)]["model_state_bytes"] == 2 * self.GB
        assert rows[(3, False)]["verdict"] == "ok"
        # offload moves master+moments (and at stage 3 the params) host-side
        assert rows[(0, True)]["host_bytes"] == 12 * self.GB   # unsharded
        assert rows[(2, True)]["host_bytes"] == int(1.5 * self.GB)
        assert rows[(3, True)]["model_state_bytes"] == int(0.25 * self.GB)
        assert rows[(3, True)]["host_bytes"] == int(1.75 * self.GB)
        text = render_plan_table(plan)
        assert "OVER" in text and "stage0 *" in text

    def test_offload_rows_keep_fp32_compute_copy(self):
        """Review fix: a pure-fp32 run has compute_params_bytes 0 (the
        master IS the compute tree), but the offload what-if rows must
        put the fp32 copy back on device — optimizer offload moves the
        master host-side and materializes device compute params."""
        plan = plan_capacity(
            compute_params_bytes=0,
            offload_compute_params_bytes=4 * self.GB,
            grads_bytes=4 * self.GB, master_optim_bytes=12 * self.GB,
            num_shards=8, chosen_stage=1)
        rows = {(r["stage"], r["offload"]): r for r in plan["rows"]}
        # non-offload stage1: 0 + 4 + 12/8 = 5.5 GB
        assert rows[(1, False)]["model_state_bytes"] == int(5.5 * self.GB)
        # stage1+offload: the 4 GB fp32 copy + grads; mo host-side
        assert rows[(1, True)]["model_state_bytes"] == 8 * self.GB
        assert rows[(1, True)]["host_bytes"] == int(1.5 * self.GB)
        # stage3+offload: (4+4+12)/8 − 12/8 − 4/8 = 0.5 GB on device
        assert rows[(3, True)]["model_state_bytes"] == int(0.5 * self.GB)
        assert rows[(3, True)]["host_bytes"] == 2 * self.GB

    def test_microbatch_projection(self):
        plan = plan_capacity(
            compute_params_bytes=self.GB, grads_bytes=self.GB,
            master_optim_bytes=self.GB, num_shards=1, microbatch=4,
            act_bytes_per_sample=0.5 * self.GB,
            hbm_limit_bytes=6 * self.GB, chosen_stage=0)
        proj = {m["microbatch"]: m for m in plan["microbatch_projection"]}
        assert proj[4]["verdict"] == "ok"       # 3 + 2 = 5 GB
        assert proj[8]["verdict"] == "over"     # 3 + 4 = 7 GB
        assert proj[16]["verdict"] == "over"

    def test_engine_warns_when_chosen_config_over_hbm(
            self, eight_devices, tmp_path, monkeypatch):
        """The loud pre-compile warning: a config whose projection
        exceeds the (overridden) HBM limit."""
        from deepspeed_tpu.telemetry import memory as memory_mod
        warnings = []
        monkeypatch.setattr(memory_mod.logger, "warning",
                            lambda msg, *a: warnings.append(msg))
        engine = _engine(_tel_cfg(tmp_path, memory={
            "enabled": True, "hbm_limit_gb": 1e-6}))
        assert any("projects" in m and "OOM" in m for m in warnings)
        chosen = [r for r in engine.memory.last_plan["rows"]
                  if r["chosen"]]
        assert chosen[0]["verdict"] == "over"
        # the plan is persisted for memory_report
        doc = json.load(open(tmp_path / "memory_plan.json"))
        assert doc["rows"] and doc["hbm_limit_bytes"] > 0

    def test_fitting_config_no_warning(self, eight_devices, tmp_path,
                                       monkeypatch):
        from deepspeed_tpu.telemetry import memory as memory_mod
        warnings = []
        monkeypatch.setattr(memory_mod.logger, "warning",
                            lambda msg, *a: warnings.append(msg))
        engine = _engine(_tel_cfg(tmp_path, memory={
            "enabled": True, "hbm_limit_gb": 64.0}))
        chosen = [r for r in engine.memory.last_plan["rows"]
                  if r["chosen"]]
        assert chosen[0]["verdict"] == "ok"
        assert not any("expected to OOM" in m for m in warnings)


# ---------------------------------------------------------------------------
# Per-step headroom
# ---------------------------------------------------------------------------
class TestHeadroom:
    def test_note_hbm_gauges_and_low_instant(self, eight_devices,
                                             tmp_path):
        engine = _engine(_tel_cfg(tmp_path, trace=True,
                                  memory={"enabled": True,
                                          "headroom_warn_frac": 0.1}))
        gb = 1024**3
        engine.memory.note_hbm([2 * gb], [10 * gb], step=1)
        mem = engine.telemetry.registry.sinks[0]
        assert mem.values("memory/hbm_headroom_bytes")[-1] == 8 * gb
        assert mem.values("memory/hbm_limit_bytes")[-1] == 10 * gb
        instants = [e for e in engine.telemetry.tracer.events
                    if e.get("ph") == "i"
                    and e["name"] == "memory/headroom_low"]
        assert not instants
        # drop below 10% of the limit -> instant fires once
        engine.memory.note_hbm([int(9.5 * gb)], [10 * gb], step=2)
        engine.memory.note_hbm([int(9.6 * gb)], [10 * gb], step=3)
        instants = [e for e in engine.telemetry.tracer.events
                    if e.get("ph") == "i"
                    and e["name"] == "memory/headroom_low"]
        assert len(instants) == 1
        assert instants[0]["args"]["headroom_bytes"] == int(0.5 * gb)

    def test_step_path_emits_headroom_with_device_stats(
            self, eight_devices, tmp_path, monkeypatch):
        """CPU devices report no memory_stats; fake them to drive the
        real _emit_step_telemetry -> note_hbm wiring, and check the
        fleet vector picks the gauge up."""
        engine = _engine(_tel_cfg(tmp_path, memory={"enabled": True}))
        gb = 1024**3
        fake = [SimpleNamespace(memory_stats=lambda: {
            "peak_bytes_in_use": 3 * gb, "bytes_in_use": 2 * gb,
            "bytes_limit": 16 * gb})]
        monkeypatch.setattr(jax, "local_devices", lambda: fake)
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        engine.train_batch(batches)
        mem = engine.telemetry.registry.sinks[0]
        assert mem.values("memory/hbm_headroom_bytes")[-1] == 13 * gb
        assert mem.values("engine/hbm_peak_bytes")[-1] == 3 * gb

    def test_fleet_vector_carries_headroom(self, eight_devices, tmp_path):
        """The fleet satellite: memory observatory headroom feeds the
        fleet gather, and argmin names the tightest host."""
        engine = _engine({**_tel_cfg(tmp_path, memory={"enabled": True}),
                          "telemetry": {
                              **_tel_cfg(tmp_path)["telemetry"],
                              "memory": {"enabled": True},
                              "fleet": {"enabled": True,
                                        "min_window": 1}}})
        gb = 1024**3
        engine.memory.note_hbm([2 * gb], [10 * gb], step=0)
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        for _ in range(2):
            engine.train_batch(batches)
            engine.memory.note_hbm([2 * gb], [10 * gb],
                                   step=engine.global_steps)
        mem = engine.telemetry.registry.sinks[0]
        assert mem.values("fleet/hbm_headroom_bytes_min")[-1] == 8 * gb
        assert mem.values("fleet/hbm_headroom_bytes_argmin_host")[-1] == 0


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------
class TestOOMForensics:
    def _oom_engine(self, tmp_path, dumps):
        return _engine(_tel_cfg(tmp_path, sinks=("memory", "jsonl"),
                                memory={"enabled": True,
                                        "crashdump_dir": str(dumps)}))

    def test_is_resource_exhausted(self):
        assert is_resource_exhausted(RuntimeError(OOM_MSG))

        class XlaRuntimeError(Exception):
            pass

        assert is_resource_exhausted(
            XlaRuntimeError("Out of memory allocating 99 bytes"))
        # NARROW by design (review fix): a bare "out of memory" quoted in
        # some unrelated error must not trip the no-restart policy — only
        # the XLA status code / an XLA runtime error does.
        assert not is_resource_exhausted(
            RuntimeError("worker log said: out of memory"))
        assert not is_resource_exhausted(ValueError("shape mismatch"))

    def test_oom_writes_crashdump_and_exits_distinct_rc(
            self, eight_devices, tmp_path):
        dumps = tmp_path / "dumps"
        engine = self._oom_engine(tmp_path, dumps)
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        engine.train_batch(batches)          # prime ledger + attribution

        def boom(*a, **k):
            raise RuntimeError(OOM_MSG)

        engine._train_step = boom
        rcs = []
        engine.memory._exit_fn = rcs.append
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            engine.train_batch(batches)
        assert rcs == [114]
        dump_dirs = [d for d in os.listdir(dumps)
                     if d.startswith("oom_step")]
        assert len(dump_dirs) == 1
        out = dumps / dump_dirs[0]
        info = json.load(open(out / "info.json"))
        assert info["kind"] == "oom" and info["exit_code"] == 114
        assert "RESOURCE_EXHAUSTED" in info["error"]
        assert info["label"] == "train_step"
        # the forensic artifacts
        mem_doc = json.load(open(out / "memory.json"))
        assert "devices" in mem_doc
        ledger = json.load(open(out / "ledger.json"))
        assert ledger["per_device"]["model_state_bytes"] > 0
        xla = json.load(open(out / "xla_memory_analysis.json"))
        assert xla["argument_bytes"] > 0
        plan = json.load(open(out / "plan.json"))
        assert plan["rows"]
        assert os.path.exists(out / "metrics_tail.jsonl")
        # telemetry counter + the engine-stamped manifest cause
        mem = engine.telemetry.registry.sinks[0]
        assert mem.values("memory/oom_crashdumps")[-1] == 1
        doc = json.load(open(engine.goodput.manifest_path()))
        assert doc["restart_cause"] == "oom"
        assert doc["exit_rc"] == 114

    def test_non_oom_errors_propagate_untouched(self, eight_devices,
                                                tmp_path):
        dumps = tmp_path / "dumps"
        engine = self._oom_engine(tmp_path, dumps)

        def boom(*a, **k):
            raise ValueError("shape mismatch")

        engine._train_step = boom
        rcs = []
        engine.memory._exit_fn = rcs.append
        with pytest.raises(ValueError, match="shape mismatch"):
            engine.train_batch(random_batches(np.random.default_rng(0),
                                              gas=1, batch_size=16))
        assert rcs == []
        assert not os.path.exists(dumps)

    def test_classify_exit_oom(self):
        assert classify_exit(114, (113,), (114,)) == "oom"
        assert classify_exit(113, (113,), (114,)) == "watchdog"
        assert classify_exit(-15, (113,), (114,)) == "preemption"
        assert classify_exit(1, (113,), (114,)) == "crash"
        assert classify_exit(0, (113,), (114,)) == "clean"

    def test_oom_rc_must_differ_from_watchdog_rc(self):
        with pytest.raises(ConfigError, match="collides"):
            DeepSpeedTPUConfig({
                "train_micro_batch_size_per_gpu": 1,
                "telemetry": {"enabled": True, "dir": "/tmp/x",
                              "memory": {"enabled": True,
                                         "oom_exit_code": 113}},
                "guardrails": {"enabled": True,
                               "watchdog": {"enabled": True}}},
                world_size=1)

    def test_supervisor_does_not_restart_oom(self, tmp_path):
        """A child exiting with the OOM rc must NOT be restarted — one
        attempt, cause=oom stamped, loop over with the rc."""
        from deepspeed_tpu.resilience.supervisor import Supervisor
        sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(114)"],
                         max_restarts=3, run_dir=str(tmp_path))
        rc = sup.run()
        assert rc == 114
        assert sup.exit_codes == [114]       # exactly one attempt
        assert sup.restarts == 0 and sup.oom_exits == 1
        manifests = [f for f in os.listdir(tmp_path)
                     if f.startswith("run_manifest.a0000.")]
        assert manifests
        doc = json.load(open(tmp_path / manifests[0]))
        assert doc["restart_cause"] == "oom"
        assert doc["exit_rc"] == 114

    def test_watchdog_rc_still_hot_restarts(self, tmp_path):
        """The distinct-rc contract the OOM path must not break: the
        watchdog rc keeps its immediate-restart semantics."""
        from deepspeed_tpu.resilience.supervisor import Supervisor
        sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(113)"],
                         max_restarts=1, run_dir=str(tmp_path))
        rc = sup.run()
        assert rc == 113
        assert sup.exit_codes == [113, 113]  # restarted once, immediately
        assert sup.immediate_restarts >= 1 and sup.oom_exits == 0

    def test_e2e_child_oom_to_supervisor(self, eight_devices, tmp_path):
        """The acceptance e2e: a REAL child process whose step raises
        RESOURCE_EXHAUSTED -> memory crashdump on disk -> os._exit(114)
        -> supervisor classifies cause=oom and does not hot-loop."""
        from deepspeed_tpu.resilience.supervisor import Supervisor
        run = tmp_path / "run"
        dumps = tmp_path / "dumps"
        child = f"""
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, {os.path.join(REPO, 'tests')!r})
import numpy as np
import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import build_mesh
from simple_model import mlp_loss_fn, mlp_params, random_batches
engine, _, _, _ = deepspeed_tpu.initialize(
    loss_fn=mlp_loss_fn, params=mlp_params(),
    config={{"train_micro_batch_size_per_gpu": 2,
             "optimizer": {{"type": "Adam", "params": {{"lr": 1e-2}}}},
             "telemetry": {{"enabled": True, "dir": {str(run)!r},
                            "trace": {{"enabled": False}},
                            "metrics": {{"sinks": ["jsonl"]}},
                            "memory": {{"enabled": True,
                                        "crashdump_dir": {str(dumps)!r}}}}},
             "steps_per_print": 1}},
    mesh=build_mesh(data=8))
batches = random_batches(np.random.default_rng(0), gas=1, batch_size=16)
engine.train_batch(batches)
def boom(*a, **k):
    raise RuntimeError({OOM_MSG!r})
engine._train_step = boom
engine.train_batch(batches)   # -> oom_guard -> crashdump -> os._exit(114)
raise SystemExit(99)          # must be unreachable
"""
        sup = Supervisor([sys.executable, "-c", child], max_restarts=3,
                         run_dir=str(run))
        rc = sup.run()
        assert rc == 114
        assert sup.exit_codes == [114]       # no restart loop
        dump_dirs = [d for d in os.listdir(dumps)
                     if d.startswith("oom_step")]
        assert len(dump_dirs) == 1
        info = json.load(open(dumps / dump_dirs[0] / "info.json"))
        assert "RESOURCE_EXHAUSTED" in info["error"]
        manifests = [f for f in os.listdir(run)
                     if f.startswith("run_manifest.a0000.")]
        assert manifests
        doc = json.load(open(run / manifests[0]))
        assert doc["restart_cause"] == "oom" and doc["exit_rc"] == 114


# ---------------------------------------------------------------------------
# Zero-overhead disabled contract (the fleet/goodput contract shape)
# ---------------------------------------------------------------------------
class TestDisabledContract:
    def test_disabled_memory_is_none_no_tags_zero_syncs(
            self, eight_devices, tmp_path, monkeypatch):
        engine = _engine(_tel_cfg(tmp_path))      # telemetry on, memory off
        assert engine.memory is None
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        engine.train_batch(batches)               # compile outside window
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        for _ in range(10):
            engine.train_batch(batches)
        assert calls["n"] == 0
        mem = engine.telemetry.registry.sinks[0]
        assert not {t for t in mem.tags() if t.startswith("memory/")}
        assert not os.path.exists(tmp_path / "memory_plan.json")
        # telemetry fully off too
        engine2 = _engine()
        assert engine2.memory is None

    def test_step_jaxpr_bit_identical(self, eight_devices, tmp_path):
        """Enabling the observatory must not change the compiled step
        program AT ALL — it only reads host-side state. Compare the
        lowered step text with memory off vs on."""
        batches_np = random_batches(np.random.default_rng(0), gas=1,
                                    batch_size=16)
        texts = []
        for memory in (None, {"enabled": True}):
            engine = _engine(_tel_cfg(tmp_path / str(bool(memory)),
                                      memory=memory))
            placed = engine.put_batch(batches_np, leading_gas_dim=True)
            lowered = engine._train_step.lower(engine.state, placed,
                                               jnp.float32(1e-2))
            texts.append(lowered.as_text())
        assert texts[0] == texts[1]


# ---------------------------------------------------------------------------
# Satellites: all-device memory reporting, watchdog artifact, report tool
# ---------------------------------------------------------------------------
class TestMemoryUsageSatellites:
    def _fake_devices(self):
        gb = 1024**3
        mk = lambda peak, use, limit: SimpleNamespace(  # noqa: E731
            memory_stats=lambda: {"peak_bytes_in_use": peak,
                                  "bytes_in_use": use,
                                  "bytes_limit": limit},
            id=0, platform="tpu", device_kind="fake")
        return [mk(10 * gb, 5 * gb, 32 * gb), mk(20 * gb, 6 * gb, 30 * gb)]

    def test_see_memory_usage_aggregates_all_devices(self, monkeypatch):
        from deepspeed_tpu.runtime import utils as rutils
        monkeypatch.setattr(jax, "local_devices",
                            lambda: self._fake_devices())
        lines = []
        monkeypatch.setattr(rutils.logger, "info",
                            lambda msg, *a: lines.append(msg))
        rutils.see_memory_usage("probe", force=True)
        joined = "\n".join(lines)
        # peak = MAX over devices (20), in-use = SUM (11), limit = MIN (30)
        assert "peak 20.00 GB" in joined
        assert "in-use 11.00 GB" in joined
        assert "limit 30.00 GB" in joined
        assert "2 devices" in joined

    def test_timer_memory_usage_aggregates_all_devices(self, monkeypatch):
        from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer
        monkeypatch.setattr(jax, "local_devices",
                            lambda: self._fake_devices())
        s = SynchronizedWallClockTimer.memory_usage()
        assert "in-use 11.00 GB" in s
        assert "peak 20.00 GB" in s
        assert "(2 devices)" in s

    def test_collect_memory_snapshot_headroom(self, monkeypatch):
        monkeypatch.setattr(jax, "local_devices",
                            lambda: self._fake_devices())
        snap = collect_memory_snapshot()
        gb = 1024**3
        assert len(snap["devices"]) == 2
        # tightest device: 30 - 20 = 10 GB
        assert snap["min_headroom_bytes"] == 10 * gb

    def test_watchdog_dump_gains_memory_json(self, tmp_path, monkeypatch):
        """The hung-collective post-mortem satellite: the watchdog
        crashdump now answers "was the hang memory pressure?"."""
        from deepspeed_tpu.guardrails.watchdog import StepWatchdog
        from deepspeed_tpu.telemetry import memory as memory_mod
        gb = 1024**3
        monkeypatch.setattr(
            memory_mod, "collect_memory_snapshot",
            lambda: {"devices": [{"id": 0, "stats": {"bytes_limit": 16 * gb},
                                  "headroom_bytes": 2 * gb}],
                     "min_headroom_bytes": 2 * gb})
        wd = StepWatchdog(timeout=100.0, crashdump_dir=str(tmp_path),
                          exit_fn=lambda rc: None)
        out = wd.dump_diagnostics(step=5, elapsed=120.0, label="train_step")
        info = json.load(open(os.path.join(out, "info.json")))
        assert info["memory"] == "memory.json"
        doc = json.load(open(os.path.join(out, "memory.json")))
        assert doc["min_headroom_bytes"] == 2 * gb

    def test_bench_records_headroom_per_section(self, tmp_path,
                                                monkeypatch):
        spec = importlib.util.spec_from_file_location(
            "bench", os.path.join(REPO, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        sys.modules["bench"] = bench
        spec.loader.exec_module(bench)
        monkeypatch.setattr(bench, "PARTIAL_PATH",
                            str(tmp_path / "partial.json"))
        result = {}
        assert bench.run_section("s1", lambda: None, result)
        # CPU devices report no limit -> honest None, but the key exists
        assert "peak_headroom_bytes" in result
        assert result["peak_headroom_bytes"]["s1"] is None


class TestMemoryReport:
    def test_selftest_cli(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "memory_report.py"),
             "--selftest"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "selftest ok" in proc.stdout

    def test_merges_engine_written_run_dir(self, eight_devices, tmp_path):
        """A real engine run (memory on, jsonl sink) parses into a
        report with the ledger/XLA columns and the persisted plan."""
        dumps = tmp_path / "crashdumps"
        engine = _engine(_tel_cfg(
            tmp_path, sinks=("jsonl",),
            memory={"enabled": True, "crashdump_dir": str(dumps)}))
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        for _ in range(2):
            engine.train_batch(batches)
        engine.telemetry.flush()
        mr = _load_tool("memory_report")
        report = mr.merge_memory(str(tmp_path))
        assert report["n_hosts"] == 1
        row = report["hosts"][0]
        assert row["ledger_device_bytes"] > 0
        assert row["xla_argument_bytes"] > 0
        assert "local" in report["plans"]
        text = mr.render(report)
        assert "memory report" in text and "capacity plan" in text

    def test_doc_pins_every_tag(self):
        """Belt-and-braces beside test_doc_lint: the full emitted set."""
        with open(os.path.join(REPO, "docs", "OBSERVABILITY.md")) as f:
            doc = f.read()
        assert all(t in doc for t in MEMORY_METRIC_TAGS)


class TestModelStateLedgerUnit:
    def test_ledger_pure_function_matches_known_shapes(self,
                                                       eight_devices,
                                                       tmp_path):
        """680-param MLP at stage 2 on 8 devices: the closed numbers."""
        engine = _engine({**_tel_cfg(tmp_path, memory={"enabled": True}),
                          "zero_optimization": {"stage": 2}})
        ledger = model_state_ledger(engine)
        assert ledger["total_params"] == 680
        per = ledger["per_device"]
        # stage 2: master replicated (fp32), moments + grads sharded /8
        assert per["master_bytes"] == 680 * 4
        assert per["grads_bytes"] == 680 * 4 // 8
        # Adam m+v sharded + its replicated step scalar
        assert per["optimizer_bytes"] == 2 * 680 * 4 // 8 + 4
        assert ledger["full"]["optimizer_bytes"] == 2 * 680 * 4 + 4
