"""Kernel parity for the fused LayerNorm+projection op (ops/transformer/
fused.py) — the jnp oracle defines the semantics; the Pallas kernels must
match it forward and backward (the reference's test_cuda_forward.py /
test_cuda_backward.py methodology for its fused transformer kernel,
csrc/transformer/ds_transformer_cuda.cpp:147,:295)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.fused import (ln_matmul, ln_matmul_ok,
                                                 ln_matmul_reference)


def _make(n, d, f, dtype, rng):
    x = jnp.asarray(rng.standard_normal((n, d)), dtype)
    gamma = jnp.asarray(1.0 + 0.1 * rng.standard_normal(d), jnp.float32)
    beta = jnp.asarray(0.1 * rng.standard_normal(d), jnp.float32)
    w = jnp.asarray(rng.standard_normal((d, f)) / np.sqrt(d), dtype)
    bias = jnp.asarray(0.1 * rng.standard_normal(f), dtype)
    return x, gamma, beta, w, bias


@pytest.mark.parametrize("activation", [None, "gelu"])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_forward_parity(rng, activation, dtype):
    x, gamma, beta, w, bias = _make(256, 128, 384, dtype, rng)
    got = ln_matmul(x, gamma, beta, w, bias, activation=activation,
                    block_rows=128)
    want = ln_matmul_reference(x, gamma, beta, w, bias,
                               activation=activation)
    tol = 2e-2 if dtype == jnp.bfloat16 else 2e-5
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=tol, atol=tol)


@pytest.mark.parametrize("activation", [None, "gelu"])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_backward_parity(rng, activation, dtype):
    # fp32 isolates the kernel math from rounding; the bf16 case pins the
    # backward's cast discipline (dy_c/ln_c to weight dtype before the
    # MXU dots) against the oracle at bf16-scale tolerance.
    x, gamma, beta, w, bias = _make(256, 128, 256, dtype, rng)
    dy = jnp.asarray(rng.standard_normal((256, 256)), dtype)

    def fused(x, gamma, beta, w, bias):
        out = ln_matmul(x, gamma, beta, w, bias, activation=activation,
                        block_rows=128)
        return jnp.sum(out * dy)

    def oracle(x, gamma, beta, w, bias):
        out = ln_matmul_reference(x, gamma, beta, w, bias,
                                  activation=activation)
        return jnp.sum(out * dy)

    got = jax.grad(fused, argnums=(0, 1, 2, 3, 4))(x, gamma, beta, w, bias)
    want = jax.grad(oracle, argnums=(0, 1, 2, 3, 4))(x, gamma, beta, w, bias)
    tol = 5e-2 if dtype == jnp.bfloat16 else 2e-4
    for g, wnt, name in zip(got, want, ["dx", "dgamma", "dbeta", "dw", "db"]):
        a = np.asarray(g, np.float32)
        b = np.asarray(wnt, np.float32)
        if dtype == jnp.bfloat16:
            # bulk-tight, tiny-tail-tolerant (conftest TPU-gate style):
            # the kernel recomputes gelu'(pre) from a bf16 dot while the
            # oracle's AD differentiates the fp32 epilogue — elements near
            # gelu's curvature round differently at bf16.
            bad = ~np.isclose(a, b, rtol=tol, atol=tol)
            assert bad.mean() <= 1e-3, (name, bad.mean())
            if bad.any():
                assert np.abs(a - b)[bad].max() <= 0.15, name
        else:
            np.testing.assert_allclose(a, b, rtol=tol, atol=tol,
                                       err_msg=name)


def test_multi_block_accumulation(rng):
    # dW/dgamma/dbeta accumulate across row blocks — 4 grid steps here.
    x, gamma, beta, w, bias = _make(512, 128, 128, jnp.float32, rng)

    def loss(fn):
        def wrapped(*args):
            return jnp.sum(fn(*args) ** 2)
        return wrapped

    got = jax.grad(loss(lambda *a: ln_matmul(*a, block_rows=128)),
                   argnums=(1, 3))(x, gamma, beta, w, bias)
    want = jax.grad(loss(ln_matmul_reference), argnums=(1, 3))(
        x, gamma, beta, w, bias)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]),
                               rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(got[1]), np.asarray(want[1]),
                               rtol=5e-4, atol=5e-4)


def test_leading_dims_flattened(rng):
    x, gamma, beta, w, bias = _make(256, 128, 128, jnp.float32, rng)
    x3 = x.reshape(2, 128, 128)
    out3 = ln_matmul(x3, gamma, beta, w, bias, block_rows=128)
    out2 = ln_matmul(x, gamma, beta, w, bias, block_rows=128)
    assert out3.shape == (2, 128, 128)
    np.testing.assert_array_equal(np.asarray(out3.reshape(256, 128)),
                                  np.asarray(out2))


class TestModelIntegration:
    """GPTConfig.fused_ln=True must keep the checkpointed parameter tree
    byte-identical to the unfused build and match its loss/grads."""

    def _models(self):
        from deepspeed_tpu.models import make_gpt

        kw = dict(vocab_size=512, max_seq_len=128, hidden_size=128,
                  num_layers=2, num_heads=2, dropout_rate=0.0,
                  dtype=jnp.float32)
        from deepspeed_tpu.models.gpt import GPTConfig
        un, cfg_u = make_gpt(GPTConfig(fused_ln=False, **kw))
        fu, cfg_f = make_gpt(GPTConfig(fused_ln=True, **kw))
        return un, fu

    def test_param_tree_and_trajectory_parity(self, rng):
        un, fu = self._models()
        batch = {"input_ids": jnp.asarray(
            rng.integers(0, 512, (2, 128)), jnp.int32)}
        rngs = {"params": jax.random.PRNGKey(0),
                "dropout": jax.random.PRNGKey(1)}
        pu = un.init(rngs, batch)["params"]
        pf = fu.init(rngs, batch)["params"]
        # identical tree structure AND identical initial values
        assert (jax.tree_util.tree_structure(pu)
                == jax.tree_util.tree_structure(pf))
        for a, b in zip(jax.tree_util.tree_leaves(pu),
                        jax.tree_util.tree_leaves(pf)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        def loss(model, params):
            return model.apply({"params": params}, batch,
                               deterministic=True)["loss"]

        lu, gu = jax.value_and_grad(lambda p: loss(un, p))(pu)
        lf, gf = jax.value_and_grad(lambda p: loss(fu, p))(pf)
        np.testing.assert_allclose(float(lf), float(lu), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(gu),
                        jax.tree_util.tree_leaves(gf)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4)


def test_shape_gate():
    assert ln_matmul_ok(8192, 768, 2304)
    assert ln_matmul_ok(8192, 768, 3072)
    assert not ln_matmul_ok(8192, 770, 2304)   # hidden not lane-aligned
    assert not ln_matmul_ok(100, 768, 2304)    # no viable row block
    with pytest.raises(ValueError):
        ln_matmul(jnp.zeros((100, 770)), jnp.ones(770), jnp.zeros(770),
                  jnp.zeros((770, 128)), jnp.zeros(128))
