"""Serving-under-failure tests — deadlines, shedding, chaos recovery.

The acceptance gates of docs/SERVING.md "Serving under failure":

- **deadline expiry** aborts a running sequence at a decode-step
  boundary KEEPING its partial output, and drops a queued request
  without ever admitting it; **cancel(rid)** does the same on demand,
  releasing every KV block exactly once (the pool drains to zero);
- **admission control** sheds past the depth backstop and past the
  projected-queue-wait gate with a terminal ``shed`` record per rid —
  under a FaultPlan request storm the admitted requests' queue wait
  stays bounded instead of collapsing with everyone else's;
- **in-flight recovery**: an injected decode-dispatch fault heals
  through retry (transient) or rebuild + replay (persistent) and the
  surviving requests finish token-identical to the fault-free run;
- the **degradation ladder** climbs spec-off → gather attention →
  halved batch cap and never past rung 3;
- ``run_until_complete(timeout_sec=...)`` raises loudly with queue
  diagnostics when the loop wedges (injected slow-step fault);
- the **zero-overhead off-contract**: with ``serving.resilience`` off
  the emitted tag set is byte-identical to the resilience-free engine
  and the loop performs zero device syncs;
- **terminal completeness** end to end through ``init_serving``: every
  submitted rid — finished, shed, cancelled in queue, or torn down
  with the engine — reaches ``results[rid]`` AND a ``requests.jsonl``
  record with its terminal status.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import ServingConfig
from deepspeed_tpu.models import make_gpt
from deepspeed_tpu.resilience import FaultPlan
from deepspeed_tpu.serving import TERMINAL_STATUSES, ServeEngine
from deepspeed_tpu.telemetry import (InMemorySink, MetricsRegistry,
                                     RecompileDetector, StepTracer,
                                     Telemetry)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The resilience-free engine's emitted tag set on a simple trace —
# identical to test_serving_slo.BASELINE_SIMPLE_TAGS; the off-contract
# pins it EXACTLY, so the resilience rows can never leak into it.
BASELINE_SIMPLE_TAGS = {
    "serving/ttft_ms", "serving/batch_occupancy",
    "serving/kv_blocks_in_use", "serving/queue_depth",
    "serving/tokens_per_sec", "serving/requests_completed",
}
RESIL_TAGS = {
    "serving/shed_requests", "serving/deadline_expired",
    "serving/cancelled", "serving/recoveries", "serving/retries",
    "serving/degraded_level",
}


@pytest.fixture(scope="module")
def gpt_setup():
    # fp32 like test_serving.py: argmax tie-flips are noise at bf16.
    model, cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=64,
                          dtype=jnp.float32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    return model, cfg, params


def _serve(model, params, fault=None, telemetry=None, **overrides):
    scfg = ServingConfig(**{
        "max_batch_size": 2, "kv_block_size": 4, "kv_num_blocks": 64,
        "max_model_len": 48, **overrides})
    eng = deepspeed_tpu.init_inference(model, params=params,
                                       dtype=jnp.float32)
    plan = FaultPlan.resolve(fault) if fault else None
    return ServeEngine(eng, config=scfg, telemetry=telemetry,
                       fault_plan=plan)


def _mem_telemetry():
    reg = MetricsRegistry()
    sink = reg.add_sink(InMemorySink())
    tracer = StepTracer(path=None, enabled=False, sync_spans=False)
    return Telemetry(reg, tracer, RecompileDetector(enabled=False)), sink


def _prompts(cfg, n=3, seed=17):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab_size, (4 + i,)).tolist()
            for i in range(n)]


# ---------------------------------------------------------------------------
# Deadlines + cancellation
# ---------------------------------------------------------------------------

class TestDeadlinesAndCancel:
    def test_deadline_expiry_keeps_partial_output(self, gpt_setup):
        """A running sequence whose deadline passes is aborted at the
        next step boundary with its partial output in the terminal
        record, and every KV block it held is released."""
        model, cfg, params = gpt_setup
        srv = _serve(model, params, resilience=True)
        prompt = _prompts(cfg, n=1)[0]
        rid = srv.submit(prompt, 30, deadline_ms=60_000.0)
        # the stamp is absolute: arrival + deadline
        req = srv.sched.waiting[0]
        assert req.deadline == pytest.approx(req.arrival + 60.0, abs=1e-6)
        for _ in range(3):
            srv.step()
        seq = next(iter(srv.sched.running.values()))
        n_partial = len(seq.tokens)
        assert n_partial > len(prompt)          # generated something
        seq.request.deadline = time.monotonic() - 1.0   # force expiry
        srv.step()
        rec = srv.results[rid]
        assert rec["status"] == "deadline_expired"
        assert len(prompt) < len(rec["tokens"]) < len(prompt) + 1 + 30
        assert rec["tokens"][:len(prompt)] == prompt
        assert srv._resil.counters["deadline_expired"] == 1
        assert srv.pool.used_blocks == 0        # released exactly once
        assert srv.idle()

    def test_queued_deadline_drops_without_admission(self, gpt_setup):
        """A request that expires while still queued terminates without
        ever taking a slot: tokens == prompt, no queue-wait stamp."""
        model, cfg, params = gpt_setup
        srv = _serve(model, params, resilience=True)
        p = _prompts(cfg, n=3)
        r0 = srv.submit(p[0], 12)
        r1 = srv.submit(p[1], 12)
        r2 = srv.submit(p[2], 12, deadline_ms=0.5)
        time.sleep(0.01)                        # let the 0.5ms pass
        res = srv.run_until_complete(timeout_sec=120.0)
        assert res[r2]["status"] == "deadline_expired"
        assert res[r2]["tokens"] == p[2]
        assert res[r2]["queue_wait_ms"] is None
        assert res[r0]["status"] == res[r1]["status"] == "finished"
        assert srv.pool.used_blocks == 0

    def test_cancel_releases_blocks_exactly_once(self, gpt_setup):
        """cancel(rid) on a RUNNING sequence resolves at the next step
        boundary: partial output kept, blocks freed (the BlockPool
        refcounts raise on a double free, so draining to zero is the
        structural leak check), and the other request is undisturbed."""
        model, cfg, params = gpt_setup
        srv = _serve(model, params, resilience=True)
        p = _prompts(cfg, n=2)
        r0 = srv.submit(p[0], 20)
        r1 = srv.submit(p[1], 6)
        for _ in range(3):
            srv.step()
        assert srv.cancel(r0)
        assert not srv.cancel(r0 + 999)         # unknown rid
        res = srv.run_until_complete(timeout_sec=120.0)
        assert res[r0]["status"] == "cancelled"
        assert len(p[0]) < len(res[r0]["tokens"]) < len(p[0]) + 1 + 20
        assert res[r1]["status"] == "finished"
        assert srv._resil.counters["cancelled"] == 1
        assert srv.pool.used_blocks == 0
        assert not srv.cancel(r1)               # already terminal

    def test_cancel_in_queue_and_off_wall(self, gpt_setup):
        """A queued rid cancels without admission; cancel() without the
        resilience layer is a loud error, not a silent no-op."""
        model, cfg, params = gpt_setup
        srv = _serve(model, params, resilience=True, max_batch_size=2)
        p = _prompts(cfg, n=3)
        rids = [srv.submit(pp, 8) for pp in p]
        assert srv.cancel(rids[2])              # still queued (2 slots)
        res = srv.run_until_complete(timeout_sec=120.0)
        assert res[rids[2]]["status"] == "cancelled"
        assert res[rids[2]]["tokens"] == p[2]
        assert {res[r]["status"] for r in rids[:2]} == {"finished"}

        off = _serve(model, params)
        with pytest.raises(RuntimeError, match="resilience"):
            off.cancel(0)


# ---------------------------------------------------------------------------
# Admission control + load shedding
# ---------------------------------------------------------------------------

class TestAdmissionControl:
    def test_depth_backstop_sheds_with_terminal_records(self, gpt_setup):
        """Past max_queue_depth every submit returns a real rid whose
        terminal ``shed`` record (with the gate's reason) is already in
        results — and the admitted work all finishes."""
        model, cfg, params = gpt_setup
        srv = _serve(model, params, resilience=True,
                     resil_max_queue_depth=2)
        rng = np.random.default_rng(3)
        rids = [srv.submit(rng.integers(0, cfg.vocab_size, (5,)).tolist(),
                           6) for _ in range(8)]
        shed = [r for r in rids if r in srv.results]
        assert shed and len(shed) == srv._resil.counters["shed_requests"]
        for r in shed:
            assert srv.results[r]["status"] == "shed"
            assert "max_queue_depth" in srv.results[r]["shed_reason"]
        res = srv.run_until_complete(timeout_sec=120.0)
        assert set(res) == set(rids)            # every rid terminal
        assert all(res[r]["status"] in ("finished", "shed") for r in rids)
        assert [r for r in rids if res[r]["status"] == "finished"]

    def test_projected_wait_gate(self, gpt_setup):
        """With decode-rate evidence, a submission whose projected queue
        wait blows max_queue_wait_ms sheds on projection — the cold
        engine (no evidence) admits unconditionally."""
        model, cfg, params = gpt_setup
        srv = _serve(model, params, resilience=True,
                     resil_max_queue_wait_ms=0.01)
        p = _prompts(cfg, n=3)
        r0 = srv.submit(p[0], 8)                # cold: no rate evidence
        srv.run_until_complete(timeout_sec=120.0)
        assert srv.results[r0]["status"] == "finished"
        # now the engine has a measured decode rate: a queued 30-token
        # request projects far past the 0.01ms budget
        r1 = srv.submit(p[1], 30)
        r2 = srv.submit(p[2], 30)
        assert r2 in srv.results
        assert srv.results[r2]["status"] == "shed"
        assert "queue wait" in srv.results[r2]["shed_reason"]
        res = srv.run_until_complete(timeout_sec=120.0)
        assert res[r1]["status"] == "finished"

    def test_storm_shed_keeps_admitted_queue_wait_bounded(self, gpt_setup):
        """The headline property under a FaultPlan request storm: with
        shedding ON the admitted requests' worst queue wait is strictly
        below the no-shedding run's worst (where every storm duplicate
        queues up in front of someone)."""
        model, cfg, params = gpt_setup
        # the storm fires AFTER a warmup request has compiled every
        # program: queue waits then measure service time, not jit time
        storm = {"serve_storm_at_step": 10_000, "serve_storm_requests": 12}
        waits = {}
        for mode, overrides in (
                ("off", {}),
                ("on", {"resilience": True, "resil_max_queue_depth": 2})):
            srv = _serve(model, params, fault=storm, **overrides)
            rng = np.random.default_rng(11)
            warm = srv.submit(
                rng.integers(0, cfg.vocab_size, (6,)).tolist(), 4)
            srv.run_until_complete(timeout_sec=120.0)
            # fire the storm 4 steps into the measured trace — while
            # the first batch decodes and the second is still queued
            srv._fault.serve_storm_at_step = srv._step_count + 4
            for _ in range(4):
                srv.submit(rng.integers(0, cfg.vocab_size, (6,)).tolist(),
                           8)
            res = srv.run_until_complete(timeout_sec=120.0)
            del res[warm]
            assert len(res) == 4 + 12
            waits[mode] = [r["queue_wait_ms"] for r in res.values()
                           if r["status"] == "finished"
                           and r["queue_wait_ms"] is not None]
            if mode == "on":
                n_shed = sum(1 for r in res.values()
                             if r["status"] == "shed")
                assert n_shed > 0
                assert all(r["status"] in ("finished", "shed")
                           for r in res.values())
            else:
                assert all(r["status"] == "finished"
                           for r in res.values())
        # 12 duplicates over a depth-2 queue vs an unbounded one: the
        # margin is an order of magnitude, not a timing coin flip
        assert max(waits["on"]) < max(waits["off"])


# ---------------------------------------------------------------------------
# In-flight recovery + degradation ladder
# ---------------------------------------------------------------------------

class TestRecovery:
    def test_fault_retry_and_rebuild_are_token_identical(self, gpt_setup):
        """The chaos e2e: a transient decode-dispatch fault heals inside
        the retry budget (no rebuild); a persistent window exhausts it
        and forces rebuild + replay — both finish every request with
        output identical to the fault-free run."""
        model, cfg, params = gpt_setup
        p = _prompts(cfg, n=3)
        outs = [10, 6, 8]

        def run(fault):
            srv = _serve(model, params, fault=fault, resilience=True,
                         resil_retry_base_sec=0.01)
            rids = [srv.submit(pp, n) for pp, n in zip(p, outs)]
            res = srv.run_until_complete(timeout_sec=120.0)
            return [res[r]["tokens"] for r in rids], srv._resil.counters

        base, _ = run(None)
        transient, c1 = run({"serve_decode_fault_at_step": 3})
        assert transient == base
        assert c1["retries"] >= 1 and c1["recoveries"] == 0
        persistent, c2 = run({"serve_decode_fault_at_step": 3,
                              "serve_decode_fault_count": 3})
        assert persistent == base
        assert c2["recoveries"] >= 1

    def test_fault_without_resilience_crashes_the_loop(self, gpt_setup):
        """The motivating failure: the same injected fault with the
        resilience layer off propagates out of step()."""
        model, cfg, params = gpt_setup
        srv = _serve(model, params,
                     fault={"serve_decode_fault_at_step": 1})
        srv.submit(_prompts(cfg, n=1)[0], 8)
        with pytest.raises(RuntimeError, match="injected serving"):
            srv.run_until_complete(timeout_sec=120.0)

    def test_degradation_ladder(self, gpt_setup):
        """Anomalies climb spec-off -> gather attention -> halved batch
        cap, one rung per degrade_after, capped at 3."""
        model, cfg, params = gpt_setup
        srv = _serve(model, params, resilience=True,
                     resil_degrade_after=2, spec_decode=True, spec_k=2)
        resil = srv._resil
        assert srv._spec_k == 2
        resil.note_anomaly()
        assert resil.degraded_level == 0
        resil.note_anomaly()
        assert resil.degraded_level == 1 and srv._spec_k == 0
        resil.note_anomaly()
        resil.note_anomaly()
        assert resil.degraded_level == 2 and srv._attn_impl == "gather"
        resil.note_anomaly()
        resil.note_anomaly()
        assert resil.degraded_level == 3
        assert srv.sched.slot_cap == 1          # max_batch_size 2 halved
        for _ in range(6):                      # rungs never un-climb,
            resil.note_anomaly()                # never past 3
        assert resil.degraded_level == 3
        # the capped engine still serves correctly (slots padding-masked)
        rids = [srv.submit(pp, 5) for pp in _prompts(cfg, n=2)]
        res = srv.run_until_complete(timeout_sec=120.0)
        assert all(res[r]["status"] == "finished" for r in rids)


# ---------------------------------------------------------------------------
# Wedged-loop wall clock
# ---------------------------------------------------------------------------

class TestWedgeTimeout:
    def test_run_until_complete_timeout_raises_with_diagnostics(
            self, gpt_setup):
        """Regression for the wall-clock knob: an injected slow-step
        wedge makes the loop blow timeout_sec and the error names the
        queue state instead of spinning toward max_steps."""
        model, cfg, params = gpt_setup
        srv = _serve(model, params,
                     fault={"serve_slow_step_at_step": 0,
                            "serve_slow_step_seconds": 0.4,
                            "serve_slow_step_count": 100_000})
        srv.submit(_prompts(cfg, n=1)[0], 30)
        with pytest.raises(RuntimeError,
                           match="wall-clock timeout") as exc:
            srv.run_until_complete(timeout_sec=0.3)
        assert "running=" in str(exc.value)
        assert "queue=" in str(exc.value)


# ---------------------------------------------------------------------------
# Zero-overhead off-contract
# ---------------------------------------------------------------------------

class TestOffContract:
    def test_off_tag_set_and_sync_count_unchanged(self, gpt_setup,
                                                  monkeypatch):
        """serving.resilience off: no manager, no fault hook state, the
        emitted tag set byte-identical to the resilience-free engine,
        zero device syncs in the loop."""
        model, cfg, params = gpt_setup
        tel, sink = _mem_telemetry()
        srv = _serve(model, params, telemetry=tel)
        assert srv._resil is None and srv._fault is None
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        for pp in _prompts(cfg, n=3):
            srv.submit(pp, 6)
        srv.run_until_complete(timeout_sec=120.0)
        assert calls["n"] == 0
        assert sink.tags() == BASELINE_SIMPLE_TAGS
        assert not (sink.tags() & RESIL_TAGS)
        # the one-decode-program contract still holds verbatim
        det = srv.engine.recompile_detector
        assert det.compiles("serving.decode_step") == 1
        assert det.retraces("serving.decode_step") == 0

    def test_on_emits_the_resilience_rows(self, gpt_setup):
        """With the layer on, degraded_level is always present and the
        transition counters appear exactly when their event fires."""
        model, cfg, params = gpt_setup
        tel, sink = _mem_telemetry()
        srv = _serve(model, params, telemetry=tel, resilience=True,
                     resil_max_queue_depth=1)
        rng = np.random.default_rng(9)
        for _ in range(5):
            srv.submit(rng.integers(0, cfg.vocab_size, (5,)).tolist(), 6)
        srv.run_until_complete(timeout_sec=120.0)
        tags = sink.tags()
        assert {"serving/degraded_level",
                "serving/shed_requests"} <= tags
        assert BASELINE_SIMPLE_TAGS <= tags


# ---------------------------------------------------------------------------
# Terminal completeness end to end (init_serving + requests.jsonl)
# ---------------------------------------------------------------------------

class TestTerminalCompleteness:
    def test_every_rid_terminal_in_results_and_jsonl(self, gpt_setup,
                                                     tmp_path):
        """Finished, shed, cancelled-in-queue and torn-down requests ALL
        land in results AND requests.jsonl with a terminal status;
        percentile-bearing fields exist only on admitted records."""
        model, cfg, params = gpt_setup
        srv = deepspeed_tpu.init_serving(
            model, params=params, dtype=jnp.float32,
            config={
                "serving": {"max_batch_size": 2, "kv_block_size": 4,
                            "kv_num_blocks": 64, "max_model_len": 48,
                            "resilience": {"max_queue_depth": 3}},
                "telemetry": {"enabled": True, "dir": str(tmp_path),
                              "requests": {"enabled": True}}})
        rng = np.random.default_rng(23)
        r_fin = srv.submit(rng.integers(0, cfg.vocab_size, (5,)).tolist(),
                           6)
        srv.run_until_complete(timeout_sec=120.0)
        burst = [srv.submit(rng.integers(0, cfg.vocab_size,
                                         (5,)).tolist(), 20)
                 for _ in range(6)]
        rids = [r_fin] + burst
        shed = [r for r in burst if r in srv.results]
        live = [r for r in burst if r not in srv.results]
        assert shed and len(live) == 3
        assert srv.cancel(live[-1])             # still queued (2 slots)
        srv.step()
        srv.step()
        srv.close()                             # tears down in-flight
        assert set(srv.results) == set(rids)
        statuses = {r: srv.results[r]["status"] for r in rids}
        assert set(statuses.values()) <= set(TERMINAL_STATUSES)
        assert statuses[r_fin] == "finished"
        assert statuses[live[-1]] == "cancelled"
        assert all(statuses[r] == "shed" for r in shed)
        assert "aborted" in statuses.values()

        with open(os.path.join(str(tmp_path), "requests.jsonl")) as f:
            records = [json.loads(line) for line in f if line.strip()]
        assert len(records) == len(rids)
        by_status = {}
        for rec in records:
            by_status.setdefault(rec["status"], []).append(rec)
            if not rec["admitted"]:
                assert rec["new_tokens"] == 0
                assert rec["ttft_ms"] is None
        assert set(by_status) == set(statuses.values())
        assert len(by_status["shed"]) == len(shed)


# ---------------------------------------------------------------------------
# Probe CLI (tier-1 hook)
# ---------------------------------------------------------------------------

def test_probe_serving_resilience_selftest():
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "probe_serving_resilience.py"),
         "--selftest"], capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr + proc.stdout
    assert "selftest ok" in proc.stdout
    assert "token-identical" in proc.stdout
    assert "load shedding" in proc.stdout
