"""Tiny model fixtures (analogue of the reference tests/unit/simple_model.py)."""

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np


def mlp_params(key=0, hidden: int = 16, layers: int = 2, out: int = 8) -> Dict:
    """A small MLP param tree with dims divisible by 8 (test mesh size)."""
    rng = np.random.default_rng(key)
    params = {}
    dim_in = hidden
    for i in range(layers):
        params[f"layer_{i}"] = {
            "w": rng.standard_normal((dim_in, hidden)).astype(np.float32) * 0.1,
            "b": np.zeros((hidden,), np.float32),
        }
        dim_in = hidden
    params["head"] = {
        "w": rng.standard_normal((hidden, out)).astype(np.float32) * 0.1,
        "b": np.zeros((out,), np.float32),
    }
    return params


def mlp_loss_fn(params, batch, rng):
    """MSE regression loss; batch = {'x': [B, H], 'y': [B, O]}."""
    h = batch["x"]
    i = 0
    while f"layer_{i}" in params:
        layer = params[f"layer_{i}"]
        h = jnp.tanh(h @ layer["w"] + layer["b"])
        i += 1
    pred = h @ params["head"]["w"] + params["head"]["b"]
    return jnp.mean(jnp.square(pred - batch["y"]))


def random_batch(rng, batch_size: int = 8, hidden: int = 16, out: int = 8):
    return {
        "x": rng.standard_normal((batch_size, hidden)).astype(np.float32),
        "y": rng.standard_normal((batch_size, out)).astype(np.float32),
    }


def random_batches(rng, gas: int, batch_size: int = 8, hidden: int = 16, out: int = 8):
    """Stacked micro-batches with leading GAS dim (train_batch path)."""
    return {
        "x": rng.standard_normal((gas, batch_size, hidden)).astype(np.float32),
        "y": rng.standard_normal((gas, batch_size, out)).astype(np.float32),
    }
