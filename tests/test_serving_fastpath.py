"""Decode fast path tests — paged decode-attention kernel, prefix-cache
reuse, speculative decoding (docs/SERVING.md "Decode fast path").

The acceptance gates:

- the Pallas kernel (interpret path) is **parity-exact within fp32
  rounding** against the gather+masked-attention reference — including
  partial last blocks, scrambled block tables and all-scratch (block 0)
  inactive rows — and within RTNE tolerance for int8 pools (dequantized
  in-kernel);
- every fast-path configuration (kernel, capped gather, prefix cache,
  speculative, all together) produces outputs **token-identical** to the
  fully-off engine on a mixed continuous-batching trace;
- prefix COW survives youngest-first preemption (the evicted request
  re-admits warm and still finishes with correct tokens), and refcounts
  leak nothing: after ``run_until_complete`` the pool holds exactly the
  cache's blocks, and zero after a cache clear (or immediately, with the
  cache off);
- speculative decode is token-identical to greedy by construction and
  emits its accept-rate evidence;
- fast path fully off ⇒ the decode program's lowering is bit-identical
  to the pre-fast-path (PR 8) program, reconstructed here from the same
  public pieces (jaxpr pin), and no fast-path tags are emitted.
"""

import functools
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import ConfigError, ServingConfig
from deepspeed_tpu.models import make_gpt
from deepspeed_tpu.ops.transformer.attention import xla_attention
from deepspeed_tpu.ops.transformer.paged_attention import (
    paged_decode_attention, paged_decode_ok)
from deepspeed_tpu.serving import PagedLayerCache, ServeEngine
from deepspeed_tpu.serving.kv_cache import _quant_tokens, init_paged_pools
from deepspeed_tpu.telemetry import (InMemorySink, MetricsRegistry,
                                     RecompileDetector, StepTracer,
                                     Telemetry)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def gpt_setup():
    # fp32 like tests/test_serving.py: the parity oracles compare
    # numerically-different-but-equivalent paths whose bf16 argmax
    # tie-flips are noise, not bugs.
    model, cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=64,
                          dtype=jnp.float32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    return model, cfg, params


def _serve(model, params, telemetry=None, **overrides):
    scfg = ServingConfig(**{
        "max_batch_size": 2, "kv_block_size": 4, "kv_num_blocks": 64,
        "max_model_len": 48, **overrides})
    eng = deepspeed_tpu.init_inference(model, params=params,
                                       dtype=jnp.float32)
    return ServeEngine(eng, config=scfg, telemetry=telemetry)


def _mem_telemetry():
    reg = MetricsRegistry()
    sink = reg.add_sink(InMemorySink())
    tracer = StepTracer(path=None, enabled=False)
    return Telemetry(reg, tracer, RecompileDetector(enabled=False)), sink


TRACE = [(5, 12), (9, 3), (3, 10), (12, 4), (7, 8)]


def _run_trace(srv, cfg, seed=7):
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, cfg.vocab_size, (t,)).tolist()
               for t, _ in TRACE]
    rids = [srv.submit(p, n) for p, (_, n) in zip(prompts, TRACE)]
    res = srv.run_until_complete()
    return prompts, [res[r]["tokens"] for r in rids]


# ---------------------------------------------------------------------------
# Kernel parity vs the gather path
# ---------------------------------------------------------------------------

class TestPagedKernelParity:
    """The kernel-vs-gather numerics rungs, on raw pools (no model):
    scrambled non-contiguous tables, partial last blocks (pos mid-block),
    and an all-scratch inactive row — the exact decode-batch shapes."""

    B, H, D, BS, N, MB = 3, 4, 16, 4, 12, 5

    def _fixture(self, int8, seed=0):
        rng = np.random.default_rng(seed)
        shape = (self.N, self.BS, self.H, self.D)
        k = jnp.asarray(rng.normal(size=shape), jnp.float32)
        v = jnp.asarray(rng.normal(size=shape), jnp.float32)
        bt = np.zeros((self.B, self.MB), np.int32)
        bt[0, :3] = [3, 7, 2]            # scrambled, non-contiguous
        bt[1, :2] = [5, 1]
        # row 2 stays all-zeros: an inactive slot pointing at scratch
        pos = jnp.asarray([9, 5, 0], jnp.int32)   # 9, 5: partial blocks
        if int8:
            kq, ks = _quant_tokens(k)
            vq, vs = _quant_tokens(v)
            return kq, vq, ks, vs, jnp.asarray(bt), pos
        return k, v, None, None, jnp.asarray(bt), pos

    def _reference(self, q, k, v, ks, vs, bt, pos):
        lc = PagedLayerCache(k, v, ks, vs, bt, pos, self.BS, "float32")
        kk, vv = lc._gather(k, ks), lc._gather(v, vs)
        s = q.shape[1]
        qpos = pos[:, None] + jnp.arange(s)[None, :]
        kpos = jnp.arange(lc.key_len)
        mask = (kpos[None, None, :] <= qpos[:, :, None])[:, None]
        return xla_attention(q, kk, vv, causal=False, mask=mask)

    @pytest.mark.parametrize("s", [1, 4])
    def test_fp32_parity(self, s):
        k, v, ks, vs, bt, pos = self._fixture(int8=False)
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(self.B, s, self.H, self.D)),
                        jnp.float32)
        want = self._reference(q, k, v, ks, vs, bt, pos)
        got = paged_decode_attention(q, k, v, ks, vs, bt, pos,
                                     block_size=self.BS)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-6, rtol=2e-6)

    def test_int8_in_kernel_dequant_parity(self):
        """int8 pools: the in-kernel dequant must agree with the gather
        path's dequantized copy within fp32 rounding (the dequantized
        values are identical by construction — only summation order
        differs)."""
        k, v, ks, vs, bt, pos = self._fixture(int8=True)
        rng = np.random.default_rng(2)
        q = jnp.asarray(rng.normal(size=(self.B, 1, self.H, self.D)),
                        jnp.float32)
        want = self._reference(q, k, v, ks, vs, bt, pos)
        got = paged_decode_attention(q, k, v, ks, vs, bt, pos,
                                     block_size=self.BS)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-6, rtol=2e-6)

    def test_update_attend_matches_update_plus_attention(self):
        """The cache-level fast path (write + kernel) against the
        cache-level slow path (write + gather + masked attention)."""
        k, v, ks, vs, bt, pos = self._fixture(int8=False)
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.normal(size=(self.B, 1, self.H, self.D)),
                        jnp.float32)
        knew = jnp.asarray(rng.normal(size=(self.B, 1, self.H, self.D)),
                           jnp.float32)
        vnew = jnp.asarray(rng.normal(size=(self.B, 1, self.H, self.D)),
                           jnp.float32)
        slow = PagedLayerCache(k, v, ks, vs, bt, pos, self.BS, "float32")
        new_s, kk, vv, mask = slow.update(knew, vnew)
        want = xla_attention(q, kk, vv, causal=False, mask=mask)
        fast = PagedLayerCache(k, v, ks, vs, bt, pos, self.BS, "float32",
                               attn_impl="kernel")
        new_f, got = fast.update_attend(q, knew, vnew)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-6, rtol=2e-6)
        np.testing.assert_array_equal(np.asarray(new_f.k),
                                      np.asarray(new_s.k))

    def test_dispatch_gate(self):
        assert paged_decode_ok(128, 16)
        assert paged_decode_ok(256, 8)
        assert not paged_decode_ok(64, 16)      # head_dim not 128-aligned
        assert not paged_decode_ok(128, 5)      # block not 8-aligned


# ---------------------------------------------------------------------------
# Engine-level token identity + window capping
# ---------------------------------------------------------------------------

class TestFastPathTokenIdentity:
    def test_every_configuration_matches_off(self, gpt_setup):
        model, cfg, params = gpt_setup
        srv_off = _serve(model, params)
        _, base = _run_trace(srv_off, cfg)
        for over in ({"decode_attention": "kernel"},
                     {"decode_attention": "auto"},
                     {"prefix_cache": True},
                     {"spec_decode": True, "spec_k": 3},
                     {"decode_attention": "kernel", "prefix_cache": True,
                      "spec_decode": True, "spec_k": 3}):
            srv = _serve(model, params, **over)
            _, got = _run_trace(srv, cfg)
            assert got == base, over

    def test_capped_gather_shrinks_window(self, gpt_setup):
        """The capped-fallback satellite: under auto (no TPU -> capped
        gather) the decode key window tracks the max ACTIVE length, so
        the modeled gathered positions drop well below the full-window
        program's on the same trace."""
        model, cfg, params = gpt_setup
        srv_off = _serve(model, params)
        _run_trace(srv_off, cfg)
        srv = _serve(model, params, decode_attention="auto")
        _run_trace(srv, cfg)
        assert srv.stats["full_positions"] == \
            srv_off.stats["gathered_positions"]
        assert srv.stats["gathered_positions"] < \
            0.7 * srv.stats["full_positions"]
        # each window bucket is its own expected-first-compile scope —
        # no retraces under any of them
        det = srv.engine.recompile_detector
        scopes = [f for f in det.stats
                  if f.startswith("serving.decode_step_w")]
        assert scopes, det.stats
        for f in scopes:
            assert det.compiles(f) == 1 and det.retraces(f) == 0

    def test_kernel_gauge_emitted(self, gpt_setup):
        model, cfg, params = gpt_setup
        tel, sink = _mem_telemetry()
        srv = _serve(model, params, telemetry=tel,
                     decode_attention="kernel")
        _run_trace(srv, cfg)
        vals = sink.values("serving/decode_attn_kernel")
        assert vals and all(v == 1.0 for v in vals)
        assert srv.stats["kernel_steps"] == srv.stats["decode_steps"]


# ---------------------------------------------------------------------------
# Prefix-cache reuse
# ---------------------------------------------------------------------------

class TestPrefixCache:
    def test_shared_head_hits_and_identity(self, gpt_setup):
        """A shared-head workload: later requests adopt the head blocks
        (hit counters move), prefill only their tail, and outputs stay
        token-identical to one-shot generate()."""
        model, cfg, params = gpt_setup
        rng = np.random.default_rng(11)
        head = rng.integers(0, cfg.vocab_size, (16,)).tolist()
        prompts = [head + rng.integers(0, cfg.vocab_size, (3,)).tolist()
                   for _ in range(4)]
        tel, sink = _mem_telemetry()
        srv = _serve(model, params, telemetry=tel, prefix_cache=True)
        rids = [srv.submit(p, 6) for p in prompts]
        res = srv.run_until_complete()
        assert srv.prefix_cache.hits >= 3
        assert srv.prefix_cache.blocks_reused >= 9     # 4-block head x 3
        assert sink.values("serving/prefix_hits")
        for rid, p in zip(rids, prompts):
            want = np.asarray(srv.engine.generate(
                np.asarray([p], np.int32), max_new_tokens=6))[0]
            assert res[rid]["tokens"] == want.tolist()

    def test_cow_survives_preemption_and_restart_identity(self, gpt_setup):
        """Youngest-first preemption releases the victim's references but
        the cache keeps the prompt-head blocks alive: the evicted request
        re-admits WARM (hits grow) and still finishes token-identical."""
        model, cfg, params = gpt_setup
        rng = np.random.default_rng(5)
        head = rng.integers(0, cfg.vocab_size, (8,)).tolist()
        p0 = head + rng.integers(0, cfg.vocab_size, (3,)).tolist()
        p1 = head + rng.integers(0, cfg.vocab_size, (2,)).tolist()
        # capacity 11: the two runs need 8 + 7 - 2 shared = 13 blocks at
        # their peaks, so the younger must be evicted mid-flight (sharing
        # alone cannot absorb the pressure)
        srv = _serve(model, params, prefix_cache=True, kv_num_blocks=12,
                     max_model_len=32)
        r0 = srv.submit(p0, 20)
        r1 = srv.submit(p1, 18)
        res = srv.run_until_complete()
        assert srv.sched.preempted_total >= 1
        hits_after = srv.prefix_cache.hits
        assert hits_after >= 2     # p1's admission + its warm re-admission
        for rid, p, n in ((r0, p0, 20), (r1, p1, 18)):
            want = np.asarray(srv.engine.generate(
                np.asarray([p], np.int32), max_new_tokens=n))[0]
            assert res[rid]["tokens"] == want.tolist()

    def test_refcount_leak_check(self, gpt_setup):
        """After run_until_complete: with the cache off the pool is
        empty; with it on, exactly the cache's nodes hold blocks and a
        clear() drains the pool to zero (no leaked references)."""
        model, cfg, params = gpt_setup
        srv = _serve(model, params)
        _run_trace(srv, cfg)
        assert srv.pool.used_blocks == 0
        srv = _serve(model, params, prefix_cache=True)
        _run_trace(srv, cfg)
        assert srv.pool.used_blocks == srv.prefix_cache.nodes
        srv.prefix_cache.clear()
        assert srv.pool.used_blocks == 0
        assert srv.pool.free_blocks == srv.pool.capacity

    def test_pool_pressure_evicts_cache_before_sequences(self, gpt_setup):
        """Cold cache entries yield: a full-pool admission evicts LRU
        leaves instead of failing (or preempting a running row)."""
        model, cfg, params = gpt_setup
        rng = np.random.default_rng(13)
        srv = _serve(model, params, prefix_cache=True, kv_num_blocks=14,
                     max_model_len=32)
        a = srv.submit(rng.integers(0, cfg.vocab_size, (10,)).tolist(), 4)
        srv.run_until_complete()
        nodes_before = srv.prefix_cache.nodes
        assert nodes_before > 0
        b = srv.submit(rng.integers(0, cfg.vocab_size, (12,)).tolist(), 16)
        res = srv.run_until_complete()
        assert b in res and a in res
        assert srv.sched.preempted_total == 0


# ---------------------------------------------------------------------------
# Speculative decoding
# ---------------------------------------------------------------------------

class TestSpeculative:
    def test_greedy_identity_and_gauges(self, gpt_setup):
        model, cfg, params = gpt_setup
        tel, sink = _mem_telemetry()
        srv = _serve(model, params, telemetry=tel, spec_decode=True,
                     spec_k=3)
        prompts, got = _run_trace(srv, cfg)
        for p, (_, n), toks in zip(prompts, TRACE, got):
            want = np.asarray(srv.engine.generate(
                np.asarray([p], np.int32), max_new_tokens=n))[0]
            assert toks == want.tolist()
        assert srv.stats["spec_rounds"] > 0
        # k proposals per active row per round: at least one row active
        assert srv.stats["spec_proposed"] >= 3 * srv.stats["spec_rounds"]
        assert srv.stats["spec_accepted"] <= srv.stats["spec_proposed"]
        rates = sink.values("serving/spec_accept_rate")
        tpv = sink.values("serving/spec_tokens_per_verify")
        assert rates and 0.0 <= rates[-1] <= 1.0
        # every round appends at least one token per active row
        assert tpv and tpv[-1] >= 1.0

    def test_spec_respects_eos_and_max_tokens(self, gpt_setup):
        """Tokens accepted past EOS/max_new must be truncated exactly
        like greedy decode (finish checks run per appended token)."""
        model, cfg, params = gpt_setup
        rng = np.random.default_rng(9)
        prompt = rng.integers(0, cfg.vocab_size, (5,)).tolist()
        srv0 = _serve(model, params)
        rid0 = srv0.submit(prompt, 10)
        full = srv0.run_until_complete()[rid0]["tokens"]
        eos = full[len(prompt) + 4]
        srv = _serve(model, params, spec_decode=True, spec_k=4)
        rid = srv.submit(prompt, 10, eos_token_id=eos)
        got = srv.run_until_complete()[rid]["tokens"]
        srv0b = _serve(model, params)
        rid0b = srv0b.submit(prompt, 10, eos_token_id=eos)
        want = srv0b.run_until_complete()[rid0b]["tokens"]
        assert got == want

    def test_config_walls(self, gpt_setup):
        model, cfg, params = gpt_setup
        with pytest.raises(ConfigError, match="temperature"):
            ServingConfig.from_dict({"speculative": {"enabled": True},
                                     "temperature": 0.7})
        with pytest.raises(ConfigError, match="k must be"):
            ServingConfig.from_dict({"speculative": {"k": 0}})
        with pytest.raises(ConfigError, match="decode_attention"):
            ServingConfig.from_dict({"decode_attention": "warp"})
        with pytest.raises(ValueError, match="draft_layers"):
            _serve(model, params, spec_decode=True,
                   spec_draft_layers=cfg.num_layers)
        # capture_logits has no per-step row under spec — loud, not
        # silently empty
        srv = _serve(model, params, spec_decode=True, spec_k=2)
        srv.capture_logits = True
        srv.submit([1, 2, 3], 4)
        with pytest.raises(ValueError, match="capture_logits"):
            srv.run_until_complete()


# ---------------------------------------------------------------------------
# Off contract: bit-identical decode program, no fast-path tags
# ---------------------------------------------------------------------------

class TestOffContract:
    def test_decode_lowering_pinned_to_pr8_program(self, gpt_setup):
        """Jaxpr pin: with the fast path fully off, the engine's decode
        program lowers bit-identically to the pre-fast-path (PR 8)
        decode impl, reconstructed here from the same public pieces —
        full-window gather, no window slicing, no kernel, no clamps."""
        from deepspeed_tpu.inference.engine import sample_logits

        model, cfg, params = gpt_setup
        srv = _serve(model, params)
        nb, mb = srv.scfg.max_batch_size, srv.max_blocks
        bt = jnp.zeros((nb, mb), jnp.int32)
        pos = jnp.zeros((nb,), jnp.int32)
        toks = jnp.zeros((nb,), jnp.int32)
        rng = jax.random.fold_in(srv._base_key, 0)
        args = (srv.engine.params, srv._pools, bt, pos, toks, rng)

        def pr8_decode_impl(params, pools, bt, pos, toks, rng):
            cache = tuple(
                PagedLayerCache(*pools[i], bt, pos, srv.block_size,
                                srv._dtype_name)
                for i in range(cfg.num_layers))
            out = srv.module.apply(
                {"params": srv.engine._materialized(params)},
                {"input_ids": toks[:, None], "position_ids": pos[:, None]},
                deterministic=True, cache=cache, pos=None)
            logits = out["logits"][:, -1].astype(jnp.float32)
            tok = sample_logits(logits, rng, srv.scfg.temperature,
                                srv.scfg.top_k)
            return tok, logits, tuple(c.pools for c in out["cache"])

        import re

        def canon(text):
            # the module carries the python function's name — the only
            # legitimate difference between the two lowerings
            return re.sub(r"module @\S+", "module @m", text)

        ours = jax.jit(functools.partial(srv._decode_impl,
                                         attn_impl="gather"),
                       donate_argnums=(1,)).lower(*args).as_text()
        pr8 = jax.jit(pr8_decode_impl,
                      donate_argnums=(1,)).lower(*args).as_text()
        assert canon(ours) == canon(pr8)

    def test_off_emits_no_fastpath_tags(self, gpt_setup):
        """A fully-off engine's emitted tag set is byte-identical to the
        pre-fast-path engine's."""
        model, cfg, params = gpt_setup
        tel, sink = _mem_telemetry()
        srv = _serve(model, params, telemetry=tel)
        _run_trace(srv, cfg)
        new_tags = {"serving/decode_attn_kernel", "serving/prefix_hits",
                    "serving/prefix_blocks_reused",
                    "serving/spec_accept_rate",
                    "serving/spec_tokens_per_verify"}
        assert not (sink.tags() & new_tags)
        # and the one-decode-program contract still holds verbatim
        det = srv.engine.recompile_detector
        assert det.compiles("serving.decode_step") == 1
        assert det.retraces("serving.decode_step") == 0


# ---------------------------------------------------------------------------
# Probe CLI (tier-1 hook)
# ---------------------------------------------------------------------------

def test_probe_serving_fastpath_selftest():
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "probe_serving_fastpath.py"),
         "--selftest"], capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr + proc.stdout
    assert "selftest ok" in proc.stdout
    assert "token identity" in proc.stdout
    assert "prefix reuse" in proc.stdout
