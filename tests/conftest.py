"""Test harness.

The reference's answer to "multi-node without a cluster" is forking N
processes over NCCL/Gloo on one host (tests/unit/common.py:16
@distributed_test). The TPU-native answer is simpler and faster: a single
process with a virtual 8-device CPU mesh
(--xla_force_host_platform_device_count), over which real NamedSharding /
collective lowering runs exactly as on a pod. Real-TPU tests can opt in via
DSTPU_TEST_TPU=1.
"""

import os

# Must happen before any backend initialisation. The axon sitecustomize
# imports jax at interpreter start, so env vars alone are too late — use
# jax.config.update, which works any time before first device use.
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

if os.environ.get("DSTPU_TEST_TPU", "0") != "1":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Real-chip parity runs (round-3 VERDICT task 9): Mosaic and XLA both
# execute fp32 matmuls as bf16 MXU passes but in different reduction
# orders, so kernel-vs-oracle comparisons land at bf16 scale (measured
# r4: max abs ~4e-3 on O(0.1) attention outputs) — far looser than the
# CPU interpreter, where both paths are exact fp32. The gate is
# bulk-tight / tail-tolerant: everything must sit within the bf16 floor
# EXCEPT up to 1% of elements, which may reach 0.1 abs (softmax-saturated
# rows and head_dim-128 reductions amplify tiny lse rounding; worst
# measured case dk at d=128 causal: 0.72% / 0.086). A mask/sign/logic
# regression flips tens of percent at O(1) magnitude and still fails both
# prongs. Scoped to the KERNEL-parity modules only (an autouse fixture
# below) so engine/optimizer/checkpoint assertions keep their exact
# tolerances on TPU runs too.
_TPU_PARITY_MODULES = ("tests.test_flash_attention",
                       "tests.test_sparse_attention", "tests.test_xent",
                       "tests.test_fused_ln",
                       "test_flash_attention", "test_sparse_attention",
                       "test_xent", "test_fused_ln")
_ORIG_ALLCLOSE = np.testing.assert_allclose


# Contiguous elements per tail-accounting window. Sized so legitimate
# per-ROW rounding tails pass (a softmax-saturated dk row at d=128 is 128
# contiguous bad elements = 1.6% of a window) while a corrupted kernel
# TILE (>= 128x128 = 16384 elements at ~100%) saturates whole windows.
_TAIL_BLOCK = 8192


def _tpu_allclose(actual, desired, rtol=1e-7, atol=0, **kw):
    rt, at = max(rtol, 2e-2), max(atol, 5e-3)
    try:
        return _ORIG_ALLCLOSE(actual, desired, rtol=rt, atol=at, **kw)
    except AssertionError:
        a = np.asarray(actual, np.float64)
        d = np.asarray(desired, np.float64)
        if a.shape != d.shape:
            raise
        err = np.abs(a - d)
        bad = err > (at + rt * np.abs(d))
        if bad.mean() > 0.01 or (bad.any() and err[bad].max() > 0.1):
            raise
        # Per-window tail accounting (round-4 VERDICT weak #7): the global
        # 1% allowance must be SCATTERED rounding noise, not one corrupted
        # kernel tile — a localized regression (e.g. a bad 128x128 block
        # in a 16k-seq layout) concentrates its errors in a contiguous
        # run, so also cap the bad fraction per _TAIL_BLOCK-element
        # window at 5% (a legitimate lse-rounding ROW at d=128 is 1.6%
        # of a window; a corrupted tile saturates windows). Limitation:
        # corruption STRIDED across many heads (64-element stripes every
        # h*d elements) dilutes below this cap — contiguous-window
        # accounting can't see row structure from a generic allclose.
        flat = bad.reshape(-1)
        pad = (-flat.size) % _TAIL_BLOCK
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, bool)])
        per_block = flat.reshape(-1, _TAIL_BLOCK).mean(axis=1)
        if per_block.max() > 0.05:
            raise AssertionError(
                f"clustered kernel-parity tail: block "
                f"{int(per_block.argmax())} has "
                f"{per_block.max():.1%} elements outside "
                f"rtol={rt}/atol={at} (global tail "
                f"{bad.mean():.3%} <= 1% but localized — likely a "
                f"corrupted kernel tile, not rounding)")
        return


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True)
def _tpu_kernel_parity_tolerance(request, monkeypatch):
    """See the bf16-floor note above: active only on DSTPU_TEST_TPU=1 runs
    and only inside the kernel-parity modules."""
    if (os.environ.get("DSTPU_TEST_TPU", "0") == "1"
            and request.module.__name__ in _TPU_PARITY_MODULES):
        monkeypatch.setattr(np.testing, "assert_allclose", _tpu_allclose)
    yield
