"""Test harness.

The reference's answer to "multi-node without a cluster" is forking N
processes over NCCL/Gloo on one host (tests/unit/common.py:16
@distributed_test). The TPU-native answer is simpler and faster: a single
process with a virtual 8-device CPU mesh
(--xla_force_host_platform_device_count), over which real NamedSharding /
collective lowering runs exactly as on a pod. Real-TPU tests can opt in via
DSTPU_TEST_TPU=1.
"""

import os

# Must happen before any backend initialisation. The axon sitecustomize
# imports jax at interpreter start, so env vars alone are too late — use
# jax.config.update, which works any time before first device use.
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

if os.environ.get("DSTPU_TEST_TPU", "0") != "1":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
