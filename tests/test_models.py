"""Model-family tests: forward shapes, loss finiteness, engine integration,
TP partition-rule coverage (the analogue of the reference's simple_model.py
fixtures + Megatron model tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import deepspeed_tpu
from deepspeed_tpu.models import (build_specs, bert_partition_rules,
                                  gpt_partition_rules, make_bert, make_gpt)


def _gpt_batch(rng, cfg, batch=4, seq=32):
    ids = rng.integers(0, cfg.vocab_size, (batch, seq), dtype=np.int32)
    return {"input_ids": ids}


def _bert_batch(rng, cfg, batch=4, seq=32):
    ids = rng.integers(0, cfg.vocab_size, (batch, seq), dtype=np.int32)
    labels = np.where(rng.random((batch, seq)) < 0.15, ids, -100).astype(np.int32)
    return {"input_ids": ids, "attention_mask": np.ones((batch, seq), np.int32),
            "labels": labels,
            "next_sentence_label": rng.integers(0, 2, (batch,), dtype=np.int32)}


class TestGPT:
    def test_forward_loss(self):
        model, cfg = make_gpt("tiny")
        rng = np.random.default_rng(0)
        batch = _gpt_batch(rng, cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)
        out = model.apply(variables, batch, deterministic=True)
        assert out["logits"].shape == (4, 32, cfg.vocab_size)
        assert np.isfinite(float(out["loss"]))
        # random init → loss ≈ ln(vocab)
        assert abs(float(out["loss"]) - np.log(cfg.vocab_size)) < 1.0

    def test_grads_finite(self):
        model, cfg = make_gpt("tiny")
        rng = np.random.default_rng(0)
        batch = _gpt_batch(rng, cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)

        def loss_fn(p):
            return model.apply({"params": p}, batch, deterministic=True)["loss"]

        grads = jax.grad(loss_fn)(variables["params"])
        leaves = jax.tree_util.tree_leaves(grads)
        assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
        # every param gets gradient signal somewhere
        nonzero = sum(float(np.abs(np.asarray(g)).sum()) > 0 for g in leaves)
        assert nonzero > len(leaves) * 0.8

    def test_remat_matches(self):
        model, cfg = make_gpt("tiny")
        model_r, _ = make_gpt("tiny", remat=True)
        rng = np.random.default_rng(0)
        batch = _gpt_batch(rng, cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)
        l0 = model.apply(variables, batch, deterministic=True)["loss"]
        l1 = model_r.apply(variables, batch, deterministic=True)["loss"]
        assert abs(float(l0) - float(l1)) < 1e-4

    def test_partition_rules_cover_params(self):
        model, cfg = make_gpt("tiny")
        batch = _gpt_batch(np.random.default_rng(0), cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)
        specs = build_specs(variables["params"], gpt_partition_rules(),
                            mesh_axes={"model": 2})
        leaves = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
        assert all(isinstance(s, PartitionSpec) for s in leaves)
        sharded = [s for s in leaves if any(d is not None for d in tuple(s))]
        assert len(sharded) >= cfg.num_layers * 4  # qkv/fc kernels+biases

    def test_mesh_axes_size1_drops_sharding(self):
        model, cfg = make_gpt("tiny")
        batch = _gpt_batch(np.random.default_rng(0), cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)
        specs = build_specs(variables["params"], gpt_partition_rules(),
                            mesh_axes={"model": 1})
        for s in jax.tree_util.tree_leaves(
                specs, is_leaf=lambda x: isinstance(x, PartitionSpec)):
            assert all(d is None for d in tuple(s))


class TestBert:
    def test_forward_loss_mlm_nsp(self):
        model, cfg = make_bert("tiny")
        rng = np.random.default_rng(0)
        batch = _bert_batch(rng, cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)
        out = model.apply(variables, batch, deterministic=True)
        assert out["logits"].shape == (4, 32, cfg.vocab_size)
        assert out["nsp_logits"].shape == (4, 2)
        assert np.isfinite(float(out["loss"]))

    def test_postln_variant(self):
        model, cfg = make_bert("tiny", pre_layer_norm=False)
        rng = np.random.default_rng(0)
        batch = _bert_batch(rng, cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)
        out = model.apply(variables, batch, deterministic=True)
        assert np.isfinite(float(out["loss"]))

    def test_partition_rules(self):
        model, cfg = make_bert("tiny")
        batch = _bert_batch(np.random.default_rng(0), cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)
        specs = build_specs(variables["params"], bert_partition_rules(),
                            mesh_axes={"model": 2})
        sharded = [s for s in jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
            if any(d is not None for d in tuple(s))]
        assert len(sharded) >= cfg.num_layers * 4


class TestEngineIntegration:
    @pytest.mark.parametrize("zero_stage", [0, 2])
    def test_gpt_trains_loss_decreases(self, zero_stage):
        model, cfg = make_gpt("tiny")
        rng = np.random.default_rng(0)
        batch = _gpt_batch(rng, cfg, batch=8, seq=32)
        ds_config = {
            "train_micro_batch_size_per_gpu": 8,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": zero_stage},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, config=ds_config,
            params=model.init({"params": jax.random.PRNGKey(0),
                               "dropout": jax.random.PRNGKey(1)}, batch)["params"])
        losses = []
        for _ in range(20):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0] - 0.5, losses
