"""Model-family tests: forward shapes, loss finiteness, engine integration,
TP partition-rule coverage (the analogue of the reference's simple_model.py
fixtures + Megatron model tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import deepspeed_tpu
from deepspeed_tpu.models import (build_specs, bert_partition_rules,
                                  gpt_partition_rules, make_bert, make_gpt)


def _gpt_batch(rng, cfg, batch=4, seq=32):
    ids = rng.integers(0, cfg.vocab_size, (batch, seq), dtype=np.int32)
    return {"input_ids": ids}


def _bert_batch(rng, cfg, batch=4, seq=32):
    ids = rng.integers(0, cfg.vocab_size, (batch, seq), dtype=np.int32)
    labels = np.where(rng.random((batch, seq)) < 0.15, ids, -100).astype(np.int32)
    return {"input_ids": ids, "attention_mask": np.ones((batch, seq), np.int32),
            "labels": labels,
            "next_sentence_label": rng.integers(0, 2, (batch,), dtype=np.int32)}


class TestGPT:
    def test_forward_loss(self):
        model, cfg = make_gpt("tiny")
        rng = np.random.default_rng(0)
        batch = _gpt_batch(rng, cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)
        out = model.apply(variables, batch, deterministic=True)
        assert out["logits"].shape == (4, 32, cfg.vocab_size)
        assert np.isfinite(float(out["loss"]))
        # random init → loss ≈ ln(vocab)
        assert abs(float(out["loss"]) - np.log(cfg.vocab_size)) < 1.0

    def test_grads_finite(self):
        model, cfg = make_gpt("tiny")
        rng = np.random.default_rng(0)
        batch = _gpt_batch(rng, cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)

        def loss_fn(p):
            return model.apply({"params": p}, batch, deterministic=True)["loss"]

        grads = jax.grad(loss_fn)(variables["params"])
        leaves = jax.tree_util.tree_leaves(grads)
        assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
        # every param gets gradient signal somewhere
        nonzero = sum(float(np.abs(np.asarray(g)).sum()) > 0 for g in leaves)
        assert nonzero > len(leaves) * 0.8

    def test_remat_matches(self):
        model, cfg = make_gpt("tiny")
        model_r, _ = make_gpt("tiny", remat=True)
        rng = np.random.default_rng(0)
        batch = _gpt_batch(rng, cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)
        l0 = model.apply(variables, batch, deterministic=True)["loss"]
        l1 = model_r.apply(variables, batch, deterministic=True)["loss"]
        assert abs(float(l0) - float(l1)) < 1e-4

    def test_partition_rules_cover_params(self):
        model, cfg = make_gpt("tiny")
        batch = _gpt_batch(np.random.default_rng(0), cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)
        specs = build_specs(variables["params"], gpt_partition_rules(),
                            mesh_axes={"model": 2})
        leaves = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
        assert all(isinstance(s, PartitionSpec) for s in leaves)
        sharded = [s for s in leaves if any(d is not None for d in tuple(s))]
        assert len(sharded) >= cfg.num_layers * 4  # qkv/fc kernels+biases

    def test_mesh_axes_size1_drops_sharding(self):
        model, cfg = make_gpt("tiny")
        batch = _gpt_batch(np.random.default_rng(0), cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)
        specs = build_specs(variables["params"], gpt_partition_rules(),
                            mesh_axes={"model": 1})
        for s in jax.tree_util.tree_leaves(
                specs, is_leaf=lambda x: isinstance(x, PartitionSpec)):
            assert all(d is None for d in tuple(s))


class TestBert:
    def test_forward_loss_mlm_nsp(self):
        model, cfg = make_bert("tiny")
        rng = np.random.default_rng(0)
        batch = _bert_batch(rng, cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)
        out = model.apply(variables, batch, deterministic=True)
        assert out["logits"].shape == (4, 32, cfg.vocab_size)
        assert out["nsp_logits"].shape == (4, 2)
        assert np.isfinite(float(out["loss"]))

    def test_postln_variant(self):
        model, cfg = make_bert("tiny", pre_layer_norm=False)
        rng = np.random.default_rng(0)
        batch = _bert_batch(rng, cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)
        out = model.apply(variables, batch, deterministic=True)
        assert np.isfinite(float(out["loss"]))

    def test_partition_rules(self):
        model, cfg = make_bert("tiny")
        batch = _bert_batch(np.random.default_rng(0), cfg)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)}, batch)
        specs = build_specs(variables["params"], bert_partition_rules(),
                            mesh_axes={"model": 2})
        sharded = [s for s in jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
            if any(d is not None for d in tuple(s))]
        assert len(sharded) >= cfg.num_layers * 4


class TestEngineIntegration:
    @pytest.mark.parametrize("zero_stage", [0, 2])
    def test_gpt_trains_loss_decreases(self, zero_stage):
        model, cfg = make_gpt("tiny")
        rng = np.random.default_rng(0)
        batch = _gpt_batch(rng, cfg, batch=8, seq=32)
        ds_config = {
            "train_micro_batch_size_per_gpu": 8,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": zero_stage},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, config=ds_config,
            params=model.init({"params": jax.random.PRNGKey(0),
                               "dropout": jax.random.PRNGKey(1)}, batch)["params"])
        losses = []
        for _ in range(20):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0] - 0.5, losses


class TestGPTMFULevers:
    """The two PROFILE.md r3 GPT-2 levers (round-3 VERDICT task 4):
    vocab padding to an MXU tile multiple must be numerically INVISIBLE
    (pad logits masked out of the CE), and the one-hot-matmul embedding
    gradient must match XLA's scatter-add."""

    def _batch(self, rng, v, bs=4, seq=32):
        return {"input_ids": rng.integers(0, v, (bs, seq), dtype=np.int32)}

    def test_vocab_padding_exact_parity(self):
        from deepspeed_tpu.models.gpt import make_gpt

        v = 500  # not a multiple of 128 -> pads to 512
        m_u, c_u = make_gpt("tiny", vocab_size=v, dropout_rate=0.0,
                            dtype=jnp.float32)
        m_p, c_p = make_gpt("tiny", vocab_size=v, dropout_rate=0.0,
                            dtype=jnp.float32, vocab_pad_multiple=128)
        assert c_p.padded_vocab == 512
        rng = np.random.default_rng(0)
        batch = self._batch(rng, v)
        pu = m_u.init({"params": jax.random.PRNGKey(0),
                       "dropout": jax.random.PRNGKey(1)}, batch)["params"]
        # Build the padded model's params by zero-padding the wte rows.
        pp = dict(pu)
        pp["wte"] = jnp.pad(pu["wte"], ((0, 512 - v), (0, 0)))

        def loss_u(p):
            return m_u.apply({"params": p}, batch, deterministic=True)["loss"]

        def loss_p(p):
            return m_p.apply({"params": p}, batch, deterministic=True)["loss"]

        (lu, gu) = jax.value_and_grad(loss_u)(pu)
        (lp, gp) = jax.value_and_grad(loss_p)(pp)
        np.testing.assert_allclose(float(lu), float(lp), rtol=1e-6)
        # real rows match; pad rows get exactly zero gradient
        np.testing.assert_allclose(np.asarray(gp["wte"][:v]),
                                   np.asarray(gu["wte"]), rtol=1e-5,
                                   atol=1e-7)
        np.testing.assert_array_equal(np.asarray(gp["wte"][v:]), 0.0)
        # logits output stays [.., vocab_size] and matches
        ou = m_u.apply({"params": pu}, batch, deterministic=True)["logits"]
        op = m_p.apply({"params": pp}, batch, deterministic=True)["logits"]
        assert op.shape[-1] == v
        np.testing.assert_allclose(np.asarray(ou), np.asarray(op),
                                   rtol=1e-5, atol=1e-5)

    def test_embed_grad_matmul_parity(self):
        from deepspeed_tpu.models.gpt import make_gpt

        m_s, _ = make_gpt("tiny", dropout_rate=0.0, dtype=jnp.float32)
        m_m, cfg = make_gpt("tiny", dropout_rate=0.0, dtype=jnp.float32,
                            embed_grad_matmul=True)
        rng = np.random.default_rng(1)
        batch = self._batch(rng, cfg.vocab_size)
        p = m_s.init({"params": jax.random.PRNGKey(0),
                      "dropout": jax.random.PRNGKey(1)}, batch)["params"]
        gs = jax.grad(lambda p: m_s.apply({"params": p}, batch,
                                          deterministic=True)["loss"])(p)
        gm = jax.grad(lambda p: m_m.apply({"params": p}, batch,
                                          deterministic=True)["loss"])(p)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), gs, gm)

    def test_both_levers_train(self, eight_devices):
        from deepspeed_tpu.models.gpt import make_gpt

        model, cfg = make_gpt("tiny", vocab_size=500, dropout_rate=0.0,
                              vocab_pad_multiple=128, embed_grad_matmul=True)
        rng = np.random.default_rng(2)
        batches = {"input_ids": rng.integers(0, 500, (2, 8, 32),
                                             dtype=np.int32)}
        params = model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(1)},
            {"input_ids": batches["input_ids"][0]})["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params,
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 2},
                    "bf16": {"enabled": True}})
        losses = [float(engine.train_batch(batches)) for _ in range(8)]
        assert losses[-1] < losses[0] - 0.3, losses


class TestHashDropout:
    """Counter-hash dropout (ops/dropout.py; reference
    dropout_kernels.cu's fused-dropout economy)."""

    def test_statistics_and_scaling(self):
        from deepspeed_tpu.ops.dropout import hash_dropout

        x = jnp.ones((512, 512), jnp.float32)
        rate = 0.1
        y = hash_dropout(x, rate, jax.random.PRNGKey(0))
        kept = np.asarray(y) > 0
        assert abs(kept.mean() - (1 - rate)) < 0.01
        np.testing.assert_allclose(np.asarray(y)[kept], 1.0 / (1 - rate),
                                   rtol=1e-6)
        # mean preserved
        assert abs(float(jnp.mean(y)) - 1.0) < 0.02

    def test_deterministic_per_key_decorrelated_across_keys(self):
        from deepspeed_tpu.ops.dropout import hash_dropout

        x = jnp.ones((64, 64), jnp.float32)
        a = hash_dropout(x, 0.2, jax.random.PRNGKey(1))
        b = hash_dropout(x, 0.2, jax.random.PRNGKey(1))
        c = hash_dropout(x, 0.2, jax.random.PRNGKey(2))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_zero_rate_and_eval_identity(self):
        from deepspeed_tpu.ops.dropout import HashDropout, hash_dropout

        x = jnp.ones((8, 8), jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(hash_dropout(x, 0.0, jax.random.PRNGKey(0))),
            np.asarray(x))
        y = HashDropout(0.5, deterministic=True).apply({}, x)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))

    def test_gpt_trains_with_fast_dropout(self, eight_devices):
        from deepspeed_tpu.models.gpt import make_gpt

        model, cfg = make_gpt("tiny", dropout_rate=0.1, fast_dropout=True)
        rng = np.random.default_rng(0)
        batches = {"input_ids": rng.integers(0, cfg.vocab_size, (2, 8, 32),
                                             dtype=np.int32)}
        params = model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(1)},
            {"input_ids": batches["input_ids"][0]})["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params,
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 0}})
        losses = [float(engine.train_batch(batches)) for _ in range(8)]
        assert all(np.isfinite(losses)) and losses[-1] < losses[0] - 0.3
