"""Config parsing + batch-triple solver (reference tests/unit/test_config.py,
test_ds_config.py)."""

import json

import pytest

from deepspeed_tpu.config.config import ConfigError, DeepSpeedTPUConfig


def test_batch_triple_all_given_consistent():
    c = DeepSpeedTPUConfig(
        {"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4,
         "gradient_accumulation_steps": 2}, world_size=4)
    assert c.train_batch_size == 32
    assert c.gradient_accumulation_steps == 2


def test_batch_triple_inconsistent_raises():
    with pytest.raises(ConfigError):
        DeepSpeedTPUConfig(
            {"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4,
             "gradient_accumulation_steps": 3}, world_size=4)


def test_batch_triple_infer_gas():
    c = DeepSpeedTPUConfig(
        {"train_batch_size": 64, "train_micro_batch_size_per_gpu": 4}, world_size=4)
    assert c.gradient_accumulation_steps == 4


def test_batch_triple_infer_micro():
    c = DeepSpeedTPUConfig(
        {"train_batch_size": 64, "gradient_accumulation_steps": 2}, world_size=4)
    assert c.train_micro_batch_size_per_gpu == 8


def test_batch_triple_infer_train():
    c = DeepSpeedTPUConfig({"train_micro_batch_size_per_gpu": 4}, world_size=4)
    assert c.train_batch_size == 16
    assert c.gradient_accumulation_steps == 1


def test_batch_triple_none_raises():
    with pytest.raises(ConfigError):
        DeepSpeedTPUConfig({}, world_size=4)


def test_micro_batch_chip_alias():
    c = DeepSpeedTPUConfig({"train_micro_batch_size_per_chip": 2}, world_size=2)
    assert c.train_micro_batch_size_per_gpu == 2


def test_zero_config_parsing():
    c = DeepSpeedTPUConfig(
        {"train_batch_size": 8,
         "zero_optimization": {"stage": 2, "overlap_comm": True,
                               "offload_optimizer": {"device": "cpu"}}},
        world_size=1)
    assert c.zero_config.stage == 2
    assert c.zero_config.overlap_comm
    assert c.zero_config.offload_optimizer.device == "cpu"
    assert c.zero_enabled


def test_zero_unknown_key_raises():
    with pytest.raises(ValueError):
        DeepSpeedTPUConfig(
            {"train_batch_size": 8, "zero_optimization": {"stage": 1, "bogus": 1}},
            world_size=1)


def test_fp16_bf16_exclusive():
    with pytest.raises(ConfigError):
        DeepSpeedTPUConfig(
            {"train_batch_size": 8, "fp16": {"enabled": True},
             "bf16": {"enabled": True}}, world_size=1)


def test_precision_selection():
    c = DeepSpeedTPUConfig({"train_batch_size": 8, "bf16": {"enabled": True}},
                           world_size=1)
    assert c.precision_dtype == "bfloat16"
    c = DeepSpeedTPUConfig({"train_batch_size": 8, "fp16": {"enabled": True}},
                           world_size=1)
    assert c.precision_dtype == "float16"
    assert c.dynamic_loss_scale


def test_config_from_file(tmp_path):
    p = tmp_path / "ds_config.json"
    p.write_text(json.dumps({"train_batch_size": 16,
                             "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                             "scheduler": {"type": "WarmupLR",
                                           "params": {"warmup_num_steps": 10}}}))
    c = DeepSpeedTPUConfig(str(p), world_size=2)
    assert c.optimizer_name == "adam"
    assert c.scheduler_name == "WarmupLR"
    assert c.optimizer_params["lr"] == 1e-3


def test_mesh_block():
    c = DeepSpeedTPUConfig({"train_batch_size": 8, "mesh": {"model": 2}},
                           world_size=8)
    assert c.data_parallel_size == 4


def test_mesh_indivisible_raises():
    with pytest.raises(ConfigError):
        DeepSpeedTPUConfig({"train_batch_size": 8, "mesh": {"model": 3}},
                           world_size=8)


def test_zero2_with_pipeline_raises():
    with pytest.raises(ConfigError):
        DeepSpeedTPUConfig(
            {"train_batch_size": 8, "mesh": {"pipe": 2},
             "zero_optimization": {"stage": 2}}, world_size=8)


def test_comm_block_defaults():
    c = DeepSpeedTPUConfig({"train_batch_size": 8}, world_size=8)
    assert c.comm.hierarchical == "off"
    assert c.comm.dcn_quant_bits == 8
    assert c.comm.quant_block_size == 1024
    assert c.comm.bucket_mb == 16.0
    assert c.communication_data_type is None


def test_comm_block_parsing():
    c = DeepSpeedTPUConfig(
        {"train_batch_size": 8,
         "comm": {"hierarchical": "ON", "dcn_quant_bits": 16,
                  "quant_block_size": 256, "bucket_mb": 4}},
        world_size=8)
    assert c.comm.hierarchical == "on"
    assert c.comm.dcn_quant_bits == 16
    assert c.comm.quant_block_size == 256
    assert c.comm.bucket_mb == 4.0


@pytest.mark.parametrize("block,match", [
    ({"hierarchical": "sometimes"}, "auto|on|off"),
    ({"dcn_quant_bits": 4}, "dcn_quant_bits"),
    ({"quant_block_size": 0}, "quant_block_size"),
    ({"bucket_mb": -1}, "bucket_mb"),
])
def test_comm_block_invalid_raises(block, match):
    with pytest.raises(ConfigError, match=match):
        DeepSpeedTPUConfig({"train_batch_size": 8, "comm": block},
                           world_size=8)


@pytest.mark.parametrize("value", ["fp32", "float32", "bf16", "bfloat16",
                                   "fp16", "float16", "BF16"])
def test_communication_data_type_valid(value):
    c = DeepSpeedTPUConfig(
        {"train_batch_size": 8, "communication_data_type": value},
        world_size=8)
    assert c.communication_data_type == value.lower()


def test_communication_data_type_invalid_raises():
    with pytest.raises(ConfigError, match="communication_data_type"):
        DeepSpeedTPUConfig(
            {"train_batch_size": 8, "communication_data_type": "int7"},
            world_size=8)


def test_moe_block_defaults():
    c = DeepSpeedTPUConfig(
        {"train_batch_size": 8, "mesh": {"expert": 2}, "moe": {}},
        world_size=8)
    # presence of the block opts in
    assert c.moe.enabled
    assert c.moe.num_experts == 8 and c.moe.k == 1
    assert c.moe.capacity_factor == 1.25
    assert c.moe.eval_capacity_factor == 2.0
    assert c.moe.dispatch == "scatter"
    # absence keeps it off (the zero-overhead contract's config half)
    assert not DeepSpeedTPUConfig({"train_batch_size": 8},
                                  world_size=8).moe.enabled


@pytest.mark.parametrize("block,match", [
    ({"num_experts": 1}, "num_experts"),
    ({"k": 3}, "moe.k"),
    ({"layer_freq": 0}, "layer_freq"),
    ({"capacity_factor": 0}, "capacity"),
    ({"eval_capacity_factor": -1}, "capacity"),
    ({"min_capacity": 0}, "min_capacity"),
    ({"aux_alpha": -0.1}, "aux_alpha"),
    ({"router_jitter": 1.5}, "router_jitter"),
    ({"dispatch": "magic"}, "dispatch"),
])
def test_moe_block_invalid_raises(block, match):
    with pytest.raises(ConfigError, match=match):
        DeepSpeedTPUConfig({"train_batch_size": 8, "moe": block},
                           world_size=8)


def test_moe_expert_axis_divisibility_raises():
    with pytest.raises(ConfigError, match="num_experts"):
        DeepSpeedTPUConfig(
            {"train_batch_size": 8, "mesh": {"expert": 4},
             "moe": {"num_experts": 6}}, world_size=8)


@pytest.mark.parametrize("extra,match", [
    ({"pipeline": {"stages": 2}, "zero_optimization": {"stage": 1}},
     "pipeline"),
    ({"zero_optimization": {"stage": 2,
                            "offload_optimizer": {"device": "cpu"}}},
     "offload"),
    ({"optimizer": {"type": "OneBitAdam", "params": {"lr": 1e-3}}},
     "1-bit"),
])
def test_moe_composition_walls(extra, match):
    with pytest.raises(ConfigError, match=match):
        DeepSpeedTPUConfig(
            {"train_batch_size": 8, "mesh": {"expert": 2},
             "moe": {"num_experts": 4}, **extra}, world_size=8)


def test_moe_disabled_block_composes_freely():
    c = DeepSpeedTPUConfig(
        {"train_batch_size": 8, "moe": {"enabled": False},
         "pipeline": {"stages": 2}}, world_size=8)
    assert not c.moe.enabled
