"""Config parsing + batch-triple solver (reference tests/unit/test_config.py,
test_ds_config.py)."""

import json

import pytest

from deepspeed_tpu.config.config import ConfigError, DeepSpeedTPUConfig


def test_batch_triple_all_given_consistent():
    c = DeepSpeedTPUConfig(
        {"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4,
         "gradient_accumulation_steps": 2}, world_size=4)
    assert c.train_batch_size == 32
    assert c.gradient_accumulation_steps == 2


def test_batch_triple_inconsistent_raises():
    with pytest.raises(ConfigError):
        DeepSpeedTPUConfig(
            {"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4,
             "gradient_accumulation_steps": 3}, world_size=4)


def test_batch_triple_infer_gas():
    c = DeepSpeedTPUConfig(
        {"train_batch_size": 64, "train_micro_batch_size_per_gpu": 4}, world_size=4)
    assert c.gradient_accumulation_steps == 4


def test_batch_triple_infer_micro():
    c = DeepSpeedTPUConfig(
        {"train_batch_size": 64, "gradient_accumulation_steps": 2}, world_size=4)
    assert c.train_micro_batch_size_per_gpu == 8


def test_batch_triple_infer_train():
    c = DeepSpeedTPUConfig({"train_micro_batch_size_per_gpu": 4}, world_size=4)
    assert c.train_batch_size == 16
    assert c.gradient_accumulation_steps == 1


def test_batch_triple_none_raises():
    with pytest.raises(ConfigError):
        DeepSpeedTPUConfig({}, world_size=4)


def test_micro_batch_chip_alias():
    c = DeepSpeedTPUConfig({"train_micro_batch_size_per_chip": 2}, world_size=2)
    assert c.train_micro_batch_size_per_gpu == 2


def test_zero_config_parsing():
    c = DeepSpeedTPUConfig(
        {"train_batch_size": 8,
         "zero_optimization": {"stage": 2, "overlap_comm": True,
                               "offload_optimizer": {"device": "cpu"}}},
        world_size=1)
    assert c.zero_config.stage == 2
    assert c.zero_config.overlap_comm
    assert c.zero_config.offload_optimizer.device == "cpu"
    assert c.zero_enabled


def test_zero_unknown_key_raises():
    with pytest.raises(ValueError):
        DeepSpeedTPUConfig(
            {"train_batch_size": 8, "zero_optimization": {"stage": 1, "bogus": 1}},
            world_size=1)


def test_fp16_bf16_exclusive():
    with pytest.raises(ConfigError):
        DeepSpeedTPUConfig(
            {"train_batch_size": 8, "fp16": {"enabled": True},
             "bf16": {"enabled": True}}, world_size=1)


def test_precision_selection():
    c = DeepSpeedTPUConfig({"train_batch_size": 8, "bf16": {"enabled": True}},
                           world_size=1)
    assert c.precision_dtype == "bfloat16"
    c = DeepSpeedTPUConfig({"train_batch_size": 8, "fp16": {"enabled": True}},
                           world_size=1)
    assert c.precision_dtype == "float16"
    assert c.dynamic_loss_scale


def test_config_from_file(tmp_path):
    p = tmp_path / "ds_config.json"
    p.write_text(json.dumps({"train_batch_size": 16,
                             "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                             "scheduler": {"type": "WarmupLR",
                                           "params": {"warmup_num_steps": 10}}}))
    c = DeepSpeedTPUConfig(str(p), world_size=2)
    assert c.optimizer_name == "adam"
    assert c.scheduler_name == "WarmupLR"
    assert c.optimizer_params["lr"] == 1e-3


def test_mesh_block():
    c = DeepSpeedTPUConfig({"train_batch_size": 8, "mesh": {"model": 2}},
                           world_size=8)
    assert c.data_parallel_size == 4


def test_mesh_indivisible_raises():
    with pytest.raises(ConfigError):
        DeepSpeedTPUConfig({"train_batch_size": 8, "mesh": {"model": 3}},
                           world_size=8)


def test_zero2_with_pipeline_raises():
    with pytest.raises(ConfigError):
        DeepSpeedTPUConfig(
            {"train_batch_size": 8, "mesh": {"pipe": 2},
             "zero_optimization": {"stage": 2}}, world_size=8)
