"""Inference engine tests — generation parity, TP, quantization.

Models the reference's inference checks (tests/unit/test_inference* are not
in this reference snapshot; methodology follows test_cuda_forward.py parity
style): the KV-cache incremental decode must reproduce the full-forward
argmax path exactly, and TP/int8 variants must agree with the plain engine.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference import (InferenceEngine, QuantizedWeight,
                                     dequantize_params, quantize_params,
                                     quantized_nbytes)
from deepspeed_tpu.models import make_gpt


@pytest.fixture(scope="module")
def gpt_setup():
    # fp32 weights/activations: the parity oracle re-runs the full forward
    # per token, and in bf16 argmax tie-flips between the (numerically
    # different but equally valid) cache and full paths are expected noise.
    model, cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=64,
                          dtype=jnp.float32)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (2, 8), dtype=np.int32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": ids})["params"]
    return model, cfg, params, ids


def naive_generate(model, params, ids, n):
    """Re-run the full forward each step — the no-cache oracle."""
    ids = jnp.asarray(ids)
    for _ in range(n):
        out = model.apply({"params": params}, {"input_ids": ids},
                          deterministic=True)
        nxt = jnp.argmax(out["logits"][:, -1], axis=-1).astype(jnp.int32)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return ids


class TestGenerate:
    def test_greedy_matches_full_forward(self, gpt_setup):
        model, cfg, params, ids = gpt_setup
        engine = deepspeed_tpu.init_inference(model, params=params, dtype=jnp.float32)
        got = engine.generate(ids, max_new_tokens=6, temperature=0.0)
        want = naive_generate(model, params, ids, 6)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_forward_matches_module(self, gpt_setup):
        model, cfg, params, ids = gpt_setup
        engine = deepspeed_tpu.init_inference(model, params=params, dtype=jnp.float32)
        out = engine.forward({"input_ids": ids})
        want = model.apply({"params": params},
            {"input_ids": ids}, deterministic=True)
        np.testing.assert_allclose(np.asarray(out["logits"]),
                                   np.asarray(want["logits"]),
                                   rtol=1e-2, atol=1e-2)

    def test_sampled_generation_shape_and_range(self, gpt_setup):
        model, cfg, params, ids = gpt_setup
        engine = deepspeed_tpu.init_inference(model, params=params, dtype=jnp.float32)
        out = engine.generate(ids, max_new_tokens=5, temperature=0.8,
                              top_k=8, seed=3)
        out = np.asarray(out)
        assert out.shape == (2, ids.shape[1] + 5)
        assert (out >= 0).all() and (out < cfg.vocab_size).all()
        np.testing.assert_array_equal(out[:, :ids.shape[1]], ids)

    def test_prefill_with_attention_mask(self, gpt_setup):
        """Cache-mode prefill under a key-padding mask must match the
        no-cache forward under the same mask (regression: the chunk mask
        must be re-based onto the cache's key axis)."""
        model, cfg, params, ids = gpt_setup
        am = np.ones_like(ids)
        am[0, :3] = 0  # left-pad row 0
        from deepspeed_tpu.models.gpt import init_kv_cache
        cache = init_kv_cache(cfg, ids.shape[0], ids.shape[1] + 4,
                              dtype=jnp.float32)
        out_c = model.apply({"params": params},
                            {"input_ids": ids, "attention_mask": am},
                            deterministic=True, cache=cache, pos=0)
        out_f = model.apply({"params": params},
                            {"input_ids": ids, "attention_mask": am},
                            deterministic=True)
        np.testing.assert_allclose(np.asarray(out_c["logits"][:, -1]),
                                   np.asarray(out_f["logits"][:, -1]),
                                   rtol=2e-4, atol=2e-4)

    def test_single_new_token(self, gpt_setup):
        model, cfg, params, ids = gpt_setup
        engine = deepspeed_tpu.init_inference(model, params=params, dtype=jnp.float32)
        got = engine.generate(ids, max_new_tokens=1)
        want = naive_generate(model, params, ids, 1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_left_padded_generate_matches_unpadded(self, gpt_setup):
        """Each left-padded row must generate exactly what the same prompt
        generates unpadded (pads invisible to attention, positions
        re-based)."""
        model, cfg, params, ids = gpt_setup
        engine = deepspeed_tpu.init_inference(model, params=params,
                                              dtype=jnp.float32)
        pad = 3
        padded = np.concatenate(
            [np.zeros((2, pad), np.int32), ids], axis=1)
        mask = np.concatenate(
            [np.zeros((2, pad), np.int32), np.ones_like(ids)], axis=1)
        got = np.asarray(engine.generate(padded, max_new_tokens=5,
                                         attention_mask=mask))
        want = np.asarray(engine.generate(ids, max_new_tokens=5))
        np.testing.assert_array_equal(got[:, pad + ids.shape[1]:],
                                      want[:, ids.shape[1]:])

    def test_right_padded_mask_rejected(self, gpt_setup):
        model, cfg, params, ids = gpt_setup
        engine = deepspeed_tpu.init_inference(model, params=params,
                                              dtype=jnp.float32)
        mask = np.ones_like(ids)
        mask[0, -2:] = 0  # trailing pads = right padding
        with pytest.raises(ValueError, match="left-padded"):
            engine.generate(ids, max_new_tokens=2, attention_mask=mask)

    def test_default_seed_varies_per_call(self, gpt_setup):
        model, cfg, params, ids = gpt_setup
        engine = deepspeed_tpu.init_inference(model, params=params,
                                              dtype=jnp.float32)
        a = np.asarray(engine.generate(ids, max_new_tokens=8,
                                       temperature=1.5))
        b = np.asarray(engine.generate(ids, max_new_tokens=8,
                                       temperature=1.5))
        c = np.asarray(engine.generate(ids, max_new_tokens=8,
                                       temperature=1.5, seed=0))
        # seed=0 reproduces call #0; unseeded calls differ from each other
        np.testing.assert_array_equal(a, c)
        assert not np.array_equal(a, b)


class TestTensorParallel:
    def test_tp2_matches_single(self, gpt_setup, eight_devices):
        model, cfg, params, ids = gpt_setup
        single = deepspeed_tpu.init_inference(model, params=params, dtype=jnp.float32)
        tp = deepspeed_tpu.init_inference(model, params=params, mp_size=2, dtype=jnp.float32)
        assert tp.mesh is not None and dict(tp.mesh.shape)["model"] == 2
        got = tp.generate(ids, max_new_tokens=6, temperature=0.0)
        want = single.generate(ids, max_new_tokens=6, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_tp_params_actually_sharded(self, gpt_setup, eight_devices):
        model, cfg, params, ids = gpt_setup
        tp = deepspeed_tpu.init_inference(model, params=params, mp_size=4)
        kern = tp.params["h_0"]["c_attn"]["kernel"]
        shard_shape = kern.sharding.shard_shape(kern.shape)
        assert shard_shape[-1] == kern.shape[-1] // 4


class TestQuantization:
    def test_quantize_roundtrip_error(self, gpt_setup):
        _, _, params, _ = gpt_setup
        q = quantize_params(params, groups=4, min_size=16)
        deq = dequantize_params(q, jnp.float32)
        w = params["h_0"]["c_attn"]["kernel"]
        w2 = deq["h_0"]["c_attn"]["kernel"]
        err = np.abs(np.asarray(w) - np.asarray(w2)).max()
        assert err <= np.abs(np.asarray(w)).max() / 127.0 + 1e-6

    def test_quantized_bytes_shrink(self, gpt_setup):
        _, _, params, _ = gpt_setup
        fp = jax.tree_util.tree_map(lambda p: p.astype(jnp.float32), params)
        q = quantize_params(fp, min_size=16)
        n_q = sum(isinstance(l, QuantizedWeight) for l in
                  jax.tree_util.tree_leaves(
                      q, is_leaf=lambda x: isinstance(x, QuantizedWeight)))
        assert n_q > 0
        assert quantized_nbytes(q) < 0.5 * quantized_nbytes(fp)

    def test_quantized_generation_close(self, gpt_setup):
        model, cfg, params, ids = gpt_setup
        plain = deepspeed_tpu.init_inference(model, params=params,
                                             dtype=jnp.float32)
        quant = deepspeed_tpu.init_inference(model, params=params,
                                             dtype=jnp.float32,
                                             quantize=True,
                                             quantize_groups=4)
        got = np.asarray(quant.generate(ids, max_new_tokens=4))
        assert got.shape == (2, ids.shape[1] + 4)
        # int8 weights perturb logits; tokens may differ, but the engine must
        # produce valid ids and identical prompt prefix.
        np.testing.assert_array_equal(got[:, :ids.shape[1]], np.asarray(ids))
        out_q = quant.forward({"input_ids": ids})["logits"]
        out_p = plain.forward({"input_ids": ids})["logits"]
        # logits agree to quantization tolerance
        denom = np.abs(np.asarray(out_p)).max() + 1e-6
        rel = np.abs(np.asarray(out_q) - np.asarray(out_p)).max() / denom
        assert rel < 0.12, rel

    def test_quantized_tp_runs(self, gpt_setup, eight_devices):
        model, cfg, params, ids = gpt_setup
        eng = deepspeed_tpu.init_inference(model, params=params, mp_size=2,
                                           quantize=True)
        out = eng.generate(ids, max_new_tokens=3)
        assert np.asarray(out).shape == (2, ids.shape[1] + 3)


class TestInitInferenceAPI:
    def test_returns_engine_with_module(self, gpt_setup):
        model, cfg, params, ids = gpt_setup
        eng = deepspeed_tpu.init_inference(model, params=params)
        assert isinstance(eng, InferenceEngine)
        assert eng.module is model

    def test_checkpoint_loading(self, gpt_setup, tmp_path):
        model, cfg, params, ids = gpt_setup
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params,
            config={"train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 0}})
        engine.save_checkpoint(str(tmp_path))
        inf = deepspeed_tpu.init_inference(model, checkpoint=str(tmp_path))
        out = inf.generate(ids, max_new_tokens=2)
        assert np.asarray(out).shape == (2, ids.shape[1] + 2)


class TestPromptBucketing:
    """generate() pads prompts to power-of-two buckets (left, masked):
    varying lengths must share ONE compiled program per bucket instead of
    retracing per length — proven through the RecompileDetector."""

    def test_one_compile_per_bucket(self, gpt_setup):
        model, cfg, params, ids = gpt_setup
        engine = deepspeed_tpu.init_inference(model, params=params,
                                              dtype=jnp.float32)
        for t0 in (5, 6, 7, 8):               # all land in the 8-bucket
            engine.generate(ids[:, :t0], max_new_tokens=4)
        det = engine.recompile_detector
        assert det.compiles("inference.generate") == 1, det.stats
        assert len(engine._generate_jit) == 1
        long_ids = jnp.tile(jnp.asarray(ids), (1, 2))     # [2, 16]
        for t0 in (9, 11, 16):                # the 16-bucket
            engine.generate(long_ids[:, :t0], max_new_tokens=4)
        assert det.compiles("inference.generate") == 2, det.stats
        assert len(engine._generate_jit) == 2

    def test_bucketed_matches_unbucketed(self, gpt_setup):
        model, cfg, params, ids = gpt_setup
        bucketed = deepspeed_tpu.init_inference(model, params=params,
                                                dtype=jnp.float32)
        plain = deepspeed_tpu.init_inference(model, params=params,
                                             dtype=jnp.float32,
                                             bucket_prompts=False)
        for t0 in (3, 5, 7, 8):
            got = np.asarray(bucketed.generate(ids[:, :t0],
                                               max_new_tokens=5))
            want = np.asarray(plain.generate(ids[:, :t0],
                                             max_new_tokens=5))
            np.testing.assert_array_equal(got, want)
            assert got.shape == (2, t0 + 5)   # pad columns stripped

    def test_bucket_respects_context_cap(self, gpt_setup):
        """At the context boundary the bucket is clamped so prompt +
        decode still fits; generation succeeds rather than overflowing
        the cache."""
        model, cfg, params, ids = gpt_setup
        engine = deepspeed_tpu.init_inference(model, params=params,
                                              dtype=jnp.float32)
        t0 = 20                                # pow2 bucket would be 32
        mnt = cfg.max_seq_len - t0             # exactly fills the context
        big = jnp.tile(ids, (1, 4))[:, :t0]
        out = engine.generate(big, max_new_tokens=mnt)
        assert np.asarray(out).shape == (2, cfg.max_seq_len)


class TestQuantizerUnification:
    """inference/quantization.py carries NO quantizer of its own: it
    reshapes onto comm/quantize.py's RTNE core (one int8 implementation
    in the tree) and inherits its tested properties."""

    def test_delegates_to_comm_core(self, gpt_setup, monkeypatch):
        _, _, params, _ = gpt_setup
        import deepspeed_tpu.inference.quantization as iq
        calls = {"n": 0}
        real = iq.quantize_blockwise

        def spy(x, block_size, bits=8):
            calls["n"] += 1
            return real(x, block_size, bits)

        monkeypatch.setattr(iq, "quantize_blockwise", spy)
        q = quantize_params(params, min_size=16)
        n_q = sum(isinstance(l, QuantizedWeight)
                  for l in jax.tree_util.tree_leaves(
                      q, is_leaf=lambda x: isinstance(x, QuantizedWeight)))
        assert n_q > 0 and calls["n"] == n_q

    def test_roundtrip_equals_comm_roundtrip(self):
        """The weight quantizer's round-trip is EXACTLY the comm core's
        on the moved-axis layout — shared semantics, not merely close."""
        from deepspeed_tpu.comm.quantize import (dequantize_blockwise,
                                                 quantize_blockwise)
        from deepspeed_tpu.inference.quantization import _quantize_leaf
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(size=(16, 6)).astype(np.float32))
        qw = _quantize_leaf(w, groups=4)
        got = np.asarray(qw.dequantize(jnp.float32))
        moved = jnp.moveaxis(w.reshape(4, 4, 6), 1, -1)   # [4, 6, 4]
        q, s = quantize_blockwise(moved, 4)
        want = jnp.moveaxis(dequantize_blockwise(q, s, 4), -1, 1)
        np.testing.assert_array_equal(got, np.asarray(want.reshape(16, 6)))

    def test_comm_properties_inherited(self):
        """Zero-preserving and max-preserving — the comm/quantize.py
        contract, now holding for weight quantization by construction."""
        from deepspeed_tpu.inference.quantization import _quantize_leaf
        z = jnp.zeros((8, 4), jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(_quantize_leaf(z, 2).dequantize(jnp.float32)), 0.0)
        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.normal(size=(32, 5)).astype(np.float32))
        deq = np.asarray(_quantize_leaf(w, 4).dequantize(jnp.float32))
        grouped = np.asarray(w).reshape(4, 8, 5)
        amax = np.abs(grouped).max(axis=1)
        amax_rt = np.abs(deq.reshape(4, 8, 5)).max(axis=1)
        np.testing.assert_allclose(amax_rt, amax, rtol=1e-6)


class TestReviewRegressions:
    def test_generate_past_context_raises(self, gpt_setup):
        model, cfg, params, ids = gpt_setup
        eng = deepspeed_tpu.init_inference(model, params=params)
        with pytest.raises(ValueError, match="exceeds the usable context"):
            eng.generate(ids, max_new_tokens=cfg.max_seq_len)

    def test_max_tokens_enforced(self, gpt_setup):
        model, cfg, params, ids = gpt_setup
        eng = deepspeed_tpu.init_inference(model, params=params,
                                           max_tokens=12)
        with pytest.raises(ValueError, match="exceeds the usable context"):
            eng.generate(ids, max_new_tokens=8)  # 8 prompt + 8 > 12

    def test_mp_without_rules_raises(self, eight_devices):
        import flax.linen as nn

        class Plain(nn.Module):
            @nn.compact
            def __call__(self, batch, deterministic=True):
                return {"logits": nn.Dense(4)(batch["x"])}

        with pytest.raises(ValueError, match="partition rules"):
            deepspeed_tpu.init_inference(
                Plain(), mp_size=2,
                example_batch={"x": np.zeros((2, 8), np.float32)})
