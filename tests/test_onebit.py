"""1-bit compressed comm + optimizer tests (reference tests/unit/test_onebit.py
and tests/onebit/): pack/unpack roundtrip, error-compensated allreduce
convergence, and OneBitAdam/Lamb end-to-end training on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.compressed import (compressed_allreduce, pack_signs,
                                           unpack_signs)
from deepspeed_tpu.parallel.mesh import build_mesh


class TestPacking:
    def test_roundtrip(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal(256), jnp.float32)
        bits = x >= 0
        packed = pack_signs(bits)
        assert packed.dtype == jnp.uint8 and packed.shape == (32,)
        signs = unpack_signs(packed, 256)
        np.testing.assert_array_equal(np.asarray(signs),
                                      np.where(np.asarray(bits), 1.0, -1.0))

    def test_partial_tail(self):
        bits = jnp.asarray([1, 0, 1, 1, 0, 0, 0, 1, 1, 0, 1, 0, 1, 0, 1, 0],
                           jnp.bool_)
        signs = unpack_signs(pack_signs(bits), 16)
        np.testing.assert_array_equal(np.asarray(signs),
                                      np.where(np.asarray(bits), 1.0, -1.0))


class TestCompressedAllreduce:
    def test_error_compensation_converges(self, eight_devices):
        """Repeatedly allreducing the SAME tensors: with error feedback the
        time-average of results converges to the true mean (the 1-bit Adam
        convergence argument)."""
        n, numel = 8, 512
        mesh = build_mesh(data=n)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((n, numel)), jnp.float32)
        true_mean = np.asarray(jnp.mean(x, axis=0))

        we = jnp.zeros((n, numel), jnp.float32)
        se = jnp.zeros((n, numel // n), jnp.float32)
        acc = np.zeros(numel)
        iters = 50
        for _ in range(iters):
            out, we, se = compressed_allreduce(x, we, se, mesh)
            acc += np.asarray(out[0])
        err0 = np.abs(np.asarray(
            compressed_allreduce(x, jnp.zeros_like(we), jnp.zeros_like(se),
                                 mesh)[0][0]) - true_mean).mean()
        err_avg = np.abs(acc / iters - true_mean).mean()
        # error-compensated average is much closer than a single 1-bit pass
        assert err_avg < err0 * 0.25, (err_avg, err0)

    def test_all_ranks_agree(self, eight_devices):
        n, numel = 8, 128
        mesh = build_mesh(data=n)
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((n, numel)), jnp.float32)
        out, _, _ = compressed_allreduce(
            x, jnp.zeros((n, numel)), jnp.zeros((n, numel // n)), mesh)
        out = np.asarray(out)
        for r in range(1, n):
            np.testing.assert_array_equal(out[0], out[r])


class TestOneBitOptimizers:
    def _train(self, opt_name, eight, freeze_step=5, steps=25, lr=1e-3):
        from deepspeed_tpu.models import make_gpt

        mesh = build_mesh(data=8)
        model, cfg = make_gpt("tiny", dtype=jnp.float32)
        rng = np.random.default_rng(0)
        gas, bs, seq = 2, 8, 32
        batches = {"input_ids": rng.integers(0, cfg.vocab_size,
                                             (gas, bs, seq), dtype=np.int32)}
        params = model.init(
            {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
            {"input_ids": batches["input_ids"][0]})["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params, mesh=mesh,
            config={
                "train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": gas,
                "optimizer": {"type": opt_name,
                              "params": {"lr": lr,
                                         "freeze_step": freeze_step}},
                "zero_optimization": {"stage": 0},
            })
        losses = [float(engine.train_batch(batches)) for _ in range(steps)]
        return losses, engine

    @pytest.mark.parametrize("opt,lr", [("OneBitAdam", 1e-3),
                                        ("OneBitLamb", 2e-2)])
    def test_trains_through_both_phases(self, eight_devices, opt, lr):
        """Loss keeps decreasing through the warmup -> compressed switch."""
        losses, engine = self._train(opt, eight_devices, lr=lr)
        assert losses[-1] < losses[0] - 0.5, losses
        # after freeze_step, still improving (compressed phase works)
        assert losses[-1] < losses[10] - 0.05, losses

    def test_forward_backward_step_loop(self, eight_devices):
        """The reference-style micro-batch loop works for 1-bit configs
        (round-3 VERDICT weak #5 lifted): forward() stashes, step() runs
        the fused window — same trajectory as train_batch()."""
        rng = np.random.default_rng(0)
        from deepspeed_tpu.models import make_gpt
        from deepspeed_tpu.parallel.mesh import build_mesh

        mesh = build_mesh(data=8)
        model, cfg = make_gpt("tiny", dtype=jnp.float32)
        gas, bs, seq = 2, 8, 32
        batches = {"input_ids": rng.integers(0, cfg.vocab_size,
                                             (gas, bs, seq),
                                             dtype=np.int32)}
        params = model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(1)},
            {"input_ids": batches["input_ids"][0]})["params"]

        def build():
            e, _, _, _ = deepspeed_tpu.initialize(
                model=model, params=params, mesh=mesh,
                config={
                    "train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": gas,
                    "optimizer": {"type": "OneBitAdam",
                                  "params": {"lr": 1e-3, "freeze_step": 2}},
                    "zero_optimization": {"stage": 1},
                })
            return e

        e_loop, e_tb = build(), build()
        for _ in range(4):
            for m in range(gas):
                one = {"input_ids": batches["input_ids"][m]}
                loss = e_loop.forward(one)
                e_loop.backward(loss)
            e_loop.step()
            e_tb.train_batch(batches)
        assert e_loop.global_steps == e_tb.global_steps == 4
        for a, b in zip(jax.tree_util.tree_leaves(e_loop.state.params),
                        jax.tree_util.tree_leaves(e_tb.state.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)
        # an eval-style probe (forward without backward) must not wedge
        # the window: it is replaced by the next training forward
        e_loop.forward({"input_ids": batches["input_ids"][0]})
        for m in range(gas):
            loss = e_loop.forward({"input_ids": batches["input_ids"][m]})
            e_loop.backward(loss)
        e_loop.step()
        assert e_loop.global_steps == 5
        # over-calling forward+backward beyond the window is caught
        for m in range(gas):
            loss = e_loop.forward({"input_ids": batches["input_ids"][m]})
            e_loop.backward(loss)
        with pytest.raises(RuntimeError, match="without an intervening"):
            e_loop.forward({"input_ids": batches["input_ids"][0]})

    def test_zero_stage_guard(self, eight_devices):
        """ZeRO-2+ shards grads, breaking the rank-local protocol — rejected.
        ZeRO-1 (opt-state placement) composes (round-3 VERDICT task 1)."""
        from deepspeed_tpu.models import make_gpt

        mesh = build_mesh(data=8)
        model, cfg = make_gpt("tiny", dtype=jnp.float32)
        batch = {"input_ids": np.zeros((8, 32), np.int32)}
        params = model.init(
            {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
            batch)["params"]
        with pytest.raises(ValueError, match="stage 0 or 1"):
            deepspeed_tpu.initialize(
                model=model, params=params, mesh=mesh,
                config={"train_micro_batch_size_per_gpu": 1,
                        "optimizer": {"type": "OneBitAdam", "params": {}},
                        "zero_optimization": {"stage": 2}})

    def test_zero1_matches_zero0_trajectory(self, eight_devices):
        """ZeRO-1 is a placement policy: sharding the 1-bit moments over
        data must not change the numerics, through BOTH phases."""
        from deepspeed_tpu.models import make_gpt

        def run(stage):
            mesh = build_mesh(data=8)
            model, cfg = make_gpt("tiny", dtype=jnp.float32)
            rng = np.random.default_rng(0)
            gas, bs, seq = 2, 8, 32
            batches = {"input_ids": rng.integers(
                0, cfg.vocab_size, (gas, bs, seq), dtype=np.int32)}
            params = model.init(
                {"params": jax.random.PRNGKey(0),
                 "dropout": jax.random.PRNGKey(1)},
                {"input_ids": batches["input_ids"][0]})["params"]
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=model, params=params, mesh=mesh,
                config={
                    "train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": gas,
                    "optimizer": {"type": "OneBitAdam",
                                  "params": {"lr": 1e-3, "freeze_step": 3}},
                    "zero_optimization": {"stage": stage},
                })
            losses = [float(engine.train_batch(batches)) for _ in range(6)]
            return losses, jax.tree_util.tree_map(np.asarray,
                                                  engine.state.params)

        l0, p0 = run(0)
        l1, p1 = run(1)
        np.testing.assert_allclose(l0, l1, rtol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5,
                                                    atol=1e-6), p0, p1)


class TestOneBitClipping:
    """gradient_clipping composes with the 1-bit path (round-2 VERDICT
    weak #3 / task 10a): previously accepted but silently ignored."""

    def test_clipping_changes_trajectory_and_bounds_updates(
            self, eight_devices):
        import deepspeed_tpu

        def loss_fn(p, b, r):
            return jnp.mean((jnp.tanh(b["x"] @ p["w"]) - b["y"]) ** 2)

        def build(clip):
            params = {"w": jax.random.normal(jax.random.PRNGKey(0),
                                             (8, 4)) * 0.1}
            cfgd = {
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "OneBitAdam",
                              "params": {"lr": 1e-2, "freeze_step": 100}},
                "zero_optimization": {"stage": 0},
            }
            if clip:
                cfgd["gradient_clipping"] = clip
            e, _, _, _ = deepspeed_tpu.initialize(
                loss_fn=loss_fn, params=params, config=cfgd)
            return e

        rng = np.random.default_rng(0)
        # large targets -> large grads, so a tiny clip threshold bites
        batches = {"x": rng.standard_normal((2, 16, 8)).astype(np.float32),
                   "y": (100 * rng.standard_normal((2, 16, 4))).astype(
                       np.float32)}
        e_free = build(None)
        e_clip = build(1e-3)
        for _ in range(3):
            lf = float(e_free.train_batch(batches))
            lc = float(e_clip.train_batch(batches))
        w_free = np.asarray(e_free.state.params["w"])
        w_clip = np.asarray(e_clip.state.params["w"])
        assert np.isfinite(lf) and np.isfinite(lc)
        # clipped run must have moved the weights differently (clip active)
        assert not np.allclose(w_free, w_clip)

    def test_clipping_noop_when_under_threshold(self, eight_devices):
        import deepspeed_tpu

        def loss_fn(p, b, r):
            return jnp.mean((jnp.tanh(b["x"] @ p["w"]) - b["y"]) ** 2)

        def build(clip):
            params = {"w": jax.random.normal(jax.random.PRNGKey(0),
                                             (8, 4)) * 0.1}
            cfgd = {
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "OneBitAdam",
                              "params": {"lr": 1e-2, "freeze_step": 100}},
                "zero_optimization": {"stage": 0},
            }
            if clip:
                cfgd["gradient_clipping"] = clip
            e, _, _, _ = deepspeed_tpu.initialize(
                loss_fn=loss_fn, params=params, config=cfgd)
            return e

        rng = np.random.default_rng(1)
        batches = {"x": rng.standard_normal((2, 16, 8)).astype(np.float32),
                   "y": (0.1 * rng.standard_normal((2, 16, 4))).astype(
                       np.float32)}
        e_free = build(None)
        e_clip = build(1e6)   # threshold far above any realistic norm
        traj_f = [float(e_free.train_batch(batches)) for _ in range(3)]
        traj_c = [float(e_clip.train_batch(batches)) for _ in range(3)]
        np.testing.assert_allclose(traj_f, traj_c, rtol=1e-6)


class TestCompressedDtypePreservation:
    """The 1-bit pipeline must not upcast: with bf16 error-feedback
    traffic the whole compress → all_to_all → server-average → all_gather
    chain stays bf16 (ISSUE 4 satellite — unpack_signs/_compress used to
    hard-code fp32)."""

    def test_bf16_no_f32_convert_in_jaxpr(self, eight_devices):
        import re

        mesh = build_mesh(data=8)
        n, numel = 8, 512
        x = jnp.zeros((n, numel), jnp.bfloat16)
        we = jnp.zeros((n, numel), jnp.bfloat16)
        se = jnp.zeros((n, numel // n), jnp.bfloat16)
        txt = str(jax.make_jaxpr(
            lambda a, b, c: compressed_allreduce(a, b, c, mesh))(x, we, se))
        assert not re.findall(
            r"convert_element_type\[new_dtype=float32\]", txt), \
            "bf16 compressed path upcasts to f32"

    def test_bf16_roundtrip_dtypes_and_values(self, eight_devices):
        mesh = build_mesh(data=8)
        n, numel = 8, 512
        rng = np.random.default_rng(0)
        x16 = jnp.asarray(rng.standard_normal((n, numel)), jnp.bfloat16)
        out, we, se = compressed_allreduce(
            x16, jnp.zeros((n, numel), jnp.bfloat16),
            jnp.zeros((n, numel // n), jnp.bfloat16), mesh)
        assert out.dtype == jnp.bfloat16
        assert we.dtype == jnp.bfloat16 and se.dtype == jnp.bfloat16
        # same computation in fp32 agrees to bf16 resolution
        o32, _, _ = compressed_allreduce(
            x16.astype(jnp.float32),
            jnp.zeros((n, numel), jnp.float32),
            jnp.zeros((n, numel // n), jnp.float32), mesh)
        np.testing.assert_allclose(
            np.asarray(out[0], np.float32), np.asarray(o32[0]),
            atol=0.05, rtol=0.05)
