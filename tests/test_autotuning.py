"""Autotuner tests (autotuning/; docs/PERFORMANCE.md "Autotuning"):
config block + walls, the zero-overhead-off contract (no import at
engine init, zero syncs, bit-identical lowered step), the standalone
capacity projection pinned against the engine ledger path on MLP + GPT,
the ladder-reuse invariant (every tuner batch triple preserves the
global batch), the e2e search (capacity prune + trial elimination +
measured adoption + trajectory equality vs a hand-built engine), and
the probe/report CLI selftests (tier-1 wiring)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.config.config import (AutotuningConfig, ConfigError,
                                         DeepSpeedTPUConfig)

from simple_model import mlp_loss_fn, mlp_params

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS_DIR)

HIDDEN = 64


def _base_cfg(micro=2, gas=4, stage=2, **extra):
    cfg = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "steps_per_print": 10_000,
    }
    cfg.update(extra)
    return cfg


def _engine(cfg):
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn,
        params=mlp_params(hidden=HIDDEN, layers=2),
        config=cfg, rng_seed=0)
    return engine


def _make_batches_fn(seed=0):
    rng = np.random.default_rng(seed)

    def make_batches(micro, gas):
        return {
            "x": rng.standard_normal((gas, micro, HIDDEN)).astype(
                np.float32),
            "y": rng.standard_normal((gas, micro, 8)).astype(np.float32),
        }

    return make_batches


# ---------------------------------------------------------------------------
# Config block
# ---------------------------------------------------------------------------

class TestAutotuningConfig:
    def test_defaults(self):
        cfg = AutotuningConfig.from_dict(None)
        assert not cfg.enabled
        assert cfg.top_k == 3 and cfg.trial_steps == 3
        assert cfg.headroom_frac == 0.9
        assert cfg.result_file == "autotune_result.json"

    def test_env_override_enables(self, monkeypatch):
        monkeypatch.setenv("DSTPU_AUTOTUNE", "1")
        assert AutotuningConfig.from_dict(None).enabled
        monkeypatch.setenv("DSTPU_AUTOTUNE", "0")
        assert not AutotuningConfig.from_dict(None).enabled

    def test_explicit_enabled_false_beats_env(self, monkeypatch):
        """materialize() writes `enabled: false` into every candidate so
        nothing recursively searches — the launcher env must only flip
        configs that do NOT state a value."""
        monkeypatch.setenv("DSTPU_AUTOTUNE", "1")
        assert not AutotuningConfig.from_dict({"enabled": False}).enabled
        assert AutotuningConfig.from_dict({}).enabled

    @pytest.mark.parametrize("bad", [
        {"top_k": 0}, {"trial_steps": 0}, {"trial_warmup": -1},
        {"halving_factor": 1.0}, {"headroom_frac": 0.0},
        {"headroom_frac": 1.5}, {"hbm_limit_gb": -1},
        {"zero_stages": [5]}, {"dcn_quant_bits": [4]},
        {"overlap": ["maybe"]}, {"zeropp": ["fp8"]},
        {"micro_gas": [[0, 2]]}, {"micro_gas": "2x4"},
        {"bucket_mbs": 4.0}, {"overlap": "on"}, {"zeropp": "int8"},
        {"result_file": "tuned.json"}, {"max_candidates": 0},
    ])
    def test_validation(self, bad):
        with pytest.raises(ConfigError):
            AutotuningConfig.from_dict(bad)

    def test_walls_pipe_offload_onebit(self):
        at = {"autotuning": {"enabled": True}}
        with pytest.raises(ConfigError, match="pipeline"):
            DeepSpeedTPUConfig({**_base_cfg(stage=1), **at,
                                "pipeline": {"stages": 2}}, world_size=8)
        with pytest.raises(ConfigError, match="offload"):
            DeepSpeedTPUConfig({**_base_cfg(), **at,
                                "zero_optimization": {
                                    "stage": 2,
                                    "offload_optimizer": {"device": "cpu"}}},
                               world_size=8)
        with pytest.raises(ConfigError, match="1-bit"):
            DeepSpeedTPUConfig({**_base_cfg(), **at,
                                "optimizer": {"type": "OneBitAdam",
                                              "params": {"lr": 1e-3}}},
                               world_size=8)

    def test_micro_gas_override_must_preserve_global_batch(self):
        """A half-batch pair would trial ~2x 'faster' and silently change
        convergence — the enumeration refuses it with the valid splits."""
        cfg = DeepSpeedTPUConfig(
            _base_cfg(micro=2, gas=4,
                      autotuning={"micro_gas": [[2, 4], [2, 2]]}),
            world_size=8)
        from deepspeed_tpu.autotuning import enumerate_candidates
        with pytest.raises(ConfigError, match="change the global batch"):
            enumerate_candidates(cfg, {"data": 8, "dcn": 1}, world_size=8)

    def test_multi_process_search_walled(self, eight_devices,
                                         monkeypatch):
        """Per-host trial timings could adopt diverging configs on a
        multi-process fleet (mismatched collectives) — the explicit
        entry refuses until the measurements are agreed collectively."""
        engine = _engine(_base_cfg())
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        with pytest.raises(ConfigError, match="not coordinated"):
            deepspeed_tpu.autotune(engine, _make_batches_fn())

    def test_host_implied_tier_walled_at_autotune(self, eight_devices):
        # cpuadam resolves the host tier only at engine level — the
        # explicit autotune() entry must refuse it with the real cause.
        engine = _engine(_base_cfg(
            stage=1, optimizer={"type": "cpuadam", "params": {"lr": 1e-3}}))
        with pytest.raises(ConfigError, match="host optimizer tier"):
            deepspeed_tpu.autotune(engine, _make_batches_fn())


# ---------------------------------------------------------------------------
# Zero-overhead-off contract
# ---------------------------------------------------------------------------

class TestZeroOverheadOff:
    def test_no_autotuning_import_at_engine_init(self, eight_devices):
        for mod in list(sys.modules):
            if mod.startswith("deepspeed_tpu.autotuning"):
                sys.modules.pop(mod)
        _engine(_base_cfg())
        leaked = [m for m in sys.modules
                  if m.startswith("deepspeed_tpu.autotuning")]
        assert not leaked, leaked

    def test_lowered_step_bit_identical_when_off(self, eight_devices):
        batches = _make_batches_fn()(16, 4)
        texts = {}
        for name, extra in (("absent", {}),
                            ("disabled", {"autotuning":
                                          {"enabled": False}})):
            engine = _engine(_base_cfg(**extra))
            placed = engine.put_batch(batches, leading_gas_dim=True)
            texts[name] = engine._train_step.lower(
                engine.state, placed, jnp.float32(1e-3)).as_text()
        assert texts["absent"] == texts["disabled"]

    def test_zero_extra_syncs_when_off(self, eight_devices, monkeypatch):
        engine = _engine(_base_cfg())
        batches = _make_batches_fn()(16, 4)
        engine.train_batch(batches)          # compile outside the window
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        for _ in range(5):
            engine.train_batch(batches)
        assert calls["n"] == 0


# ---------------------------------------------------------------------------
# Satellite: standalone capacity projection == engine ledger path
# ---------------------------------------------------------------------------

class TestStandaloneProjection:
    def _engine_plan(self, engine):
        assert engine.memory is not None
        return engine.memory.last_plan

    def _tel(self, tmp_path):
        return {"telemetry": {"enabled": True, "dir": str(tmp_path),
                              "metrics": {"sinks": ["memory"]},
                              "trace": {"enabled": False},
                              "memory": {"enabled": True,
                                         "hbm_limit_gb": 1.0}}}

    def test_mlp_paths_agree(self, eight_devices, tmp_path):
        cfg_dict = {**_base_cfg(stage=2), **self._tel(tmp_path)}
        engine = _engine(cfg_dict)
        from deepspeed_tpu.telemetry.memory import plan_capacity_from_config
        standalone = plan_capacity_from_config(
            engine.config, engine.state.params,
            hbm_limit_bytes=1.0 * 1024**3)
        assert standalone == self._engine_plan(engine)

    def test_gpt_mixed_precision_paths_agree(self, eight_devices,
                                             tmp_path):
        from deepspeed_tpu.models import make_gpt
        model, mcfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=128)
        ids = np.zeros((2, 32), np.int32)
        params = model.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)},
                            {"input_ids": ids})["params"]
        cfg_dict = {**_base_cfg(micro=1, gas=2, stage=3),
                    "bf16": {"enabled": True},
                    "data_types": {"grad_accum_dtype": "bfloat16"},
                    **self._tel(tmp_path)}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params, config=cfg_dict)
        from deepspeed_tpu.telemetry.memory import plan_capacity_from_config
        standalone = plan_capacity_from_config(
            engine.config, engine.state.params,
            hbm_limit_bytes=1.0 * 1024**3)
        assert standalone == self._engine_plan(engine)

    def test_shape_only_leaves_work(self):
        # The tuner's pruning path has no placed arrays — ShapeDtypeStructs
        # must be enough.
        from deepspeed_tpu.telemetry.memory import state_totals_from_shapes
        shapes = {"w": jax.ShapeDtypeStruct((64, 64), jnp.float32),
                  "b": jax.ShapeDtypeStruct((64,), jnp.float32)}
        t = state_totals_from_shapes(shapes, optimizer_name="adam")
        p = 64 * 64 + 64
        assert t["total_params"] == p
        assert t["master_bytes"] == 4 * p
        assert t["optimizer_bytes"] == 8 * p + 4
        assert t["compute_params_bytes"] == 0
        t2 = state_totals_from_shapes(shapes, optimizer_name="sgd",
                                      optimizer_params={"momentum": 0.9},
                                      precision_dtype="bfloat16",
                                      grad_accum_dtype="bfloat16")
        assert t2["optimizer_bytes"] == 4 * p
        assert t2["compute_params_bytes"] == 2 * p
        assert t2["grads_bytes"] == 2 * p


# ---------------------------------------------------------------------------
# Satellite: ladder reuse — every tuner batch triple preserves the
# global batch
# ---------------------------------------------------------------------------

class TestLadderReuse:
    ELASTIC = {
        "elasticity": {"enabled": True, "max_train_batch_size": 128,
                       "micro_batch_sizes": [1, 2, 4], "min_chips": 1,
                       "max_chips": 64, "version": 0.1},
    }

    def test_valid_batch_splits_preserve_global_batch(self):
        from deepspeed_tpu.elasticity import (compute_elastic_config,
                                              valid_batch_splits)
        final, valid = compute_elastic_config(self.ELASTIC, "0.1.0")
        for world in valid:
            splits = valid_batch_splits(self.ELASTIC, world)
            assert splits, world
            for micro, gas in splits:
                assert micro * gas * world == final, (micro, gas, world)
        # the world_size mode's micro is the head of the same list — one
        # implementation, not a copy
        _, _, micro = compute_elastic_config(self.ELASTIC, "0.1.0",
                                             world_size=valid[0])
        assert micro == valid_batch_splits(self.ELASTIC, valid[0])[0][0]

    def test_tuner_candidates_preserve_global_batch_elastic(self):
        # elasticity owns the batch keys — no explicit triple
        cfg = DeepSpeedTPUConfig(
            {"optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
             "zero_optimization": {"stage": 2}, **self.ELASTIC},
            world_size=8)
        from deepspeed_tpu.autotuning import enumerate_candidates
        cands, _ = enumerate_candidates(
            cfg, {"data": 8, "dcn": 1}, world_size=8)
        assert len(cands) >= 3
        for c in cands:
            assert (c.micro * c.gas * 8 == cfg.train_batch_size), c

    def test_overlap_variants_dedupe_and_names_unique(self):
        """overlap auto/on resolve identically (resolve_overlap), so the
        pair must collapse to ONE candidate; and names are globally
        unique — search.py keys its records/configs by name."""
        cfg = DeepSpeedTPUConfig(
            {**_base_cfg(), "mesh": {"slices": 2},
             "autotuning": {"zero_stages": [2], "micro_gas": [[2, 4]],
                            "dcn_quant_bits": [8], "bucket_mbs": [4.0],
                            "overlap": ["auto", "on"], "zeropp": ["off"]}},
            world_size=8)
        from deepspeed_tpu.autotuning import enumerate_candidates
        cands, _ = enumerate_candidates(cfg, {"data": 4, "dcn": 2},
                                        world_size=8)
        names = [c.name for c in cands]
        assert len(names) == len(set(names)), names
        on_like = [c for c in cands
                   if c.overlap in ("auto", "on") and c.hierarchical
                   in ("auto", "on")]
        assert len(on_like) == 1, names

    def test_tuner_candidates_preserve_global_batch_non_elastic(self):
        cfg = DeepSpeedTPUConfig(_base_cfg(micro=2, gas=4), world_size=8)
        from deepspeed_tpu.autotuning import enumerate_candidates
        cands, _ = enumerate_candidates(cfg, {"data": 8, "dcn": 1},
                                        world_size=8)
        assert len(cands) >= 3
        for c in cands:
            assert c.micro * c.gas == 8, c   # per-chip product preserved


# ---------------------------------------------------------------------------
# The e2e acceptance search
# ---------------------------------------------------------------------------

class TestEndToEndSearch:
    def test_capacity_prune_trial_eliminate_adopt_and_trajectory(
            self, eight_devices, tmp_path):
        """A search over >= 3 candidates: one projected over the HBM
        budget (pruned with its reason), one measurably slower
        (eliminated by the trial's successive halving), the winner's
        measured step time <= the default's — all three verdicts in
        autotune_result.json — and the adopted engine training the SAME
        loss trajectory as a hand-built engine with the winning
        config."""
        # MLP model states are ~KBs; the activation term dominates, so a
        # per-sample estimate of 1 MB against a 4 MB HBM budget prunes
        # exactly the micro=8 candidate (8 MB) and keeps micro<=2.
        at = {"enabled": False,       # explicit autotune() call below
              "zero_stages": [2],
              "micro_gas": [[2, 4], [1, 8], [8, 1]],
              "top_k": 2, "trial_steps": 3, "trial_warmup": 1,
              # any strictly-slower trial is eliminated, so the
              # "measurably slower" verdict is recorded deterministically
              "halving_factor": 1.0001,
              "activation_bytes_per_sample": 1e6,
              "hbm_limit_gb": 0.004}
        engine = _engine(_base_cfg(autotuning=at))
        make_batches = _make_batches_fn()
        result = deepspeed_tpu.autotune(engine, make_batches,
                                        result_dir=str(tmp_path))

        by_name = {r["name"]: r for r in result["candidates"]}
        assert len(by_name) >= 3
        fat = by_name["stage2-mb8x1"]
        assert fat["status"] == "pruned_capacity"
        assert "capacity:" in fat["reason"]
        assert fat["projected_device_bytes"] > 0.9 * 0.004 * 1024**3
        # both surviving candidates were MEASURED; the loser was
        # eliminated by the trial with the halving reason recorded
        trialed = [r for r in result["candidates"]
                   if r["measured_step_ms"] is not None]
        assert len(trialed) == 2
        loser = next(r for r in trialed
                     if r["name"] != result["adopted"]["name"])
        assert loser["status"] == "eliminated"
        assert "successive halving" in loser["reason"]
        # the winner's measured step time <= the default's (the default
        # is always trialed, so this is a measured statement)
        assert result["default_measured_step_ms"] is not None
        assert (result["adopted"]["measured_step_ms"]
                <= result["default_measured_step_ms"])
        # persisted with all three verdicts
        path = result["result_path"]
        assert os.path.exists(path)
        disk = json.load(open(path))
        statuses = {r["name"]: r["status"] for r in disk["candidates"]}
        assert statuses["stage2-mb8x1"] == "pruned_capacity"
        assert statuses[loser["name"]] == "eliminated"
        assert statuses[result["adopted"]["name"]] == "adopted"

        # the search restored the pre-search state: step counters intact
        assert engine.global_steps == 0

        # trajectory equality: the adopted engine == a hand-built engine
        # with the winning config, from the same params/seed
        micro = engine.train_micro_batch_size_per_gpu * engine.dp_size
        gas = engine.gradient_accumulation_steps
        feed = _make_batches_fn(seed=123)
        fixed = [feed(micro, gas) for _ in range(4)]
        losses_tuned = [float(engine.train_batch(b)) for b in fixed]

        hand, _, _, _ = deepspeed_tpu.initialize(
            loss_fn=mlp_loss_fn,
            params=mlp_params(hidden=HIDDEN, layers=2),
            config=result["adopted"]["config"], rng_seed=0)
        assert (hand.train_micro_batch_size_per_gpu,
                hand.gradient_accumulation_steps) == (
                    engine.train_micro_batch_size_per_gpu, gas)
        losses_hand = [float(hand.train_batch(b)) for b in fixed]
        np.testing.assert_allclose(losses_tuned, losses_hand, rtol=1e-6)

    def test_gauges_goodput_and_state_restore(self, eight_devices,
                                              tmp_path):
        at = {"zero_stages": [2], "micro_gas": [[2, 4], [1, 8]],
              "top_k": 2, "trial_steps": 2, "trial_warmup": 1}
        cfg = _base_cfg(
            autotuning=at,
            telemetry={"enabled": True, "dir": str(tmp_path),
                       "metrics": {"sinks": ["memory"]},
                       "trace": {"enabled": False}})
        engine = _engine(cfg)
        make_batches = _make_batches_fn()
        # a couple of real steps BEFORE the search: the restore must
        # bring the counters back to exactly this point
        pre = [float(engine.train_batch(make_batches(16, 4)))
               for _ in range(2)]
        assert engine.global_steps == 2
        result = deepspeed_tpu.autotune(engine, make_batches)
        assert engine.global_steps == 2, "search must restore step count"
        del pre
        # gauges emitted
        mem = engine.telemetry.registry.sinks[0]
        tags = {t for t in mem.tags() if t.startswith("autotune/")}
        assert {"autotune/candidates", "autotune/pruned",
                "autotune/trials", "autotune/search_sec",
                "autotune/best_step_ms"} <= tags
        # the whole window books to the autotune_search category — and
        # NOT to productive_step (trial steps are quiesced)
        totals = engine.goodput.totals()
        assert totals["autotune_search"] > 0
        assert result["search_sec"] > 0
        # result persisted into the telemetry dir without an explicit
        # result_dir
        assert os.path.exists(tmp_path / "autotune_result.json")
        # the engine keeps training after the search
        float(engine.train_batch(make_batches(
            engine.train_micro_batch_size_per_gpu * engine.dp_size,
            engine.gradient_accumulation_steps)))

    def test_trial_steps_never_emit_numerics(self, eight_devices,
                                             tmp_path):
        """Trial steps run under CANDIDATE configs — their per-group
        stats must never land in the production numerics series (the
        observatory's emission is quiesced; the plan stays, so trial
        programs match the adopted one)."""
        at = {"zero_stages": [2], "micro_gas": [[2, 4], [1, 8]],
              "top_k": 2, "trial_steps": 2, "trial_warmup": 1}
        cfg = _base_cfg(
            autotuning=at, steps_per_print=1,   # every step flushes
            telemetry={"enabled": True, "dir": str(tmp_path),
                       "metrics": {"sinks": ["memory"]},
                       "trace": {"enabled": False},
                       "numerics": {"enabled": True}})
        engine = _engine(cfg)
        make_batches = _make_batches_fn()
        deepspeed_tpu.autotune(engine, make_batches)
        mem = engine.telemetry.registry.sinks[0]
        trial_rows = {t for t in mem.tags() if t.startswith("numerics/")}
        assert not trial_rows, trial_rows
        # emission restored: a REAL step emits again
        float(engine.train_batch(make_batches(
            engine.train_micro_batch_size_per_gpu * engine.dp_size,
            engine.gradient_accumulation_steps)))
        assert any(t.startswith("numerics/") for t in mem.tags())

    def test_failed_search_still_books_goodput_window(self,
                                                      eight_devices,
                                                      tmp_path):
        """Every trial failing must raise — but the search window still
        books to autotune_search, never to the next productive mark."""
        at = {"zero_stages": [2], "micro_gas": [[2, 4]], "top_k": 1,
              "trial_steps": 1, "trial_warmup": 1}
        cfg = _base_cfg(
            autotuning=at,
            telemetry={"enabled": True, "dir": str(tmp_path),
                       "metrics": {"sinks": ["memory"]},
                       "trace": {"enabled": False}})
        engine = _engine(cfg)

        def broken(micro, gas):
            raise ValueError("no data source")

        with pytest.raises(ConfigError, match="every measured trial"):
            deepspeed_tpu.autotune(engine, broken)
        totals = engine.goodput.totals()
        assert totals["autotune_search"] > 0
        assert totals["productive_step"] == 0

    def test_initialize_autotune_batches_entry(self, eight_devices,
                                               tmp_path):
        """The initialize(autotune_batches=...) wiring: enabled block +
        batch source => the engine comes back already tuned."""
        at = {"enabled": True, "zero_stages": [2],
              "micro_gas": [[2, 4], [1, 8]], "top_k": 2,
              "trial_steps": 2, "trial_warmup": 1}
        engine, _, _, _ = deepspeed_tpu.initialize(
            loss_fn=mlp_loss_fn,
            params=mlp_params(hidden=HIDDEN, layers=2),
            config=_base_cfg(autotuning=at), rng_seed=0,
            autotune_batches=_make_batches_fn())
        # adopted config is one of the two splits, state restored
        assert engine.global_steps == 0
        assert (engine.train_micro_batch_size_per_gpu,
                engine.gradient_accumulation_steps) in ((2, 4), (1, 8))

    def test_default_itself_capacity_pruned(self, eight_devices,
                                            tmp_path):
        """The tuner's prime scenario: the hand-picked config projects
        over HBM. The incumbent is pruned (not trialed) and the search
        still adopts the fastest FITTING candidate instead of dying."""
        at = {"zero_stages": [2], "micro_gas": [[8, 1], [1, 8]],
              "top_k": 2, "trial_steps": 2, "trial_warmup": 1,
              "activation_bytes_per_sample": 1e6,
              "hbm_limit_gb": 0.004}
        # base micro=8 => 8 MB activations projected against a ~3.9 MB
        # budget: the default candidate itself is pruned_capacity
        engine = _engine(_base_cfg(micro=8, gas=1, autotuning=at))
        result = deepspeed_tpu.autotune(engine, _make_batches_fn(),
                                        result_dir=str(tmp_path))
        by_name = {r["name"]: r for r in result["candidates"]}
        assert by_name["default"]["status"] == "pruned_capacity"
        assert result["default_measured_step_ms"] is None
        assert result["adopted"]["name"] == "stage2-mb1x8"
        assert result["adopted"]["measured_step_ms"] is not None
        assert (engine.train_micro_batch_size_per_gpu,
                engine.gradient_accumulation_steps) == (1, 8)

    def test_adopted_hash_distinct_across_elastic_splits(self):
        """Under the elastic ladder two batch splits materialize
        byte-identical config dicts — the adopted hash must still tell
        them apart (it folds the batch triple in)."""
        from deepspeed_tpu.telemetry.goodput import config_hash
        d = {"optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
        h1 = config_hash({**d, "_autotune_batch_triple": [1, 8]})
        h2 = config_hash({**d, "_autotune_batch_triple": [8, 1]})
        assert h1 != h2

    def test_zeropp_candidate_trial_rebuild(self, eight_devices):
        """The zeropp search axis exercises the _elastic_rebuild param-
        gather re-derivation: a forced int8 candidate must trial (and
        train) without poisoning the search."""
        at = {"zero_stages": [3], "micro_gas": [[2, 1]],
              "zeropp": ["off", "int8"], "top_k": 3,
              "trial_steps": 2, "trial_warmup": 1}
        engine = _engine(_base_cfg(
            gas=1, stage=3,
            zero_optimization={"stage": 3,
                               "stage3_param_persistence_threshold": 0},
            autotuning=at))
        result = deepspeed_tpu.autotune(engine, _make_batches_fn())
        by_name = {r["name"]: r for r in result["candidates"]}
        zpp = next(r for n, r in by_name.items() if "zpp-int8" in n)
        assert zpp["measured_step_ms"] is not None, zpp
        # whichever won, the engine still trains
        float(engine.train_batch(_make_batches_fn()(
            engine.train_micro_batch_size_per_gpu * engine.dp_size,
            engine.gradient_accumulation_steps)))


# ---------------------------------------------------------------------------
# CLI selftests (tier-1 wiring)
# ---------------------------------------------------------------------------

class TestCLISelftests:
    def test_probe_autotune_selftest(self, tmp_path):
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "probe_autotune.py"),
             "--selftest"],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=570)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "selftest ok" in proc.stdout
        row = json.loads([l for l in proc.stdout.splitlines()
                          if l.startswith("{")][-1])
        assert row["adopted_ms"] is not None

    def test_autotune_report_selftest(self):
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "autotune_report.py"),
             "--selftest"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "selftest ok" in proc.stdout

    def test_autotune_report_renders_real_result(self, eight_devices,
                                                 tmp_path):
        at = {"zero_stages": [2], "micro_gas": [[2, 4], [1, 8]],
              "top_k": 2, "trial_steps": 2, "trial_warmup": 1}
        engine = _engine(_base_cfg(autotuning=at))
        deepspeed_tpu.autotune(engine, _make_batches_fn(),
                               result_dir=str(tmp_path))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "autotune_report.py"),
             str(tmp_path)],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "adopted:" in proc.stdout
        assert "default" in proc.stdout


# ---------------------------------------------------------------------------
# MoE axes (num_experts prune-only, capacity_factor/dispatch trialable)
# ---------------------------------------------------------------------------

class TestMoEAxes:
    MOE = {"moe": {"enabled": True, "num_experts": 8, "k": 1,
                   "dispatch": "scatter"},
           "mesh": {"expert": 2}}

    def _cfg(self, autotuning):
        from deepspeed_tpu.config.config import DeepSpeedTPUConfig
        return DeepSpeedTPUConfig(
            {**_base_cfg(), **self.MOE, "autotuning": autotuning},
            world_size=8)

    def test_enumerate_crosses_moe_axes(self):
        from deepspeed_tpu.autotuning import enumerate_candidates
        cfg = self._cfg({"enabled": True, "zero_stages": [1],
                         "moe_capacity_factors": [1.0, 1.25, 2.0],
                         "moe_dispatch": ["scatter", "alltoall"]})
        cands, _notes = enumerate_candidates(cfg, {"data": 4, "dcn": 1,
                                                   "expert": 2},
                                             world_size=8)
        combos = {(c.moe_capacity_factor, c.moe_dispatch) for c in cands}
        assert {(1.0, "scatter"), (1.25, "alltoall"),
                (2.0, "alltoall")} <= combos
        # every candidate on an MoE workload carries the moe knobs
        assert all(c.moe_experts is not None for c in cands)
        named = [c.name for c in cands if c.moe_dispatch == "alltoall"]
        assert named and all("alltoall" in n and "e8" in n for n in named)

    def test_axes_collapse_when_moe_off(self):
        from deepspeed_tpu.autotuning import enumerate_candidates
        from deepspeed_tpu.config.config import DeepSpeedTPUConfig
        cfg = DeepSpeedTPUConfig(
            {**_base_cfg(),
             "autotuning": {"enabled": True, "zero_stages": [1],
                            "moe_dispatch": ["alltoall"]}},
            world_size=8)
        cands, notes = enumerate_candidates(cfg, {"data": 8, "dcn": 1},
                                            world_size=8)
        assert all(c.moe_experts is None and c.moe_dispatch is None
                   for c in cands)
        assert any("moe axes collapsed" in n for n in notes)

    def test_materialize_writes_moe_block(self):
        from deepspeed_tpu.autotuning import enumerate_candidates
        from deepspeed_tpu.autotuning.space import materialize
        cfg = self._cfg({"enabled": True, "zero_stages": [1],
                         "moe_capacity_factors": [2.0],
                         "moe_dispatch": ["alltoall"]})
        cands, _ = enumerate_candidates(cfg, {"data": 4, "dcn": 1,
                                              "expert": 2}, world_size=8)
        cand = next(c for c in cands if c.moe_dispatch == "alltoall")
        d = materialize({**_base_cfg(), **self.MOE}, cand, cfg)
        assert d["moe"]["enabled"] is True
        assert d["moe"]["num_experts"] == 8
        assert d["moe"]["capacity_factor"] == 2.0
        assert d["moe"]["dispatch"] == "alltoall"
        # the untouched knobs survive (k from the base block)
        assert d["moe"]["k"] == 1

    def test_invalid_expert_count_pruned_by_config_parse(self):
        """Stage-1 pruning IS the ordinary config validation: an expert
        count the mesh can't shard fails the parse, costing nothing."""
        from deepspeed_tpu.autotuning.space import Candidate, materialize
        from deepspeed_tpu.config.config import (ConfigError,
                                                 DeepSpeedTPUConfig)
        cfg = self._cfg({"enabled": True})
        cand = Candidate(name="bad", zero_stage=1, micro=2, gas=4,
                         moe_experts=5, moe_capacity_factor=1.25,
                         moe_dispatch="scatter")
        d = materialize({**_base_cfg(), **self.MOE}, cand, cfg)
        with pytest.raises(ConfigError, match="num_experts"):
            DeepSpeedTPUConfig(d, world_size=8)
