"""Doc-drift lint (tier-1): the metric tables in docs/OBSERVABILITY.md are
enforced against the code, not aspirational.

Statically scans every ``deepspeed_tpu/**/*.py`` for registry metric tag
literals — ``.gauge("…")`` / ``.counter("…")`` / ``.histogram("…")`` plus
the ``self._counter("…")`` wrappers — and asserts each emitted tag appears
in the doc. For the goodput surface the check runs in BOTH directions:
every ``goodput/*`` (and ``engine/mfu``) tag the accountant can emit must
be documented, and every goodput tag the doc names must be one the code
emits, so the doc cannot silently rot in either direction.

Pure text scanning, no jax import beyond the package's own — fast enough
for tier-1.
"""

import os
import re

from deepspeed_tpu.autotuning.search import AUTOTUNE_METRIC_TAGS
from deepspeed_tpu.comm.grad_sync import COMM_PARAM_METRIC_TAGS
from deepspeed_tpu.resilience.elastic import ELASTIC_METRIC_TAGS
from deepspeed_tpu.serving.engine import SERVING_METRIC_TAGS
from deepspeed_tpu.telemetry.devicetime import DEVICETIME_METRIC_TAGS
from deepspeed_tpu.telemetry.fleet import FLEET_METRIC_TAGS
from deepspeed_tpu.telemetry.goodput import GOODPUT_METRIC_TAGS
from deepspeed_tpu.telemetry.memory import MEMORY_METRIC_TAGS
from deepspeed_tpu.telemetry.moe import MOE_METRIC_TAGS
from deepspeed_tpu.telemetry.numerics import NUMERICS_METRIC_TAGS
from deepspeed_tpu.telemetry.requests import (
    ENGINE_CATEGORIES,
    REQUEST_CATEGORIES,
    REQUEST_METRIC_TAGS,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "deepspeed_tpu")
DOC = os.path.join(REPO, "docs", "OBSERVABILITY.md")

# .gauge("a/b") / .counter(f"a/{x}") / .histogram('a') / ._counter("a/b")
_METRIC_CALL_RE = re.compile(
    r"\.(?:gauge|counter|histogram|_counter)\(\s*(f?)([\"'])([^\"']+)\2")
_GOODPUT_TOKEN_RE = re.compile(r"goodput/[A-Za-z_]+")
_FLEET_TOKEN_RE = re.compile(r"fleet/[A-Za-z_]+")
_MEMORY_TOKEN_RE = re.compile(r"memory/[A-Za-z_]+")
_SERVING_TOKEN_RE = re.compile(r"serving/[A-Za-z_]+")
_DEVICETIME_TOKEN_RE = re.compile(r"devicetime/[A-Za-z_]+")
_NUMERICS_TOKEN_RE = re.compile(r"numerics/[A-Za-z_]+")
_COMM_PARAMS_TOKEN_RE = re.compile(r"comm/[A-Za-z_]+_params")
# \b so "elasticity/" (the package path) never false-positives
_ELASTIC_TOKEN_RE = re.compile(r"\belastic/[A-Za-z_]+")
# \b so "autotuning/" (the package path) never false-positives
_AUTOTUNE_TOKEN_RE = re.compile(r"\bautotune/[A-Za-z_]+")
# "moe/" is ALSO the package path (moe/layer.py, moe/dispatch.py), so a
# token followed by a dot/slash/word char (a file or module reference)
# is not a metric tag.
_MOE_TOKEN_RE = re.compile(r"\bmoe/[A-Za-z_]+(?![\w./])")
# the doc writes the templated "requests/engine_<category>_sec" — the
# (?![\w<]) lookahead (with backtracking blocked by \w) keeps the
# "requests/engine_" prefix of that placeholder from scanning as a tag
_REQUESTS_TOKEN_RE = re.compile(r"\brequests/[A-Za-z_]+(?![\w<])")


def _iter_py_files():
    for root, _dirs, files in os.walk(PKG):
        if "__pycache__" in root:
            continue
        for name in files:
            if name.endswith(".py"):
                yield os.path.join(root, name)


def _emitted_literals():
    """[(file, is_fstring, tag_literal)] for every metric-call literal in
    the package."""
    out = []
    for path in _iter_py_files():
        with open(path) as f:
            src = f.read()
        for m in _METRIC_CALL_RE.finditer(src):
            out.append((os.path.relpath(path, REPO), bool(m.group(1)),
                        m.group(3)))
    return out


def _doc_text():
    with open(DOC) as f:
        return f.read()


class TestDocDrift:
    def test_scan_finds_the_known_call_sites(self):
        """The regex must actually see the tree's emissions — if the scan
        collapses to nothing, the lint below would pass vacuously."""
        tags = {t for _, _, t in _emitted_literals()}
        assert "engine/hbm_peak_bytes" in tags
        assert "ckpt/write_latency_sec" in tags      # _counter/gauge wrappers
        assert "guardrails/rollbacks" in tags
        assert any(t.startswith("goodput/") for t in tags)
        assert len(tags) > 10

    def test_every_emitted_tag_is_documented(self):
        doc = _doc_text()
        missing = []
        for fname, is_fstring, tag in _emitted_literals():
            # f-strings contribute their static prefix (e.g.
            # f"guardrails/steps_{kind}" -> "guardrails/steps_", a
            # substring of the documented guardrails/steps_ok row).
            probe = tag.split("{")[0] if is_fstring else tag
            if not probe:
                continue
            if probe not in doc:
                missing.append(f"{fname}: {tag!r}")
        assert not missing, (
            "metric tags emitted but absent from docs/OBSERVABILITY.md "
            f"(add rows): {sorted(set(missing))}")

    def test_goodput_tags_documented_and_vice_versa(self):
        doc = _doc_text()
        # forward: everything the accountant can emit is in the doc
        undocumented = sorted(t for t in GOODPUT_METRIC_TAGS
                              if t not in doc)
        assert not undocumented, undocumented
        # reverse: every goodput/* token the doc names is really emitted
        doc_tokens = set(_GOODPUT_TOKEN_RE.findall(doc))
        phantom = sorted(t for t in doc_tokens
                         if t not in GOODPUT_METRIC_TAGS)
        assert not phantom, (
            f"docs/OBSERVABILITY.md names goodput tags the code never "
            f"emits: {phantom}")
        assert "engine/mfu" in doc

    def test_fleet_tags_documented_and_vice_versa(self):
        """The fleet surface (telemetry/fleet.py) is pinned in BOTH
        directions like goodput: every tag the aggregator can emit —
        the fleet/* gauges, the straggler instant and counter — must be
        in the doc, and every fleet/* token the doc names must be one
        the code emits."""
        doc = _doc_text()
        undocumented = sorted(t for t in FLEET_METRIC_TAGS if t not in doc)
        assert not undocumented, undocumented
        doc_tokens = set(_FLEET_TOKEN_RE.findall(doc))
        phantom = sorted(t for t in doc_tokens
                         if t not in FLEET_METRIC_TAGS)
        assert not phantom, (
            f"docs/OBSERVABILITY.md names fleet tags the code never "
            f"emits: {phantom}")
        # the device-time attribution gauge rides the same enforcement
        assert "comm/exposed_frac" in doc

    def test_memory_tags_documented_and_vice_versa(self):
        """The memory-observatory surface (telemetry/memory.py) is
        pinned in BOTH directions like goodput/fleet: every tag the
        observatory can emit — the xla_*/ledger_*/headroom gauges, the
        OOM counter and the instant names — must be in the doc, and
        every memory/* token the doc names must be one the code
        emits."""
        doc = _doc_text()
        undocumented = sorted(t for t in MEMORY_METRIC_TAGS if t not in doc)
        assert not undocumented, undocumented
        doc_tokens = set(_MEMORY_TOKEN_RE.findall(doc))
        phantom = sorted(t for t in doc_tokens
                         if t not in MEMORY_METRIC_TAGS)
        assert not phantom, (
            f"docs/OBSERVABILITY.md names memory tags the code never "
            f"emits: {phantom}")

    def test_devicetime_tags_documented_and_vice_versa(self):
        """The device-time surface (telemetry/devicetime.py) is pinned in
        BOTH directions like goodput/fleet/memory: every tag the
        observatory can emit — the per-category gauges, the capture
        counter, the divergence instant and the measured exposed-comm
        gauge — must be in the doc, and every devicetime/* token the doc
        names must be one the code emits."""
        doc = _doc_text()
        undocumented = sorted(t for t in DEVICETIME_METRIC_TAGS
                              if t not in doc)
        assert not undocumented, undocumented
        doc_tokens = set(_DEVICETIME_TOKEN_RE.findall(doc))
        phantom = sorted(t for t in doc_tokens
                         if t not in DEVICETIME_METRIC_TAGS)
        assert not phantom, (
            f"docs/OBSERVABILITY.md names devicetime tags the code never "
            f"emits: {phantom}")
        # the measured companion of comm/exposed_frac rides the same
        # enforcement (it is a DEVICETIME_METRIC_TAGS member)
        assert "comm/measured_exposed_frac" in DEVICETIME_METRIC_TAGS
        assert "comm/measured_exposed_frac" in doc

    def test_comm_param_tags_documented_and_vice_versa(self):
        """The ZeRO++ param-hop comm gauges (comm/grad_sync.py
        COMM_PARAM_METRIC_TAGS) are pinned in BOTH directions: every tag
        the ParamGatherPlan can emit must be in the doc, every
        comm/*_params token the doc names must be one the code emits,
        and every literal *_params emission in the tree is a declared
        tag — so fleet/devicetime dashboards can rely on the param-vs-
        grad traffic split staying documented."""
        doc = _doc_text()
        undocumented = sorted(t for t in COMM_PARAM_METRIC_TAGS
                              if t not in doc)
        assert not undocumented, undocumented
        doc_tokens = set(_COMM_PARAMS_TOKEN_RE.findall(doc))
        phantom = sorted(t for t in doc_tokens
                         if t not in COMM_PARAM_METRIC_TAGS)
        assert not phantom, (
            f"docs/OBSERVABILITY.md names comm param tags the code never "
            f"emits: {phantom}")
        emitted = {t for _, _, t in _emitted_literals()
                   if _COMM_PARAMS_TOKEN_RE.fullmatch(t)}
        assert emitted, "the scan must see the param-hop emissions"
        assert emitted <= COMM_PARAM_METRIC_TAGS, (
            emitted - COMM_PARAM_METRIC_TAGS)

    def test_elastic_tags_documented_and_vice_versa(self):
        """The live-elasticity surface (resilience/elastic.py) is pinned
        in BOTH directions like goodput/fleet: every tag the coordinator
        can emit — the elastic/* gauges plus the decision/event instants
        — must be in the doc, and every elastic/* token the doc names
        must be one the code emits."""
        doc = _doc_text()
        undocumented = sorted(t for t in ELASTIC_METRIC_TAGS
                              if t not in doc)
        assert not undocumented, undocumented
        doc_tokens = set(_ELASTIC_TOKEN_RE.findall(doc))
        phantom = sorted(t for t in doc_tokens
                         if t not in ELASTIC_METRIC_TAGS)
        assert not phantom, (
            f"docs/OBSERVABILITY.md names elastic tags the code never "
            f"emits: {phantom}")
        # every literal elastic/* emission in the tree is a declared tag
        emitted = {t for _, _, t in _emitted_literals()
                   if t.startswith("elastic/")}
        assert emitted, "the scan must see the elastic gauge emissions"
        assert emitted <= ELASTIC_METRIC_TAGS, (
            emitted - ELASTIC_METRIC_TAGS)
        # the reshard wall-clock category rides the goodput enforcement
        assert "goodput/elastic_reshard_sec" in GOODPUT_METRIC_TAGS
        assert "goodput/elastic_reshard_sec" in doc

    def test_numerics_tags_documented_and_vice_versa(self):
        """The numerics surface (telemetry/numerics.py) is pinned in
        BOTH directions like goodput/fleet/memory/devicetime: every tag
        the observatory surface can emit — the per-group gauges, the
        global grad norm, the DCN and KV quantization-error gauges —
        must be in the doc, and every numerics/* token the doc names
        must be one the code emits."""
        doc = _doc_text()
        undocumented = sorted(t for t in NUMERICS_METRIC_TAGS
                              if t not in doc)
        assert not undocumented, undocumented
        doc_tokens = set(_NUMERICS_TOKEN_RE.findall(doc))
        phantom = sorted(t for t in doc_tokens
                         if t not in NUMERICS_METRIC_TAGS)
        assert not phantom, (
            f"docs/OBSERVABILITY.md names numerics tags the code never "
            f"emits: {phantom}")
        # every literal numerics/* emission in the tree is a declared tag
        emitted = {t for _, _, t in _emitted_literals()
                   if t.startswith("numerics/")}
        assert emitted, "the scan must see the numerics emissions"
        assert emitted <= NUMERICS_METRIC_TAGS, (
            emitted - NUMERICS_METRIC_TAGS)

    def test_autotune_tags_documented_and_vice_versa(self):
        """The autotuner surface (autotuning/search.py) is pinned in BOTH
        directions like goodput/fleet/memory: every tag the search can
        emit — the autotune/* gauges plus the adoption instant — must be
        in the doc, and every autotune/* token the doc names must be one
        the code emits."""
        doc = _doc_text()
        undocumented = sorted(t for t in AUTOTUNE_METRIC_TAGS
                              if t not in doc)
        assert not undocumented, undocumented
        doc_tokens = set(_AUTOTUNE_TOKEN_RE.findall(doc))
        phantom = sorted(t for t in doc_tokens
                         if t not in AUTOTUNE_METRIC_TAGS)
        assert not phantom, (
            f"docs/OBSERVABILITY.md names autotune tags the code never "
            f"emits: {phantom}")
        # every literal autotune/* emission in the tree is a declared tag
        emitted = {t for _, _, t in _emitted_literals()
                   if t.startswith("autotune/")}
        assert emitted, "the scan must see the autotune gauge emissions"
        assert emitted <= AUTOTUNE_METRIC_TAGS, (
            emitted - AUTOTUNE_METRIC_TAGS)
        # the search-window wall-clock category rides the goodput
        # enforcement
        assert "goodput/autotune_search_sec" in GOODPUT_METRIC_TAGS
        assert "goodput/autotune_search_sec" in doc

    def test_moe_tags_documented_and_vice_versa(self):
        """The MoE observatory surface (telemetry/moe.py) is pinned in
        BOTH directions like goodput/fleet/numerics: every tag the
        monitor can emit — the four moe/* gauges — must be in the doc,
        and every moe/* metric token the doc names (file references like
        moe/layer.py are screened by the regex) must be one the code
        emits."""
        doc = _doc_text()
        undocumented = sorted(t for t in MOE_METRIC_TAGS if t not in doc)
        assert not undocumented, undocumented
        doc_tokens = set(_MOE_TOKEN_RE.findall(doc))
        phantom = sorted(t for t in doc_tokens
                         if t not in MOE_METRIC_TAGS)
        assert not phantom, (
            f"docs/OBSERVABILITY.md names moe tags the code never "
            f"emits: {phantom}")
        # the monitor's computed emission ("moe/" + aux suffix) must map
        # exactly onto the declared tag set — a renamed aux key would
        # silently drop a gauge otherwise
        from deepspeed_tpu.telemetry.moe import MOE_AUX_KEYS
        derived = {"moe/" + k[len("moe_"):] for k in MOE_AUX_KEYS}
        assert derived == set(MOE_METRIC_TAGS), (
            derived ^ set(MOE_METRIC_TAGS))

    def test_autotune_report_tags_in_sync(self):
        """tools/autotune_report.py is stdlib-only by design (no package
        import), so its private tag tuple is pinned here instead — every
        autotune/* literal the report reads must be one the search
        emits."""
        with open(os.path.join(REPO, "tools", "autotune_report.py")) as f:
            src = f.read()
        report_tags = set(re.findall(r'"(autotune/[A-Za-z_]+)"', src))
        assert report_tags, "scan must see autotune_report's tags"
        phantom = sorted(t for t in report_tags
                         if t not in AUTOTUNE_METRIC_TAGS)
        assert not phantom, (
            f"tools/autotune_report.py reads tags the code never emits: "
            f"{phantom} — keep it in sync with autotuning/search.py")

    def test_numerics_report_tags_in_sync(self):
        """tools/numerics_report.py is stdlib-only by design (no package
        import), so its private tag tuples are pinned here instead —
        every numerics/* literal the report reads must be one the
        observatory surface emits."""
        with open(os.path.join(REPO, "tools", "numerics_report.py")) as f:
            src = f.read()
        report_tags = set(re.findall(r'"(numerics/[A-Za-z_]+)"', src))
        assert report_tags, "scan must see numerics_report's tags"
        phantom = sorted(t for t in report_tags
                         if t not in NUMERICS_METRIC_TAGS)
        assert not phantom, (
            f"tools/numerics_report.py reads tags the code never emits: "
            f"{phantom} — keep it in sync with telemetry/numerics.py")

    def test_devicetime_report_tags_in_sync(self):
        """tools/devicetime_report.py is stdlib-only by design (it loads
        traceparse by file path, no package import), so its tag/key
        strings are pinned here instead — every devicetime/* literal the
        report names must be one the observatory emits."""
        with open(os.path.join(REPO, "tools",
                               "devicetime_report.py")) as f:
            src = f.read()
        report_tags = set(re.findall(r'"(devicetime/[A-Za-z_]+)"', src))
        phantom = sorted(t for t in report_tags
                         if t not in DEVICETIME_METRIC_TAGS)
        assert not phantom, (
            f"tools/devicetime_report.py reads tags the code never emits: "
            f"{phantom} — keep it in sync with telemetry/devicetime.py")

    def test_serving_tags_documented_and_vice_versa(self):
        """The serving SLO surface (serving/engine.py) is pinned in BOTH
        directions like goodput/fleet/memory: every tag in
        SERVING_METRIC_TAGS must be in the doc, and every serving/* token
        the doc names must be one the code emits."""
        doc = _doc_text()
        undocumented = sorted(t for t in SERVING_METRIC_TAGS
                              if t not in doc)
        assert not undocumented, undocumented
        doc_tokens = set(_SERVING_TOKEN_RE.findall(doc))
        phantom = sorted(t for t in doc_tokens
                         if t not in SERVING_METRIC_TAGS)
        assert not phantom, (
            f"docs/OBSERVABILITY.md names serving tags the code never "
            f"emits: {phantom}")
        # every literal serving/* emission in the tree is a declared tag
        emitted = {t for _, _, t in _emitted_literals()
                   if t.startswith("serving/")}
        assert emitted, "the scan must see the serving emissions"
        assert emitted <= SERVING_METRIC_TAGS, (
            emitted - SERVING_METRIC_TAGS)
        # the decode fast path's per-piece gauges ride this enforcement —
        # pin them explicitly so a rename can't silently drop a piece's
        # attribution (docs/SERVING.md "Decode fast path")
        assert {"serving/decode_attn_kernel", "serving/prefix_hits",
                "serving/prefix_blocks_reused", "serving/spec_accept_rate",
                "serving/spec_tokens_per_verify"} <= SERVING_METRIC_TAGS
        # the resilience layer's counters/gauge likewise (docs/SERVING.md
        # "Serving under failure")
        assert {"serving/shed_requests", "serving/deadline_expired",
                "serving/cancelled", "serving/retries",
                "serving/recoveries",
                "serving/degraded_level"} <= SERVING_METRIC_TAGS

    def test_request_tags_documented_and_vice_versa(self):
        """The request-observatory surface (telemetry/requests.py) is
        pinned in BOTH directions like goodput/fleet/serving: every tag
        in REQUEST_METRIC_TAGS must be in the doc, and every requests/*
        token the doc names must be one the accountant emits. The
        per-category gauges are f-string emissions
        (f"requests/{c}_sec"), so the literal-emission check covers the
        non-f-string tags and the tag set itself covers the rest."""
        doc = _doc_text()
        undocumented = sorted(t for t in REQUEST_METRIC_TAGS
                              if t not in doc)
        assert not undocumented, undocumented
        doc_tokens = set(_REQUESTS_TOKEN_RE.findall(doc))
        assert doc_tokens, "the scan must see the documented request tags"
        phantom = sorted(t for t in doc_tokens
                         if t not in REQUEST_METRIC_TAGS)
        assert not phantom, (
            f"docs/OBSERVABILITY.md names request tags the code never "
            f"emits: {phantom}")
        # every literal (non-f-string) requests/* emission in the tree
        # is a declared tag
        emitted = {t for _, is_f, t in _emitted_literals()
                   if not is_f and t.startswith("requests/")}
        assert emitted, "the scan must see the request emissions"
        assert emitted <= REQUEST_METRIC_TAGS, (
            emitted - REQUEST_METRIC_TAGS)
        # the derived per-category tags must map exactly onto the
        # declared set — a renamed category would silently drop a gauge
        derived = ({f"requests/{c}_sec" for c in REQUEST_CATEGORIES}
                   | {f"requests/engine_{c}_sec"
                      for c in ENGINE_CATEGORIES})
        assert derived <= REQUEST_METRIC_TAGS, (
            derived - REQUEST_METRIC_TAGS)
        # the rolling-window companion gauge rides the serving
        # enforcement
        assert "serving/tokens_per_sec_window" in SERVING_METRIC_TAGS
        assert "serving/tokens_per_sec_window" in doc

    def test_slo_report_tags_in_sync(self):
        """tools/slo_report.py is stdlib-only by design (no package
        import), so its private tag/category copies are pinned here
        instead — every requests/* literal the report reads must be one
        the accountant emits, and its category tuples must mirror
        telemetry/requests.py exactly."""
        with open(os.path.join(REPO, "tools", "slo_report.py")) as f:
            src = f.read()
        report_tags = set(re.findall(r'"(requests/[A-Za-z_]+)"', src))
        assert report_tags, "scan must see slo_report's tags"
        # trailing-underscore literals are startswith() prefix probes
        # (e.g. "requests/engine_"), not tags
        phantom = sorted(t for t in report_tags
                         if not t.endswith("_")
                         and t not in REQUEST_METRIC_TAGS)
        assert not phantom, (
            f"tools/slo_report.py reads tags the code never emits: "
            f"{phantom} — keep it in sync with telemetry/requests.py")
        for cat in REQUEST_CATEGORIES + ENGINE_CATEGORIES:
            assert f'"{cat}"' in src, (
                f"tools/slo_report.py category tuples are missing "
                f"{cat!r} — keep them in sync with telemetry/requests.py")

    def test_serving_report_tags_in_sync(self):
        """tools/serving_report.py is stdlib-only by design (no package
        import), so its private tag tuples are pinned here instead —
        every tag the report reads must be one the engine emits."""
        with open(os.path.join(REPO, "tools", "serving_report.py")) as f:
            src = f.read()
        report_tags = set(re.findall(r'"(serving/[A-Za-z_]+)"', src))
        assert report_tags, "scan must see serving_report's tags"
        phantom = sorted(t for t in report_tags
                         if t not in SERVING_METRIC_TAGS)
        assert not phantom, (
            f"tools/serving_report.py reads tags the code never emits: "
            f"{phantom} — keep it in sync with serving/engine.py")

    def test_memory_report_gauges_in_sync(self):
        """tools/memory_report.py is stdlib-only by design (no package
        import), so its private gauge lists are pinned here instead —
        every gauge the report reads must be one the code emits."""
        with open(os.path.join(REPO, "tools", "memory_report.py")) as f:
            src = f.read()
        report_tags = set(re.findall(r'"((?:memory|engine)/[A-Za-z_]+)"',
                                     src))
        known = MEMORY_METRIC_TAGS | {"engine/hbm_peak_bytes"}
        phantom = sorted(t for t in report_tags if t not in known)
        assert not phantom, (
            f"tools/memory_report.py reads gauges the code never emits: "
            f"{phantom} — keep it in sync with telemetry/memory.py")

    def test_goodput_report_categories_in_sync(self):
        """tools/goodput_report.py is stdlib-only by design (no package
        import), so its private copy of the category list is pinned here
        instead."""
        from deepspeed_tpu.telemetry.goodput import CATEGORIES
        with open(os.path.join(REPO, "tools", "goodput_report.py")) as f:
            src = f.read()
        for cat in CATEGORIES:
            assert f'"{cat}"' in src, (
                f"tools/goodput_report.py CATEGORIES is missing {cat!r} — "
                "keep it in sync with telemetry/goodput.py")
