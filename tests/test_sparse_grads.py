"""Engine-automatic sparse-gradient exchange (config `sparse_gradients:
true` — reference deepspeed/runtime/engine.py:1530-1586, csr_tensor.py):
the in-tree families' embedding_lookup VJP exchanges (ids, touched rows)
over the data axes instead of letting GSPMD all-reduce the dense [V, D]
cotangent. Wire bytes ∝ batch tokens; trajectory matches dense."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import make_gpt
from deepspeed_tpu.parallel.mesh import build_mesh

# SEQ chosen so BS*SEQ=384 collides with no weight dimension of the tiny
# model (the HLO shape assertions below must be unambiguous).
VOCAB, HIDDEN, SEQ, BS, GAS = 2048, 64, 24, 16, 2


def _engine(mesh, sparse: bool, model=None, cfg=None):
    if model is None:
        model, cfg = make_gpt("tiny", dtype=jnp.float32, dropout_rate=0.0,
                              vocab_size=VOCAB, max_seq_len=SEQ)
    rng = np.random.default_rng(0)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        {"input_ids": np.zeros((2, SEQ), np.int32)})["params"]
    config = {
        "train_micro_batch_size_per_gpu": BS // 8,
        "gradient_accumulation_steps": GAS,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
    }
    if sparse:
        config["sparse_gradients"] = True
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=params, mesh=mesh, config=config)
    return engine


def _batches(rng):
    return {"input_ids": rng.integers(0, VOCAB, (GAS, BS, SEQ),
                                      dtype=np.int32)}


class TestSparseGradients:
    def test_trajectory_parity_vs_dense(self, eight_devices, rng):
        mesh = build_mesh(data=8)
        batches = _batches(rng)
        dense = _engine(mesh, sparse=False)
        sparse = _engine(mesh, sparse=True)
        for step in range(3):
            ld = float(dense.train_batch(batches))
            ls = float(sparse.train_batch(batches))
            # same math, different summation route (all_gather+scatter vs
            # GSPMD all-reduce) — fp32-close, not bitwise
            np.testing.assert_allclose(ls, ld, rtol=1e-5,
                                       err_msg=f"step {step}")

    def test_wire_bytes_proportional_to_touched_rows(self, eight_devices,
                                                     rng):
        """The sparse build's cross-rank exchange for the embedding leaf
        is an all_gather of (ids, rows) — per-rank wire bytes
        N_local * D * 4 — and the compiled step stops all-reducing any
        [V, D] buffer. The dense build all-reduces the full table grad."""
        mesh = build_mesh(data=8)
        batches = _batches(rng)

        def hlo(engine):
            b = engine.put_batch(batches, leading_gas_dim=True)
            lowered = engine._train_step.lower(
                engine.state, b, jnp.float32(1e-3))
            return lowered.compile().as_text()

        dense_hlo = hlo(_engine(mesh, sparse=False))
        sparse_hlo = hlo(_engine(mesh, sparse=True))

        # Structural: the rows exchange (an all_gather producing the
        # global [tokens, D] row set; shard_map-lowered collectives keep
        # jaxpr-style underscore names) exists ONLY in the sparse build.
        # The GSPMD-inserted dense table-grad reduction is NOT visible in
        # XLA:CPU's compiled text (partitioner collectives lower to
        # runtime thunks), so the quantitative wire accounting lives at
        # the op level: tests/test_memory.py's row_sparse_allreduce test
        # and the byte arithmetic below.
        tokens = BS * SEQ
        rows_pat = (rf"all[-_]gather[\w.]*\s*=\s*\(?f32\[{tokens},"
                    rf"{HIDDEN}\]")
        assert re.search(rows_pat, sparse_hlo), "rows all-gather missing"
        assert not re.search(rows_pat, dense_hlo)

        # Per-rank wire bytes of the exchange the sparse build performs
        # instead of the dense [V, D] ring all-reduce: ids + rows.
        table_bytes = 4 * VOCAB * HIDDEN          # dense exchange operand
        rows_bytes = 4 * tokens * (HIDDEN + 1)    # sparse exchange, global
        assert rows_bytes < table_bytes / 3       # the premise: tokens << V

    def test_exchange_operand_is_rows_not_table(self, eight_devices, rng):
        """jaxpr-level: the sparse VJP's collective moves the LOCAL token
        rows ([tokens/8, D] per rank), never a [V, ...] operand."""
        from deepspeed_tpu.ops.embedding import embedding_lookup
        from deepspeed_tpu.parallel.mesh import (get_default_mesh,
                                                 set_default_mesh)

        saved = get_default_mesh()
        mesh = build_mesh(data=8)
        set_default_mesh(mesh)
        table = jnp.zeros((VOCAB, HIDDEN), jnp.float32)
        ids = jnp.zeros((BS, SEQ), jnp.int32)

        def loss(t):
            out = embedding_lookup(t, ids, sparse_grad_axes=("data",))
            return jnp.sum(out * out)

        try:
            text = str(jax.make_jaxpr(jax.grad(loss))(table))
        finally:
            set_default_mesh(saved)
        # the exchange's outputs are the gathered global rows (+ids)...
        tokens = BS * SEQ
        assert re.search(rf"f32\[{tokens},{HIDDEN}\] = all_gather", text)
        assert re.search(rf"i32\[{tokens}\] = all_gather", text)
        # ...and no collective anywhere produces a [V, ...] operand
        assert not re.search(
            rf"f32\[{VOCAB},[\d]*\] = (all_gather|psum|all_to_all)", text)

    def test_custom_loss_fn_still_raises(self, eight_devices):
        from deepspeed_tpu.config.config import ConfigError

        def loss_fn(params, batch, rng):
            return jnp.sum(params["w"] ** 2)

        with pytest.raises(ConfigError, match="sparse_grad"):
            deepspeed_tpu.TPUEngine(
                loss_fn=loss_fn, params={"w": jnp.ones(4)},
                config=deepspeed_tpu.DeepSpeedTPUConfig({
                    "train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "sparse_gradients": True}),
                mesh=build_mesh(data=8))

    def test_engine_mesh_pinned_not_ambient(self, eight_devices, rng):
        """The surgery bakes the ENGINE's mesh into the model config: a
        stale ambient mesh from an unrelated engine (the multi-engine
        footgun — an aborted compile in-suite before this fix) must not
        capture the exchange."""
        from deepspeed_tpu.parallel.mesh import (get_default_mesh,
                                                 set_default_mesh)

        saved = get_default_mesh()
        try:
            # poison the ambient mesh with mismatched axes
            set_default_mesh(build_mesh(data=2, pipe=2, sequence=2))
            mesh = build_mesh(data=8)
            engine = _engine(mesh, sparse=True)
            batches = _batches(rng)
            loss = float(engine.train_batch(batches))
            assert np.isfinite(loss)
        finally:
            set_default_mesh(saved)

    def test_op_level_sum_semantics(self, eight_devices, rng):
        """embedding_lookup(sparse_grad_axes) must produce the SAME dense
        cotangent as plain take under a data-sharded batch."""
        from deepspeed_tpu.ops.embedding import embedding_lookup
        from deepspeed_tpu.parallel.mesh import (get_default_mesh,
                                                 set_default_mesh)

        saved_mesh = get_default_mesh()
        mesh = build_mesh(data=8)
        set_default_mesh(mesh)
        table = jnp.asarray(rng.standard_normal((VOCAB, HIDDEN)),
                            jnp.float32)
        ids = jnp.asarray(rng.integers(0, VOCAB, (BS, SEQ)), jnp.int32)

        def loss(fn):
            def f(t):
                out = fn(t, ids)
                return jnp.sum(out * (out + 1.0))
            return f

        from jax.sharding import NamedSharding, PartitionSpec as P
        ids = jax.device_put(ids, NamedSharding(mesh, P("data")))

        try:
            g_sparse = jax.jit(jax.grad(loss(
                lambda t, i: embedding_lookup(
                    t, i, sparse_grad_axes=("data",)))))(table)
            g_dense = jax.jit(jax.grad(loss(
                lambda t, i: embedding_lookup(t, i))))(table)
        finally:
            set_default_mesh(saved_mesh)
        np.testing.assert_allclose(np.asarray(g_sparse),
                                   np.asarray(g_dense),
                                   rtol=1e-5, atol=1e-5)
