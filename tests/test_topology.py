"""Topology/grid unit tests (reference tests/unit/test_topology.py — pure
Python, no devices)."""

import pytest

from deepspeed_tpu.parallel.topology import (PipeDataParallelTopology,
                                             PipeModelDataParallelTopology,
                                             PipelineParallelGrid,
                                             ProcessTopology)


def test_topology_2d():
    topo = ProcessTopology(axes=["row", "col"], dims=[2, 2])
    assert topo.world_size() == 4
    assert topo.get_rank(row=0, col=0) == 0
    assert topo.get_rank(row=0, col=1) == 1
    assert topo.get_rank(row=1, col=0) == 2
    assert topo.get_rank(row=1, col=1) == 3


def test_topology_coord_roundtrip():
    topo = ProcessTopology(axes=["pipe", "data"], dims=[2, 4])
    for r in range(8):
        c = topo.get_coord(r)
        assert topo.get_rank(pipe=c.pipe, data=c.data) == r


def test_topology_comm_lists():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
    pipe_lists = topo.get_axis_comm_lists("pipe")
    assert pipe_lists == [[0, 2], [1, 3]]
    data_lists = topo.get_axis_comm_lists("data")
    assert data_lists == [[0, 1], [2, 3]]


def test_topology_filter_match():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    assert topo.world_size() == 8
    ranks = topo.filter_match(pipe=0)
    assert len(ranks) == 4
    assert all(topo.get_coord(r).pipe == 0 for r in ranks)


def test_topology_rank_repr():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=1)
    s = topo.get_rank_repr(rank=0)
    assert "pipe_00" in s and "model_00" in s


def test_grid_stage_ids():
    topo = PipeDataParallelTopology(num_pp=4, num_dp=2)
    grid = PipelineParallelGrid(topo, global_rank=5)
    assert grid.pipe_parallel_size == 4
    assert grid.data_parallel_size == 2
    coord = topo.get_coord(5)
    assert grid.stage_id == coord.pipe
    assert grid.data_parallel_id == coord.data
    assert not grid.is_first_stage() or coord.pipe == 0


def test_grid_p2p_pairs_cover_all_stages():
    topo = PipeDataParallelTopology(num_pp=4, num_dp=2)
    grid = PipelineParallelGrid(topo, global_rank=0)
    # each dp slice contributes num_pp pairs (incl. wraparound)
    assert len(grid.p2p_matrix) == 4 * 2


def test_grid_stage_to_global():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
    grid = PipelineParallelGrid(topo, global_rank=0)
    r = grid.stage_to_global(stage_id=1)
    assert topo.get_coord(r).pipe == 1
    assert topo.get_coord(r).data == grid.data_parallel_id


def test_invalid_axes():
    with pytest.raises(ValueError):
        ProcessTopology(axes=["a", "a"], dims=[2, 2])
    with pytest.raises(ValueError):
        ProcessTopology(axes=["a"], dims=[2, 2])
