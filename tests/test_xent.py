"""Fused cross-entropy head (ops/xent.py) — parity against the stock
log-softmax path, gradients included. In fp32 the fused op is numerically
the same computation, so parity is tight."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.gpt import cross_entropy_with_ignore
from deepspeed_tpu.ops.xent import fused_cross_entropy


def _data(rng, n=64, d=32, v=97, ignore_frac=0.2):
    x = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((v, d)) * 0.1, jnp.float32)
    labels = rng.integers(0, v, n)
    labels = np.where(rng.random(n) < ignore_frac, -100, labels)
    return x, w, jnp.asarray(labels, jnp.int32)


class TestFusedXent:
    def test_loss_parity_fp32(self):
        rng = np.random.default_rng(0)
        x, w, labels = _data(rng)

        ref = cross_entropy_with_ignore(
            jnp.einsum("nd,vd->nv", x, w,
                       preferred_element_type=jnp.float32)[None],
            labels[None])
        got = fused_cross_entropy(x, w, labels)
        np.testing.assert_allclose(float(ref), float(got), rtol=1e-6)

    def test_grad_parity_fp32(self):
        rng = np.random.default_rng(1)
        x, w, labels = _data(rng)

        def ref_loss(x, w):
            logits = jnp.einsum("nd,vd->nv", x, w,
                                preferred_element_type=jnp.float32)
            return cross_entropy_with_ignore(logits[None], labels[None])

        def fused_loss(x, w):
            return fused_cross_entropy(x, w, labels)

        gx_r, gw_r = jax.grad(ref_loss, argnums=(0, 1))(x, w)
        gx_f, gw_f = jax.grad(fused_loss, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx_r), np.asarray(gx_f),
                                   rtol=1e-4, atol=1e-7)
        np.testing.assert_allclose(np.asarray(gw_r), np.asarray(gw_f),
                                   rtol=1e-4, atol=1e-7)

    def test_all_ignored_is_zero(self):
        rng = np.random.default_rng(2)
        x, w, _ = _data(rng)
        labels = jnp.full((x.shape[0],), -100, jnp.int32)
        assert float(fused_cross_entropy(x, w, labels)) == 0.0
        g = jax.grad(lambda x: fused_cross_entropy(x, w, labels))(x)
        assert float(jnp.abs(g).max()) == 0.0

    def test_transposed_kernel(self):
        rng = np.random.default_rng(3)
        x, w, labels = _data(rng)
        a = fused_cross_entropy(x, w, labels)
        b = fused_cross_entropy(x, w.T, labels, w_transposed=True)
        np.testing.assert_allclose(float(a), float(b), rtol=1e-6)

    def test_bias_parity(self):
        rng = np.random.default_rng(4)
        x, w, labels = _data(rng)
        bias = jnp.asarray(rng.standard_normal(w.shape[0]), jnp.float32)

        def ref_loss(x, w, b):
            logits = jnp.einsum("nd,vd->nv", x, w,
                                preferred_element_type=jnp.float32) + b
            return cross_entropy_with_ignore(logits[None], labels[None])

        def fused_loss(x, w, b):
            return fused_cross_entropy(x, w, labels, bias=b)

        np.testing.assert_allclose(float(ref_loss(x, w, bias)),
                                   float(fused_loss(x, w, bias)), rtol=1e-6)
        gr = jax.grad(ref_loss, argnums=(0, 1, 2))(x, w, bias)
        gf = jax.grad(fused_loss, argnums=(0, 1, 2))(x, w, bias)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-7)

    def test_batched_shape(self):
        """[B, S, D] activations flatten internally."""
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((33, 16)) * 0.1, jnp.float32)
        labels = jnp.asarray(rng.integers(0, 33, (2, 8)), jnp.int32)
        ref = cross_entropy_with_ignore(
            jnp.einsum("bsd,vd->bsv", x, w,
                       preferred_element_type=jnp.float32), labels)
        got = fused_cross_entropy(x, w, labels)
        np.testing.assert_allclose(float(ref), float(got), rtol=1e-6)

    def test_residuals_exclude_logits(self):
        """The point of the op: no [N, V]-sized residual survives from
        forward to backward (only lse [N] + the inputs)."""
        rng = np.random.default_rng(6)
        x, w, labels = _data(rng, n=32, d=16, v=1024)

        def loss(x, w):
            return fused_cross_entropy(x, w, labels)

        # jaxpr of the vjp: residual avals between fwd and bwd
        _, vjp = jax.vjp(loss, x, w)
        n, v = 32, 1024
        res_sizes = [int(np.prod(var.aval.shape))
                     for var in jax.tree_util.tree_leaves(vjp)
                     if hasattr(var, "aval")]
        big = [s for s in res_sizes if s >= n * v]
        assert not big, f"[N,V]-sized residuals saved: {res_sizes}"


class TestFp32LogitsMode:
    """logits_fp32=True (ADVICE r3): bf16 inputs must reproduce the unfused
    fp32-logits path EXACTLY — no bf16 rounding of the logits before the
    logsumexp — while the default mode is allowed to differ."""

    def test_bf16_exact_parity_with_unfused(self):
        rng = np.random.default_rng(0)
        x, w, labels = _data(rng)
        xb, wb = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)

        def unfused(xb, wb):
            logits = jnp.einsum("nd,vd->nv", xb, wb,
                                preferred_element_type=jnp.float32)
            return cross_entropy_with_ignore(logits, labels)

        def fused32(xb, wb):
            return fused_cross_entropy(xb, wb, labels, logits_fp32=True)

        l_ref, g_ref = jax.value_and_grad(unfused, argnums=(0, 1))(xb, wb)
        l_f32, g_f32 = jax.value_and_grad(fused32, argnums=(0, 1))(xb, wb)
        np.testing.assert_allclose(float(l_ref), float(l_f32), rtol=1e-6)
        for a, b in zip(g_ref, g_f32):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=1e-5, atol=1e-6)

    def test_default_mode_unchanged(self):
        rng = np.random.default_rng(1)
        x, w, labels = _data(rng)
        l_def = fused_cross_entropy(x, w, labels)
        l_32 = fused_cross_entropy(x, w, labels, logits_fp32=True)
        # fp32 inputs: both modes identical
        np.testing.assert_allclose(float(l_def), float(l_32), rtol=1e-6)
