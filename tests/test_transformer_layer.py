"""DeepSpeedTransformerLayer parity grid — the test_cuda_forward/backward
analogue (reference tests/unit/test_cuda_forward.py: sweep (batch, seq,
hidden, heads) and compare the fused layer against the reference modeling
math; here the oracle is the in-tree BertLayer, whose math the layer must
reproduce exactly when the kernel options are off, and to remat-tolerance
when they are on)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.bert import BertConfig, BertLayer
from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                           DeepSpeedTransformerLayer)

GRID = [
    # (batch, seq, hidden, heads)
    (2, 16, 32, 4),
    (1, 64, 64, 8),
    (3, 8, 48, 3),
]


def make_pair(b, s, d, h, pre_ln=True, **opts):
    cfg = DeepSpeedTransformerConfig(
        batch_size=b, hidden_size=d, heads=h, max_seq_length=s,
        attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
        pre_layer_norm=pre_ln, num_hidden_layers=1, **opts)
    layer = DeepSpeedTransformerLayer(cfg)
    bcfg = BertConfig(hidden_size=d, num_heads=h, dropout_rate=0.0,
                      pre_layer_norm=pre_ln, max_seq_len=s,
                      dtype=jnp.float32, layer_norm_epsilon=1e-12)
    oracle = BertLayer(bcfg)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((b, s, d)), jnp.float32)
    params = layer.init({"params": jax.random.PRNGKey(0)}, x)["params"]
    return layer, oracle, params, x


class TestForwardParity:
    @pytest.mark.parametrize("b,s,d,h", GRID)
    @pytest.mark.parametrize("pre_ln", [True, False])
    def test_matches_bert_layer(self, b, s, d, h, pre_ln):
        layer, oracle, params, x = make_pair(b, s, d, h, pre_ln)
        got = layer.apply({"params": params}, x, deterministic=True)
        want = oracle.apply({"params": params}, x, None, True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_param_tree_matches_bert_naming(self):
        layer, _, params, _ = make_pair(2, 16, 32, 4)
        assert {"ln_attn", "ln_mlp", "c_attn", "c_proj", "c_fc", "mlp_proj"} <= \
            set(params)

    def test_attention_mask_applied(self):
        layer, oracle, params, x = make_pair(2, 16, 32, 4)
        am = np.ones((2, 16), np.int32)
        am[0, 8:] = 0
        mask = jnp.asarray(am)[:, None, None, :].astype(bool)
        got = layer.apply({"params": params}, x, mask, True)
        want = oracle.apply({"params": params}, x, mask, True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


class TestBackwardParity:
    @pytest.mark.parametrize("b,s,d,h", GRID[:2])
    @pytest.mark.parametrize("opts", [
        {},
        {"normalize_invertible": True},
        {"gelu_checkpoint": True},
        {"attn_dropout_checkpoint": True},
        {"normalize_invertible": True, "gelu_checkpoint": True,
         "attn_dropout_checkpoint": True},
    ])
    def test_grads_match_oracle(self, b, s, d, h, opts):
        """The kernel memory options must not change gradients — remat
        recomputes, it does not reorder math."""
        layer, oracle, params, x = make_pair(b, s, d, h, **opts)

        def loss_fused(p):
            return jnp.sum(layer.apply({"params": p}, x,
                                       deterministic=True) ** 2)

        def loss_oracle(p):
            return jnp.sum(oracle.apply({"params": p}, x, None, True) ** 2)

        g_fused = jax.grad(loss_fused)(params)
        g_oracle = jax.grad(loss_oracle)(params)
        for a, b_ in zip(jax.tree_util.tree_leaves(g_fused),
                         jax.tree_util.tree_leaves(g_oracle)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-4, atol=2e-4)


class TestOptions:
    def test_dropout_stochastic_between_calls(self):
        layer, _, params, x = make_pair(2, 16, 32, 4)
        cfg = DeepSpeedTransformerConfig(
            hidden_size=32, heads=4, attn_dropout_ratio=0.2,
            hidden_dropout_ratio=0.2, num_hidden_layers=1,
            stochastic_mode=True)
        drop_layer = DeepSpeedTransformerLayer(cfg)
        p = drop_layer.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)}, x)["params"]
        a = drop_layer.apply({"params": p}, x, None, False,
                             rngs={"dropout": jax.random.PRNGKey(2)})
        b = drop_layer.apply({"params": p}, x, None, False,
                             rngs={"dropout": jax.random.PRNGKey(3)})
        assert np.abs(np.asarray(a) - np.asarray(b)).max() > 1e-4

    def test_intermediate_size_defaults_to_4x(self):
        cfg = DeepSpeedTransformerConfig(hidden_size=64, heads=4)
        assert cfg.intermediate_size == 256

    def test_tp_rules_shard_the_layer(self, eight_devices):
        from deepspeed_tpu.models import bert_partition_rules, build_specs
        from jax.sharding import PartitionSpec

        layer, _, params, _ = make_pair(2, 16, 256, 4)
        specs = build_specs(params, bert_partition_rules(),
                            mesh_axes={"model": 4})
        assert specs["c_attn"]["kernel"] == PartitionSpec(None, "model")
        assert specs["c_proj"]["kernel"] == PartitionSpec("model", None)
