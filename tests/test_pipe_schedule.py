"""Pipeline schedule unit tests — pure Python, no devices (the analogue of
reference tests/unit/test_pipe_schedule.py): completeness, causality, and
1F1B interleaving properties over a grid of (microbatches, stages)."""

import itertools

import pytest

from deepspeed_tpu.parallel.pipe import schedule as sched


GRID = [(m, s) for m, s in itertools.product([1, 2, 4, 8], [1, 2, 3, 4])
        if m >= 1 and s >= 1]


def _collect(schedule_cls, micro_batches, stages):
    """Returns {stage: [(tick, instr), ...]}."""
    out = {}
    for stage in range(stages):
        sch = schedule_cls(micro_batches=micro_batches, stages=stages,
                           stage_id=stage)
        out[stage] = [(t, instr) for t, cmds in enumerate(sch.steps())
                      for instr in cmds]
    return out


def _ticks_of(events, kind, stage):
    return {instr.buffer_id if hasattr(instr, "buffer_id") else None: t
            for t, instr in events[stage] if isinstance(instr, kind)}


class TestTrainSchedule:
    @pytest.mark.parametrize("m,s", GRID)
    def test_each_microbatch_forward_and_backward_once(self, m, s):
        events = _collect(sched.TrainSchedule, m, s)
        for stage in range(s):
            fwd = [i for _, i in events[stage]
                   if isinstance(i, sched.ForwardPass)]
            bwd = [i for _, i in events[stage]
                   if isinstance(i, sched.BackwardPass)]
            assert len(fwd) == m, f"stage {stage}: {len(fwd)} forwards"
            assert len(bwd) == m, f"stage {stage}: {len(bwd)} backwards"

    @pytest.mark.parametrize("m,s", GRID)
    def test_causality(self, m, s):
        """fwd(mb, s) < fwd(mb, s+1); bwd(mb, s+1) < bwd(mb, s);
        fwd(mb, s) < bwd(mb, s)."""
        # Track by microbatch order of ForwardPass/BackwardPass appearance:
        # buffer ids recycle, so reconstruct microbatch ids by order.
        for stage in range(s):
            sch = sched.TrainSchedule(micro_batches=m, stages=s,
                                      stage_id=stage)
            fwd_ticks, bwd_ticks = [], []
            for t, cmds in enumerate(sch.steps()):
                for i in cmds:
                    if isinstance(i, sched.ForwardPass):
                        fwd_ticks.append(t)
                    elif isinstance(i, sched.BackwardPass):
                        bwd_ticks.append(t)
            # forwards and backwards are in microbatch order per stage
            assert fwd_ticks == sorted(fwd_ticks)
            assert bwd_ticks == sorted(bwd_ticks)
            for mb in range(m):
                assert fwd_ticks[mb] < bwd_ticks[mb]
            if stage > 0:
                prev = sched.TrainSchedule(micro_batches=m, stages=s,
                                           stage_id=stage - 1)
                prev_fwd = [t for t, cmds in enumerate(prev.steps())
                            for i in cmds if isinstance(i, sched.ForwardPass)]
                prev_bwd = [t for t, cmds in enumerate(prev.steps())
                            for i in cmds if isinstance(i, sched.BackwardPass)]
                for mb in range(m):
                    assert prev_fwd[mb] < fwd_ticks[mb]
                    assert bwd_ticks[mb] < prev_bwd[mb]

    @pytest.mark.parametrize("m,s", GRID)
    def test_sends_match_recvs(self, m, s):
        events = _collect(sched.TrainSchedule, m, s)
        for stage in range(s - 1):
            sends = sum(isinstance(i, sched.SendActivation)
                        for _, i in events[stage])
            recvs = sum(isinstance(i, sched.RecvActivation)
                        for _, i in events[stage + 1])
            assert sends == recvs == m
            gsends = sum(isinstance(i, sched.SendGrad)
                         for _, i in events[stage + 1])
            grecvs = sum(isinstance(i, sched.RecvGrad)
                         for _, i in events[stage])
            assert gsends == grecvs == m

    def test_terminates_with_step(self):
        sch = sched.TrainSchedule(micro_batches=4, stages=2, stage_id=0)
        steps = list(sch.steps())
        assert sched.OptimizerStep() in steps[-1]
        assert sched.ReduceGrads() in steps[-1]
        assert len(steps) == 2 * (4 + 2 - 1)

    def test_first_stage_loads_microbatches(self):
        sch = sched.TrainSchedule(micro_batches=3, stages=2, stage_id=0)
        loads = [i for cmds in sch.steps() for i in cmds
                 if isinstance(i, sched.LoadMicroBatch)]
        assert len(loads) == 3

    def test_steady_state_interleaves_1f1b(self):
        """With plenty of microbatches, mid-schedule ticks alternate
        fwd/bwd on every stage (the 1F1B property)."""
        m, s = 8, 4
        for stage in range(s):
            sch = sched.TrainSchedule(micro_batches=m, stages=s,
                                      stage_id=stage)
            kinds = []
            for cmds in sch.steps():
                for i in cmds:
                    if isinstance(i, (sched.ForwardPass, sched.BackwardPass)):
                        kinds.append(type(i).__name__)
            middle = kinds[s:-s] if s else kinds
            for a, b in zip(middle, middle[1:]):
                assert a != b, f"stage {stage} not interleaved: {kinds}"


class TestInferenceSchedule:
    @pytest.mark.parametrize("m,s", GRID)
    def test_forward_only_complete(self, m, s):
        events = _collect(sched.InferenceSchedule, m, s)
        for stage in range(s):
            fwd = [i for _, i in events[stage]
                   if isinstance(i, sched.ForwardPass)]
            assert len(fwd) == m
            assert not any(isinstance(i, sched.BackwardPass)
                           for _, i in events[stage])


class TestDataParallelSchedule:
    def test_degenerate(self):
        sch = sched.DataParallelSchedule(micro_batches=3, stages=1, stage_id=0)
        steps = list(sch.steps())
        assert len(steps) == 3
        assert sched.OptimizerStep() in steps[-1]


def test_bubble_fraction():
    assert sched.bubble_fraction(8, 1) == 0.0
    assert abs(sched.bubble_fraction(8, 4) - 3 / 11) < 1e-9
