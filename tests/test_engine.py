"""End-to-end engine tests over an 8-device CPU mesh: DP training, ZeRO
stages 0-3 parity, fp16 loss scaling, grad accumulation, fused train_batch.
(Reference analogues: tests/unit/test_fp16.py, test_zero.py,
test_dynamic_loss_scale.py.)"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deepspeed_tpu import initialize
from deepspeed_tpu.parallel.mesh import build_mesh

from simple_model import mlp_params, mlp_loss_fn, random_batch, random_batches


def _config(zero_stage=0, precision=None, gas=1, micro=8, world=8, **extra):
    cfg = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": zero_stage},
    }
    if precision == "bf16":
        cfg["bf16"] = {"enabled": True}
    elif precision == "fp16":
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
    cfg.update(extra)
    return cfg


def _make_engine(zero_stage=0, precision=None, gas=1, **extra):
    mesh = build_mesh(data=8)
    engine, _, _, _ = initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(),
        config=_config(zero_stage=zero_stage, precision=precision, gas=gas, **extra),
        mesh=mesh)
    return engine


def test_basic_training_reduces_loss(rng):
    engine = _make_engine()
    batch = random_batch(rng, batch_size=16)
    losses = []
    for _ in range(20):
        loss = engine.forward(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7
    assert engine.global_steps == 20


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stages_match_stage0(stage, rng):
    """All ZeRO stages must produce identical training trajectories — they
    change placement, not math (reference test_zero.py correctness idea)."""
    batches = [random_batch(rng, batch_size=16) for _ in range(5)]
    ref = _make_engine(zero_stage=0)
    for b in batches:
        ref.forward(b)
        ref.backward(None)
        ref.step()
    eng = _make_engine(zero_stage=stage)
    for b in batches:
        eng.forward(b)
        eng.backward(None)
        eng.step()
    ref_params = jax.device_get(ref.state.params)
    got_params = jax.device_get(eng.state.params)
    flat_ref = jax.tree_util.tree_leaves(ref_params)
    flat_got = jax.tree_util.tree_leaves(got_params)
    for a, b in zip(flat_ref, flat_got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def _is_sharded(arr) -> bool:
    return np.prod(arr.addressable_shards[0].data.shape) < arr.size


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_state_actually_sharded(stage):
    # persistence threshold 0 so the tiny test params shard in stage 3 too
    eng = _make_engine(zero_stage=stage,
                       zero_optimization={"stage": stage,
                                          "stage3_param_persistence_threshold": 0})
    m_leaves = jax.tree_util.tree_leaves(eng.state.opt_state.exp_avg)
    big = max(m_leaves, key=lambda x: x.size)
    assert _is_sharded(big), f"stage {stage}: moments not sharded over data axis"
    g_big = max(jax.tree_util.tree_leaves(eng.state.grad_acc), key=lambda x: x.size)
    p_big = max(jax.tree_util.tree_leaves(eng.state.params), key=lambda x: x.size)
    assert _is_sharded(g_big) == (stage >= 2)
    assert _is_sharded(p_big) == (stage == 3)


def test_zero0_nothing_sharded():
    eng = _make_engine(zero_stage=0)
    for leaf in jax.tree_util.tree_leaves(eng.state.params) + \
            jax.tree_util.tree_leaves(eng.state.grad_acc):
        assert not _is_sharded(leaf)


def test_gradient_accumulation_equivalence(rng):
    """gas=2 over half-batches == gas=1 over the full batch."""
    b1 = random_batch(rng, batch_size=8)
    b2 = random_batch(rng, batch_size=8)
    full = {k: np.concatenate([b1[k], b2[k]]) for k in b1}

    e_full = _make_engine(gas=1, micro=16)
    e_full.forward(full)
    e_full.backward(None)
    e_full.step()

    e_acc = _make_engine(gas=2, micro=8)
    for b in (b1, b2):
        e_acc.forward(b)
        e_acc.backward(None)
        e_acc.step()
    assert e_acc.global_steps == 1

    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(e_full.state.params)),
                    jax.tree_util.tree_leaves(jax.device_get(e_acc.state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_train_batch_fused_path(rng):
    """Fused scan path == loop of forward/backward/step."""
    gas = 4
    batches = random_batches(rng, gas=gas, batch_size=8)
    e1 = _make_engine(gas=gas, micro=8)
    loss = e1.train_batch(batches)
    assert np.isfinite(float(loss))
    assert e1.global_steps == 1

    e2 = _make_engine(gas=gas, micro=8)
    for i in range(gas):
        e2.forward({k: v[i] for k, v in batches.items()})
        e2.backward(None)
        e2.step()
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(e1.state.params)),
                    jax.tree_util.tree_leaves(jax.device_get(e2.state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_bf16_training_runs(rng):
    engine = _make_engine(precision="bf16")
    batch = random_batch(rng, batch_size=16)
    for _ in range(3):
        loss = engine.forward(batch)
        engine.backward(loss)
        engine.step()
    assert np.isfinite(float(loss))


def test_fp16_dynamic_loss_scale_overflow_skip(rng):
    """Inject an inf-producing batch: step must be skipped and scale lowered
    (reference test_dynamic_loss_scale.py)."""
    engine = _make_engine(precision="fp16")
    good = random_batch(rng, batch_size=16)
    engine.forward(good)
    engine.backward(None)
    engine.step()
    params_before = jax.device_get(engine.state.params)
    scale_before = engine.loss_scale()

    bad = {k: v.copy() for k, v in good.items()}
    bad["y"] = bad["y"] * np.float32(1e30)  # (pred - 1e30)^2 overflows fp32 loss
    engine.forward(bad)
    engine.backward(None)
    engine.step()
    assert engine.skipped_steps >= 1
    # params unchanged after the skipped step
    for a, b in zip(jax.tree_util.tree_leaves(params_before),
                    jax.tree_util.tree_leaves(jax.device_get(engine.state.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # hysteresis=2 default: scale may not shrink until exhausted; force another
    engine.forward(bad)
    engine.backward(None)
    engine.step()
    assert engine.loss_scale() <= scale_before


def test_gradient_clipping(rng):
    # SGD so the update magnitude tracks the (clipped) grad magnitude —
    # Adam's normalised update hides clipping.
    engine = _make_engine(gradient_clipping=1e-6,
                          optimizer={"type": "SGD", "params": {"lr": 1.0}})
    batch = random_batch(rng, batch_size=16)
    p_before = jax.device_get(engine.state.params)
    engine.forward(batch)
    engine.backward(None)
    engine.step()
    # with a tiny clip threshold the update must be tiny even at lr=1
    for a, b in zip(jax.tree_util.tree_leaves(p_before),
                    jax.tree_util.tree_leaves(jax.device_get(engine.state.params))):
        assert np.abs(np.asarray(a) - np.asarray(b)).max() < 1e-5


def test_lr_scheduler_integration(rng):
    engine = _make_engine(scheduler={"type": "WarmupLR",
                                     "params": {"warmup_max_lr": 0.1,
                                                "warmup_num_steps": 10}})
    assert engine.get_lr()[0] == pytest.approx(0.0)
    batch = random_batch(rng, batch_size=16)
    for _ in range(5):
        engine.forward(batch)
        engine.backward(None)
        engine.step()
    assert engine.get_lr()[0] == pytest.approx(0.05)


class TestGradAccumDtype:
    def test_bf16_accumulator_tracks_fp32(self, eight_devices):
        """data_types.grad_accum_dtype=bfloat16 (the reference's fp16-
        buffer analogue) must track the fp32-accumulator trajectory to
        bf16 tolerance, with the accumulator actually stored bf16."""
        import deepspeed_tpu

        def loss_fn(p, b, r):
            return jnp.mean((jnp.tanh(b["x"] @ p["w"]) - b["y"]) ** 2)

        def build(acc):
            params = {"w": jax.random.normal(jax.random.PRNGKey(0),
                                             (16, 8)) * 0.1}
            e, _, _, _ = deepspeed_tpu.initialize(
                loss_fn=loss_fn, params=params,
                config={"train_micro_batch_size_per_gpu": 2,
                        "gradient_accumulation_steps": 4,
                        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                        "zero_optimization": {"stage": 2},
                        "data_types": {"grad_accum_dtype": acc}})
            return e

        rng = np.random.default_rng(0)
        b = {"x": rng.standard_normal((4, 16, 16)).astype(np.float32),
             "y": rng.standard_normal((4, 16, 8)).astype(np.float32)}
        e32, e16 = build("float32"), build("bfloat16")
        assert e16.state.grad_acc["w"].dtype == jnp.bfloat16
        l32 = [float(e32.train_batch(b)) for _ in range(5)]
        l16 = [float(e16.train_batch(b)) for _ in range(5)]
        np.testing.assert_allclose(l32, l16, rtol=2e-2)

    def test_rejects_unknown_dtype(self, eight_devices):
        import deepspeed_tpu
        from deepspeed_tpu.config.config import ConfigError

        with pytest.raises(ConfigError, match="grad_accum_dtype"):
            deepspeed_tpu.DeepSpeedTPUConfig(
                {"train_micro_batch_size_per_gpu": 1,
                 "data_types": {"grad_accum_dtype": "fp8"}})


class TestCheckNumerics:
    """`check_numerics` debug mode (SURVEY §5 determinism/debug lever):
    fail fast with step + leaf names instead of training on NaNs."""

    def _engine(self, check, blowup):
        import deepspeed_tpu

        def loss_fn(p, b, r):
            # loss blows up via the params themselves after a huge update
            return jnp.mean((b["x"] @ p["w"]) ** 2)

        params = {"w": jnp.ones((4, 4), jnp.float32)}
        cfg = {"train_micro_batch_size_per_gpu": 2,
               "gradient_accumulation_steps": 1,
               "optimizer": {"type": "SGD",
                             "params": {"lr": 1e30 if blowup else 1e-2}},
               "zero_optimization": {"stage": 0}}
        if check:
            cfg["check_numerics"] = True
        e, _, _, _ = deepspeed_tpu.initialize(loss_fn=loss_fn,
                                              params=params, config=cfg)
        return e

    def test_raises_on_nonfinite(self, eight_devices):
        e = self._engine(check=True, blowup=True)
        batch = {"x": np.full((1, 2, 4), 1e20, np.float32)}
        with pytest.raises(FloatingPointError, match="check_numerics"):
            for _ in range(4):
                e.train_batch(batch)

    def test_off_by_default_stays_silent(self, eight_devices):
        e = self._engine(check=False, blowup=True)
        batch = {"x": np.full((1, 2, 4), 1e20, np.float32)}
        for _ in range(3):
            loss = e.train_batch(batch)   # silently inf/nan, no raise
        assert not np.isfinite(float(loss))

    def test_clean_run_unaffected(self, eight_devices):
        e = self._engine(check=True, blowup=False)
        batch = {"x": np.ones((1, 2, 4), np.float32)}
        losses = [float(e.train_batch(batch)) for _ in range(3)]
        assert all(np.isfinite(losses))
        assert losses[-1] <= losses[0]
