"""Fleet observability tests (telemetry/fleet.py; docs/OBSERVABILITY.md
"Fleet observability"): the cross-host all-gather driven on a
multi-device CPU mesh, straggler injection (an inflated host's step
marks must yield a verdict NAMING that host in the instants stream, the
breakdown file AND the merged fleet report), the zero-overhead disabled
contract (no device syncs, no collective, no host fetch), device-time
comm attribution (comm/exposed_frac on a 2-slice mesh), per-host file
namespacing with the single-host compat alias, the StepTracer
jax.profiler stop guarantee, multi-trace trace_report, and
tools/fleet_report.py."""

import importlib.util
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import (ConfigError, DeepSpeedTPUConfig,
                                         TelemetryFleetConfig)
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.telemetry import (InMemorySink, MetricsRegistry,
                                     StepTracer, Telemetry)
from deepspeed_tpu.telemetry.fleet import (FLEET_FIELDS, _FLEET_STATS,
                                           FleetAggregator,
                                           _decode_host, _encode_host,
                                           all_gather_rows,
                                           host_scoped_path,
                                           read_persistent_stragglers)
from deepspeed_tpu.telemetry.goodput import GoodputAccountant
from deepspeed_tpu.telemetry.recompile import RecompileDetector

from simple_model import mlp_loss_fn, mlp_params, random_batches

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _engine(config_extra=None, world=8, mesh=None):
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": 1},
                **(config_extra or {})},
        mesh=mesh if mesh is not None else build_mesh(data=world))
    return engine


def _tel_cfg(tmp_path, fleet=None, goodput=True, sinks=("memory",),
             trace=False):
    tel = {"enabled": True, "dir": str(tmp_path),
           "trace": {"enabled": trace},
           "metrics": {"sinks": list(sinks)},
           "goodput": goodput}
    if fleet is not None:
        tel["fleet"] = fleet
    return {"telemetry": tel, "steps_per_print": 1}


def _facade(tmp_path, trace=True):
    reg = MetricsRegistry()
    mem = reg.add_sink(InMemorySink())
    tracer = StepTracer(path=(str(tmp_path / "trace.json") if trace
                              else None))
    tel = Telemetry(reg, tracer, RecompileDetector(enabled=False))
    return tel, mem


def _aggregator(tmp_path, min_window=2, persist=2, zscore=3.0, window=8):
    fcfg = TelemetryFleetConfig(enabled=True, window=window,
                                min_window=min_window, zscore=zscore,
                                persist=persist)
    tel, mem = _facade(tmp_path)
    g = GoodputAccountant(registry=None)
    agg = FleetAggregator(fcfg, run_dir=str(tmp_path), telemetry=tel,
                          goodput=g, host="host0", leader=True)
    return agg, tel, mem, g


# ---------------------------------------------------------------------------
# The jitted gather, driven on the multi-device CPU mesh
# ---------------------------------------------------------------------------
class TestGather:
    def test_all_gather_rows_over_devices(self, eight_devices):
        """The real collective path: 8 owner devices (one per simulated
        host), distinct rows, one jitted all-gather, full matrix back."""
        devs = jax.devices()[:8]
        rows = {i: np.array([i, 10.0 * i, 100.0 + i], np.float32)
                for i in range(8)}
        out = all_gather_rows(devs, rows)
        assert out.shape == (8, 3)
        for i in range(8):
            np.testing.assert_allclose(out[i], rows[i])

    def test_host_name_gather_roundtrip(self, eight_devices):
        devs = jax.devices()[:3]
        names = ["worker-0", "tpu-host-17.cell", "z"]
        rows = {i: _encode_host(n) for i, n in enumerate(names)}
        out = all_gather_rows(devs, rows)
        assert [_decode_host(r) for r in out] == names


# ---------------------------------------------------------------------------
# Aggregation + straggler verdicts (gather-independent ingest seam)
# ---------------------------------------------------------------------------
class TestAggregator:
    HOSTS = ["hostA", "hostB", "hostC"]

    def _matrix(self, step_times, stall=0.1, hbm=1000.0, prod=1.0,
                exposed=0.05, headroom=500.0, grad_norm=0.14):
        # headroom decreases with host index: the LAST host is the
        # tightest (argmin names it).
        return np.array([[st, stall, hbm * (i + 1), prod, exposed,
                          headroom / (i + 1), grad_norm]
                         for i, st in enumerate(step_times)], np.float32)

    def test_stats_and_argmax_emitted(self, tmp_path):
        agg, tel, mem, _ = self._build(tmp_path)
        agg.ingest(5, self._matrix([1.0, 2.0, 1.5]), hosts=self.HOSTS)
        assert mem.values("fleet/step_time_sec_min")[-1] == 1.0
        assert mem.values("fleet/step_time_sec_median")[-1] == 1.5
        assert mem.values("fleet/step_time_sec_max")[-1] == 2.0
        assert mem.values("fleet/step_time_sec_argmax_host")[-1] == 1
        assert mem.values("fleet/hbm_peak_bytes_argmax_host")[-1] == 2
        # the tightest-headroom host is NAMED by argmin (host index 2)
        assert mem.values("fleet/hbm_headroom_bytes_argmin_host")[-1] == 2
        assert mem.values("fleet/hosts")[-1] == 3
        # every field emits its five stats
        for f in FLEET_FIELDS:
            for s in _FLEET_STATS:
                assert mem.values(f"fleet/{f}_{s}"), (f, s)

    def _build(self, tmp_path, **kw):
        return _aggregator(tmp_path, **kw)

    def test_straggler_injection_names_the_host(self, tmp_path):
        """The acceptance injection: hostC's step marks inflated 2x -> the
        verdict names hostC in the instants stream, the counter, the
        goodput sub-attribution and the breakdown file."""
        agg, tel, mem, g = self._build(tmp_path, min_window=2, persist=2)
        verdicts = []
        for step in range(1, 5):
            out = agg.ingest(step, self._matrix([1.0, 1.0, 2.0]),
                             hosts=self.HOSTS, steps_delta=4)
            verdicts.append(out["straggler"])
        assert verdicts[0] is None                 # below min_window
        assert verdicts[1] is not None
        assert all(v["host"] == "hostC" for v in verdicts[1:])
        assert verdicts[2]["persistent"]           # persist=2 reached
        # instants stream names the host
        instants = [e for e in tel.tracer.events
                    if e.get("ph") == "i" and e["name"] == "fleet/straggler"]
        assert instants and instants[-1]["args"]["host"] == "hostC"
        # counter + time-lost sub-attribution
        assert mem.values("telemetry/stragglers")[-1] == 3
        # lost = (2.0 - median 1.0) * steps_delta 4 per flagged flush
        assert g.aux_totals()["straggler_sec"] == pytest.approx(3 * 4.0)
        # breakdown file carries the named verdict
        doc = json.load(open(tmp_path / "fleet_breakdown.json"))
        assert doc["hosts"] == self.HOSTS
        assert doc["stragglers"]["hostC"]["persistent"]
        assert doc["stats"]["step_time_sec"]["argmax_host_name"] == "hostC"
        assert read_persistent_stragglers(str(tmp_path)) == ["hostC"]

    def test_uniform_fleet_never_flags(self, tmp_path):
        """Sigma floor: near-identical hosts must not produce verdicts
        (sd ~ 0 would otherwise make any jitter a >3-sigma event)."""
        agg, _, mem, _ = self._build(tmp_path, min_window=2)
        rng = np.random.default_rng(0)
        for step in range(1, 12):
            times = 1.0 + rng.normal(0, 1e-3, 3)
            out = agg.ingest(step, self._matrix(list(times)),
                             hosts=self.HOSTS)
            assert out["straggler"] is None
        assert "telemetry/stragglers" not in mem.tags()

    def test_merged_fleet_report_names_the_straggler(self, tmp_path):
        """Acceptance second half: the same injected run dir, merged by
        tools/fleet_report.py, yields the verdict on the right host."""
        agg, _, _, _ = self._build(tmp_path, min_window=2, persist=2)
        for step in range(1, 5):
            agg.ingest(step, self._matrix([1.0, 1.0, 2.0]),
                       hosts=self.HOSTS, steps_delta=4)
        fr = _load_tool("fleet_report")
        report = fr.merge_fleet(str(tmp_path))
        by_host = {r["host"]: r for r in report["hosts"]}
        assert by_host["hostC"]["straggler"]
        assert by_host["hostC"]["straggler_persistent"]
        assert not by_host["hostA"]["straggler"]
        assert report["persistent_stragglers"] == ["hostC"]
        text = fr.render(report)
        assert "hostC" in text and "persistent" in text


# ---------------------------------------------------------------------------
# Engine integration — the acceptance multi-device run
# ---------------------------------------------------------------------------
class TestEngineFleet:
    def test_fleet_gauges_and_breakdown_on_multi_device_run(
            self, eight_devices, tmp_path):
        engine = _engine(_tel_cfg(tmp_path,
                                  fleet={"enabled": True, "min_window": 1}))
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=1, batch_size=16)
        for _ in range(4):
            engine.train_batch(batches)
        assert engine.fleet is not None
        mem = engine.telemetry.registry.sinks[0]
        fleet_tags = {t for t in mem.tags() if t.startswith("fleet/")}
        # 6 fields x 5 stats + fleet/hosts
        assert len(fleet_tags) == \
            len(FLEET_FIELDS) * len(_FLEET_STATS) + 1, fleet_tags
        assert mem.values("fleet/hosts")[-1] == 1
        assert mem.values("fleet/step_time_sec_max")[-1] > 0
        doc = json.load(open(tmp_path / "fleet_breakdown.json"))
        assert len(doc["hosts"]) == 1
        assert set(doc["fields"]) == set(FLEET_FIELDS)
        # single host: the straggler detector must stay silent
        assert "telemetry/stragglers" not in mem.tags()

    def test_disabled_fleet_is_none_and_runs_no_collective(
            self, eight_devices, tmp_path, monkeypatch):
        """Zero-overhead contract: fleet off (the default) => engine.fleet
        is None, the gather is never invoked (it raises if touched), no
        fleet/* tags, no breakdown file, and ZERO device syncs on the
        step path (tracer off)."""
        from deepspeed_tpu.telemetry import fleet as fleet_mod
        monkeypatch.setattr(
            fleet_mod, "all_gather_rows",
            lambda *a, **k: (_ for _ in ()).throw(
                AssertionError("fleet gather invoked while disabled")))
        engine = _engine(_tel_cfg(tmp_path))
        assert engine.fleet is None
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=1, batch_size=16)
        engine.train_batch(batches)          # compile outside the window
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        for _ in range(10):
            engine.train_batch(batches)
        assert calls["n"] == 0
        mem = engine.telemetry.registry.sinks[0]
        assert not {t for t in mem.tags() if t.startswith("fleet/")}
        assert not os.path.exists(tmp_path / "fleet_breakdown.json")

    def test_fleet_requires_goodput(self):
        with pytest.raises(ConfigError, match="fleet requires"):
            DeepSpeedTPUConfig({
                "train_micro_batch_size_per_gpu": 1,
                "telemetry": {
                    "enabled": True, "dir": "/tmp/x", "goodput": False,
                    "fleet": {"enabled": True}}}, world_size=1)

    def test_fleet_config_validation(self):
        with pytest.raises(ConfigError, match="window"):
            TelemetryFleetConfig.from_dict({"window": 1, "min_window": 4})
        with pytest.raises(ConfigError, match="zscore"):
            TelemetryFleetConfig.from_dict({"zscore": 0})
        # readers discover the breakdown by pattern — off-pattern names
        # would be written but never read
        with pytest.raises(ConfigError, match="fleet_breakdown"):
            TelemetryFleetConfig.from_dict({"breakdown_file": "fb.json"})
        cfg = TelemetryFleetConfig.from_dict(
            {"breakdown_file": "fleet_breakdown.run7.json"})
        assert cfg.breakdown_file == "fleet_breakdown.run7.json"

    def test_unsynced_spans_fall_back_to_goodput_step_time(
            self, eight_devices, tmp_path, monkeypatch):
        """With sync_spans off the train_step span brackets only the
        async dispatch — the fleet must NOT ingest it as step time (the
        goodput host-clock delta is the honest estimate)."""
        cfg = _tel_cfg(tmp_path, fleet={"enabled": True, "min_window": 1})
        cfg["telemetry"]["trace"] = {"enabled": True, "sync_spans": False}
        engine = _engine(cfg)
        noted = []
        monkeypatch.setattr(engine.fleet, "note_step_time",
                            lambda s: noted.append(s))
        rng = np.random.default_rng(0)
        for _ in range(3):
            engine.train_batch(random_batches(rng, gas=1, batch_size=16))
        assert noted == []
        mem = engine.telemetry.registry.sinks[0]
        assert mem.values("fleet/step_time_sec_max")[-1] > 0  # fallback


# ---------------------------------------------------------------------------
# Device-time comm attribution (comm/exposed_frac) on a 2-slice mesh
# ---------------------------------------------------------------------------
class TestExposedComm:
    def _dcn_engine(self, tmp_path, fleet=False):
        cfg = _tel_cfg(tmp_path,
                       fleet=({"enabled": True, "min_window": 1}
                              if fleet else None))
        cfg.update({
            "gradient_accumulation_steps": 2,
            "comm": {"hierarchical": "on", "dcn_quant_bits": 8},
            "zero_optimization": {"stage": 2},
        })
        return _engine(cfg, mesh=build_mesh(slices=2))

    def _batches(self, rng, gas=2, bs=16):
        return random_batches(rng, gas=gas, batch_size=bs)

    def test_exposed_frac_emitted_and_bounded(self, eight_devices,
                                              tmp_path):
        engine = self._dcn_engine(tmp_path)
        rng = np.random.default_rng(0)
        batches = self._batches(rng)
        for _ in range(3):
            engine.train_batch(batches)
        mem = engine.telemetry.registry.sinks[0]
        fracs = mem.values("comm/exposed_frac")
        assert fracs, "comm/exposed_frac never emitted"
        assert all(0.0 < f <= 1.0 for f in fracs)
        aux = engine.goodput.aux_totals()
        assert aux["exposed_comm_sec"] > 0
        # modeled seconds come from the plan's bandwidth model
        plan_sec = engine.grad_sync_plan.modeled_exposed_seconds()
        assert plan_sec > 0
        # manifest persists the sub-attribution for goodput_report
        doc = json.load(open(engine.goodput.manifest_path()))
        assert doc["aux"]["exposed_comm_sec"] == pytest.approx(
            aux["exposed_comm_sec"])

    def test_exposed_feeds_fleet_vector(self, eight_devices, tmp_path):
        engine = self._dcn_engine(tmp_path, fleet=True)
        rng = np.random.default_rng(0)
        batches = self._batches(rng)
        for _ in range(3):
            engine.train_batch(batches)
        mem = engine.telemetry.registry.sinks[0]
        assert mem.values("fleet/exposed_comm_sec_max")[-1] > 0

    def test_implicit_path_emits_no_exposed_frac(self, eight_devices,
                                                 tmp_path):
        engine = _engine(_tel_cfg(tmp_path))      # no comm block
        rng = np.random.default_rng(0)
        engine.train_batch(random_batches(rng, gas=1, batch_size=16))
        mem = engine.telemetry.registry.sinks[0]
        assert "comm/exposed_frac" not in mem.tags()


# ---------------------------------------------------------------------------
# Per-host file namespacing (satellite): compat alias + forced scoping
# ---------------------------------------------------------------------------
class TestHostScopedFiles:
    def test_host_scoped_path_unit(self):
        assert host_scoped_path("metrics.jsonl", None) == "metrics.jsonl"
        assert host_scoped_path("metrics.jsonl", "w3") == "metrics.w3.jsonl"
        assert host_scoped_path("trace.json", "a.b") == "trace.a.b.json"
        assert host_scoped_path("noext", "h") == "noext.h"

    def test_single_host_filenames_stable(self, eight_devices, tmp_path):
        engine = _engine(_tel_cfg(tmp_path, sinks=("jsonl",)))
        rng = np.random.default_rng(0)
        engine.train_batch(random_batches(rng, gas=1, batch_size=16))
        engine.telemetry.flush()
        assert os.path.exists(tmp_path / "metrics.jsonl")
        assert engine.telemetry.metrics_path == str(
            tmp_path / "metrics.jsonl")

    def test_forced_host_scoping(self, eight_devices, tmp_path,
                                 monkeypatch):
        monkeypatch.setenv("DSTPU_TELEMETRY_HOST", "workerX")
        cfg = _tel_cfg(tmp_path, sinks=("jsonl",), trace=True)
        engine = _engine(cfg)
        rng = np.random.default_rng(0)
        engine.train_batch(random_batches(rng, gas=1, batch_size=16))
        engine.telemetry.flush()
        assert os.path.exists(tmp_path / "metrics.workerX.jsonl")
        assert os.path.exists(tmp_path / "trace.workerX.json")
        assert not os.path.exists(tmp_path / "metrics.jsonl")
        assert not os.path.exists(tmp_path / "trace.json")
        # the facade reports the real (scoped) metrics path
        assert engine.telemetry.metrics_path.endswith(
            "metrics.workerX.jsonl")
        # the trace stamps its host + wall anchor for fleet_report
        doc = json.load(open(tmp_path / "trace.workerX.json"))
        assert doc["metadata"]["host"] == "workerX"
        assert doc["metadata"]["wall_epoch"] > 0


# ---------------------------------------------------------------------------
# StepTracer jax.profiler stop guarantee (satellite)
# ---------------------------------------------------------------------------
class TestProfilerLifecycle:
    def test_stop_trace_guaranteed_on_crash(self, tmp_path, monkeypatch):
        """An exception between start and stop must not leak the profiler
        session: the atexit hook registered at start stops it, and a
        later close() must not double-stop."""
        counts = {"start": 0, "stop": 0}
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda d: counts.__setitem__(
                                "start", counts["start"] + 1))
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            lambda: counts.__setitem__(
                                "stop", counts["stop"] + 1))
        import atexit
        registered = []
        monkeypatch.setattr(atexit, "register",
                            lambda fn, *a, **k: registered.append(fn))
        tracer = StepTracer(path=str(tmp_path / "t.json"),
                            jax_profiler_dir=str(tmp_path / "prof"))
        assert counts["start"] == 1 and tracer._profiler_active
        assert tracer.stop_jax_profiler in registered
        # simulated crash: close() never runs; the atexit hook fires
        registered[0]()
        assert counts["stop"] == 1
        assert not tracer._profiler_active
        tracer.close()                      # idempotent
        assert counts["stop"] == 1

    def test_clean_close_stops_once(self, tmp_path, monkeypatch):
        counts = {"start": 0, "stop": 0}
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda d: counts.__setitem__(
                                "start", counts["start"] + 1))
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            lambda: counts.__setitem__(
                                "stop", counts["stop"] + 1))
        tracer = StepTracer(path=str(tmp_path / "t.json"),
                            jax_profiler_dir=str(tmp_path / "prof"))
        tracer.close()
        assert counts["stop"] == 1
        tracer.stop_jax_profiler()          # the atexit double-fire
        assert counts["stop"] == 1


# ---------------------------------------------------------------------------
# trace_report multi-file (satellite)
# ---------------------------------------------------------------------------
class TestTraceReportMultiFile:
    def _write_trace(self, path, host, with_meta=True):
        doc = {"traceEvents": [
            {"name": "train_step", "ph": "X", "pid": 1, "tid": 1,
             "ts": 0.0, "dur": 1000.0},
            {"name": "recompile", "ph": "i", "s": "t", "pid": 1,
             "tid": 1, "ts": 0.0}]}
        if with_meta:
            doc["metadata"] = {"host": host, "wall_epoch": 1000.0}
        with open(path, "w") as f:
            json.dump(doc, f)

    def test_multi_file_rows_are_host_prefixed(self, tmp_path):
        tr = _load_tool("trace_report")
        self._write_trace(tmp_path / "trace.hostA.json", "hostA")
        # no metadata: the filename component is the fallback label
        self._write_trace(tmp_path / "trace.hostB.json", "hostB",
                          with_meta=False)
        paths = tr.expand_paths([str(tmp_path / "trace.*.json")])
        assert len(paths) == 2
        summary = tr.summarize(tr.load_many(paths))
        names = {r["name"] for r in summary["spans"]}
        assert names == {"hostA:train_step", "hostB:train_step"}
        assert summary["instants"] == {"hostA:recompile": 1,
                                       "hostB:recompile": 1}
        text = tr.render(summary)
        assert "hostA:train_step" in text

    def test_single_file_unprefixed(self, tmp_path):
        tr = _load_tool("trace_report")
        self._write_trace(tmp_path / "trace.json", "solo")
        summary = tr.summarize(tr.load_events(str(tmp_path / "trace.json")))
        assert {r["name"] for r in summary["spans"]} == {"train_step"}

    def test_selftest_cli(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "trace_report.py"), "--selftest"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "selftest ok" in proc.stdout


# ---------------------------------------------------------------------------
# tools/fleet_report.py
# ---------------------------------------------------------------------------
class TestFleetReport:
    def test_selftest_cli(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "fleet_report.py"), "--selftest"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "selftest ok" in proc.stdout

    def test_merges_engine_written_run_dir(self, eight_devices, tmp_path):
        """A real single-host engine run (fleet on, jsonl + trace) parses
        into a 1-host report and a mergeable timeline."""
        engine = _engine(_tel_cfg(tmp_path, sinks=("jsonl",), trace=True,
                                  fleet={"enabled": True,
                                         "min_window": 1}))
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=1, batch_size=16)
        for _ in range(3):
            engine.train_batch(batches)
        engine.telemetry.flush()
        engine.goodput.write_manifest()
        fr = _load_tool("fleet_report")
        report = fr.merge_fleet(str(tmp_path))
        assert report["n_hosts"] == 1
        row = report["hosts"][0]
        assert row["steps_committed"] >= 3
        assert row["goodput_frac"] is not None and row["goodput_frac"] > 0
        assert not row["straggler"]
        timeline = fr.merge_timeline(
            {h: p for h, p in report["trace_files"].items()})
        assert any(e.get("ph") == "X" for e in timeline["traceEvents"])
        fr.render(report)                    # renders without error

    def test_timeline_tolerates_anchorless_trace(self, tmp_path):
        """A legacy trace without a wall_epoch anchor must stay
        base-aligned, not drag the base to unix epoch 0 (which would
        shift every anchored host by ~1.7e9 s)."""
        fr = _load_tool("fleet_report")
        with open(tmp_path / "trace.hostA.json", "w") as f:
            json.dump({"traceEvents": [
                {"name": "train_step", "ph": "X", "pid": 1, "tid": 1,
                 "ts": 7.0, "dur": 5.0}],
                "metadata": {"wall_epoch": 1.7e9, "host": "hostA"}}, f)
        with open(tmp_path / "trace.old.json", "w") as f:
            json.dump([{"name": "train_step", "ph": "X", "pid": 1,
                        "tid": 1, "ts": 3.0, "dur": 5.0}], f)
        tl = fr.merge_timeline({"hostA": str(tmp_path / "trace.hostA.json"),
                                "old": str(tmp_path / "trace.old.json")})
        spans = {e["pid"]: e for e in tl["traceEvents"]
                 if e.get("ph") == "X"}
        # anchored host keeps its own ts (it IS the base); anchorless one
        # is unshifted
        assert sorted(e["ts"] for e in spans.values()) == [3.0, 7.0]
        assert tl["metadata"]["aligned_to_wall_epoch"] == 1.7e9


# ---------------------------------------------------------------------------
# Supervisor surfaces persistent stragglers
# ---------------------------------------------------------------------------
class TestSupervisorStragglers:
    def test_supervisor_reads_breakdown(self, tmp_path):
        from deepspeed_tpu.resilience.supervisor import Supervisor
        with open(tmp_path / "fleet_breakdown.json", "w") as f:
            json.dump({"format": 1, "hosts": ["a", "b"],
                       "stragglers": {"b": {"count": 4,
                                            "persistent": True}}}, f)
        sup = Supervisor([sys.executable, "-c", "pass"], max_restarts=0,
                         run_dir=str(tmp_path))
        assert sup.run() == 0
        assert sup.straggler_hosts == ["b"]
