"""Pipeline execution tests on the virtual 8-device CPU mesh: the pipelined
program must match the sequential (single-stage) reference bit-for-bit-ish,
including gradients — and compose with DP and ZeRO-1 (reference
tests/unit/test_pipe.py compares pipeline vs DP loss trajectories)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.config.config import DeepSpeedTPUConfig
from deepspeed_tpu.models.gpt import GPTConfig
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.parallel.pipe import (PipelineEngine, gpt_pipe_model,
                                         pipeline_apply, stack_blocks)


def _block_fn(p, x, aux=None, rng=None):
    # toy "transformer block": y = gelu(x @ w + b) + x
    return jax.nn.gelu(x @ p["w"] + p["b"]) + x


def _make_blocks(rng, n_layers, d):
    return stack_blocks([
        {"w": jnp.asarray(rng.standard_normal((d, d)) * 0.1, jnp.float32),
         "b": jnp.zeros((d,), jnp.float32)}
        for _ in range(n_layers)])


class TestPipelineApply:
    @pytest.mark.parametrize("stages", [1, 2, 4])
    def test_matches_sequential(self, eight_devices, stages):
        rng = np.random.default_rng(0)
        d, M, mb = 16, 4, 8
        blocks = _make_blocks(rng, 4, d)
        x = jnp.asarray(rng.standard_normal((M, mb, d)), jnp.float32)

        mesh1 = build_mesh(data=1, pipe=1, devices=jax.devices()[:1])
        ref = pipeline_apply(_block_fn, blocks, x, mesh1, remat_blocks=False)

        mesh = build_mesh(data=8 // stages, pipe=stages)
        out = jax.jit(lambda b, xx: pipeline_apply(
            _block_fn, b, xx, mesh, remat_blocks=False))(blocks, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_gradients_match_sequential(self, eight_devices):
        rng = np.random.default_rng(1)
        d, M, mb = 16, 4, 8
        blocks = _make_blocks(rng, 4, d)
        x = jnp.asarray(rng.standard_normal((M, mb, d)), jnp.float32)
        mesh1 = build_mesh(data=1, pipe=1, devices=jax.devices()[:1])
        mesh = build_mesh(data=2, pipe=4)

        def loss(b, mesh_, remat):
            return jnp.sum(pipeline_apply(_block_fn, b, x, mesh_,
                                          remat_blocks=remat) ** 2)

        g_ref = jax.grad(lambda b: loss(b, mesh1, False))(blocks)
        g_pipe = jax.jit(jax.grad(lambda b: loss(b, mesh, True)))(blocks)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4),
            g_ref, g_pipe)

    def test_rejects_indivisible_layers(self, eight_devices):
        rng = np.random.default_rng(0)
        blocks = _make_blocks(rng, 3, 8)
        mesh = build_mesh(data=4, pipe=2)
        x = jnp.zeros((2, 2, 8))
        with pytest.raises(ValueError, match="not divisible"):
            pipeline_apply(_block_fn, blocks, x, mesh)


class TestPipelineEngine:
    def _make(self, eight, stages=2, zero_stage=1, gas=4, layers=4):
        cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=32,
                        num_layers=layers, num_heads=2, dropout_rate=0.0,
                        dtype=jnp.float32)
        pm = gpt_pipe_model(cfg)
        mesh = build_mesh(data=8 // stages, pipe=stages)
        ds = DeepSpeedTPUConfig({
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": zero_stage},
        })
        engine = PipelineEngine(pm, ds, mesh=mesh)
        return engine, cfg

    def _batches(self, rng, cfg, gas, mb=8, seq=16):
        return {"input_ids": rng.integers(
            0, cfg.vocab_size, (gas, mb, seq), dtype=np.int32)}

    def test_train_batch_loss_decreases(self, eight_devices):
        engine, cfg = self._make(eight_devices)
        rng = np.random.default_rng(0)
        batches = self._batches(rng, cfg, engine.micro_batches)
        losses = [float(engine.train_batch(batches)) for _ in range(15)]
        assert losses[-1] < losses[0] - 0.3, losses
        assert engine.global_steps == 15

    def test_matches_single_stage_trajectory(self, eight_devices):
        """Pipelined (pipe=4) and non-pipelined (pipe=1) runs from identical
        init follow the same loss trajectory — the reference's pipeline-vs-DP
        parity test."""
        rng = np.random.default_rng(0)
        e_pipe, cfg = self._make(eight_devices, stages=4)
        batches = self._batches(rng, cfg, e_pipe.micro_batches)
        e_seq, _ = self._make(eight_devices, stages=1)
        l_pipe = [float(e_pipe.train_batch(batches)) for _ in range(5)]
        l_seq = [float(e_seq.train_batch(batches)) for _ in range(5)]
        np.testing.assert_allclose(l_pipe, l_seq, atol=2e-3, rtol=2e-3)

    def test_rejects_zero2(self, eight_devices):
        with pytest.raises(ValueError, match="ZeRO-2/3"):
            self._make(eight_devices, zero_stage=2)

    def test_forward_backward_raise(self, eight_devices):
        engine, cfg = self._make(eight_devices)
        with pytest.raises(RuntimeError):
            engine.forward({})
        with pytest.raises(RuntimeError):
            engine.backward()

    def test_split_batch(self, eight_devices):
        engine, cfg = self._make(eight_devices, gas=4)
        flat = {"input_ids": np.zeros((32, 16), np.int32)}
        split = engine.split_batch(flat)
        assert split["input_ids"].shape == (4, 8, 16)

    def test_eval_batch(self, eight_devices):
        engine, cfg = self._make(eight_devices)
        rng = np.random.default_rng(0)
        batches = self._batches(rng, cfg, engine.micro_batches)
        loss = float(engine.eval_batch(batches))
        assert np.isfinite(loss)

    def test_attention_mask_and_untied_match_single_stage(self, eight_devices):
        """Padded batches (attention_mask) and untied embeddings follow the
        same trajectory pipelined as single-stage."""
        rng = np.random.default_rng(0)
        cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=32,
                        num_layers=4, num_heads=2, dropout_rate=0.0,
                        dtype=jnp.float32, tie_embeddings=False)

        def make(stages):
            pm = gpt_pipe_model(cfg)
            mesh = build_mesh(data=8 // stages, pipe=stages)
            ds = DeepSpeedTPUConfig({
                "train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0},
            })
            return PipelineEngine(pm, ds, mesh=mesh)

        mask = np.ones((4, 8, 16), np.int32)
        mask[:, :, 12:] = 0     # padded tail
        batches = {"input_ids": rng.integers(0, cfg.vocab_size, (4, 8, 16),
                                             dtype=np.int32),
                   "attention_mask": mask}
        e_pipe, e_seq = make(4), make(1)
        l_pipe = [float(e_pipe.train_batch(batches)) for _ in range(4)]
        l_seq = [float(e_seq.train_batch(batches)) for _ in range(4)]
        np.testing.assert_allclose(l_pipe, l_seq, atol=2e-3, rtol=2e-3)

    def test_checkpoint_roundtrip(self, eight_devices, tmp_path):
        engine, cfg = self._make(eight_devices)
        rng = np.random.default_rng(0)
        batches = self._batches(rng, cfg, engine.micro_batches)
        for _ in range(3):
            engine.train_batch(batches)
        engine.save_checkpoint(str(tmp_path))
        engine2, _ = self._make(eight_devices)
        engine2.load_checkpoint(str(tmp_path))
        l1 = float(engine.eval_batch(batches))
        l2 = float(engine2.eval_batch(batches))
        assert abs(l1 - l2) < 1e-6


class TestPipelineOneBit:
    """Pipeline × ZeRO-0/1 × 1-bit Adam — the BASELINE ladder's final rung
    (GPT-2 1.5B "Pipeline + ZeRO-1 + 1-bit Adam"; round-3 VERDICT task 1).
    The reference composes 1-bit Adam with its engines by switching comm
    paths (deepspeed/runtime/fp16/onebit/adam.py:92-104); here the
    PipelineEngine extends the two-phase local-grad path with the pipe
    axis (parallel/pipe/engine.py)."""

    def _make(self, stages=2, zero_stage=1, gas=4, layers=4, data=None,
              opt="OneBitAdam", freeze_step=100, lr=1e-3, tie=True):
        cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=32,
                        num_layers=layers, num_heads=2, dropout_rate=0.0,
                        dtype=jnp.float32, tie_embeddings=tie)
        pm = gpt_pipe_model(cfg)
        data = (8 // stages) if data is None else data
        mesh = build_mesh(data=data, pipe=stages,
                          devices=jax.devices()[:data * stages])
        ds = DeepSpeedTPUConfig({
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": opt,
                          "params": ({"lr": lr, "freeze_step": freeze_step}
                                     if opt.startswith("OneBit")
                                     else {"lr": lr})},
            "zero_optimization": {"stage": zero_stage},
        })
        return PipelineEngine(pm, ds, mesh=mesh), cfg

    def _batches(self, rng, cfg, gas, mb=8, seq=16):
        return {"input_ids": rng.integers(
            0, cfg.vocab_size, (gas, mb, seq), dtype=np.int32)}

    def test_trains_through_both_phases(self, eight_devices):
        engine, cfg = self._make(stages=2, zero_stage=1, freeze_step=3)
        rng = np.random.default_rng(0)
        batches = self._batches(rng, cfg, engine.micro_batches)
        losses = [float(engine.train_batch(batches)) for _ in range(12)]
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0] - 0.3, losses
        # still improving after the freeze -> compressed sync works under pp
        assert losses[-1] < losses[5] - 0.02, losses

    def test_warmup_matches_dense_adam(self, eight_devices):
        """During warmup 1-bit Adam IS dense Adam (same update formula as
        FusedAdam with wd=0) — the pipelined local-grad path must reproduce
        the dense pipeline engine's trajectory."""
        rng = np.random.default_rng(1)
        e_1bit, cfg = self._make(stages=2, zero_stage=1, freeze_step=100)
        batches = self._batches(rng, cfg, e_1bit.micro_batches)
        e_dense, _ = self._make(stages=2, zero_stage=1, opt="Adam")
        l_1bit = [float(e_1bit.train_batch(batches)) for _ in range(5)]
        l_dense = [float(e_dense.train_batch(batches)) for _ in range(5)]
        np.testing.assert_allclose(l_1bit, l_dense, rtol=2e-4, atol=2e-4)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4),
            e_1bit.state.params, e_dense.state.params)

    def test_matches_single_stage(self, eight_devices):
        """pipe=2 vs pipe=1 with the SAME data-axis size (n=4): identical
        compression semantics, so the trajectories must match through BOTH
        phases — exercises the psum-over-pipe gradient fix-up, incl. tied
        embeddings (wte grads combine rank-0 embed + rank-1 head parts)."""
        rng = np.random.default_rng(2)
        e_pipe, cfg = self._make(stages=2, data=4, freeze_step=2)
        batches = self._batches(rng, cfg, e_pipe.micro_batches)
        e_seq, _ = self._make(stages=1, data=4, freeze_step=2)
        l_pipe = [float(e_pipe.train_batch(batches)) for _ in range(6)]
        l_seq = [float(e_seq.train_batch(batches)) for _ in range(6)]
        np.testing.assert_allclose(l_pipe, l_seq, atol=2e-3, rtol=2e-3)

    def test_untied_matches_single_stage(self, eight_devices):
        rng = np.random.default_rng(3)
        e_pipe, cfg = self._make(stages=2, data=4, freeze_step=2, tie=False)
        batches = self._batches(rng, cfg, e_pipe.micro_batches)
        e_seq, _ = self._make(stages=1, data=4, freeze_step=2, tie=False)
        l_pipe = [float(e_pipe.train_batch(batches)) for _ in range(5)]
        l_seq = [float(e_seq.train_batch(batches)) for _ in range(5)]
        np.testing.assert_allclose(l_pipe, l_seq, atol=2e-3, rtol=2e-3)

    def test_zero1_matches_zero0(self, eight_devices):
        """ZeRO-1 under the pipelined 1-bit path is placement-only."""
        rng = np.random.default_rng(4)
        e_z1, cfg = self._make(stages=2, zero_stage=1, freeze_step=2)
        batches = self._batches(rng, cfg, e_z1.micro_batches)
        e_z0, _ = self._make(stages=2, zero_stage=0, freeze_step=2)
        l_z1 = [float(e_z1.train_batch(batches)) for _ in range(5)]
        l_z0 = [float(e_z0.train_batch(batches)) for _ in range(5)]
        np.testing.assert_allclose(l_z1, l_z0, rtol=1e-5)

    def test_onebit_lamb_trains(self, eight_devices):
        engine, cfg = self._make(stages=2, zero_stage=1, opt="OneBitLamb",
                                 freeze_step=3, lr=2e-2)
        rng = np.random.default_rng(5)
        batches = self._batches(rng, cfg, engine.micro_batches)
        losses = [float(engine.train_batch(batches)) for _ in range(10)]
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0] - 0.2, losses

    def test_eval_batch_works(self, eight_devices):
        engine, cfg = self._make(stages=2, zero_stage=1)
        rng = np.random.default_rng(6)
        batches = self._batches(rng, cfg, engine.micro_batches)
        engine.train_batch(batches)
        assert np.isfinite(float(engine.eval_batch(batches)))


class TestPipelineComputeAccounting:
    def test_per_device_compute_matches_bubble_theory(self, eight_devices):
        """Per-device executed compute must equal the GPipe/1F1B bubble
        theory exactly: ONE scan of T = M+S-1 ticks whose body applies one
        stage (L/S blocks) — i.e. (M+S-1)/(M*S) of the serial total, no
        hidden extra compute from the SPMD formulation. Wall-clock equals
        the same critical path (every tick some rank is active; ppermute
        keeps ranks in lockstep), so this ratio IS the pipeline
        efficiency — the round-2 VERDICT weak-#2 accounting, made
        inspectable. (XLA's cost_analysis cannot measure this — it counts
        while-loop bodies once, not x trip-count — so the assertion is
        structural on the jaxpr. A lax.cond skip of the bubble-tick
        compute is blocked on an XLA:CPU partial-manual bug — see the
        pipeline.py tick note.)"""
        rng = np.random.default_rng(0)
        d, M, mb, L, S = 64, 8, 4, 4, 4
        blocks = _make_blocks(rng, L, d)
        x = jnp.asarray(rng.standard_normal((M, mb, d)), jnp.float32)
        mesh = build_mesh(data=1, pipe=S, devices=jax.devices()[:S])

        traced = jax.jit(lambda b, xx: pipeline_apply(
            _block_fn, b, xx, mesh, remat_blocks=False)).trace(blocks, x)

        def sub_jaxprs(eqn):
            for v in eqn.params.values():
                inner = getattr(v, "jaxpr", None)
                if inner is None and type(v).__name__ == "Jaxpr":
                    inner = v   # shard_map holds a raw Jaxpr
                if inner is not None:
                    yield inner

        def find_scans(jaxpr, out):
            for eqn in jaxpr.eqns:
                if eqn.primitive.name == "scan":
                    out.append(eqn)
                for inner in sub_jaxprs(eqn):
                    find_scans(inner, out)
            return out

        def count_dots(jaxpr):
            n = 0
            for eqn in jaxpr.eqns:
                if eqn.primitive.name == "dot_general":
                    n += 1
                for inner in sub_jaxprs(eqn):
                    n += count_dots(inner)
            return n

        scans = find_scans(traced.jaxpr.jaxpr, [])
        tick_scans = [e for e in scans if e.params["length"] == M + S - 1]
        assert tick_scans, [e.params["length"] for e in scans]
        tick = tick_scans[0]
        # Body: an inner scan over this stage's L/S blocks, each with ONE
        # block matmul — total dot_generals in the tick body == 1 (the
        # block fn) regardless of bubble ticks (no duplicated compute).
        body = tick.params["jaxpr"]
        body = getattr(body, "jaxpr", body)
        inner = find_scans(body, [])
        assert inner and inner[0].params["length"] == L // S
        assert count_dots(body) == 1, count_dots(body)


class TestBubbleSkip:
    """The 1F1B bubble skip (lax.cond on the per-rank validity predicate
    — reference pipe/schedule.py:182 executes no bubble instructions).
    Default-on for TPU; exercised here on CPU with ZeRO-0 (the ZeRO-1 ×
    cond × XLA:CPU second-step rendezvous deadlock is pinned in
    tools/repro_cond_ppermute_deadlock.py, docs/ISSUES.md #1)."""

    def _engine(self, monkeypatch, skip, stage=0):
        import deepspeed_tpu.parallel.pipe.pipeline as pl

        monkeypatch.setattr(pl, "default_skip_bubble", lambda: skip)
        cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=32,
                        num_layers=4, num_heads=2, dropout_rate=0.0,
                        dtype=jnp.float32)
        return PipelineEngine(gpt_pipe_model(cfg), DeepSpeedTPUConfig({
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": stage}}),
            mesh=build_mesh(data=4, pipe=2))

    def test_cond_matches_always_execute(self, eight_devices, monkeypatch):
        """Skipping bubble compute must be numerically transparent: the
        garbage ticks never fed a valid output anyway."""
        rng = np.random.default_rng(0)
        b = {"input_ids": rng.integers(0, 128, (4, 4, 32), dtype=np.int32)}
        e_skip = self._engine(monkeypatch, True)
        l_skip = [float(e_skip.train_batch(b)) for _ in range(3)]
        e_run = self._engine(monkeypatch, False)
        l_run = [float(e_run.train_batch(b)) for _ in range(3)]
        np.testing.assert_allclose(l_skip, l_run, rtol=1e-6)

    def test_cond_present_in_jaxpr(self, eight_devices, monkeypatch):
        """Structural evidence for the TPU default (un-runnable multi-chip
        here): with skip on, the tick body's stage compute sits under a
        cond — bubble ticks execute no dots."""
        from deepspeed_tpu.parallel.pipe.pipeline import (
            pipeline_apply_manual)
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = build_mesh(data=4, pipe=2)
        blocks = {"w": jnp.zeros((4, 16, 16), jnp.float32)}

        def block_fn(p, x, a, k):
            return jnp.tanh(x @ p["w"])

        def run(blocks, x):
            return shard_map(
                lambda bl, xx: pipeline_apply_manual(
                    block_fn, bl, xx, None, None, stages=2,
                    num_microbatches=4, remat_blocks=False,
                    skip_bubble=True),
                mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P(),
                axis_names={"pipe"}, check_vma=False)(blocks, x)

        jaxpr = jax.make_jaxpr(run)(blocks,
                                    jnp.zeros((4, 8, 16), jnp.float32))
        text = str(jaxpr)
        assert "cond[" in text
        # the dot lives inside a cond branch, not the raw tick body
        tick_scan = text.split("cond[")[0]
        assert "dot_general" not in tick_scan.split("scan[")[-1]


class TestPipelineMoE:
    """MoE FFN blocks through the pipeline (moe_layer_freq=1 keeps the
    stacked-block contract): the load-balance aux rides the scan, bubble
    ticks masked, psum'd over pipe — trajectory must match the flat MoE
    family."""

    CFG = dict(vocab_size=128, max_seq_len=32, hidden_size=32, num_layers=4,
               num_heads=2, dropout_rate=0.0, dtype=jnp.float32,
               moe_experts=2, moe_k=1, moe_layer_freq=1)

    def _batches(self):
        rng = np.random.default_rng(0)
        return {"input_ids": rng.integers(0, 128, (4, 8, 32),
                                          dtype=np.int32)}

    def test_pp2_matches_flat_moe(self, eight_devices):
        import deepspeed_tpu
        from deepspeed_tpu.models import make_gpt

        batches = self._batches()
        config = {
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1}}

        cfg = GPTConfig(**self.CFG)
        pm = gpt_pipe_model(cfg)
        pipe = PipelineEngine(pm, DeepSpeedTPUConfig(config),
                              mesh=build_mesh(data=4, pipe=2))
        l_pipe = [float(pipe.train_batch(batches)) for _ in range(3)]

        model, _ = make_gpt(cfg)
        params = model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(0)},
            {"input_ids": batches["input_ids"][0]})["params"]
        flat, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params, mesh=build_mesh(data=8),
            config={**config, "train_micro_batch_size_per_gpu": 1})
        l_flat = [float(flat.train_batch(batches)) for _ in range(3)]
        np.testing.assert_allclose(l_pipe, l_flat, rtol=2e-4,
                                   err_msg="MoE pipeline vs flat")

    def test_heterogeneous_moe_rejected(self):
        cfg = GPTConfig(**{**self.CFG, "moe_layer_freq": 2})
        with pytest.raises(ValueError, match="moe_layer_freq"):
            gpt_pipe_model(cfg)


class TestPipelinePLD:
    """Progressive Layer Drop composes with the PipelineEngine (reference:
    engine.forward threads PLD kwargs, /root/reference/deepspeed/runtime/
    engine.py:1085, which pipe/engine.py:540 reaches via super().forward())
    — the pipelined block path consumes pld_theta via aux and the global
    layer index."""

    def _engine(self, mesh, pld_cfg):
        cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=32,
                        num_layers=4, num_heads=2, dropout_rate=0.0,
                        dtype=jnp.float32)
        pm = gpt_pipe_model(cfg)
        extra = ({"progressive_layer_drop": pld_cfg} if pld_cfg else {})
        ds = DeepSpeedTPUConfig({
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1}, **extra})
        return PipelineEngine(pm, ds, mesh=mesh)

    def _batches(self):
        rng = np.random.default_rng(0)
        return {"input_ids": rng.integers(0, 128, (4, 4, 32),
                                          dtype=np.int32)}

    def test_pp2_trains_and_theta_decays(self, eight_devices):
        mesh = build_mesh(data=4, pipe=2)
        eng = self._engine(mesh, {"enabled": True, "theta": 0.5,
                                  "gamma": 0.01})
        losses = [float(eng.train_batch(self._batches())) for _ in range(6)]
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses
        assert eng.progressive_layer_drop.current_theta < 1.0

    def test_theta_one_matches_pld_off(self, eight_devices):
        """theta=1, gamma=0 keeps every layer (p_keep = 1 for all l) —
        the pipelined loss must equal the PLD-off pipeline bit-for-bit,
        pinning the gate's theta schedule against the flat family's."""
        mesh = build_mesh(data=4, pipe=2)
        batches = self._batches()
        l_off = float(self._engine(mesh, None).train_batch(batches))
        l_one = float(self._engine(
            mesh, {"enabled": True, "theta": 1.0,
                   "gamma": 0.0}).train_batch(batches))
        assert l_one == pytest.approx(l_off, rel=1e-6)

    def test_low_theta_differs(self, eight_devices):
        """theta(0) is always 1.0 (the schedule decays from keep-all), so
        step 1 matches PLD-off; with gamma=5 theta(1)~=theta_bar=0.05 and
        step 2's gates actually drop layers — its loss must diverge."""
        mesh = build_mesh(data=4, pipe=2)
        batches = self._batches()
        e_off = self._engine(mesh, None)
        e_low = self._engine(mesh, {"enabled": True, "theta": 0.05,
                                    "gamma": 5.0})
        l_off1, l_off2 = (float(e_off.train_batch(batches))
                          for _ in range(2))
        l_low1, l_low2 = (float(e_low.train_batch(batches))
                          for _ in range(2))
        assert l_low1 == pytest.approx(l_off1, rel=1e-6)   # theta(0) = 1
        assert np.isfinite(l_low2)
        assert abs(l_low2 - l_off2) > 1e-6

    def test_custom_model_without_layer_idx_rejected(self, eight_devices):
        from dataclasses import replace

        cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=32,
                        num_layers=4, num_heads=2, dropout_rate=0.0,
                        dtype=jnp.float32)
        pm = replace(gpt_pipe_model(cfg), block_takes_layer_idx=False)
        ds = DeepSpeedTPUConfig({
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "progressive_layer_drop": {"enabled": True}})
        with pytest.raises(ValueError, match="block_takes_layer_idx"):
            PipelineEngine(pm, ds, mesh=build_mesh(data=4, pipe=2))
