"""Request observatory tests — per-request SLO accounting for serving.

The acceptance gates of the request observatory (docs/OBSERVABILITY.md
"Request observatory"):

- **exact partition**: every finished request's six-category lifetime
  partition sums to its measured lifetime — by construction, not within
  a sampled tolerance;
- a **preempted** request shows nonzero ``preempted_requeue``, resumes
  WARM through the prefix cache, and its eviction count lands in the
  record and the ``requests/preemptions`` counter;
- the **zero-overhead off-contract**: with ``telemetry.requests`` off
  the emitted tag set is byte-identical to the pre-observatory engine
  and the device-sync count is unchanged (and the accountant itself
  adds zero syncs even when on — host clocks only);
- ``results[rid]`` carries ``finish_time`` / ``e2e_ms`` /
  ``queue_wait_ms`` / ``preempted_count`` even with NO telemetry at all
  (the always-on enrichment);
- a **mixed trace** (preemption + prefix cache + speculative decode)
  through ``run_until_complete`` produces host-scoped records whose
  percentiles ``tools/slo_report.py`` reproduces from the files alone,
  plus per-request async tracks in the Perfetto trace.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import (ConfigError, ServingConfig,
                                         TelemetryConfig,
                                         TelemetryRequestsConfig)
from deepspeed_tpu.models import make_gpt
from deepspeed_tpu.serving import ServeEngine
from deepspeed_tpu.serving.engine import SERVING_METRIC_TAGS
from deepspeed_tpu.telemetry import (ENGINE_CATEGORIES, InMemorySink,
                                     MetricsRegistry, RecompileDetector,
                                     REQUEST_CATEGORIES, RequestAccountant,
                                     StepTracer, Telemetry, build_requests)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The pre-observatory engine's emitted tag set on a simple trace (no
# fast path, no preemption) — the off-contract pins this EXACTLY.
BASELINE_SIMPLE_TAGS = {
    "serving/ttft_ms", "serving/batch_occupancy",
    "serving/kv_blocks_in_use", "serving/queue_depth",
    "serving/tokens_per_sec", "serving/requests_completed",
}


@pytest.fixture(scope="module")
def gpt_setup():
    # fp32 like test_serving.py: argmax tie-flips are noise at bf16.
    model, cfg = make_gpt("tiny", dropout_rate=0.0, max_seq_len=64,
                          dtype=jnp.float32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    return model, cfg, params


def _serve(model, params, telemetry=None, accountant=None, **overrides):
    scfg = ServingConfig(**{
        "max_batch_size": 2, "kv_block_size": 4, "kv_num_blocks": 64,
        "max_model_len": 48, **overrides})
    eng = deepspeed_tpu.init_inference(model, params=params,
                                       dtype=jnp.float32)
    return ServeEngine(eng, config=scfg, telemetry=telemetry,
                       request_accountant=accountant)


def _mem_telemetry():
    reg = MetricsRegistry()
    sink = reg.add_sink(InMemorySink())
    tracer = StepTracer(path=None, enabled=False, sync_spans=False)
    return Telemetry(reg, tracer, RecompileDetector(enabled=False)), sink


def _mem_accountant(window_sec=10.0):
    tel, sink = _mem_telemetry()
    acc = RequestAccountant(registry=tel.registry, tracer=tel.tracer,
                            window_sec=window_sec)
    return tel, sink, acc


def _drive(srv, cfg, n=3, seed=17):
    rng = np.random.default_rng(seed)
    rids = [srv.submit(rng.integers(0, cfg.vocab_size, (4 + i,)).tolist(),
                       4 + i) for i in range(n)]
    srv.run_until_complete()
    return rids


# ---------------------------------------------------------------------------
# Exact partition
# ---------------------------------------------------------------------------

class TestExactPartition:
    def test_categories_sum_to_lifetime(self, gpt_setup):
        """The tentpole property: for EVERY finished request the six
        categories sum to the measured lifetime — the mark cursor
        attributes each slice exactly once, so nothing is dropped or
        double-counted."""
        model, cfg, params = gpt_setup
        tel, sink, acc = _mem_accountant()
        srv = _serve(model, params, telemetry=tel, accountant=acc)
        rids = _drive(srv, cfg, n=3)
        for rid in rids:
            slo = srv.results[rid]["slo"]
            parts = slo["categories"]
            assert set(parts) == set(REQUEST_CATEGORIES)
            assert sum(parts.values()) == pytest.approx(
                slo["lifetime_sec"], abs=1e-6)
            assert all(v >= 0.0 for v in parts.values()), parts
            # a normal trace spends nothing preempted
            assert parts["preempted_requeue"] == 0.0
            assert parts["decode_active"] > 0.0
        # the cumulative gauges equal the per-request sums
        acc.emit(step=10_000)
        for c in REQUEST_CATEGORIES:
            want = sum(srv.results[r]["slo"]["categories"][c] for r in rids)
            assert sink.values(f"requests/{c}_sec")[-1] == pytest.approx(
                want, abs=1e-9)
        # latency histograms observed once per request, TPOT per token
        assert len(sink.values("requests/e2e_ms")) == len(rids)
        assert len(sink.values("requests/queue_wait_ms")) == len(rids)
        total_new = sum(srv.results[r]["slo"]["tpot_obs"] for r in rids)
        assert len(sink.values("requests/tpot_ms")) == total_new > 0

    def test_engine_partition_accounts_the_wall(self, gpt_setup):
        """The engine-side cursor: the five serving-time categories sum
        to (approximately) the engine wall clock, and a run that decodes
        spends most marked time in decode+compile."""
        model, cfg, params = gpt_setup
        tel, sink, acc = _mem_accountant()
        srv = _serve(model, params, telemetry=tel, accountant=acc)
        _drive(srv, cfg, n=2)
        acc.emit(step=10_000)
        parts = {c: sink.values(f"requests/engine_{c}_sec")[-1]
                 for c in ENGINE_CATEGORIES}
        wall = sink.values("requests/engine_wall_sec")[-1]
        # everything up to the last mark is attributed; only the tail
        # between that mark and the emit is residue
        assert sum(parts.values()) <= wall
        assert sum(parts.values()) == pytest.approx(wall, abs=0.1)
        assert parts["decode"] + parts["compile"] > 0.0
        # rolling window gauge landed beside the cumulative one
        assert sink.values("serving/tokens_per_sec_window")
        assert sink.values("serving/tokens_per_sec_window")[-1] > 0


# ---------------------------------------------------------------------------
# Preemption
# ---------------------------------------------------------------------------

class TestPreemption:
    def test_preempted_request_accounts_requeue_and_resumes_warm(
            self, gpt_setup):
        """Same KV-pressure scenario as test_serving.py's preemption
        test, with the prefix cache on: the evicted (youngest) request
        shows nonzero ``preempted_requeue`` in its partition, its
        eviction lands in the record and counter, and its re-admission
        adopts the cached prompt head (warm resume — nonzero
        ``prefix_tokens_saved``)."""
        model, cfg, params = gpt_setup
        rng = np.random.default_rng(5)
        tel, sink, acc = _mem_accountant()
        srv = _serve(model, params, telemetry=tel, accountant=acc,
                     kv_num_blocks=12, max_model_len=32, prefix_cache=True)
        p0 = rng.integers(0, cfg.vocab_size, (7,)).tolist()
        p1 = rng.integers(0, cfg.vocab_size, (6,)).tolist()
        r0 = srv.submit(p0, 24)
        r1 = srv.submit(p1, 20)
        res = srv.run_until_complete()
        assert srv.sched.preempted_total == 1
        # the youngest (r1) was the victim
        assert res[r1]["preempted_count"] == 1
        assert res[r0]["preempted_count"] == 0
        slo = res[r1]["slo"]
        parts = slo["categories"]
        assert parts["preempted_requeue"] > 0.0
        assert sum(parts.values()) == pytest.approx(slo["lifetime_sec"],
                                                    abs=1e-6)
        assert res[r0]["slo"]["categories"]["preempted_requeue"] == 0.0
        # warm resume: the first prefill registered r1's full prompt-head
        # block, so the re-admission adopted it instead of re-prefilling
        assert slo["prefix_tokens_saved"] >= 4
        assert sink.values("requests/preemptions")[-1] == 1
        assert sink.values("requests/prefix_tokens_saved")[-1] >= 4


# ---------------------------------------------------------------------------
# Zero-overhead off-contract
# ---------------------------------------------------------------------------

class TestOffContract:
    def test_tag_set_unchanged_with_requests_off(self, gpt_setup,
                                                 monkeypatch):
        """With telemetry ON but no accountant, the emitted tag set is
        byte-identical to the pre-observatory engine — no ``requests/*``
        tags, no window gauge — and the loop performs zero device
        syncs."""
        model, cfg, params = gpt_setup
        tel, sink = _mem_telemetry()
        srv = _serve(model, params, telemetry=tel)
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        _drive(srv, cfg)
        assert calls["n"] == 0
        tags = {r["tag"] for r in sink.rows}
        assert tags == BASELINE_SIMPLE_TAGS
        assert not any(t.startswith("requests/") for t in tags)
        assert "serving/tokens_per_sec_window" not in tags

    def test_accountant_adds_zero_device_syncs(self, gpt_setup,
                                               monkeypatch):
        """The accountant is host ``time.monotonic`` arithmetic only:
        turning it ON must not add a single device sync."""
        model, cfg, params = gpt_setup
        tel, sink, acc = _mem_accountant()
        srv = _serve(model, params, telemetry=tel, accountant=acc)
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        _drive(srv, cfg)
        assert calls["n"] == 0
        tags = {r["tag"] for r in sink.rows}
        # ... while the new surface IS present
        assert BASELINE_SIMPLE_TAGS < tags
        assert "serving/tokens_per_sec_window" in tags
        new = tags - BASELINE_SIMPLE_TAGS - {"serving/tokens_per_sec_window"}
        assert new and all(t.startswith("requests/") for t in new)


# ---------------------------------------------------------------------------
# results[rid] enrichment (always on, telemetry or not)
# ---------------------------------------------------------------------------

class TestResultsEnrichment:
    def test_results_carry_slo_fields_without_telemetry(self, gpt_setup):
        model, cfg, params = gpt_setup
        srv = _serve(model, params)                   # no telemetry at all
        rids = _drive(srv, cfg, n=2)
        for rid in rids:
            res = srv.results[rid]
            assert isinstance(res["finish_time"], float)
            assert res["e2e_ms"] > 0.0
            assert res["queue_wait_ms"] is not None
            assert res["queue_wait_ms"] >= 0.0
            assert res["queue_wait_ms"] < res["e2e_ms"]
            assert res["preempted_count"] == 0
            assert "slo" not in res                   # accountant-only


# ---------------------------------------------------------------------------
# Mixed-trace e2e: records + slo_report + Perfetto tracks
# ---------------------------------------------------------------------------

class TestMixedTraceE2E:
    def test_mixed_trace_reproduced_by_slo_report(self, gpt_setup,
                                                  tmp_path):
        """The acceptance gate: preemption + prefix cache + speculative
        decode through ``init_serving``/``run_until_complete``; every
        record's partition sums to its lifetime; ``slo_report --json``
        reproduces the e2e percentiles from ``requests*.jsonl`` +
        ``metrics*.jsonl`` alone; the trace holds per-request async
        tracks."""
        model, cfg, params = gpt_setup
        srv = deepspeed_tpu.init_serving(
            model, params=params, dtype=jnp.float32,
            config={
                "serving": {"max_batch_size": 2, "kv_block_size": 4,
                            "kv_num_blocks": 12, "max_model_len": 32,
                            "prefix_cache": True,
                            "speculative": {"enabled": True, "k": 2}},
                "telemetry": {"enabled": True, "dir": str(tmp_path),
                              "trace": {"enabled": True},
                              "requests": {"enabled": True,
                                           "window_sec": 5.0}}})
        rng = np.random.default_rng(5)
        p0 = rng.integers(0, cfg.vocab_size, (7,)).tolist()
        p1 = rng.integers(0, cfg.vocab_size, (6,)).tolist()
        srv.submit(p0, 24)
        srv.submit(p1, 20)
        srv.run_until_complete()
        assert srv.sched.preempted_total >= 1
        srv.close()

        rec_path = os.path.join(str(tmp_path), "requests.jsonl")
        assert os.path.exists(rec_path)
        with open(rec_path) as f:
            records = [json.loads(line) for line in f if line.strip()]
        assert len(records) == 2
        for rec in records:
            assert rec["format"] == 1
            assert sum(rec["categories"].values()) == pytest.approx(
                rec["lifetime_sec"], abs=1e-6)
            assert rec["e2e_ms"] == pytest.approx(
                rec["lifetime_sec"] * 1e3, abs=1e-3)
            assert rec["ttft_ms"] is not None and rec["ttft_ms"] > 0
        assert any(r["preempted_count"] >= 1 for r in records)
        assert any(r["categories"]["preempted_requeue"] > 0
                   for r in records)
        # speculative decode ran: its overhead is attributed somewhere
        assert any(r["categories"]["spec_overhead"] > 0 for r in records)

        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "slo_report.py"),
             str(tmp_path), "--json"], capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        report = json.loads(proc.stdout)
        assert report["n_requests"] == 2
        e2es = sorted(r["e2e_ms"] for r in records)
        assert report["e2e_ms"]["p50"] == pytest.approx(
            (e2es[0] + e2es[1]) / 2, rel=1e-9)
        assert report["tpot_source"] == "metrics"
        assert report["tpot_ms"]["p50"] > 0
        for c in REQUEST_CATEGORIES:
            want = sum(r["categories"][c] for r in records)
            assert report["category_sec"][c] == pytest.approx(want,
                                                              abs=1e-9)
        assert report["engine_partition_sec"]["decode"] > 0
        assert report["preemptions"] >= 1
        assert report["prefix_tokens_saved"] >= 4

        # the human rendering works on the same dir
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "slo_report.py"),
             str(tmp_path)], capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        assert "time lost per category" in proc.stdout
        assert "preemptions" in proc.stdout

        # per-request async tracks in the Perfetto trace
        with open(os.path.join(str(tmp_path), "trace.json")) as f:
            events = json.load(f)["traceEvents"]
        async_names = {e["name"] for e in events if e.get("ph") == "b"}
        assert {"req/queue", "req/prefill", "req/decode",
                "req/preempted"} <= async_names
        assert any(e.get("ph") == "e" for e in events)

        # the window gauge landed in the metrics JSONL
        with open(os.path.join(str(tmp_path), "metrics.jsonl")) as f:
            assert any('"serving/tokens_per_sec_window"' in line
                       for line in f)

        # serving_report picks up the record-sourced latency columns too
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "serving_report.py"),
             str(tmp_path)], capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        assert "request records" in proc.stdout


# ---------------------------------------------------------------------------
# Config + factory
# ---------------------------------------------------------------------------

class TestRequestsConfig:
    def test_defaults_off(self):
        tcfg = TelemetryConfig.from_dict(None)
        assert tcfg.requests.enabled is False
        assert build_requests(tcfg) is None

    def test_enabled_telemetry_disabled_requests_is_none(self, tmp_path):
        tcfg = TelemetryConfig.from_dict(
            {"enabled": True, "dir": str(tmp_path)})
        assert build_requests(tcfg) is None

    def test_factory_builds_when_both_enabled(self, tmp_path):
        tcfg = TelemetryConfig.from_dict(
            {"enabled": True, "dir": str(tmp_path),
             "requests": {"enabled": True, "window_sec": 3.0}})
        acc = build_requests(tcfg)
        assert isinstance(acc, RequestAccountant)
        assert acc.window_sec == 3.0
        assert acc.path == os.path.join(str(tmp_path), "requests.jsonl")

    def test_rejects_bad_file_pattern(self):
        with pytest.raises(ConfigError, match="requests"):
            TelemetryRequestsConfig.from_dict({"file": "slo.jsonl"})
        with pytest.raises(ConfigError, match="requests"):
            TelemetryRequestsConfig.from_dict({"file": "requests.txt"})

    def test_rejects_bad_window(self):
        with pytest.raises(ConfigError, match="window_sec"):
            TelemetryRequestsConfig.from_dict({"window_sec": 0})


class TestSloReportCLI:
    def test_selftest(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "slo_report.py"),
             "--selftest"], capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr + proc.stdout
        assert "selftest ok" in proc.stdout
