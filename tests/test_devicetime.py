"""Device-time observatory tests (telemetry/devicetime.py +
telemetry/traceparse.py; docs/OBSERVABILITY.md "Device-time
observatory"): the shared parser's category mapping and overlap/
exposed-comm math on synthetic gzip perfetto fixtures (multi-device
streams, torn/empty captures tolerated), the production capture
scheduler driving REAL jax.profiler captures on the CPU backend into
nonzero devicetime/* gauges + a top-K table + keep-last GC, the
measured-vs-modeled exposed-comm cross-check on a 2-slice mesh, the
divergence warning, the zero-sync + bit-identical-step disabled
contract, StepTracer host-scoped capture dirs, and the
devicetime_report / bench_gate selftests (tier-1)."""

import gzip
import importlib.util
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import ConfigError, DeepSpeedTPUConfig
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.telemetry import (InMemorySink, MetricsRegistry,
                                     StepTracer, Telemetry, traceparse)
from deepspeed_tpu.telemetry.devicetime import (DEVICETIME_METRIC_TAGS,
                                                DIVERGENCE_INSTANT,
                                                DeviceTimeObservatory,
                                                build_devicetime,
                                                roofline_verdicts)
from deepspeed_tpu.telemetry.recompile import RecompileDetector

from simple_model import mlp_loss_fn, mlp_params, random_batches

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leftover_profiler_session():
    """jax.profiler is a process-wide singleton: a test that ends with a
    scheduled capture still open would starve every later test's capture.
    Always drain it."""
    yield
    try:
        jax.profiler.stop_trace()
    except Exception:  # noqa: BLE001 — nothing was active
        pass


def _load_tool(name):
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _engine(config_extra=None, mesh=None):
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": 1},
                **(config_extra or {})},
        mesh=mesh if mesh is not None else build_mesh(data=8))
    return engine


def _tel_cfg(tmp_path, devicetime=None, trace=False, extra=None):
    tel = {"enabled": True, "dir": str(tmp_path),
           "trace": {"enabled": trace},
           "metrics": {"sinks": ["memory"]}}
    if devicetime is not None:
        tel["devicetime"] = devicetime
    return {"telemetry": tel, "steps_per_print": 1, **(extra or {})}


def _fast_devicetime(**over):
    return {"enabled": True, "capture_steps": 1, "every_steps": 2,
            "keep_last": 1, **over}


def _write_capture(dirpath, events, name="host.trace.json.gz"):
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, name)
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return path


def _x(name, pid, tid, ts_ms, dur_ms):
    return {"name": name, "ph": "X", "pid": pid, "tid": tid,
            "ts": ts_ms * 1e3, "dur": dur_ms * 1e3}


def _proc(pid, name):
    return {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name}}


# ---------------------------------------------------------------------------
# The shared parser: category mapping + overlap math on synthetic fixtures
# ---------------------------------------------------------------------------
class TestTraceparse:
    def test_category_mapping(self):
        cases = {
            "dot.3": "matmul", "convolution.1": "matmul",
            "dot-general": "matmul",
            "fusion.12": "elementwise", "loop_fusion": "elementwise",
            "reduce.8": "elementwise", "reduce-window": "elementwise",
            "convert.5": "elementwise", "add.1": "elementwise",
            "all-reduce.63": "collective", "all-gather.96": "collective",
            "reduce-scatter.2": "collective", "all-to-all": "collective",
            "collective-permute.1": "collective",
            "all-reduce-start.4": "collective",
            "copy.2": "copy", "transpose.9": "copy", "bitcast.1": "copy",
            "dynamic-update-slice.3": "copy",
            "custom-call.4": "other",
        }
        for name, want in cases.items():
            assert traceparse.classify_op(name) == want, name
        # runtime/host scaffolding is never attributed
        for noise in ("ThreadpoolListener::StartRegion",
                      "TfrtCpuExecutable::Execute",
                      "PjitFunction(<lambda>)", "ParseArguments",
                      "$profiler.py:91 start_trace", ""):
            assert traceparse.classify_op(noise) is None, noise

    def test_overlap_and_exposed_math_exact(self, tmp_path):
        """compute [0,10ms] on one stream, collective [5,15ms] on another
        -> 10ms collective of which 5ms exposed; busy = union = 15ms."""
        events = [
            _proc(1, "/device:TPU:0"),
            _x("dot.1", 1, 1, 0.0, 10.0),
            _x("all-reduce.7", 1, 2, 5.0, 10.0),
        ]
        _write_capture(str(tmp_path), events)
        a = traceparse.parse_capture_dir(str(tmp_path))
        assert abs(a["categories"]["matmul"] - 0.010) < 1e-12
        assert abs(a["collective_sec"] - 0.010) < 1e-12
        assert abs(a["exposed_collective_sec"] - 0.005) < 1e-12
        assert abs(a["busy_sec"] - 0.015) < 1e-12
        assert abs(a["window_sec"] - 0.015) < 1e-12
        assert a["gap_sec"] < 1e-12
        assert a["n_devices"] == 1

    def test_exposed_uses_interval_union(self, tmp_path):
        """N streams running the SAME collective concurrently (the CPU
        backend's one-process-many-shards layout) must count the wall
        time once: 8 copies of [0,10ms] with compute over [0,4ms] ->
        6ms exposed, not 48."""
        events = [_proc(1, "/device:TPU:0"), _x("dot.1", 1, 99, 0.0, 4.0)]
        for tid in range(8):
            events.append(_x("all-reduce.1", 1, tid, 0.0, 10.0))
        _write_capture(str(tmp_path), events)
        a = traceparse.parse_capture_dir(str(tmp_path))
        assert abs(a["exposed_collective_sec"] - 0.006) < 1e-12
        # category seconds stay device-second sums (8 x 10ms)
        assert abs(a["categories"]["collective"] - 0.080) < 1e-12
        window = a["window_sec"]
        assert a["exposed_collective_sec"] <= window + 1e-12

    def test_multi_device_streams_and_host_exclusion(self, tmp_path):
        """Two device pids aggregate busy/window/gap; the /host: pid's
        HLO-looking events are excluded when device rows exist."""
        events = [
            _proc(1, "/device:TPU:0"), _proc(2, "/device:TPU:1"),
            _proc(9, "/host:CPU"),
            _x("fusion.1", 1, 1, 0.0, 2.0),
            _x("fusion.2", 1, 1, 5.0, 1.0),      # 3ms gap on dev0
            _x("dot.1", 2, 1, 0.0, 4.0),
            _x("dot.99", 9, 1, 0.0, 100.0),      # host: ignored
        ]
        _write_capture(str(tmp_path), events)
        a = traceparse.parse_capture_dir(str(tmp_path))
        assert a["n_devices"] == 2
        assert abs(a["busy_sec"] - 0.007) < 1e-12
        assert abs(a["window_sec"] - 0.010) < 1e-12
        assert abs(a["gap_sec"] - 0.003) < 1e-12
        assert abs(a["categories"]["matmul"] - 0.004) < 1e-12
        names = set(a["ops"])
        assert "dot.99" not in names

    def test_torn_and_empty_captures_tolerated(self, tmp_path):
        empty = tmp_path / "empty"
        empty.mkdir()
        a = traceparse.parse_capture_dir(str(empty))
        assert a["n_devices"] == 0 and a["busy_sec"] == 0.0
        assert a["captures"] == []
        # torn gzip + valid capture side by side: torn skipped
        torn_dir = tmp_path / "torn"
        torn_dir.mkdir()
        with open(torn_dir / "x.trace.json.gz", "wb") as f:
            f.write(b"\x1f\x8b\x08\x00garbage-not-gzip")
        _write_capture(str(torn_dir),
                       [_x("dot.1", 1, 1, 0.0, 1.0)],
                       name="ok.trace.json.gz")
        a = traceparse.parse_capture_dir(str(torn_dir))
        assert len(a["captures"]) == 1
        assert abs(a["categories"]["matmul"] - 0.001) < 1e-12

    def test_top_ops_table(self, tmp_path):
        events = [_x("dot.1", 1, 1, 0.0, 8.0),
                  _x("fusion.2", 1, 1, 8.0, 2.0),
                  _x("dot.1", 1, 1, 10.0, 8.0)]
        _write_capture(str(tmp_path), events)
        a = traceparse.parse_capture_dir(str(tmp_path))
        hot = traceparse.top_ops(a, 1)
        assert len(hot) == 1
        assert hot[0]["name"] == "dot.1" and hot[0]["count"] == 2
        assert abs(hot[0]["sec"] - 0.016) < 1e-12
        assert hot[0]["share_of_busy"] > 0.8

    def test_scan_profile_dir_legacy_semantics(self, tmp_path):
        """fleet_report --profile-dir output is unchanged by the
        consolidation: total sums ALL duration events (runtime noise
        included), collective by the shared regex."""
        events = [_x("all-reduce.1", 1, 1, 0.0, 3.0),
                  _x("dot.1", 1, 1, 3.0, 6.0),
                  {"name": "ThunkExecutor::Execute", "ph": "X", "pid": 1,
                   "tid": 2, "ts": 0.0, "dur": 1_000.0}]
        _write_capture(str(tmp_path / "plugins"), events)
        fr = _load_tool("fleet_report")
        out = fr.scan_profile_dir(str(tmp_path))
        (rel, row), = out.items()
        assert rel.endswith("host.trace.json.gz")
        assert abs(row["collective_ms"] - 3.0) < 1e-9
        assert abs(row["total_ms"] - 10.0) < 1e-9
        assert abs(row["collective_frac"] - 0.3) < 1e-9

    def test_one_collective_list_in_tree(self):
        """THE collective-op-name list lives in traceparse; fleet_report
        re-binds it (satellite: one list in the tree)."""
        fr = _load_tool("fleet_report")
        assert fr.COLLECTIVE_RE is not None
        assert fr.COLLECTIVE_RE.pattern == traceparse.COLLECTIVE_RE.pattern


# ---------------------------------------------------------------------------
# Capture scheduler on the real CPU backend (acceptance: a real capture
# round-trips into nonzero devicetime/* gauges + a top-K table)
# ---------------------------------------------------------------------------
class TestScheduler:
    def test_real_capture_roundtrip_nonzero_gauges(self, eight_devices,
                                                   tmp_path):
        engine = _engine(_tel_cfg(tmp_path,
                                  devicetime=_fast_devicetime(top_k=5)))
        assert engine.devicetime is not None
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=1, batch_size=16)
        for _ in range(5):
            engine.train_batch(batches)
        assert engine.devicetime.captures_done >= 1
        mem = engine.telemetry.registry.sinks[0]
        tags = mem.tags()
        for tag in ("devicetime/busy_sec", "devicetime/window_sec",
                    "devicetime/steps_captured",
                    "comm/measured_exposed_frac"):
            assert tag in tags, tag
        assert mem.values("devicetime/busy_sec")[-1] > 0
        assert mem.values("devicetime/window_sec")[-1] > 0
        # a ZeRO-1 MLP step on the 8-device mesh has real matmuls and
        # real collectives in the capture
        assert (mem.values("devicetime/matmul_sec")[-1] > 0
                or mem.values("devicetime/elementwise_sec")[-1] > 0)
        assert mem.values("devicetime/collective_sec")[-1] > 0
        frac = mem.values("comm/measured_exposed_frac")[-1]
        assert 0.0 <= frac <= 1.0
        # top-K hottest-op table in the breakdown artifact
        bd = engine.devicetime.last_breakdown
        assert bd is not None and bd["top_ops"]
        assert all(r["sec"] > 0 for r in bd["top_ops"])
        assert os.path.exists(engine.devicetime.breakdown_path)
        doc = json.load(open(engine.devicetime.breakdown_path))
        assert doc["steps_captured"] >= 1
        assert set(doc["categories_sec"]) == set(traceparse.CATEGORIES)
        # mfu_measured rides the cost-analysis join (engine feeds flops)
        assert doc["mfu_measured"] is None or doc["mfu_measured"] > 0

    def test_keep_last_gc(self, eight_devices, tmp_path):
        engine = _engine(_tel_cfg(
            tmp_path, devicetime=_fast_devicetime(keep_last=1)))
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=1, batch_size=16)
        # 9 steps: captures open at 2/4/6/8 and each closes on the next
        # step, so the run ends with no capture in flight and the GC has
        # run after every close.
        for _ in range(9):
            engine.train_batch(batches)
        assert engine.devicetime.captures_done >= 2
        cap_root = os.path.join(str(tmp_path), "devicetime")
        dirs = [d for d in os.listdir(cap_root)
                if d.startswith("capture_step")]
        assert len(dirs) == 1, dirs

    def test_report_tool_renders_engine_breakdown(self, eight_devices,
                                                  tmp_path):
        engine = _engine(_tel_cfg(tmp_path, devicetime=_fast_devicetime()))
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=1, batch_size=16)
        for _ in range(4):
            engine.train_batch(batches)
        assert engine.devicetime.captures_done >= 1
        dr = _load_tool("devicetime_report")
        breakdowns = dr.load_breakdowns(str(tmp_path))
        assert len(breakdowns) == 1
        text = dr.render_breakdown(breakdowns[0])
        assert "collective" in text and "hottest ops" in text

    def test_tracer_capture_dirs_host_scoped(self, tmp_path, monkeypatch):
        """Satellite: start_jax_profiler lands in a per-host subdir
        whenever the run spans processes (forced here via
        DSTPU_TELEMETRY_HOST, the PR 6 convention), so multi-host
        captures on shared storage never collide."""
        started = {}
        import jax.profiler as jprof
        monkeypatch.setattr(jprof, "start_trace",
                            lambda d: started.__setitem__("dir", d))
        monkeypatch.setattr(jprof, "stop_trace", lambda: None)
        monkeypatch.setenv("DSTPU_TELEMETRY_HOST", "worker-3")
        tracer = StepTracer(enabled=False)
        out = tracer.start_jax_profiler(dir=str(tmp_path / "cap"))
        assert out == started["dir"]
        assert os.path.basename(out) == "worker-3"
        assert os.path.dirname(out) == str(tmp_path / "cap")
        assert tracer.profiler_active
        assert tracer.stop_jax_profiler() == out
        assert not tracer.profiler_active
        # single-host: path unchanged
        monkeypatch.delenv("DSTPU_TELEMETRY_HOST")
        out2 = tracer.start_jax_profiler(dir=str(tmp_path / "cap2"))
        assert out2 == str(tmp_path / "cap2")

    def test_divergence_warning_and_instant(self, tmp_path, monkeypatch):
        """A measured exposed-comm fraction far from the modeled one must
        warn loudly and drop the divergence instant."""
        warnings = []
        from deepspeed_tpu.telemetry import devicetime as dt_mod
        monkeypatch.setattr(
            dt_mod.logger, "warning",
            lambda msg, *a, **k: warnings.append(msg % a if a else msg))
        reg = MetricsRegistry()
        reg.add_sink(InMemorySink())
        tracer = StepTracer(path=str(tmp_path / "trace.json"))
        tel = Telemetry(reg, tracer, RecompileDetector(enabled=False))
        cfg = DeepSpeedTPUConfig(
            {"train_micro_batch_size_per_gpu": 1,
             "telemetry": {"enabled": True, "dir": str(tmp_path),
                           "devicetime": {"enabled": True}}}
        ).telemetry.devicetime
        obs = DeviceTimeObservatory(cfg, run_dir=str(tmp_path),
                                    telemetry=tel, host="h0")
        reg.gauge("comm/exposed_frac").set(0.9, step=1)
        analysis = traceparse.merge_analyses([])
        analysis["categories"]["collective"] = 0.001
        analysis["collective_sec"] = 0.001
        analysis["exposed_collective_sec"] = 0.0
        analysis["window_sec"] = 0.010
        analysis["busy_sec"] = 0.010
        analysis["n_devices"] = 1
        obs._emit(analysis, step=1, steps_captured=1)
        assert any("diverges" in w for w in warnings), warnings
        assert DIVERGENCE_INSTANT in {e["name"] for e in tracer.events
                                      if e.get("ph") == "i"}
        # and the modeled value landed in the breakdown for the report
        assert obs.last_breakdown["exposed_comm"]["modeled_frac"] == 0.9

    def _obs(self, tmp_path, devicetime=None):
        reg = MetricsRegistry()
        mem = reg.add_sink(InMemorySink())
        tracer = StepTracer(path=str(tmp_path / "trace.json"))
        tel = Telemetry(reg, tracer, RecompileDetector(enabled=False))
        cfg = DeepSpeedTPUConfig(
            {"train_micro_batch_size_per_gpu": 1,
             "telemetry": {"enabled": True, "dir": str(tmp_path),
                           "devicetime": {"enabled": True,
                                          **(devicetime or {})}}}
        ).telemetry.devicetime
        obs = DeviceTimeObservatory(cfg, run_dir=str(tmp_path),
                                    telemetry=tel, host="h0")
        return obs, tel, mem

    def test_empty_capture_skips_emission_no_false_divergence(
            self, tmp_path, monkeypatch):
        """A capture that closes with no parseable device events must not
        zero the gauges — and must not fire a spurious divergence
        warning against a high modeled fraction."""
        obs, tel, mem = self._obs(tmp_path, devicetime={
            "capture_steps": 1, "every_steps": 2})
        tel.registry.gauge("comm/exposed_frac").set(0.9, step=2)
        import jax.profiler as jprof
        monkeypatch.setattr(jprof, "start_trace", lambda d: None)
        monkeypatch.setattr(jprof, "stop_trace", lambda: None)
        obs._start_capture(2)
        assert obs._capture_dir is not None
        obs.step_hook(3)                       # closes: dir has no captures
        assert obs.captures_done == 0
        assert "comm/measured_exposed_frac" not in mem.tags()
        assert not {t for t in mem.tags() if t.startswith("devicetime/")}
        assert DIVERGENCE_INSTANT not in {e["name"] for e in
                                          tel.tracer.events
                                          if e.get("ph") == "i"}

    def test_capture_dir_host_scoped_parse_and_gc(self, tmp_path,
                                                  monkeypatch):
        """Multi-host: the observatory parses and GCs only THIS host's
        subdir of the shared per-step capture root (and drops the root
        once empty) — never another host's capture."""
        monkeypatch.setenv("DSTPU_TELEMETRY_HOST", "workerA")
        obs, tel, mem = self._obs(tmp_path, devicetime={
            "keep_last": 1, "capture_steps": 1, "every_steps": 2})
        import jax.profiler as jprof
        monkeypatch.setattr(jprof, "start_trace", lambda d: None)
        monkeypatch.setattr(jprof, "stop_trace", lambda: None)

        def run_capture(step, dur_ms):
            obs._start_capture(step)
            assert os.path.basename(obs._capture_dir) == "workerA"
            # another host's capture lands beside ours in the same root
            root = os.path.dirname(obs._capture_dir)
            _write_capture(os.path.join(root, "workerB"),
                           [_x("all-reduce.9", 1, 1, 0.0, 500.0)])
            _write_capture(obs._capture_dir,
                           [_x("dot.1", 1, 1, 0.0, dur_ms)])
            obs.step_hook(step + 1)
            return root

        root1 = run_capture(2, 3.0)
        assert obs.captures_done == 1
        # only OUR host's events were parsed (no collective from workerB)
        assert mem.values("devicetime/collective_sec")[-1] == 0.0
        assert abs(mem.values("devicetime/matmul_sec")[-1] - 0.003) < 1e-12
        root2 = run_capture(4, 5.0)
        # keep_last=1: our subdir of root1 GC'd, workerB's untouched,
        # root1 itself kept (still non-empty)
        assert not os.path.exists(os.path.join(root1, "workerA"))
        assert os.path.exists(os.path.join(root1, "workerB"))
        assert os.path.exists(os.path.join(root2, "workerA"))

    def test_roofline_verdicts(self):
        v = roofline_verdicts(intensity=500.0, ridge=240.0)
        assert v["matmul"] == "compute-bound"
        v = roofline_verdicts(intensity=100.0, ridge=240.0)
        assert v["matmul"] == "hbm-bound"
        assert v["collective"] == "network-bound"
        assert roofline_verdicts(None, 240.0)["matmul"] == "unknown"


# ---------------------------------------------------------------------------
# Measured-vs-modeled cross-check on a 2-slice mesh (acceptance)
# ---------------------------------------------------------------------------
class TestExposedCrossCheck:
    def test_measured_vs_modeled_on_two_slices(self, eight_devices,
                                               tmp_path):
        engine = _engine(
            _tel_cfg(tmp_path, devicetime=_fast_devicetime(),
                     extra={"gradient_accumulation_steps": 2,
                            "comm": {"hierarchical": "on",
                                     "dcn_quant_bits": 8},
                            "zero_optimization": {"stage": 2}}),
            mesh=build_mesh(slices=2))
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=2, batch_size=16)
        for _ in range(5):
            engine.train_batch(batches)
        assert engine.devicetime.captures_done >= 1
        mem = engine.telemetry.registry.sinks[0]
        modeled = mem.values("comm/exposed_frac")
        measured = mem.values("comm/measured_exposed_frac")
        assert modeled and measured, (mem.tags())
        assert all(0.0 < f <= 1.0 for f in modeled)
        assert all(0.0 <= f <= 1.0 for f in measured)
        bd = engine.devicetime.last_breakdown
        assert bd["exposed_comm"]["modeled_frac"] is not None
        assert bd["exposed_comm"]["measured_frac"] is not None
        # the hierarchical step's collectives are visible in the capture
        assert bd["categories_sec"]["collective"] > 0


# ---------------------------------------------------------------------------
# Zero-overhead disabled contract (the fleet/goodput/memory gate shape)
# ---------------------------------------------------------------------------
class TestDisabledContract:
    def test_disabled_devicetime_is_none_no_tags_zero_syncs(
            self, eight_devices, tmp_path, monkeypatch):
        engine = _engine(_tel_cfg(tmp_path))  # telemetry on, devicetime off
        assert engine.devicetime is None
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        engine.train_batch(batches)           # compile outside the window
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        for _ in range(10):
            engine.train_batch(batches)
        assert calls["n"] == 0
        mem = engine.telemetry.registry.sinks[0]
        assert not {t for t in mem.tags()
                    if t.startswith("devicetime/")
                    or t == "comm/measured_exposed_frac"}
        assert not os.path.exists(tmp_path / "devicetime")
        # telemetry fully off too
        engine2 = _engine()
        assert engine2.devicetime is None

    def test_enabled_between_captures_zero_syncs(self, eight_devices,
                                                 tmp_path, monkeypatch):
        """Enabled devicetime must only touch the device at capture
        boundaries: with the next capture far away, the step path shows
        ZERO devicetime-originated syncs."""
        engine = _engine(_tel_cfg(
            tmp_path, devicetime=_fast_devicetime(every_steps=10_000,
                                                  capture_steps=1)))
        assert engine.devicetime is not None
        batches = random_batches(np.random.default_rng(0), gas=1,
                                 batch_size=16)
        engine.train_batch(batches)
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        for _ in range(10):
            engine.train_batch(batches)
        assert calls["n"] == 0

    def test_step_lowered_bit_identical(self, eight_devices, tmp_path):
        """The observatory never touches the jitted step functions —
        lowered step text identical with devicetime off vs on."""
        batches_np = random_batches(np.random.default_rng(0), gas=1,
                                    batch_size=16)
        texts = []
        for dt in (None, _fast_devicetime()):
            engine = _engine(_tel_cfg(tmp_path / str(bool(dt)),
                                      devicetime=dt))
            placed = engine.put_batch(batches_np, leading_gas_dim=True)
            lowered = engine._train_step.lower(engine.state, placed,
                                               jnp.float32(1e-2))
            texts.append(lowered.as_text())
        assert texts[0] == texts[1]


# ---------------------------------------------------------------------------
# Config validation
# ---------------------------------------------------------------------------
class TestConfig:
    def _cfg(self, devicetime, trace=None):
        tel = {"enabled": True, "dir": "/tmp/x", "devicetime": devicetime}
        if trace:
            tel["trace"] = trace
        return DeepSpeedTPUConfig({"train_micro_batch_size_per_gpu": 1,
                                   "telemetry": tel})

    def test_defaults_off(self):
        cfg = DeepSpeedTPUConfig({"train_micro_batch_size_per_gpu": 1})
        assert not cfg.telemetry.devicetime.enabled

    def test_every_steps_must_exceed_capture(self):
        with pytest.raises(ConfigError, match="every_steps"):
            self._cfg({"enabled": True, "every_steps": 3,
                       "capture_steps": 3})

    def test_divergence_warn_range(self):
        with pytest.raises(ConfigError, match="divergence_warn"):
            self._cfg({"enabled": True, "divergence_warn": 0.0})

    def test_keep_last_positive(self):
        with pytest.raises(ConfigError, match="keep_last"):
            self._cfg({"enabled": True, "keep_last": 0})

    def test_passthrough_conflict_rejected(self):
        with pytest.raises(ConfigError, match="jax_profiler_dir"):
            self._cfg({"enabled": True},
                      trace={"jax_profiler_dir": "/tmp/p"})


# ---------------------------------------------------------------------------
# CI/tooling: report + gate selftests run in tier-1 (satellite)
# ---------------------------------------------------------------------------
class TestToolSelftests:
    def test_devicetime_report_selftest(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "devicetime_report.py"),
             "--selftest"],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "selftest ok" in out.stdout

    def test_bench_gate_selftest(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
             "--selftest"],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "selftest ok" in out.stdout

    def test_bench_gate_detects_injected_regression(self, tmp_path):
        """Acceptance: the gate passes a clean candidate (rc 0) and
        catches an injected regression with a NONZERO rc (2)."""
        gate = _load_tool("bench_gate")
        baseline = {"sections": {"gpt2": {"tokens_per_sec": 100_000.0,
                                          "mfu": 0.60}}}
        basep = tmp_path / "BENCH_baseline.json"
        basep.write_text(json.dumps(baseline))
        clean = tmp_path / "clean.json"
        clean.write_text(json.dumps(
            {"sections": {"gpt2": {"tokens_per_sec": 98_000.0,
                                   "mfu": 0.61}}}))
        assert gate.main([str(clean), "--baseline", str(basep)]) == 0
        regressed = tmp_path / "regressed.json"
        regressed.write_text(json.dumps(
            {"sections": {"gpt2": {"tokens_per_sec": 60_000.0,
                                   "mfu": 0.60}}}))
        rc = gate.main([str(regressed), "--baseline", str(basep)])
        assert rc == 2, rc

    def test_committed_baseline_parses_and_gates_its_source(self):
        """BENCH_baseline.json (seeded from the last green TPU round) is
        valid gate input, and its source bench JSON passes against it."""
        gate = _load_tool("bench_gate")
        rc = gate.main([os.path.join(REPO, "BENCH_r4_local.json")])
        assert rc == 0

    def test_bench_sections_schema_matches_gate(self):
        """bench.py's _section_rows emits the schema sections_of consumes
        (satellite: bench rows ride the gate's contract)."""
        gate = _load_tool("bench_gate")
        import bench
        result = {}
        bench._section_rows(result, "gpt2", tokens_per_sec=1000.0,
                            mfu=0.5, skipped=None)
        secs = gate.sections_of(result)
        assert secs == {"gpt2": {"tokens_per_sec": 1000.0, "mfu": 0.5}}
