"""DCN-aware hierarchical mesh (round-2 VERDICT task 5) + the
hierarchical quantized gradient sync (ISSUE 4, comm/grad_sync.py).

A virtual "two-slice" 2x4 mesh: the outer ``dcn`` axis stands for slow
inter-slice links, the inner ``data`` axis for ICI. Assertions:

- training over dcn x data is numerically the same as over flat data
  (grad averaging spans both axes);
- ZeRO sharding stays on the ICI-inner ``data`` axis;
- OneBitAdam compresses over ``dcn`` only — the jaxpr shows the 1-bit
  ``all_to_all`` on the dcn axis and a dense psum on the data axis;
- the grad-sync strategy ladder: ``hierarchical: off`` adds zero new
  collectives (jaxpr-identical to a comm-less config); ``on`` with fp32
  passthrough tracks ``off`` at float reduction-ordering precision;
  int8 stays within tolerance over a short GPT trajectory; the
  quantizer round-trips deterministically.

Reference positioning: runtime/comm/nccl.py:47 (1-bit over Ethernet
clusters), SURVEY §2.5 TPU-native row; ZeRO++ (arXiv 2306.10209) and
EQuARX (arXiv 2506.17615) for the quantized hierarchical collectives.
"""

import os
import re
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.quantize import (dequantize_blockwise,
                                         quantize_blockwise)
from deepspeed_tpu.parallel.mesh import (DATA_AXIS, DCN_AXIS, build_mesh)


def mlp_loss_fn(params, batch, rng):
    h = jnp.tanh(batch["x"] @ params["w1"])
    pred = h @ params["w2"]
    return jnp.mean((pred - batch["y"]) ** 2)


def mlp_params():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    return {"w1": jax.random.normal(k1, (16, 64)) * 0.1,
            "w2": jax.random.normal(k2, (64, 8)) * 0.1}


def make_batches(rng, gas, bs):
    return {"x": rng.standard_normal((gas, bs, 16)).astype(np.float32),
            "y": rng.standard_normal((gas, bs, 8)).astype(np.float32)}


def build(mesh, optimizer_type="Adam", stage=2, extra=None, comm=None,
          config_extra=None, **init_kwargs):
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": optimizer_type,
                      "params": dict({"lr": 1e-2}, **(extra or {}))},
        "zero_optimization": {"stage": stage},
    }
    if comm is not None:
        config["comm"] = comm
    if config_extra:
        config.update(config_extra)
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(), mesh=mesh, config=config,
        **init_kwargs)
    return engine


class TestHierarchicalMesh:
    def test_build_mesh_slices(self, eight_devices):
        mesh = build_mesh(slices=2)
        assert mesh.shape[DCN_AXIS] == 2
        assert mesh.shape[DATA_AXIS] == 4

    def test_training_parity_vs_flat(self, eight_devices):
        """Same data, same init: dcn2 x data4 must track flat data8."""
        rng = np.random.default_rng(0)
        batches = [make_batches(rng, 2, 16) for _ in range(5)]

        flat = build(build_mesh(data=8))
        hier = build(build_mesh(slices=2))
        assert hier.dp_size == 8
        for b in batches:
            lf = float(flat.train_batch(b))
            lh = float(hier.train_batch(b))
            np.testing.assert_allclose(lf, lh, rtol=1e-5)

    def test_zero_shards_stay_ici_inner(self, eight_devices):
        """Optimizer-state shards split over `data` (4-way), NOT over the
        8-way dcn x data product — ZeRO collectives ride ICI."""
        hier = build(build_mesh(slices=2), stage=2)
        m = hier.state.opt_state.exp_avg["w1"]
        shard_elems = int(np.prod(m.sharding.shard_shape(m.shape)))
        assert shard_elems == 16 * 64 // 4, shard_elems

    def test_onebit_compresses_over_dcn(self, eight_devices):
        """OneBitAdam on a hierarchical mesh: compression axis defaults to
        dcn; the jaxpr carries the 1-bit all_to_all over ('dcn',) and a
        dense psum over ('data',)."""
        hier = build(build_mesh(slices=2), optimizer_type="OneBitAdam",
                     stage=0, extra={"freeze_step": 2})
        assert hier.optimizer.axis == DCN_AXIS
        assert hier.optimizer.n == 2      # compresses across 2 slices

        rng = np.random.default_rng(1)
        batches = make_batches(rng, 2, 16)
        placed = hier.put_batch(batches, leading_gas_dim=True)
        traced = hier._train_step.trace(
            hier.state, placed, jnp.float32(1e-2))
        import re

        txt = str(traced.jaxpr)
        a2a = re.findall(r"all_to_all\[(.*?)\]", txt, re.S)
        assert a2a, "no all_to_all in jaxpr (1-bit path missing)"
        assert all("dcn" in blk for blk in a2a), a2a[0][:200]
        assert not any("'data'" in blk for blk in a2a), a2a[0][:200]
        dense = [blk for blk in re.findall(r"psum2?\[(.*?)\]", txt, re.S)
                 if "'data'" in blk and "dcn" not in blk]
        assert dense, "no dense data-axis reduction found"

        losses = [float(hier.train_batch(batches)) for _ in range(4)]
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0]

    def test_onebit_parity_flat_vs_hier_warmup(self, eight_devices):
        """During warmup (dense phase) the hierarchical 1-bit step must
        match the flat one exactly — pre-reduce over data + pmean over dcn
        is the same mean as pmean over 8 ranks."""
        rng = np.random.default_rng(2)
        batches = [make_batches(rng, 2, 16) for _ in range(3)]
        flat = build(build_mesh(data=8), optimizer_type="OneBitAdam",
                     stage=0, extra={"freeze_step": 100})
        hier = build(build_mesh(slices=2), optimizer_type="OneBitAdam",
                     stage=0, extra={"freeze_step": 100})
        for b in batches:
            lf = float(flat.train_batch(b))
            lh = float(hier.train_batch(b))
            np.testing.assert_allclose(lf, lh, rtol=2e-5)


class TestQuantizeRoundtrip:
    """comm/quantize.py properties the grad-sync protocol relies on —
    bits=8, block sizes {256, 1024}."""

    @pytest.mark.parametrize("block", [256, 1024])
    def test_roundtrip_error_bounded(self, block):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((4 * block,)).astype(np.float32)
        q, s = quantize_blockwise(jnp.asarray(x), block)
        assert q.dtype == jnp.int8 and s.shape == (4 * block // block,)
        out = np.asarray(dequantize_blockwise(q, s, block))
        # Symmetric int8: error <= scale/2 = amax/254 per block.
        amax = np.abs(x.reshape(-1, block)).max(axis=1)
        err = np.abs(out - x).reshape(-1, block).max(axis=1)
        assert (err <= amax / 254 + 1e-8).all()

    @pytest.mark.parametrize("block", [256, 1024])
    def test_zeros_roundtrip_exact(self, block):
        q, s = quantize_blockwise(jnp.zeros((2 * block,)), block)
        out = np.asarray(dequantize_blockwise(q, s, block))
        assert (out == 0.0).all()
        assert (np.asarray(q) == 0).all()

    @pytest.mark.parametrize("block", [256, 1024])
    def test_infinity_free_and_finite(self, block):
        rng = np.random.default_rng(1)
        x = (rng.standard_normal((2 * block,)) * 1e30).astype(np.float32)
        q, s = quantize_blockwise(jnp.asarray(x), block)
        out = np.asarray(dequantize_blockwise(q, s, block))
        assert np.isfinite(out).all()

    @pytest.mark.parametrize("block", [256, 1024])
    def test_per_block_max_preserved(self, block):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((8 * block,)).astype(np.float32)
        q, s = quantize_blockwise(jnp.asarray(x), block)
        out = np.asarray(dequantize_blockwise(q, s, block))
        amax_in = np.abs(x.reshape(-1, block)).max(axis=1)
        amax_out = np.abs(out.reshape(-1, block)).max(axis=1)
        # The max element maps to ±qmax exactly; dequantizing gives
        # qmax * fl(amax/qmax) — one fp32 rounding of amax.
        np.testing.assert_allclose(amax_out, amax_in, rtol=1e-6)

    def test_deterministic(self):
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((2048,)).astype(np.float32))
        q1, s1 = quantize_blockwise(x, 256)
        q2, s2 = quantize_blockwise(x, 256)
        assert np.asarray(q1).tobytes() == np.asarray(q2).tobytes()
        assert np.asarray(s1).tobytes() == np.asarray(s2).tobytes()

    def test_overflow_propagates_as_nan(self):
        """An inf/NaN block must stay visible after the round-trip —
        the fp16 loss-scaler's skip logic detects overflow on the synced
        grads."""
        x = np.ones((512,), np.float32)
        x[100] = np.inf
        q, s = quantize_blockwise(jnp.asarray(x), 256)
        out = np.asarray(dequantize_blockwise(q, s, 256))
        assert np.isnan(out[:256]).any()          # poisoned block
        assert np.isfinite(out[256:]).all()       # clean block untouched


class TestHierarchicalGradSync:
    """The grad-sync strategy parity ladder (ISSUE 4 acceptance)."""

    def test_default_off_and_zero_new_collectives(self, eight_devices):
        """`hierarchical: off` (and the default, comm block absent) must
        add ZERO new collectives: the traced train_step jaxpr is
        string-identical to a config without any comm block, and contains
        no all_to_all (the implicit path never emits one)."""
        rng = np.random.default_rng(0)
        batches = make_batches(rng, 2, 16)
        base = build(build_mesh(slices=2))
        off = build(build_mesh(slices=2), comm={"hierarchical": "off"})
        assert base.grad_sync_plan is None and off.grad_sync_plan is None
        pb = base.put_batch(batches, leading_gas_dim=True)
        jx_base = str(base._train_step.trace(
            base.state, pb, jnp.float32(1e-2)).jaxpr)
        jx_off = str(off._train_step.trace(
            off.state, pb, jnp.float32(1e-2)).jaxpr)
        assert jx_base == jx_off
        assert "all_to_all" not in jx_off

    def test_fp32_passthrough_tracks_off_at_ulp(self, eight_devices):
        """off vs on+fp32-passthrough over a 6-step trajectory. The two
        paths compute the same sums in different collective orders
        (slice-wise partials vs one 8-way reduce), so exact bit-identity
        is unattainable on non-associative floats — the bound here is
        float32 reduction-ordering noise (~1 ulp/step), orders of
        magnitude below any semantic difference."""
        rng = np.random.default_rng(0)
        batches = [make_batches(rng, 2, 16) for _ in range(6)]
        off = build(build_mesh(slices=2), comm={"hierarchical": "off"})
        on = build(build_mesh(slices=2),
                   comm={"hierarchical": "on", "dcn_quant_bits": 32})
        assert on.grad_sync_plan is not None
        for b in batches:
            lo = float(off.train_batch(b))
            lh = float(on.train_batch(b))
            np.testing.assert_allclose(lo, lh, rtol=1e-6, atol=1e-7)

    @pytest.mark.parametrize("bits,tol", [(16, 2e-3), (8, 2e-2)])
    def test_quantized_rungs_track_off(self, eight_devices, bits, tol):
        rng = np.random.default_rng(1)
        batches = [make_batches(rng, 2, 16) for _ in range(5)]
        off = build(build_mesh(slices=2), comm={"hierarchical": "off"})
        on = build(build_mesh(slices=2),
                   comm={"hierarchical": "on", "dcn_quant_bits": bits,
                         "quant_block_size": 256})
        for b in batches:
            lo = float(off.train_batch(b))
            lh = float(on.train_batch(b))
            assert np.isfinite(lh)
            np.testing.assert_allclose(lo, lh, rtol=tol, atol=tol)

    def test_int8_jaxpr_collectives_and_wire_dtype(self, eight_devices):
        """The int8 rung's jaxpr: all_to_all rides the dcn axis only, and
        the shipped operands are int8 (the wire dtype the compression
        claims)."""
        on = build(build_mesh(slices=2),
                   comm={"hierarchical": "on", "dcn_quant_bits": 8,
                         "quant_block_size": 256})
        rng = np.random.default_rng(2)
        placed = on.put_batch(make_batches(rng, 2, 16),
                              leading_gas_dim=True)
        txt = str(on._train_step.trace(
            on.state, placed, jnp.float32(1e-2)).jaxpr)
        a2a = re.findall(r"all_to_all\[(.*?)\]", txt, re.S)
        assert a2a, "no all_to_all in hierarchical jaxpr"
        assert all("dcn" in blk for blk in a2a)
        assert not any("'data'" in blk for blk in a2a)
        # int8 codes cross the dcn axis: an i8 operand feeds all_to_all.
        assert re.search(r"all_to_all\[[^\]]*\]\s+\w+", txt)
        assert "i8[" in txt, "no int8 arrays in the step at all"

    def test_modeled_compression_ratio(self, eight_devices):
        on = build(build_mesh(slices=2),
                   comm={"hierarchical": "on", "dcn_quant_bits": 8,
                         "quant_block_size": 256})
        m = on.grad_sync_plan.modeled_bytes()
        assert m["compression_ratio"] >= 3.5
        assert m["bytes_dcn"] < m["bytes_dcn_fp32"]
        assert m["fallback_elems"] == 0     # plain MLP: everything buckets

    def test_int8_gpt_trajectory(self, eight_devices):
        """Short GPT trajectory on the 2-slice mesh: int8 grad sync stays
        within tolerance of the implicit path and the loss still
        decreases (the ZeRO++ claim at toy scale)."""
        from deepspeed_tpu.models import make_gpt

        def make_engine(comm):
            model, cfg = make_gpt("tiny", num_layers=2, dropout_rate=0.0,
                                  dtype=jnp.float32)
            rng = np.random.default_rng(0)
            ids = rng.integers(0, cfg.vocab_size, (8, 16), dtype=np.int32)
            params = model.init({"params": jax.random.PRNGKey(0),
                                 "dropout": jax.random.PRNGKey(1)},
                                {"input_ids": ids})["params"]
            config = {
                "train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2},
            }
            if comm:
                config["comm"] = comm
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=model, params=params, mesh=build_mesh(slices=2),
                config=config)
            return engine, cfg

        off, cfg = make_engine(None)
        on, _ = make_engine({"hierarchical": "on", "dcn_quant_bits": 8,
                             "quant_block_size": 256})
        rng = np.random.default_rng(3)
        losses_off, losses_on = [], []
        for _ in range(5):
            ids = rng.integers(0, cfg.vocab_size, (2, 16, 16),
                               dtype=np.int32)
            batch = {"input_ids": ids}
            losses_off.append(float(off.train_batch(dict(batch))))
            losses_on.append(float(on.train_batch(dict(batch))))
        losses_off, losses_on = np.array(losses_off), np.array(losses_on)
        assert np.isfinite(losses_on).all()
        np.testing.assert_allclose(losses_on, losses_off, rtol=2e-2)
        assert losses_on[-1] < losses_on[0]     # still trains

    def test_communication_data_type_is_ici_dtype(self, eight_devices):
        """communication_data_type=bf16 shows up as the bucket's ICI
        dtype: the traced step carries bf16 buckets (2048 elems for this
        MLP at block 256) and the trajectory stays close to fp32."""
        on_bf16 = build(build_mesh(slices=2),
                        comm={"hierarchical": "on", "dcn_quant_bits": 32,
                              "quant_block_size": 256},
                        config_extra={"communication_data_type": "bf16"})
        assert on_bf16.grad_sync_plan.ici_dtype == jnp.bfloat16
        rng = np.random.default_rng(4)
        placed = on_bf16.put_batch(make_batches(rng, 2, 16),
                                   leading_gas_dim=True)
        txt = str(on_bf16._train_step.trace(
            on_bf16.state, placed, jnp.float32(1e-2)).jaxpr)
        assert "bf16[2048]" in txt      # the bucket, in the ICI dtype
        off = build(build_mesh(slices=2))
        batches = [make_batches(rng, 2, 16) for _ in range(3)]
        for b in batches:
            lo = float(off.train_batch(b))
            lh = float(on_bf16.train_batch(b))
            np.testing.assert_allclose(lo, lh, rtol=5e-3, atol=5e-3)

    def test_fallback_leaves_tp_sharded(self, eight_devices):
        """Leaves sharded over a non-data axis cannot join a flat bucket
        and ride the per-leaf fp32 dcn fallback; training still tracks
        the implicit path."""
        from jax.sharding import PartitionSpec as P

        specs = {"w1": P(None, "model"), "w2": P("model", None)}
        mesh = build_mesh(slices=2, model=2)
        off = build(mesh, comm={"hierarchical": "off"},
                    param_partition_specs=specs)
        on = build(mesh, comm={"hierarchical": "on", "dcn_quant_bits": 32},
                   param_partition_specs=specs)
        m = on.grad_sync_plan.modeled_bytes()
        assert m["fallback_elems"] == 16 * 64 + 64 * 8
        assert m["bucketed_elems"] == 0
        rng = np.random.default_rng(5)
        for b in [make_batches(rng, 2, 16) for _ in range(3)]:
            lo = float(off.train_batch(b))
            lh = float(on.train_batch(b))
            np.testing.assert_allclose(lo, lh, rtol=1e-5)

    def test_auto_engages_on_multislice_only(self, eight_devices):
        hier = build(build_mesh(slices=2), comm={"hierarchical": "auto"})
        flat = build(build_mesh(data=8), comm={"hierarchical": "auto"})
        assert hier.grad_sync_plan is not None
        assert flat.grad_sync_plan is None

    def test_hierarchical_on_rejects_onebit(self, eight_devices):
        from deepspeed_tpu.config.config import ConfigError

        with pytest.raises(ConfigError, match="1-bit"):
            build(build_mesh(slices=2), optimizer_type="OneBitAdam",
                  stage=0, extra={"freeze_step": 2},
                  comm={"hierarchical": "on"})

    def test_pipe_engine_grad_path(self, eight_devices):
        """The pipe engine's grad path through the strategy (stages == 1;
        staged pipelines are their own manual region and are rejected by
        resolve_hierarchical — asserted below)."""
        from deepspeed_tpu.config.config import (ConfigError,
                                                 DeepSpeedTPUConfig)
        from deepspeed_tpu.models.gpt import GPTConfig
        from deepspeed_tpu.parallel.pipe import (PipelineEngine,
                                                 gpt_pipe_model)

        cfg = GPTConfig(vocab_size=128, max_seq_len=32, hidden_size=32,
                        num_layers=2, num_heads=2, dropout_rate=0.0,
                        dtype=jnp.float32)
        rng = np.random.default_rng(0)
        batches = {"input_ids": rng.integers(0, 128, (2, 8, 16),
                                             dtype=np.int32)}

        def make(comm):
            d = {"train_micro_batch_size_per_gpu": 1,
                 "gradient_accumulation_steps": 2,
                 "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                 "zero_optimization": {"stage": 1}}
            if comm:
                d["comm"] = comm
            return PipelineEngine(gpt_pipe_model(cfg),
                                  DeepSpeedTPUConfig(d),
                                  mesh=build_mesh(slices=2, pipe=1))

        off = make(None)
        on = make({"hierarchical": "on", "dcn_quant_bits": 8,
                   "quant_block_size": 256})
        assert on.grad_sync_plan is not None
        for _ in range(3):
            lo = float(off.train_batch(batches))
            lh = float(on.train_batch(batches))
            assert np.isfinite(lh)
            np.testing.assert_allclose(lo, lh, rtol=2e-2)

        # stages > 1 + on: rejected with the nesting reason; auto: off.
        d2 = {"train_micro_batch_size_per_gpu": 1,
              "gradient_accumulation_steps": 2,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": 1},
              "comm": {"hierarchical": "on"}}
        with pytest.raises(ConfigError, match="pipeline"):
            PipelineEngine(gpt_pipe_model(cfg), DeepSpeedTPUConfig(d2),
                           mesh=build_mesh(data=2, slices=2, pipe=2))
        d2["comm"] = {"hierarchical": "auto"}
        auto = PipelineEngine(gpt_pipe_model(cfg), DeepSpeedTPUConfig(d2),
                              mesh=build_mesh(data=2, slices=2, pipe=2))
        assert auto.grad_sync_plan is None

    def test_offload_tier_grad_path(self, eight_devices):
        """The offload tier's device-side scan through the strategy: the
        host optimizer consumes grads whose DCN hop was quantized."""
        off = build(build_mesh(slices=2), stage=2,
                    config_extra={"zero_optimization": {
                        "stage": 2,
                        "offload_optimizer": {"device": "cpu"}}})
        on = build(build_mesh(slices=2), stage=2,
                   comm={"hierarchical": "on", "dcn_quant_bits": 8,
                         "quant_block_size": 256},
                   config_extra={"zero_optimization": {
                       "stage": 2,
                       "offload_optimizer": {"device": "cpu"}}})
        assert on.grad_sync_plan is not None
        rng = np.random.default_rng(7)
        for b in [make_batches(rng, 2, 16) for _ in range(3)]:
            lo = float(off.train_batch(b))
            lh = float(on.train_batch(b))
            assert np.isfinite(lh)
            np.testing.assert_allclose(lo, lh, rtol=2e-2, atol=2e-2)

    def test_forward_backward_step_loop_and_eval(self, eight_devices):
        """The hierarchical tier is fused-only: reference-style
        forward()/backward()/step() loops ride the stash-and-fuse shim
        (forward evaluates via eval_batch — this also pins the
        hierarchical eval_step), and the fused window matches a direct
        train_batch() trajectory."""
        rng = np.random.default_rng(8)
        flat = {"x": rng.standard_normal((32, 16)).astype(np.float32),
                "y": rng.standard_normal((32, 8)).astype(np.float32)}
        stacked = {k: v.reshape(2, 16, -1) for k, v in flat.items()}

        loop = build(build_mesh(slices=2),
                     comm={"hierarchical": "on", "dcn_quant_bits": 8,
                           "quant_block_size": 256})
        fused = build(build_mesh(slices=2),
                      comm={"hierarchical": "on", "dcn_quant_bits": 8,
                            "quant_block_size": 256})
        assert loop._micro_step is None      # fused-only configuration
        for _ in range(3):
            for i in range(2):               # gas micro-batches
                micro = {k: v[i] for k, v in stacked.items()}
                loss = loop.forward(micro)
                loop.backward(loss)
            loop.step()
            fused.train_batch({k: v.copy() for k, v in stacked.items()})
            np.testing.assert_allclose(float(loop._last_loss),
                                       float(fused._last_loss), rtol=1e-6)
        assert loop.global_steps == 3
        ev = float(loop.eval_batch({k: v[0] for k, v in stacked.items()}))
        assert np.isfinite(ev)

    def test_comm_metrics_emitted(self, eight_devices, tmp_path):
        """comm/bytes_dcn, comm/bytes_ici, comm/compression_ratio land in
        the telemetry registry each step — and with the (default)
        overlapped schedule, the overlap-aware attribution too:
        comm/exposed_frac discounted below 1 and the modeled hidden
        seconds (comm/overlap_hidden_sec)."""
        on = build(build_mesh(slices=2),
                   comm={"hierarchical": "on", "dcn_quant_bits": 8,
                         "quant_block_size": 256},
                   config_extra={"telemetry": {"enabled": True,
                                               "dir": str(tmp_path)}})
        assert on.grad_sync_plan.overlap     # auto default
        rng = np.random.default_rng(6)
        on.train_batch(make_batches(rng, 2, 16))
        from deepspeed_tpu.telemetry.registry import InMemorySink
        mem = on.telemetry.registry.add_sink(InMemorySink())
        on.train_batch(make_batches(rng, 2, 16))
        tags = {r["tag"] for r in mem.rows}
        assert {"comm/bytes_dcn", "comm/bytes_ici",
                "comm/compression_ratio", "comm/exposed_frac",
                "comm/overlap_hidden_sec"} <= tags


class TestOverlappedGradSync:
    """ISSUE 11: the overlapped schedule — per-bucket reduce-scatters
    emitted interleaved with backward ops (not all trailing), a
    double-buffered DCN accumulator with exactly one in-flight reduce,
    bucket-boundary vjp hooks on the model layer stacks, and the
    overlap-aware exposed-comm model."""

    INT8 = {"hierarchical": "on", "dcn_quant_bits": 8,
            "quant_block_size": 256}

    @staticmethod
    def _trace_txt(engine, batches):
        pb = engine.put_batch(batches, leading_gas_dim=True)
        return str(engine._train_step.trace(
            engine.state, pb, jnp.float32(1e-2)).jaxpr)

    @staticmethod
    def _runs(txt):
        """Collapse the jaxpr's dot_general / all_to_all positions into
        a run-length pattern like 'dadada' (d=compute, a=DCN wire)."""
        seq = sorted(
            [(m.start(), "a") for m in re.finditer(r"all_to_all", txt)]
            + [(m.start(), "d") for m in re.finditer(r"dot_general", txt)])
        return "".join(k for i, (_, k) in enumerate(seq)
                       if i == 0 or seq[i - 1][1] != k)

    def test_overlap_resolution(self, eight_devices):
        """auto (default) engages with the hierarchical sync; off pins
        the PR-4 boundary schedule; bad values raise at config parse."""
        from deepspeed_tpu.config.config import ConfigError

        auto = build(build_mesh(slices=2), comm=self.INT8)
        assert auto.grad_sync_plan.overlap
        off = build(build_mesh(slices=2),
                    comm=dict(self.INT8, overlap_grad_sync="off"))
        assert not off.grad_sync_plan.overlap
        with pytest.raises(ConfigError, match="overlap_grad_sync"):
            build(build_mesh(slices=2),
                  comm=dict(self.INT8, overlap_grad_sync="sometimes"))

    def test_dcn_reduces_interleaved_not_trailing(self, eight_devices):
        """gas=4: the traced program must alternate microstep compute
        and DCN collective clusters ('dadadada' — one reduce dispatched
        per microstep, overlappable with the next microstep's fwd/bwd),
        while the boundary schedule trails everything ('da'). This is
        the double-buffer structure: between consecutive microstep
        clusters there is exactly ONE dcn reduce in flight."""
        rng = np.random.default_rng(0)
        batches = make_batches(rng, 4, 16)
        extra = {"gradient_accumulation_steps": 4}

        on = build(build_mesh(slices=2), comm=self.INT8,
                   config_extra=dict(extra))
        txt_on = self._trace_txt(on, batches)
        assert self._runs(txt_on) == "da" * 4
        # trailing check, explicitly: backward/next-microstep compute
        # exists AFTER the first DCN collective
        first_a2a = txt_on.index("all_to_all")
        assert re.search(r"dot_general", txt_on[first_a2a:])

        off = build(build_mesh(slices=2),
                    comm=dict(self.INT8, overlap_grad_sync="off"),
                    config_extra=dict(extra))
        txt_off = self._trace_txt(off, batches)
        assert self._runs(txt_off) == "da"
        first_a2a = txt_off.index("all_to_all")
        assert not re.search(r"dot_general", txt_off[first_a2a:])

    def test_exactly_one_inflight_reduce(self, eight_devices):
        """The double-buffered accumulator dispatches the DCN stage once
        per microstep and never batches two microsteps' reduces: int8
        ships (codes, scales) per bucket, so the traced step carries
        exactly gas x num_buckets x 2 all_to_all collectives, in gas
        separate clusters."""
        gas = 4
        on = build(build_mesh(slices=2), comm=self.INT8,
                   config_extra={"gradient_accumulation_steps": gas})
        rng = np.random.default_rng(1)
        txt = self._trace_txt(on, make_batches(rng, gas, 16))
        n_a2a = len(re.findall(r"all_to_all", txt))
        assert n_a2a == gas * on.grad_sync_plan.num_buckets * 2, n_a2a
        assert self._runs(txt).count("a") == gas

    def test_bucket_hooks_interleave_in_backward(self, eight_devices):
        """GPT's bucket-boundary vjp markers: with overlap on, each
        layer group's ICI scatter (anchored by the marker's
        optimization_barrier) lands BETWEEN the layer backwards in the
        trace — backward matmuls exist after the first marker. Overlap
        off: zero markers, bit-for-bit the PR-4 hierarchical program."""
        from deepspeed_tpu.models import make_gpt

        def make_engine(comm):
            model, cfg = make_gpt("tiny", num_layers=2, dropout_rate=0.0,
                                  dtype=jnp.float32)
            rng = np.random.default_rng(0)
            ids = rng.integers(0, cfg.vocab_size, (8, 16), dtype=np.int32)
            params = model.init({"params": jax.random.PRNGKey(0),
                                 "dropout": jax.random.PRNGKey(1)},
                                {"input_ids": ids})["params"]
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=model, params=params, mesh=build_mesh(slices=2),
                config={"train_micro_batch_size_per_gpu": 1,
                        "gradient_accumulation_steps": 2,
                        "optimizer": {"type": "Adam",
                                      "params": {"lr": 1e-3}},
                        "zero_optimization": {"stage": 2},
                        "comm": comm})
            return engine, cfg

        on, cfg = make_engine(self.INT8)
        rng = np.random.default_rng(3)
        batch = {"input_ids": rng.integers(0, cfg.vocab_size, (2, 16, 16),
                                           dtype=np.int32)}
        pb = on.put_batch(batch, leading_gas_dim=True)
        txt = str(on._train_step.trace(
            on.state, pb, jnp.float32(1e-2)).jaxpr)
        bars = [m.start() for m in re.finditer(r"optimization_barrier",
                                               txt)]
        # one marker per (bucketed) layer group per microstep
        assert len(bars) == 2 * 2, len(bars)
        dots_after = sum(1 for m in re.finditer(r"dot_general", txt)
                         if m.start() > bars[0])
        assert dots_after > 0, "marker scatters trail the whole backward"

        off, _ = make_engine(dict(self.INT8, overlap_grad_sync="off"))
        txt_off = str(off._train_step.trace(
            off.state, pb, jnp.float32(1e-2)).jaxpr)
        assert "optimization_barrier" not in txt_off

    def test_leaf_granular_reverse_buckets(self, eight_devices):
        """Overlap buckets are leaf-granular (no straddling — each
        bucket's scatter depends only on its own leaves) and packed in
        reverse traversal order (backward readiness order)."""
        on = build(build_mesh(slices=2), comm=self.INT8)
        plan = on.grad_sync_plan
        assert plan.overlap
        seen = [i for b in plan.bucket_leaf_idx for i in b]
        assert sorted(seen) == sorted(plan.bucketed_idx)
        assert len(seen) == len(set(seen))
        assert seen == sorted(seen, reverse=True)     # readiness order
        align = plan.data_size * plan.dcn_size * plan.block
        assert all(e % align == 0 for e in plan.bucket_padded)

    def test_overlap_matches_boundary_schedule_fp32(self, eight_devices):
        """fp32 passthrough, overlap on vs off: same sums in a different
        dispatch order — the established reduction-ordering bound
        (~1 ulp/step) must hold across the schedule change too."""
        rng = np.random.default_rng(7)
        batches = [make_batches(rng, 2, 16) for _ in range(5)]
        off = build(build_mesh(slices=2),
                    comm={"hierarchical": "on", "dcn_quant_bits": 32,
                          "overlap_grad_sync": "off"})
        on = build(build_mesh(slices=2),
                   comm={"hierarchical": "on", "dcn_quant_bits": 32,
                         "overlap_grad_sync": "on"})
        for b in batches:
            lo = float(off.train_batch(b))
            lh = float(on.train_batch(b))
            np.testing.assert_allclose(lo, lh, rtol=1e-6, atol=1e-7)

    def test_modeled_exposed_discounts_overlap(self, eight_devices):
        """The overlap-aware exposed model: floor < total wire seconds,
        budget-capped hiding, and the boundary schedule still reports
        everything exposed — so the PR-9 modeled-vs-measured divergence
        warning can't fire spuriously once overlap lands."""
        on = build(build_mesh(slices=2), comm=self.INT8)
        plan = on.grad_sync_plan
        wire = plan.modeled_wire_seconds()
        floor = plan.modeled_exposed_seconds()
        assert 0 < floor < wire
        # unlimited compute budget hides everything above the floor
        assert plan.modeled_exposed_seconds(1e9) == pytest.approx(floor)
        # no compute to hide behind -> everything exposed
        assert plan.modeled_exposed_seconds(0.0) == pytest.approx(wire)
        off = build(build_mesh(slices=2),
                    comm=dict(self.INT8, overlap_grad_sync="off"))
        off_plan = off.grad_sync_plan
        assert off_plan.modeled_exposed_seconds() == pytest.approx(
            off_plan.modeled_wire_seconds())
        # overlap's per-microstep DCN reduces cost gas x the wire bytes
        # on the same tier (the hiding trade, modeled honestly)...
        assert (plan.modeled_bytes()["bytes_dcn"]
                == 2 * off_plan.modeled_bytes()["bytes_dcn"])
        # ...while the compression ratio stays schedule-invariant.
        assert (plan.modeled_bytes()["compression_ratio"]
                == pytest.approx(
                    off_plan.modeled_bytes()["compression_ratio"]))

    def test_probe_comm_overlap_ab_cli(self):
        """The overlap A/B tooling (ISSUE 11 satellite): off-vs-on on the
        2-slice mesh, step time + capture-parsed exposure reported, the
        burstiness gate green — in tier-1 via the CLI it ships as."""
        repo = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)   # the tool forces its own 8-device flag
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "probe_comm.py"),
             "--overlap-ab", "--steps", "2"],
            capture_output=True, text=True, env=env, timeout=540)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert '"pass": true' in proc.stdout
        assert "dcn_burstiness" in proc.stdout
        assert "measured_exposed_frac" in proc.stdout

    def test_boundary_marker_inert_without_hook(self):
        """comm.overlap.grad_sync_boundary with no hook installed is the
        identity — the exact object, zero trace footprint — so every
        non-overlap path (inference, serving, hierarchical off) lowers
        bit-identically to a model without markers."""
        from deepspeed_tpu.comm import overlap as ov

        tree = {"w": jnp.ones((3,))}
        assert ov.grad_sync_boundary(tree, "h_0") is tree
        with ov.install_ici_hook(None):
            assert ov.grad_sync_boundary(tree, "h_0") is tree
