"""DCN-aware hierarchical mesh (round-2 VERDICT task 5).

A virtual "two-slice" 2x4 mesh: the outer ``dcn`` axis stands for slow
inter-slice links, the inner ``data`` axis for ICI. Assertions:

- training over dcn x data is numerically the same as over flat data
  (grad averaging spans both axes);
- ZeRO sharding stays on the ICI-inner ``data`` axis;
- OneBitAdam compresses over ``dcn`` only — the jaxpr shows the 1-bit
  ``all_to_all`` on the dcn axis and a dense psum on the data axis.

Reference positioning: runtime/comm/nccl.py:47 (1-bit over Ethernet
clusters), SURVEY §2.5 TPU-native row.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import (DATA_AXIS, DCN_AXIS, build_mesh)


def mlp_loss_fn(params, batch, rng):
    h = jnp.tanh(batch["x"] @ params["w1"])
    pred = h @ params["w2"]
    return jnp.mean((pred - batch["y"]) ** 2)


def mlp_params():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    return {"w1": jax.random.normal(k1, (16, 64)) * 0.1,
            "w2": jax.random.normal(k2, (64, 8)) * 0.1}


def make_batches(rng, gas, bs):
    return {"x": rng.standard_normal((gas, bs, 16)).astype(np.float32),
            "y": rng.standard_normal((gas, bs, 8)).astype(np.float32)}


def build(mesh, optimizer_type="Adam", stage=2, extra=None):
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": optimizer_type,
                      "params": dict({"lr": 1e-2}, **(extra or {}))},
        "zero_optimization": {"stage": stage},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(), mesh=mesh, config=config)
    return engine


class TestHierarchicalMesh:
    def test_build_mesh_slices(self, eight_devices):
        mesh = build_mesh(slices=2)
        assert mesh.shape[DCN_AXIS] == 2
        assert mesh.shape[DATA_AXIS] == 4

    def test_training_parity_vs_flat(self, eight_devices):
        """Same data, same init: dcn2 x data4 must track flat data8."""
        rng = np.random.default_rng(0)
        batches = [make_batches(rng, 2, 16) for _ in range(5)]

        flat = build(build_mesh(data=8))
        hier = build(build_mesh(slices=2))
        assert hier.dp_size == 8
        for b in batches:
            lf = float(flat.train_batch(b))
            lh = float(hier.train_batch(b))
            np.testing.assert_allclose(lf, lh, rtol=1e-5)

    def test_zero_shards_stay_ici_inner(self, eight_devices):
        """Optimizer-state shards split over `data` (4-way), NOT over the
        8-way dcn x data product — ZeRO collectives ride ICI."""
        hier = build(build_mesh(slices=2), stage=2)
        m = hier.state.opt_state.exp_avg["w1"]
        shard_elems = int(np.prod(m.sharding.shard_shape(m.shape)))
        assert shard_elems == 16 * 64 // 4, shard_elems

    def test_onebit_compresses_over_dcn(self, eight_devices):
        """OneBitAdam on a hierarchical mesh: compression axis defaults to
        dcn; the jaxpr carries the 1-bit all_to_all over ('dcn',) and a
        dense psum over ('data',)."""
        hier = build(build_mesh(slices=2), optimizer_type="OneBitAdam",
                     stage=0, extra={"freeze_step": 2})
        assert hier.optimizer.axis == DCN_AXIS
        assert hier.optimizer.n == 2      # compresses across 2 slices

        rng = np.random.default_rng(1)
        batches = make_batches(rng, 2, 16)
        placed = hier.put_batch(batches, leading_gas_dim=True)
        traced = hier._train_step.trace(
            hier.state, placed, jnp.float32(1e-2))
        import re

        txt = str(traced.jaxpr)
        a2a = re.findall(r"all_to_all\[(.*?)\]", txt, re.S)
        assert a2a, "no all_to_all in jaxpr (1-bit path missing)"
        assert all("dcn" in blk for blk in a2a), a2a[0][:200]
        assert not any("'data'" in blk for blk in a2a), a2a[0][:200]
        dense = [blk for blk in re.findall(r"psum2?\[(.*?)\]", txt, re.S)
                 if "'data'" in blk and "dcn" not in blk]
        assert dense, "no dense data-axis reduction found"

        losses = [float(hier.train_batch(batches)) for _ in range(4)]
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0]

    def test_onebit_parity_flat_vs_hier_warmup(self, eight_devices):
        """During warmup (dense phase) the hierarchical 1-bit step must
        match the flat one exactly — pre-reduce over data + pmean over dcn
        is the same mean as pmean over 8 ranks."""
        rng = np.random.default_rng(2)
        batches = [make_batches(rng, 2, 16) for _ in range(3)]
        flat = build(build_mesh(data=8), optimizer_type="OneBitAdam",
                     stage=0, extra={"freeze_step": 100})
        hier = build(build_mesh(slices=2), optimizer_type="OneBitAdam",
                     stage=0, extra={"freeze_step": 100})
        for b in batches:
            lf = float(flat.train_batch(b))
            lh = float(hier.train_batch(b))
            np.testing.assert_allclose(lf, lh, rtol=2e-5)
