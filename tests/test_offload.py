"""ZeRO-Offload tier tests.

Evidence the VERDICT demanded: moments actually live on host (device
placement assertions), the offloaded step is numerically the same step as
the on-device path (loss-trajectory parity), and the NVMe tier round-trips
through the async swapper. Reference surface: ops/adam/cpu_adam.py,
runtime/swap_tensor/.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.swap_tensor import (AsyncTensorSwapper,
                                               PipelinedLeafSwapper)


def make_loss_fn():
    def loss_fn(params, batch, rng):
        x, y = batch["x"], batch["y"]
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        pred = h @ params["w2"] + params["b2"]
        return jnp.mean((pred - y) ** 2)
    return loss_fn


def make_params(key=0):
    k = jax.random.PRNGKey(key)
    k1, k2 = jax.random.split(k)
    return {
        "w1": jax.random.normal(k1, (16, 32)) * 0.1,
        "b1": jnp.zeros((32,)),
        "w2": jax.random.normal(k2, (32, 4)) * 0.1,
        "b2": jnp.zeros((4,)),
    }


def make_batches(rng, gas, bs, steps):
    out = []
    for _ in range(steps):
        x = rng.standard_normal((gas, bs, 16)).astype(np.float32)
        y = rng.standard_normal((gas, bs, 4)).astype(np.float32)
        out.append({"x": x, "y": y})
    return out


def build_engine(offload_device=None, nvme_path=None, zero_stage=2,
                 optimizer_type="Adam"):
    zero = {"stage": zero_stage}
    if offload_device:
        od = {"device": offload_device}
        if nvme_path:
            od["nvme_path"] = str(nvme_path)
        zero["offload_optimizer"] = od
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=make_loss_fn(), params=make_params(),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "gradient_clipping": 1.0,
            "optimizer": {"type": optimizer_type, "params": {"lr": 1e-2}},
            "zero_optimization": zero,
        })
    return engine


class TestCpuOffload:
    def test_moments_live_on_host(self, eight_devices):
        engine = build_engine("cpu")
        m_leaf = jax.tree_util.tree_leaves(engine.offloader.opt_state.exp_avg)[0]
        assert all(d.platform == "cpu" for d in m_leaf.devices())
        master_leaf = jax.tree_util.tree_leaves(engine.offloader.master)[0]
        assert all(d.platform == "cpu" for d in master_leaf.devices())
        # only ONE host device holds them (committed, not mesh-sharded)
        assert len(m_leaf.devices()) == 1

    def test_loss_parity_with_ondevice(self, eight_devices, rng):
        """10 steps offloaded == 10 steps on-device, same data/seed."""
        batches = make_batches(rng, gas=2, bs=16, steps=10)
        e_off = build_engine("cpu")
        e_dev = build_engine(None)
        losses_off = [float(e_off.train_batch(b)) for b in batches]
        losses_dev = [float(e_dev.train_batch(b)) for b in batches]
        np.testing.assert_allclose(losses_off, losses_dev, rtol=2e-4,
                                   atol=2e-5)
        # parameters end up in the same place
        p_off = jax.tree_util.tree_map(np.asarray, e_off.offloader.master)
        p_dev = jax.tree_util.tree_map(np.asarray, e_dev.state.params)
        for a, b in zip(jax.tree_util.tree_leaves(p_off),
                        jax.tree_util.tree_leaves(p_dev)):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)

    def test_loss_decreases(self, eight_devices, rng):
        engine = build_engine("cpu")
        batch = make_batches(rng, 2, 16, 1)[0]
        first = float(engine.train_batch(batch))
        for _ in range(15):
            last = float(engine.train_batch(batch))
        assert last < first

    def test_cpu_adam_type_implies_offload(self, eight_devices):
        engine = build_engine(None, optimizer_type="CPUAdam")
        assert hasattr(engine, "offloader")
        assert engine.offloader.tier == "cpu"

    def test_forward_loop_works(self, eight_devices, rng):
        """Reference-style forward/backward/step loop on the offload tier
        (round-3 VERDICT weak #5: previously train_batch()-only): stashed
        micro-batches run as one fused window at step(), same trajectory
        as train_batch()."""
        e_loop = build_engine("cpu")
        e_tb = build_engine("cpu")
        gas = e_loop.gradient_accumulation_steps
        batches = make_batches(rng, gas, 16, 3)
        for b in batches:
            for m in range(gas):
                one = {k: v[m] for k, v in b.items()}
                loss = e_loop.forward(one)
                e_loop.backward(loss)
            e_loop.step()
            e_tb.train_batch(b)
        assert e_loop.global_steps == e_tb.global_steps == 3
        for a, c in zip(jax.tree_util.tree_leaves(e_loop.module_params),
                        jax.tree_util.tree_leaves(e_tb.module_params)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(c, np.float32),
                                       rtol=1e-5, atol=1e-6)

    def test_checkpoint_roundtrip(self, eight_devices, rng, tmp_path):
        engine = build_engine("cpu")
        batches = make_batches(rng, 2, 16, 3)
        for b in batches:
            engine.train_batch(b)
        engine.save_checkpoint(str(tmp_path))
        fresh = build_engine("cpu")
        fresh.load_checkpoint(str(tmp_path))
        a = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, engine.offloader.master))
        b = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, fresh.offloader.master))
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        # training continues from the restored tier
        l1 = float(engine.train_batch(batches[0]))
        l2 = float(fresh.train_batch(batches[0]))
        np.testing.assert_allclose(l1, l2, rtol=1e-5)

    def test_stage3_rejected(self, eight_devices):
        with pytest.raises(ValueError, match="stage 3"):
            build_engine("cpu", zero_stage=3)


class TestNvmeOffload:
    def test_loss_parity_with_ondevice(self, eight_devices, rng, tmp_path):
        batches = make_batches(rng, 2, 16, 6)
        e_nvme = build_engine("nvme", nvme_path=tmp_path / "swap")
        e_dev = build_engine(None)
        l_n = [float(e_nvme.train_batch(b)) for b in batches]
        l_d = [float(e_dev.train_batch(b)) for b in batches]
        np.testing.assert_allclose(l_n, l_d, rtol=2e-4, atol=2e-5)
        # swap files exist and carry real traffic
        assert e_nvme.offloader.swapper.bytes_written > 0
        assert e_nvme.offloader.swapper.bytes_read > 0
        e_nvme.offloader.close()

    def test_master_tree_readback(self, eight_devices, rng, tmp_path):
        e = build_engine("nvme", nvme_path=tmp_path / "swap")
        batch = make_batches(rng, 2, 16, 1)[0]
        e.train_batch(batch)
        tree = e.offloader.master_tree()
        assert set(tree) == {"w1", "b1", "w2", "b2"}
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree_util.tree_leaves(tree))
        e.offloader.close()

    def test_nvme_requires_path(self, eight_devices):
        with pytest.raises(ValueError, match="nvme_path"):
            build_engine("nvme")

    def test_checkpoint_supported(self, eight_devices, rng, tmp_path):
        # Round-2 closed the NotImplementedError gap: nvme-tier engines
        # checkpoint by swapping the tier back in (full round-trip in
        # TestNvmeCheckpointing).
        e = build_engine("nvme", nvme_path=tmp_path / "swap")
        path = e.save_checkpoint(str(tmp_path / "ck"))
        assert path is not None
        e.offloader.close()


class TestSwapper:
    def test_roundtrip(self, tmp_path):
        sw = AsyncTensorSwapper(str(tmp_path))
        a = np.random.default_rng(0).standard_normal((64, 64)).astype(np.float32)
        sw.swap_out("layer/w", a).result()
        b = sw.swap_in("layer/w").result().copy()
        np.testing.assert_array_equal(a, b)
        assert sw.bytes_written == a.nbytes
        sw.close(remove_files=True)

    def test_unknown_name_raises(self, tmp_path):
        sw = AsyncTensorSwapper(str(tmp_path))
        with pytest.raises(KeyError):
            sw.swap_in("nope")
        sw.close()

    def test_pipelined_stream_updates_all(self, tmp_path):
        sw = AsyncTensorSwapper(str(tmp_path))
        names = [f"t{i}" for i in range(5)]
        for i, n in enumerate(names):
            sw.swap_out(n, np.full((8,), float(i), np.float32)).result()
        pipe = PipelinedLeafSwapper(sw)
        pipe.stream(names, lambda name, arr: arr + 1.0)
        for i, n in enumerate(names):
            got = sw.swap_in(n).result()
            np.testing.assert_array_equal(got, np.full((8,), i + 1.0,
                                                       np.float32))
        sw.close(remove_files=True)

    def test_fp16_loss_scaling_with_offload(self, eight_devices, rng):
        """Dynamic loss scaling drives the host tier's skip path."""
        engine, _, _, _ = deepspeed_tpu.initialize(
            loss_fn=make_loss_fn(), params=make_params(),
            config={
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "fp16": {"enabled": True, "initial_scale_power": 4},
                "zero_optimization": {"stage": 2,
                                      "offload_optimizer": {"device": "cpu"}},
            })
        batches = make_batches(rng, 2, 16, 5)
        losses = [float(engine.train_batch(b)) for b in batches]
        assert all(np.isfinite(l) for l in losses)
        assert int(engine.state.step) >= 1


class TestReviewRegressions:
    def test_nvme_rejects_non_adam_state(self, eight_devices, tmp_path):
        with pytest.raises(ValueError, match="nvme offload"):
            build_engine("nvme", nvme_path=tmp_path / "s",
                         optimizer_type="SGD")

    def test_grad_norm_reported_under_offload(self, eight_devices, rng):
        engine = build_engine("cpu")
        engine.train_batch(make_batches(rng, 2, 16, 1)[0])
        assert engine.get_global_grad_norm() > 0.0

    def test_shared_config_not_mutated(self, eight_devices):
        from deepspeed_tpu.config.config import DeepSpeedTPUConfig

        cfg = DeepSpeedTPUConfig({
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "CPUAdam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2},
        })
        e1 = deepspeed_tpu.TPUEngine(loss_fn=make_loss_fn(),
                                     params=make_params(), config=cfg)
        assert hasattr(e1, "offloader")
        assert not cfg.zero_config.offload_optimizer.enabled
        # a second engine with an explicit non-host optimizer from the SAME
        # config object must not inherit the offload tier
        from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
        e2 = deepspeed_tpu.TPUEngine(loss_fn=make_loss_fn(),
                                     params=make_params(), config=cfg,
                                     optimizer=FusedAdam(lr=1e-2))
        assert not hasattr(e2, "offloader")

    def test_user_params_survive_offload_training(self, eight_devices, rng):
        """Regression: the host tier must copy, not alias, the caller's
        params — the donating host step was deleting them."""
        params = make_params()
        engine, _, _, _ = deepspeed_tpu.initialize(
            loss_fn=make_loss_fn(), params=params,
            config={"train_micro_batch_size_per_gpu": 2,
                    "gradient_accumulation_steps": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                    "zero_optimization": {"stage": 2,
                                          "offload_optimizer":
                                          {"device": "cpu"}}})
        engine.train_batch(make_batches(rng, 2, 16, 1)[0])
        for leaf in jax.tree_util.tree_leaves(params):
            np.asarray(leaf)  # raises RuntimeError if deleted


class TestNativeAio:
    def test_native_module_roundtrip(self, tmp_path):
        from deepspeed_tpu.ops.aio_native import load_aio

        m = load_aio()
        if m is None:
            pytest.skip("no C++ toolchain")
        a = np.random.default_rng(0).standard_normal(4096).astype(np.float32)
        p = str(tmp_path / "t.bin")
        assert m.write_buffer(p, a.view(np.uint8)) == a.nbytes
        out = np.empty_like(a)
        assert m.read_buffer(p, out.view(np.uint8)) == a.nbytes
        np.testing.assert_array_equal(out, a)

    def test_swapper_uses_native_when_available(self, tmp_path):
        sw = AsyncTensorSwapper(str(tmp_path))
        a = np.arange(1000, dtype=np.float32).reshape(10, 100)
        sw.swap_out("x", a).result()
        np.testing.assert_array_equal(sw.swap_in("x").result(), a)
        sw.close(remove_files=True)
        # the per-swapper binding reflects build availability (lazy load)
        from deepspeed_tpu.ops.aio_native import load_aio
        assert (sw._native is None) == (load_aio() is None)


class TestNvmeCheckpointing:
    """NVMe-tier checkpointing (round-2 VERDICT task 9): the swapped
    (master, moments) state round-trips through save -> restart -> resume.
    Reference: stage3.py:3250 save_checkpoint_prologue."""

    def test_save_restart_resume(self, eight_devices, tmp_path):
        rng = np.random.default_rng(0)
        batches = make_batches(rng, 2, 16, 6)
        e1 = build_engine("nvme", nvme_path=tmp_path / "swap1")
        for b in batches[:3]:
            e1.train_batch(b)
        path = e1.save_checkpoint(str(tmp_path / "ckpt"), tag="t3")
        ref_losses = [float(e1.train_batch(b)) for b in batches[3:]]
        master_after_3 = None  # e1 has advanced; use the checkpoint

        e2 = build_engine("nvme", nvme_path=tmp_path / "swap2")
        p, client = e2.load_checkpoint(str(tmp_path / "ckpt"), tag="t3")
        assert p is not None
        # step counter restored into the leaf-streaming tier (3 steps had
        # run at save time)
        assert e2.offloader._step_count == 3
        # restored TrainState scalars must survive the placeholder revert
        # (review finding: the finally clause must not clobber them)
        assert int(e2.state.step) == 3
        assert int(e2.state.micro_step) == 6
        # resumed trajectory matches the original run exactly
        res_losses = [float(e2.train_batch(b)) for b in batches[3:]]
        np.testing.assert_allclose(res_losses, ref_losses, rtol=1e-5)
        e1.offloader.close()
        e2.offloader.close()

    def test_load_without_optimizer_states(self, eight_devices, tmp_path):
        rng = np.random.default_rng(1)
        batches = make_batches(rng, 2, 16, 3)
        e1 = build_engine("nvme", nvme_path=tmp_path / "swapA")
        for b in batches:
            e1.train_batch(b)
        e1.save_checkpoint(str(tmp_path / "ckptA"), tag="t")

        e2 = build_engine("nvme", nvme_path=tmp_path / "swapB")
        e2.load_checkpoint(str(tmp_path / "ckptA"), tag="t",
                           load_optimizer_states=False)
        # master restored...
        m1 = e1.offloader.export_state()[0]
        m2 = e2.offloader.export_state()[0]
        np.testing.assert_allclose(np.asarray(m1["w1"]),
                                   np.asarray(m2["w1"]), rtol=1e-6)
        # ...but moments kept fresh (zeros)
        opt2 = e2.offloader.export_state()[1]
        assert float(np.abs(np.asarray(opt2.exp_avg["w1"])).max()) == 0.0
        l = float(e2.train_batch(batches[0]))
        assert np.isfinite(l)
        e1.offloader.close()
        e2.offloader.close()
