"""ZeRO-Infinity ``offload_param`` tier tests.

Evidence the round-2 VERDICT demanded (task 1): a model whose fp32
master+param tree exceeds the per-device HBM share trains with
``offload_param: {device: cpu}``; a ``memory_analysis()`` test shows
device-resident param bytes ≈ working set (one block), not the total; and
the streamed loss is numerically the plain loss (grad parity).

Reference surface: ``swap_tensor/partitioned_param_swapper.py:36``,
``stage3.py:1084-1247``, ``partition_parameters.py:663``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import make_gpt
from deepspeed_tpu.models.adapter import flax_module_loss_fn
from deepspeed_tpu.parallel.pipe.module import gpt_pipe_model
from deepspeed_tpu.runtime.zero import param_offload as po


GPT_CFG = dict(vocab_size=512, max_seq_len=64, hidden_size=64,
               num_layers=4, num_heads=4, dropout_rate=0.0)


def gpt_batch(rng, gas, bs_per_dev, seq, vocab, dp=8):
    ids = rng.integers(0, vocab, (gas, bs_per_dev * dp, seq), dtype=np.int32)
    return {"input_ids": ids}


def build_engine(rng, extra_zero=None, gas=2, bs=2, model_kw=None):
    model, cfg = make_gpt("tiny", **{**GPT_CFG, **(model_kw or {})})
    zero = {"stage": 3, "offload_param": {"device": "cpu"},
            "offload_optimizer": {"device": "cpu"}}
    zero.update(extra_zero or {})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": bs,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": zero,
        })
    return engine, cfg


class TestStreamedLossParity:
    def test_streamed_grads_match_plain(self, eight_devices):
        """The fetch/remat/scan streamed loss must be numerically the plain
        flax forward: same loss, same grads (wte and a block leaf). fp32 so
        parity is tight (bf16 scan-vs-unrolled fusion differences would
        otherwise add rounding noise)."""
        model, cfg = make_gpt("tiny", **GPT_CFG, dtype=jnp.float32)
        rng = np.random.default_rng(0)
        batch = {"input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (2, 32), dtype=np.int32))}
        plain_loss, flat = flax_module_loss_fn(model, example_batch=batch)
        pm = gpt_pipe_model(cfg, params=flat)
        streamed, packed = po.build_streamed_loss(pm)
        mesh = deepspeed_tpu.build_mesh(data=8)
        specs = po.host_storage_specs(packed, 8)
        host_params = po.place_host(packed, mesh, specs)

        l0, g0 = jax.jit(jax.value_and_grad(
            lambda p: plain_loss(p, batch, None)[0]))(flat)
        l1, g1 = jax.jit(jax.value_and_grad(
            lambda p: streamed(p, batch, None)))(host_params)

        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(g0["wte"]),
                                   np.asarray(g1["embed"]["wte"]), rtol=1e-4,
                                   atol=1e-6)
        _, meta = po.pack_blocks(pm.params["blocks"])
        g_blk1 = po.unpack_block(g1["blocks"][1], meta)
        np.testing.assert_allclose(
            np.asarray(g0["h_1"]["c_fc"]["kernel"]),
            np.asarray(g_blk1["c_fc"]["kernel"]), rtol=1e-4,
            atol=1e-6)

    def test_layer_idx_threads_through_scan(self, eight_devices):
        """The streamed scan must hand block_fn the GLOBAL layer index
        (per-layer schedules — PLD — are inert at idx=0: keep-prob 1.0).
        With pld_theta=0 every layer l>0 has keep-prob 1-l/L, so the loss
        must differ from the no-PLD run; if the index were stuck at 0 the
        two would be bit-identical."""
        model, cfg = make_gpt("tiny", **GPT_CFG, dtype=jnp.float32)
        pm = gpt_pipe_model(cfg)
        assert pm.block_takes_layer_idx
        streamed, packed = po.build_streamed_loss(pm)
        rng = np.random.default_rng(0)
        batch = {"input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (2, 32), dtype=np.int32))}
        key = jax.random.PRNGKey(7)
        base = float(jax.jit(streamed)(packed, batch, key))
        pld = float(jax.jit(streamed)(
            packed, {**batch, "pld_theta": jnp.float32(0.0)}, key))
        assert np.isfinite(pld)
        assert abs(pld - base) > 1e-6, (base, pld)

    def test_dropout_rng_threads_per_layer(self, eight_devices):
        """With dropout on, the streamed loss must still run (per-layer rng
        split inside the scan) and give a finite loss."""
        model, cfg = make_gpt("tiny", **{**GPT_CFG, "dropout_rate": 0.1})
        pm = gpt_pipe_model(cfg)
        streamed, packed = po.build_streamed_loss(pm)
        batch = {"input_ids": jnp.zeros((2, 32), jnp.int32)}
        loss = jax.jit(streamed)(packed, batch, jax.random.PRNGKey(0))
        assert np.isfinite(float(loss))


class TestParamOffloadTraining:
    def test_trains_to_lower_loss(self, eight_devices):
        rng = np.random.default_rng(0)
        engine, cfg = build_engine(rng)
        # Params must live in pinned host memory, ZeRO-3-partitioned.
        wte = engine._compute_params["embed"]["wte"]
        assert wte.sharding.memory_kind == po.HOST_MEMORY_KIND
        losses = []
        batches = gpt_batch(rng, 2, 2, 32, cfg.vocab_size)
        for _ in range(8):
            losses.append(float(engine.train_batch(batches)))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]

    def test_rejects_stage2(self, eight_devices):
        model, _ = make_gpt("tiny", **GPT_CFG)
        with pytest.raises(Exception, match="stage 3"):
            deepspeed_tpu.initialize(
                model=model,
                config={
                    "train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {
                        "stage": 2, "offload_param": {"device": "cpu"}},
                })

    def test_rejects_opaque_loss_fn(self, eight_devices):
        def loss_fn(p, b, r):
            return jnp.mean(p["w"] ** 2)

        engine_kwargs = dict(
            loss_fn=loss_fn, params={"w": jnp.ones((8, 8))},
            config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "stage": 3, "offload_param": {"device": "cpu"}},
            })
        # A raw loss_fn cannot be streamed; initialize() builds the engine
        # anyway (the user claims their loss_fn fetches), but a plain module
        # without block structure must be rejected.
        with pytest.raises(ValueError, match="block-structured"):
            deepspeed_tpu.initialize(
                model=object(), config=engine_kwargs["config"])

    def test_checkpoint_roundtrip(self, eight_devices, tmp_path):
        rng = np.random.default_rng(0)
        engine, cfg = build_engine(rng)
        batches = gpt_batch(rng, 2, 2, 32, cfg.vocab_size)
        for _ in range(3):
            engine.train_batch(batches)
        engine.save_checkpoint(str(tmp_path), tag="t3")

        engine2, _ = build_engine(np.random.default_rng(1))
        engine2.load_checkpoint(str(tmp_path), tag="t3")
        w1 = np.asarray(engine._compute_params["embed"]["wte"])
        w2 = np.asarray(engine2._compute_params["embed"]["wte"])
        np.testing.assert_allclose(w1, w2)
        # and training continues
        l = float(engine2.train_batch(batches))
        assert np.isfinite(l)


class TestParamOffloadMemory:
    def test_device_param_bytes_are_working_set(self, eight_devices):
        """The compiled streamed step's device-argument bytes must exclude
        the (host-resident) params: arguments ≈ batch + rng, and temps stay
        far below the full param tree (only per-block fetches + the sharded
        grad accumulator live on device)."""
        rng = np.random.default_rng(0)
        # 8 layers so one block is clearly << the total.
        engine, cfg = build_engine(rng, model_kw={"num_layers": 8,
                                                  "hidden_size": 128})
        batches = engine.put_batch(
            gpt_batch(rng, 2, 2, 32, cfg.vocab_size), leading_gas_dim=True)
        lowered = engine._offload_micro_scan.lower(
            engine._compute_params, engine.state.rng, batches,
            jnp.float32(1.0))
        stats = lowered.compile().memory_analysis()

        n_params = sum(int(np.prod(x.shape))
                       for x in jax.tree_util.tree_leaves(
                           engine._compute_params))
        param_bytes_bf16 = 2 * n_params
        # fp32 grad accumulator is data-sharded (1/8 per device); block
        # params are fetched transiently. Device temps must stay below
        # params + grads as if resident (the non-offload floor).
        resident_floor = param_bytes_bf16 + 4 * n_params
        assert stats.temp_size_in_bytes < resident_floor, (
            f"temps {stats.temp_size_in_bytes} vs floor {resident_floor}")

    def test_host_placement_of_master_and_moments(self, eight_devices):
        rng = np.random.default_rng(0)
        engine, _ = build_engine(rng)
        cpu = jax.local_devices(backend="cpu")[0]
        master_leaf = jax.tree_util.tree_leaves(engine.offloader.master)[0]
        assert list(master_leaf.devices()) == [cpu]
        opt_leaf = jax.tree_util.tree_leaves(engine.offloader.opt_state)[0]
        assert list(opt_leaf.devices()) == [cpu]


class TestTPComposition:
    """offload_param x tensor parallelism (round-3 VERDICT task 6,
    reference ZeRO-Infinity composes with MP via stage3.py:590's mpu):
    shard-aligned packing stores each device's TP shard host-side, the
    streamed fetch moves 1/(dp*tp) of each block, and numerics match the
    replicated-fetch (tp=1) run."""

    def _build(self, tp, rng, gas=2, bs=4):
        from deepspeed_tpu.parallel.mesh import build_mesh

        model, cfg = make_gpt("tiny", **GPT_CFG)
        dp = 8 // tp
        mesh = build_mesh(data=dp, model=tp)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, mesh=mesh,
            config={
                "train_micro_batch_size_per_gpu": bs * 2 // dp if dp else bs,
                "gradient_accumulation_steps": gas,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "stage": 3,
                    "offload_param": {"device": "cpu"},
                    "offload_optimizer": {"device": "cpu"}},
            })
        return engine, cfg

    def test_host_shard_bytes_divide_by_tp(self, eight_devices):
        rng = np.random.default_rng(0)
        e_tp, cfg = self._build(2, rng)
        blocks = e_tp._compute_params["blocks"]
        assert isinstance(blocks, dict) and blocks["tp"] is not None
        arr = blocks["tp"]
        assert arr.sharding.memory_kind == po.HOST_MEMORY_KIND
        # per-device shard = 1/(dp*tp) of the packed buffer
        shard = arr.sharding.shard_shape(arr.shape)
        total = int(np.prod(arr.shape))
        per_dev = int(np.prod(shard))
        assert per_dev * 8 == total, (shard, arr.shape)
        # the model axis actually shards dim 1 (the tp dim)
        assert shard[1] == arr.shape[1] // 2

    def test_matches_tp1_numerics(self, eight_devices):
        rng = np.random.default_rng(1)
        e_tp, cfg = self._build(2, rng)
        e_1, _ = self._build(1, rng)
        batches = gpt_batch(rng, 2, 1, 32, cfg.vocab_size)
        l_tp = [float(e_tp.train_batch(batches)) for _ in range(4)]
        l_1 = [float(e_1.train_batch(batches)) for _ in range(4)]
        np.testing.assert_allclose(l_tp, l_1, rtol=2e-4, atol=2e-4)

    def test_pack_unpack_tp_roundtrip(self, eight_devices):
        from jax.sharding import PartitionSpec as P

        from deepspeed_tpu.parallel.mesh import build_mesh

        mesh = build_mesh(data=4, model=2)
        rng = np.random.default_rng(2)
        blocks = {
            "w_col": jnp.asarray(rng.standard_normal((3, 8, 16)),
                                 jnp.float32),   # sharded on dim 1
            "w_row": jnp.asarray(rng.standard_normal((3, 16, 8)),
                                 jnp.float32),   # sharded on dim 0
            "bias": jnp.asarray(rng.standard_normal((3, 8)), jnp.float32),
        }
        specs = {"w_col": P(None, "model"), "w_row": P("model", None),
                 "bias": P()}
        packed, meta = po.pack_blocks_tp(blocks, specs, mesh, data_size=4)
        assert packed["tp"].shape[1] == 2
        for i in range(3):
            row = jax.tree_util.tree_map(lambda a: a[i], packed)
            blk = jax.jit(lambda r: po.unpack_block_tp(r, meta, mesh))(row)
            for kname in blocks:
                np.testing.assert_array_equal(
                    np.asarray(blk[kname]), np.asarray(blocks[kname][i]),
                    err_msg=kname)
