"""Integration tier — loss-parity trajectories, elastic resume, launcher.

The reference's model-level tests train Megatron GPT-2 for hundreds of
steps and grep the loss curve (tests/model/Megatron_GPT2/run_func_test.py:
20-36); its checkpoint tests resume across world resizes. The TPU-native
equivalents run on the virtual 8-device mesh:

- ZeRO-n must reproduce plain-DP loss trajectories step for step (the whole
  point of "sharding is a placement policy, not different math");
- checkpoint → resize dp 8→4 → resume must continue the same trajectory
  (orbax resharding ≡ elastic_checkpoint);
- runner → launch.py → jax.distributed must rendezvous two real processes.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import make_gpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def gpt_data():
    model, cfg = make_gpt("tiny", dropout_rate=0.0, num_layers=2,
                          max_seq_len=32)
    rng = np.random.default_rng(7)
    steps = 200
    data = rng.integers(0, cfg.vocab_size, (steps, 1, 8, 32)).astype(np.int32)
    params = model.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        {"input_ids": data[0, 0]})["params"]
    return model, cfg, params, data


def run_trajectory(model, params, data, stage, steps, mesh=None):
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, params=params, mesh=mesh,
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "stage": stage,
                    "stage3_param_persistence_threshold": 1024}})
    losses = []
    for t in range(steps):
        losses.append(float(engine.train_batch({"input_ids": data[t]})))
    return engine, np.asarray(losses)


class TestLossParity:
    def test_zero_stages_match_dp_over_200_steps(self, gpt_data,
                                                 eight_devices):
        """ZeRO 1/2/3 trajectories == stage-0 DP trajectory, 200 steps."""
        model, cfg, params, data = gpt_data
        _, base = run_trajectory(model, params, data, stage=0, steps=200)
        assert base[-20:].mean() < base[:20].mean(), "tiny GPT must learn"
        for stage in (1, 2, 3):
            _, traj = run_trajectory(model, params, data, stage=stage,
                                     steps=200)
            np.testing.assert_allclose(
                traj, base, rtol=2e-3, atol=2e-3,
                err_msg=f"ZeRO-{stage} diverged from DP")

    def test_offload_matches_dp_over_50_steps(self, gpt_data, eight_devices):
        model, cfg, params, data = gpt_data
        _, base = run_trajectory(model, params, data, stage=2, steps=50)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params,
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {
                        "stage": 2,
                        "offload_optimizer": {"device": "cpu"}}})
        off = [float(engine.train_batch({"input_ids": data[t]}))
               for t in range(50)]
        np.testing.assert_allclose(off, base, rtol=2e-3, atol=2e-3)


class TestElasticResume:
    def test_resume_across_dp_resize(self, gpt_data, eight_devices,
                                     tmp_path):
        """Train 5 steps on dp=8, checkpoint, restore on dp=4, continue —
        the dp=4 continuation must match an unbroken dp=8 run (same global
        batch; orbax reshards the state, ≡ reference elastic_checkpoint)."""
        from deepspeed_tpu.parallel.mesh import build_mesh

        model, cfg, params, data = gpt_data
        e8, first = run_trajectory(model, params, data, stage=2, steps=5)
        e8.save_checkpoint(str(tmp_path))

        mesh4 = build_mesh(data=4, devices=jax.devices()[:4])
        engine4, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params, mesh=mesh4,
            config={"train_micro_batch_size_per_gpu": 2,  # same global batch
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 2}})
        path, _ = engine4.load_checkpoint(str(tmp_path))
        assert path is not None
        assert engine4.global_steps == 5
        cont4 = [float(engine4.train_batch({"input_ids": data[5 + t]}))
                 for t in range(5)]

        _, unbroken = run_trajectory(model, params, data, stage=2, steps=10)
        np.testing.assert_allclose(cont4, unbroken[5:], rtol=2e-3,
                                   atol=2e-3)


class TestLauncherE2E:
    def test_two_process_rendezvous(self, tmp_path):
        """launch.py → user script → init_distributed: two real processes
        rendezvous over the coordination service (the runner's ssh/pdsh
        layer is exercised up to command construction elsewhere)."""
        from deepspeed_tpu.launcher.runner import encode_world_info

        script = tmp_path / "worker.py"
        script.write_text(
            "import sys, os, json\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from deepspeed_tpu.parallel.mesh import init_distributed\n"
            "init_distributed()\n"
            "out = {'rank': jax.process_index(),\n"
            "       'nprocs': jax.process_count(),\n"
            "       'ndev': len(jax.devices())}\n"
            "print('RESULT ' + json.dumps(out))\n")
        world = encode_world_info({"host-a": [0], "host-b": [0]})
        procs = []
        for rank in (0, 1):
            env = {k: v for k, v in os.environ.items()
                   if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
                 "--world_info", world, "--node_rank", str(rank),
                 "--master_addr", "127.0.0.1", "--master_port", "29871",
                 str(script)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                env={**env, "PYTHONPATH": REPO}, text=True))
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker failed:\n{out}"
            outs.append(out)
        results = []
        for out in outs:
            lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
            assert lines, f"no RESULT line in:\n{out}"
            results.append(json.loads(lines[0][len("RESULT "):]))
        assert {r["rank"] for r in results} == {0, 1}
        assert all(r["nprocs"] == 2 for r in results)
        assert all(r["ndev"] == 2 for r in results)
