"""Fused blockwise optimizer-update tests (docs/PERFORMANCE.md "Kernel
tier round 2").

The acceptance gates:

- the Pallas kernel (interpret path) is **ulp-bounded** against the
  FusedAdam XLA elementwise chain — every leaf shape (odd sizes,
  scalars, multi-block grids), classic-L2 and AdamW decay,
  bias-correction on and off, bf16 grads, and the optional fused bf16
  compute-param cast;
- wired through ``_make_apply_step`` (the ONE update site), the fused
  step produces the **same training trajectory** as the XLA chain
  across ZeRO stages 0-3 and bf16 master precision;
- incompatible tiers are rejected at init (host offload, 1-bit sync,
  non-Adam optimizers), not silently degraded;
- fused off ⇒ zero overhead: the lowered train step is bit-identical
  with the flag absent and explicitly false, and differs once on;
- ``fused_update_cost`` books the kernel's arithmetic and single HBM
  round-trip for the MFU/roofline accounting.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import ConfigError
from deepspeed_tpu.ops.adam.fused_adam import AdamState, FusedAdam, FusedAdamW
from deepspeed_tpu.ops.adam.fused_update import (fused_adam_apply,
                                                 fused_adam_leaf,
                                                 fused_update_cost,
                                                 scalar_tile)
from deepspeed_tpu.parallel.mesh import build_mesh

from simple_model import mlp_loss_fn, mlp_params, random_batch


def _tree(rng, dtype=jnp.float32):
    return {
        "w": jnp.asarray(rng.standard_normal((37, 129)), dtype),
        "big": jnp.asarray(rng.standard_normal((41000,)), dtype),
        "b": jnp.asarray(rng.standard_normal((5,)), dtype),
        "s": jnp.asarray(rng.standard_normal(()), dtype),
    }


def _max_delta(a, b):
    return max(float(jnp.max(jnp.abs(x.astype(jnp.float32)
                                     - y.astype(jnp.float32))))
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


class TestFusedAdamKernel:
    @pytest.mark.parametrize("opt", [
        FusedAdam(lr=1e-3, weight_decay=0.01, adamw_mode=True),
        FusedAdam(lr=2e-3, weight_decay=0.01, adamw_mode=False),
        FusedAdam(lr=1e-3, bias_correction=False),
        FusedAdamW(lr=1e-3, weight_decay=0.1),
    ], ids=["adamw", "classic-l2", "no-bias-corr", "adamw-class"])
    def test_parity_vs_xla_chain(self, rng, opt):
        p = _tree(rng)
        g = _tree(rng)
        st = opt.init(p)
        for _ in range(3):
            p_ref, st_ref = opt.update(g, st, p, lr=0.005)
            p_fu, st_fu = fused_adam_apply(opt, g, st, p, lr=0.005)
            assert _max_delta(p_ref, p_fu) < 1e-6
            assert _max_delta(st_ref.exp_avg, st_fu.exp_avg) < 1e-6
            assert _max_delta(st_ref.exp_avg_sq, st_fu.exp_avg_sq) < 1e-6
            assert int(st_fu.step) == int(st_ref.step)
            p, st = p_fu, st_fu

    def test_bf16_grads(self, rng):
        opt = FusedAdam(lr=1e-3)
        p = _tree(rng)
        g = _tree(rng, jnp.bfloat16)
        st = opt.init(p)
        p_ref, _ = opt.update(g, st, p, lr=1e-3)
        p_fu, _ = fused_adam_apply(opt, g, st, p, lr=1e-3)
        assert _max_delta(p_ref, p_fu) < 1e-6

    def test_fused_cast_output(self, rng):
        """The third output is the bf16 compute-param cast of the
        updated master — the extra HBM read a separate cast pass would
        have paid."""
        opt = FusedAdam(lr=1e-3)
        p = _tree(rng)
        g = _tree(rng)
        st = opt.init(p)
        p_new, _, compute = fused_adam_apply(opt, g, st, p, lr=1e-3,
                                             cast_dtype=jnp.bfloat16)
        for leaf, ref in zip(jax.tree_util.tree_leaves(compute),
                             jax.tree_util.tree_leaves(p_new)):
            assert leaf.dtype == jnp.bfloat16
            np.testing.assert_array_equal(
                np.asarray(leaf), np.asarray(ref.astype(jnp.bfloat16)))

    def test_leaf_shapes_roundtrip(self, rng):
        """Padding to lanes/sublanes/blocks never leaks into results."""
        sc = scalar_tile(jnp.float32(1e-3), jnp.float32(1.0),
                         jnp.float32(1.0))
        for n in (1, 127, 128, 129, 4096, 128 * 256 + 7):
            p = jnp.asarray(rng.standard_normal((n,)), jnp.float32)
            g = jnp.asarray(rng.standard_normal((n,)), jnp.float32)
            m = jnp.zeros((n,), jnp.float32)
            v = jnp.zeros((n,), jnp.float32)
            outs = fused_adam_leaf(p, g, m, v, sc, b1=0.9, b2=0.999,
                                   eps=1e-8, weight_decay=0.0,
                                   adamw_mode=True)
            ref_m = 0.1 * g
            ref_v = 0.001 * jnp.square(g)
            assert outs[0].shape == (n,)
            np.testing.assert_allclose(np.asarray(outs[1]),
                                       np.asarray(ref_m), atol=1e-6)
            np.testing.assert_allclose(np.asarray(outs[2]),
                                       np.asarray(ref_v), atol=1e-7)

    def test_cost_model(self):
        params = {"a": jnp.zeros((100,)), "b": jnp.zeros((9, 10))}
        flops, bytes_ = fused_update_cost(params)
        n = 190
        assert flops == 12.0 * n
        assert bytes_ == 28.0 * n


class TestFusedEngineWiring:
    def _engine(self, fused, stage=0, precision=None, world=8):
        cfg = {"train_micro_batch_size_per_gpu": 8,
               "gradient_accumulation_steps": 1,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-2},
                             "fused_update": fused},
               "zero_optimization": {"stage": stage}}
        if precision == "bf16":
            cfg["bf16"] = {"enabled": True}
        e, _, _, _ = deepspeed_tpu.initialize(
            loss_fn=mlp_loss_fn, params=mlp_params(), config=cfg,
            mesh=build_mesh(data=world))
        return e

    @pytest.mark.parametrize("stage", [0, 1, 2, 3])
    def test_trajectory_matches_xla(self, stage, rng, eight_devices):
        batches = [random_batch(rng, batch_size=16) for _ in range(3)]
        a = self._engine(False, stage)
        b = self._engine(True, stage)
        for bt in batches:
            for e in (a, b):
                loss = e.forward(bt)
                e.backward(loss)
                e.step()
        assert float(a._last_loss) == pytest.approx(float(b._last_loss))
        assert _max_delta(a.state.params, b.state.params) < 1e-6

    def test_trajectory_matches_bf16(self, rng, eight_devices):
        batches = [random_batch(rng, batch_size=16) for _ in range(3)]
        a = self._engine(False, 0, "bf16")
        b = self._engine(True, 0, "bf16")
        for bt in batches:
            for e in (a, b):
                loss = e.forward(bt)
                e.backward(loss)
                e.step()
        assert _max_delta(a.state.params, b.state.params) < 1e-6

    def test_incompatible_tiers_rejected(self, eight_devices):
        base = {"train_micro_batch_size_per_gpu": 8,
                "gradient_accumulation_steps": 1,
                "zero_optimization": {"stage": 0}}
        with pytest.raises(ConfigError, match="Adam family"):
            deepspeed_tpu.initialize(
                loss_fn=mlp_loss_fn, params=mlp_params(),
                config={**base, "optimizer": {
                    "type": "sgd", "params": {"lr": 1e-2},
                    "fused_update": True}},
                mesh=build_mesh(data=8))
        with pytest.raises(ConfigError, match="host offload"):
            deepspeed_tpu.initialize(
                loss_fn=mlp_loss_fn, params=mlp_params(),
                config={**base,
                        "optimizer": {"type": "Adam",
                                      "params": {"lr": 1e-2},
                                      "fused_update": True},
                        "zero_optimization": {
                            "stage": 2,
                            "offload_optimizer": {"device": "cpu"}}},
                mesh=build_mesh(data=8))

    def test_off_is_bit_identical_and_on_differs(self, rng, eight_devices):
        """The zero-overhead contract: flag absent and flag false lower
        the SAME train step; turning it on swaps the update site."""
        batches = random_batch(rng, batch_size=8)
        placed = jax.tree_util.tree_map(lambda x: x[None, ...], batches)

        def lowered(opt_block):
            cfg = {"train_micro_batch_size_per_gpu": 8,
                   "gradient_accumulation_steps": 1,
                   "optimizer": opt_block,
                   "zero_optimization": {"stage": 0}}
            e, _, _, _ = deepspeed_tpu.initialize(
                loss_fn=mlp_loss_fn, params=mlp_params(), config=cfg,
                mesh=build_mesh(data=8))
            return e._train_step.lower(e.state, placed,
                                       jnp.float32(1e-2)).as_text()

        absent = lowered({"type": "Adam", "params": {"lr": 1e-2}})
        off = lowered({"type": "Adam", "params": {"lr": 1e-2},
                       "fused_update": False})
        on = lowered({"type": "Adam", "params": {"lr": 1e-2},
                      "fused_update": True})
        assert absent == off
        assert on != off


class TestAdamStateShape:
    def test_apply_preserves_tree_and_state(self, rng):
        opt = FusedAdam(lr=1e-3)
        p = _tree(rng)
        st = opt.init(p)
        g = _tree(rng)
        p2, st2 = fused_adam_apply(opt, g, st, p, lr=1e-3)
        assert isinstance(st2, AdamState)
        assert (jax.tree_util.tree_structure(p2)
                == jax.tree_util.tree_structure(p))
        assert int(st2.step) == 1
