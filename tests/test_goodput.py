"""Goodput accounting tests (telemetry/goodput.py; docs/OBSERVABILITY.md
"Goodput accounting"): the accountant's exact wall-clock partition, the
engine hooks (categories, recompile/replay classification, run manifest,
engine/mfu), the shared MFU helper, multi-device HBM aggregation, the
zero-sync disabled contract, tools/goodput_report.py, and the end-to-end
2-attempt acceptance run (FaultPlan SIGTERM → supervisor auto-resume →
one merged cross-attempt report)."""

import importlib.util
import json
import os
import subprocess
import sys
import textwrap

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.profiling import flops_profiler as fp
from deepspeed_tpu.telemetry import InMemorySink, MetricsRegistry
from deepspeed_tpu.telemetry.goodput import (ATTEMPT_START_WALL_ENV,
                                             CATEGORIES, GoodputAccountant,
                                             classify_exit,
                                             finalize_attempt_manifests)

from simple_model import mlp_loss_fn, mlp_params, random_batches

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


class FakeClock:
    """Deterministic monotonic + wall clocks for partition-exactness
    assertions (the real clocks only support tolerance checks)."""

    def __init__(self, t0=100.0, wall0=1000.0):
        self.t = t0
        self.w0 = wall0 - t0

    def mono(self):
        return self.t

    def wall(self):
        return self.w0 + self.t

    def advance(self, dt):
        self.t += dt


def _accountant(tmp_path=None, registry=None, clk=None, env=None):
    clk = clk or FakeClock()
    acc = GoodputAccountant(
        registry=registry, run_dir=str(tmp_path) if tmp_path else None,
        attempt=0, host="testhost", cfg_hash="cafe",
        clock=clk.mono, wall_clock=clk.wall, env=env if env is not None
        else {})
    return acc, clk


# ---------------------------------------------------------------------------
# Accountant unit tests
# ---------------------------------------------------------------------------
class TestAccountant:
    def test_marks_partition_wall_clock_exactly(self):
        acc, clk = _accountant()
        clk.advance(2.0)
        acc.mark_gap()                       # pre-first-step -> init_restore
        clk.advance(0.5)
        acc.mark("data_stall")
        clk.advance(3.0)
        acc.step_mark("productive_step", 1)
        clk.advance(1.0)
        acc.mark_gap()                       # post-first-step -> idle_other
        clk.advance(0.25)                    # pending tail -> idle_other
        t = acc.totals()
        assert t["init_restore"] == pytest.approx(2.0)
        assert t["data_stall"] == pytest.approx(0.5)
        assert t["productive_step"] == pytest.approx(3.0)
        assert t["idle_other"] == pytest.approx(1.25)
        assert t["wall_sec"] == pytest.approx(6.75)
        # the partition is EXACT: categories sum to wall
        assert sum(t[c] for c in CATEGORIES) == pytest.approx(t["wall_sec"])

    def test_measure_carves_out_without_double_count(self):
        acc, clk = _accountant()
        clk.advance(1.0)                     # pending (enclosing phase)
        with acc.measure("rollback_restore"):
            clk.advance(4.0)
        clk.advance(0.5)
        acc.mark("productive_step")          # pending 1.0 + 0.5, not 5.5
        t = acc.totals()
        assert t["rollback_restore"] == pytest.approx(4.0)
        assert t["productive_step"] == pytest.approx(1.5)
        assert sum(t[c] for c in CATEGORIES) == pytest.approx(t["wall_sec"])

    def test_step_stats_feed_mfu_and_exclude_recompile(self):
        acc, clk = _accountant()
        clk.advance(10.0)
        acc.step_mark("recompile", 1)        # compile-inflated: excluded
        for step in (2, 3):
            clk.advance(2.0)
            acc.step_mark("productive_step", step)
        clk.advance(4.0)
        acc.step_mark("rollback_replay", 3)  # replay counts as a step time
        assert acc.mean_step_time() == pytest.approx(8.0 / 3)
        assert acc.mfu() is None             # no flops yet
        acc.set_flops(16e12, n_chips=2, peak_tflops_per_chip=100.0)
        want = fp.mfu(16e12, 8.0 / 3, n_chips=2, peak_tflops_per_chip=100.0)
        assert acc.mfu() == pytest.approx(want)
        assert not acc.wants_flops

    def test_spawn_env_backdates_to_init_restore(self):
        clk = FakeClock()
        acc = GoodputAccountant(
            run_dir=None, attempt=0, host="h", clock=clk.mono,
            wall_clock=clk.wall,
            env={ATTEMPT_START_WALL_ENV: repr(clk.wall() - 7.5)})
        t = acc.totals()
        assert t["init_restore"] == pytest.approx(7.5)
        assert t["wall_sec"] == pytest.approx(7.5)
        assert acc.start_wall == pytest.approx(clk.wall() - 7.5)

    def test_emit_tags_and_attempt_label(self):
        reg = MetricsRegistry()
        mem = reg.add_sink(InMemorySink())
        acc, clk = _accountant(registry=reg)
        clk.advance(1.0)
        acc.step_mark("productive_step", 1)
        acc.note_aux("pipe_bubble_sec", 0.25)
        acc.emit(step=1)
        tags = mem.tags()
        for c in CATEGORIES:
            assert f"goodput/{c}_sec" in tags
        assert {"goodput/wall_sec", "goodput/goodput_frac",
                "goodput/steps_committed",
                "goodput/pipe_bubble_sec"} <= tags
        row = next(r for r in mem.rows if r["tag"] == "goodput/wall_sec")
        assert row["attempt"] == 0
        assert mem.values("goodput/productive_step_sec")[-1] == \
            pytest.approx(1.0)
        assert mem.values("goodput/goodput_frac")[-1] == pytest.approx(1.0)

    def test_manifest_write_refresh_finalize(self, tmp_path):
        acc, clk = _accountant(tmp_path=tmp_path)
        path = acc.manifest_path()
        assert os.path.exists(path)          # written at construction
        clk.advance(2.0)
        acc.step_mark("productive_step", 5)
        acc.write_manifest()
        doc = json.load(open(path))
        assert doc["format"] == 1
        assert doc["attempt"] == 0 and doc["host"] == "testhost"
        assert doc["config_hash"] == "cafe"
        assert doc["end_wall"] is None and doc["exit_rc"] is None
        assert doc["steps_committed"] == 5 and doc["first_step"] == 5
        assert doc["categories"]["productive_step"] == pytest.approx(2.0)
        assert sum(doc["categories"].values()) == \
            pytest.approx(doc["wall_sec"])
        clk.advance(1.0)
        acc.finalize()
        doc = json.load(open(path))
        assert doc["end_wall"] is not None
        assert doc["end_monotonic"] is not None
        acc.finalize()                       # idempotent

    def test_classify_exit(self):
        assert classify_exit(0) == "clean"
        assert classify_exit(113, (113,)) == "watchdog"
        assert classify_exit(-15) == "preemption"
        assert classify_exit(143) == "preemption"
        assert classify_exit(1) == "crash"

    def test_supervisor_finalize_stamps_and_stubs(self, tmp_path):
        acc, clk = _accountant(tmp_path=tmp_path)
        clk.advance(3.0)
        acc.write_manifest()
        n = finalize_attempt_manifests(str(tmp_path), 0, -15, "preemption",
                                       1000.0, 1070.0)
        assert n == 1
        doc = json.load(open(acc.manifest_path()))
        assert doc["exit_rc"] == -15
        assert doc["restart_cause"] == "preemption"
        assert doc["end_wall"] == 1070.0
        # the supervisor-observed lifetime supersedes the stale wall_sec
        assert doc["wall_sec"] >= 3.0
        # a child that died before engine init leaves a stub
        n = finalize_attempt_manifests(str(tmp_path), 7, 1, "crash",
                                       2000.0, 2004.0)
        assert n == 1
        stub = json.load(open(tmp_path / "run_manifest.a0007.unknown.json"))
        assert stub["exit_rc"] == 1 and stub["wall_sec"] == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# MFU helper (flops_profiler satellites)
# ---------------------------------------------------------------------------
class TestMfuHelper:
    def test_peak_table_and_dtype_defaults(self):
        assert fp.peak_tflops("TPU v4", "bfloat16") == 275.0
        assert fp.peak_tflops("TPU v4", "float32") == 137.5
        assert fp.peak_tflops("TPU v5 lite", "bf16") == 197.0
        assert fp.peak_tflops("TPU v6 lite") == 918.0
        # fp16 rides the bf16 MXU path
        assert fp.peak_tflops("TPU v4", "float16") == 275.0
        # unknown kind: conservative default, fp32 at half
        assert fp.peak_tflops("", "bfloat16") == fp.DEFAULT_PEAK_TFLOPS
        assert fp.peak_tflops(None, "fp32") == fp.DEFAULT_PEAK_TFLOPS / 2

    def test_mfu_math_and_degenerate_inputs(self):
        # 100 TFLOP over 1 s on 1 chip with 200 TFLOP/s peak = 50%
        assert fp.mfu(100e12, 1.0, n_chips=1,
                      peak_tflops_per_chip=200.0) == pytest.approx(0.5)
        # chip count divides
        assert fp.mfu(100e12, 1.0, n_chips=4,
                      peak_tflops_per_chip=200.0) == pytest.approx(0.125)
        # device-kind lookup path
        assert fp.mfu(275e12, 1.0, n_chips=1, device_kind="TPU v4") == \
            pytest.approx(1.0)
        assert fp.mfu(None, 1.0) == 0.0
        assert fp.mfu(0.0, 1.0) == 0.0
        assert fp.mfu(1e12, 0.0) == 0.0

    def test_profiler_method_uses_last_profile(self):
        prof = fp.FlopsProfiler()
        assert prof.mfu(1.0, peak_tflops_per_chip=100.0) == 0.0  # no profile

        def f(x):
            return (x @ x).sum()

        x = np.zeros((64, 64), np.float32)
        prof.profile_callable(f, x, measure=False, detailed=False)
        flops = prof.last["flops"]
        if flops > 0:  # CPU cost analysis may not report flops
            want = fp.mfu(flops, 2.0, n_chips=2, peak_tflops_per_chip=50.0)
            assert prof.mfu(2.0, peak_tflops_per_chip=50.0,
                            n_chips=2) == pytest.approx(want)


# ---------------------------------------------------------------------------
# Engine integration
# ---------------------------------------------------------------------------
def _engine(config_extra=None, world=8):
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": 1},
                **(config_extra or {})},
        mesh=build_mesh(data=world))
    return engine


def _tel_cfg(tmp_path, goodput=True, sinks=("memory",)):
    return {"telemetry": {"enabled": True, "dir": str(tmp_path),
                          "trace": {"enabled": False},
                          "metrics": {"sinks": list(sinks)},
                          "goodput": goodput}}


class TestEngineGoodput:
    def test_fused_loop_categories_manifest_and_mfu(self, eight_devices,
                                                    tmp_path):
        engine = _engine(_tel_cfg(tmp_path) | {"steps_per_print": 2})
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=1, batch_size=16)
        for _ in range(5):
            engine.train_batch(batches)
        g = engine.goodput
        assert g is not None
        t = g.totals()
        assert t["recompile"] > 0            # first step's trace+compile
        assert t["productive_step"] > 0
        assert t["data_stall"] > 0
        # exact partition: explicit categories sum to wall
        assert sum(t[c] for c in CATEGORIES) == \
            pytest.approx(t["wall_sec"], rel=1e-6)
        # manifest refreshed at the steps_per_print cadence
        doc = json.load(open(g.manifest_path()))
        assert doc["steps_committed"] >= 4
        assert doc["first_step"] == 1
        # engine/mfu flowed through the ONE shared helper
        mem = engine.telemetry.registry.sinks[0]
        assert isinstance(mem, InMemorySink)
        if g._flops_per_step is not None:
            want = fp.mfu(g._flops_per_step, g.mean_step_time(),
                          n_chips=engine.mesh.size,
                          peak_tflops_per_chip=g._peak_tflops)
            assert g.mfu() == pytest.approx(want)
            assert mem.values("engine/mfu")[-1] == pytest.approx(want)
        assert mem.values("goodput/steps_committed")[-1] == 5
        assert not g.wants_flops             # analysed exactly once

    def test_reference_loop_marks(self, eight_devices, tmp_path):
        from simple_model import random_batch
        engine = _engine(_tel_cfg(tmp_path))
        rng = np.random.default_rng(0)
        for _ in range(2):
            loss = engine.forward(random_batch(rng, batch_size=16))
            engine.backward(loss)
            engine.step()
        t = engine.goodput.totals()
        assert t["recompile"] > 0
        assert t["productive_step"] > 0
        assert sum(t[c] for c in CATEGORIES) == \
            pytest.approx(t["wall_sec"], rel=1e-6)

    def test_replay_classification_after_rollback_rewind(self, eight_devices,
                                                         tmp_path):
        engine = _engine(_tel_cfg(tmp_path))
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=1, batch_size=16)
        for _ in range(3):
            engine.train_batch(batches)
        g = engine.goodput
        assert g.totals()["rollback_replay"] == 0.0
        # simulate what a guardrails rollback does: rewind the committed
        # step counter below the high-water mark
        engine._goodput_replay_until = engine.global_steps
        engine.global_steps -= 2
        engine.train_batch(batches)          # commits step 2 <= hwm 3
        engine.train_batch(batches)          # commits step 3 <= hwm 3
        t = g.totals()
        assert t["rollback_replay"] > 0.0
        engine.train_batch(batches)          # step 4: productive again
        assert g.totals()["rollback_replay"] == t["rollback_replay"]

    def test_ckpt_snapshot_attributed(self, eight_devices, tmp_path):
        engine = _engine(_tel_cfg(tmp_path) | {
            "resilience": {"enabled": True,
                           "checkpoint": {"dir": str(tmp_path / "ckpt"),
                                          "interval": 1,
                                          "async": False}}})
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=1, batch_size=16)
        for _ in range(2):
            engine.train_batch(batches)
        t = engine.goodput.totals()
        assert t["ckpt_snapshot"] > 0.0
        assert t["ckpt_write_stall"] > 0.0   # sync writes stall the step
        assert sum(t[c] for c in CATEGORIES) == \
            pytest.approx(t["wall_sec"], rel=1e-6)

    def test_auto_resume_attributed_to_init_restore(self, eight_devices,
                                                    tmp_path):
        res = {"resilience": {"enabled": True,
                              "checkpoint": {"dir": str(tmp_path / "ckpt"),
                                             "interval": 1,
                                             "async": False}}}
        engine = _engine(_tel_cfg(tmp_path / "t1") | res)
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=1, batch_size=16)
        for _ in range(2):
            engine.train_batch(batches)
        engine2 = _engine(_tel_cfg(tmp_path / "t2") | res)
        before = engine2.goodput.totals()["init_restore"]
        path, _ = engine2.auto_resume()
        assert path is not None
        assert engine2.goodput.totals()["init_restore"] > before

    # -- disabled-path contract (the PR 2/3 zero-sync gate, extended) ----
    def test_telemetry_off_means_goodput_none(self):
        engine = _engine()
        assert engine.goodput is None

    def test_goodput_flag_off_means_none_and_no_manifest(self, eight_devices,
                                                         tmp_path):
        engine = _engine(_tel_cfg(tmp_path, goodput=False))
        assert engine.goodput is None
        rng = np.random.default_rng(0)
        engine.train_batch(random_batches(rng, gas=1, batch_size=16))
        assert not [f for f in os.listdir(tmp_path)
                    if f.startswith("run_manifest.")]
        mem = engine.telemetry.registry.sinks[0]
        assert not any(t.startswith("goodput/") for t in mem.tags())

    @pytest.mark.parametrize("goodput_on", [False, True])
    def test_goodput_adds_zero_device_syncs(self, eight_devices, tmp_path,
                                            monkeypatch, goodput_on):
        """The accountant is pure host clock reads: with the tracer off,
        the step path performs ZERO device syncs whether goodput is on or
        off — the accountant never adds one."""
        engine = _engine(_tel_cfg(tmp_path, goodput=goodput_on))
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=1, batch_size=16)
        for _ in range(2):
            engine.train_batch(batches)      # compile + flops analysis
        from deepspeed_tpu.utils import timer as timer_mod
        calls = {"n": 0}
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: calls.__setitem__("n", calls["n"] + 1))
        for _ in range(10):
            engine.train_batch(batches)
        assert calls["n"] == 0
        assert (engine.goodput is not None) == goodput_on


class TestHbmMultiDevice:
    def test_aggregates_across_local_devices(self, eight_devices, tmp_path,
                                             monkeypatch):
        """The satellite fix: peak = max over devices, in_use = sum, rows
        tagged with the reporting device count (the old code read only
        jax.local_devices()[0] and under-reported multi-chip hosts)."""
        engine = _engine(_tel_cfg(tmp_path))

        class FakeDev:
            def __init__(self, peak, use):
                self._stats = {"peak_bytes_in_use": peak,
                               "bytes_in_use": use}

            def memory_stats(self):
                return self._stats

        class Broken:
            def memory_stats(self):
                raise RuntimeError("no stats on this backend")

        monkeypatch.setattr(jax, "local_devices",
                            lambda: [FakeDev(100, 10), FakeDev(300, 20),
                                     FakeDev(200, 30), Broken()])
        engine._emit_step_telemetry()
        mem = engine.telemetry.registry.sinks[0]
        peak = next(r for r in mem.rows
                    if r["tag"] == "engine/hbm_peak_bytes")
        use = next(r for r in mem.rows
                   if r["tag"] == "engine/hbm_bytes_in_use")
        assert peak["value"] == 300.0 and peak["devices"] == 3
        assert use["value"] == 60.0 and use["devices"] == 3


# ---------------------------------------------------------------------------
# tools/goodput_report.py
# ---------------------------------------------------------------------------
def _load_report_mod():
    path = os.path.join(REPO, "tools", "goodput_report.py")
    spec = importlib.util.spec_from_file_location("goodput_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestGoodputReport:
    def test_selftest_cli(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "goodput_report.py"), "--selftest"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "selftest ok" in proc.stdout

    def test_merges_engine_written_run_dir(self, eight_devices, tmp_path):
        """A single-attempt dir produced by the REAL engine parses and
        balances."""
        engine = _engine(_tel_cfg(tmp_path, sinks=("jsonl",))
                         | {"steps_per_print": 1})
        rng = np.random.default_rng(0)
        batches = random_batches(rng, gas=1, batch_size=16)
        for _ in range(4):
            engine.train_batch(batches)
        engine.telemetry.flush()
        engine.goodput.finalize()
        mod = _load_report_mod()
        report = mod.merge_run(str(tmp_path))
        assert report["n_attempts"] == 1
        assert report["steps_committed"] == 4
        assert 0.0 < report["goodput_frac"] < 1.0
        assert report["attributed_frac"] > 0.95
        assert report["categories"]["recompile"] > 0
        text = mod.render(report)
        assert "productive_step" in text and "restarts:" in text


# ---------------------------------------------------------------------------
# End to end: SIGTERM mid-run -> supervisor restart -> ONE merged report
# ---------------------------------------------------------------------------
_TRAIN_SCRIPT = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, sys.argv[3])
    import numpy as np
    from deepspeed_tpu import initialize
    from deepspeed_tpu.parallel.mesh import build_mesh
    from simple_model import mlp_params, mlp_loss_fn, random_batches

    run_dir, total_steps = sys.argv[1], int(sys.argv[2])
    engine, _, _, _ = initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 1,
            "telemetry": {"enabled": True, "dir": run_dir,
                          "trace": {"enabled": False},
                          "metrics": {"sinks": ["jsonl"]}},
            "resilience": {"enabled": True,
                           "checkpoint": {"dir": os.path.join(run_dir,
                                                              "ckpt"),
                                          "interval": 2, "async": False,
                                          "backoff_seconds": 0.01}},
        },
        mesh=build_mesh(data=8), rng_seed=0)
    engine.auto_resume()
    rng = np.random.default_rng(7)
    stream = [random_batches(rng, 1, batch_size=16)
              for _ in range(total_steps)]
    for i in range(engine.global_steps, total_steps):
        engine.train_batch(stream[i])
    engine.ckpt_manager.close()
    engine.telemetry.flush()
    engine.goodput.finalize()
""")


def test_e2e_sigterm_resume_merged_goodput_report(eight_devices, tmp_path):
    """The acceptance gate: a FaultPlan SIGTERM after step 3 kills attempt
    0, the supervisor restarts it, attempt 1 resumes from the step-2
    checkpoint and finishes; tools/goodput_report.py then merges both
    attempts into ONE report where per-category seconds sum to run
    wall-clock within 5%, goodput < 1 with nonzero restart +
    init_restore + cross-attempt replay attribution, and the reported MFU
    is the FlopsProfiler-derived value the attempts emitted."""
    from deepspeed_tpu.resilience import FAULT_PLAN_ENV, Supervisor

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    total = 7
    sup = Supervisor(
        [sys.executable, "-c", _TRAIN_SCRIPT, str(run_dir), str(total),
         TESTS_DIR],
        max_restarts=2, backoff=0.05, run_dir=str(run_dir),
        env={"JAX_PLATFORMS": "cpu",
             FAULT_PLAN_ENV: json.dumps({"preempt_at_step": 3})})
    assert sup.run() == 0
    assert sup.restarts == 1

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "goodput_report.py"),
         str(run_dir), "--json"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)

    assert report["n_attempts"] == 2 and report["n_restarts"] == 1
    a0, a1 = report["attempts"]
    assert a0["restart_cause"] == "preemption" and a0["exit_rc"] != 0
    assert a1["restart_cause"] == "clean" and a1["exit_rc"] == 0
    assert a0["steps_committed"] == 3
    assert a1["steps_committed"] == total
    # attempt 1 resumed from the step-2 checkpoint below attempt 0's
    # high-water mark: the merge reclassifies the re-earned step as replay
    assert a1["first_step"] == 3
    assert report["categories"]["rollback_replay"] > 0

    # per-category seconds sum to total wall-clock within 5%
    total_attr = (sum(report["categories"].values())
                  + report["restart_sec"] + report["unaccounted_sec"])
    assert abs(total_attr - report["wall_sec"]) <= 0.05 * report["wall_sec"]
    assert report["attributed_frac"] >= 0.95

    # goodput < 1 with nonzero restart / init_restore attribution
    assert 0.0 < report["goodput_frac"] < 1.0
    assert report["restart_sec"] > 0.0
    assert report["categories"]["init_restore"] > 0.0
    assert report["categories"]["productive_step"] > 0.0

    # reported MFU is the FlopsProfiler-derived value the attempts emitted
    rows = [json.loads(l)
            for l in open(run_dir / "metrics.jsonl") if l.strip()]
    mfus = {}
    for r in rows:
        if r["tag"] == "engine/mfu":
            mfus[int(r.get("attempt", 0))] = r["value"]
    if mfus:  # CPU cost analysis reported flops
        assert report["mfu"] is not None
        assert (min(mfus.values()) - 1e-12 <= report["mfu"]
                <= max(mfus.values()) + 1e-12)
