"""Aux-subsystem tests: FlopsProfiler, tensorboard monitor, PLD,
eigenvalue, MoQ quantization, CSR tensor, activation checkpointing.

These are the config blocks VERDICT r1 flagged as parse-and-ignore; each
test drives the block through observable behavior (or the loud rejection).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import ConfigError


def mlp_loss_fn(params, batch, rng):
    h = jnp.tanh(batch["x"] @ params["w1"])
    pred = h @ params["w2"]
    return jnp.mean((pred - batch["y"]) ** 2)


def mlp_params(key=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(key))
    return {"w1": jax.random.normal(k1, (8, 16)) * 0.3,
            "w2": jax.random.normal(k2, (16, 4)) * 0.3}


def mlp_batch(rng, gas=1, bs=8):
    return {"x": rng.standard_normal((gas, bs, 8)).astype(np.float32),
            "y": rng.standard_normal((gas, bs, 4)).astype(np.float32)}


def build(config_extra, rng_seed=0):
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 0}}
    cfg.update(config_extra)
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(), config=cfg,
        rng_seed=rng_seed)
    return engine


class TestFlopsProfiler:
    def test_profile_callable_counts_matmul(self):
        from deepspeed_tpu.profiling import FlopsProfiler

        def f(a, b):
            return a @ b

        a = jnp.ones((64, 128), jnp.float32)
        b = jnp.ones((128, 32), jnp.float32)
        prof = FlopsProfiler()
        r = prof.profile_callable(f, a, b, detailed=True)
        want = 2 * 64 * 128 * 32
        assert r["flops"] >= want * 0.5  # XLA counts >= the matmul itself
        assert r["breakdown"].get("matmul", 0) == want
        assert r["latency_s"] > 0
        text = prof.print_profile(r, file=open(os.devnull, "w"))
        assert "TFLOP/s" in text

    def test_engine_profile_step_writes_file(self, rng, tmp_path):
        out = tmp_path / "flops.txt"
        engine = build({"flops_profiler": {
            "enabled": True, "profile_step": 2, "output_file": str(out)}})
        for _ in range(3):
            engine.train_batch(mlp_batch(rng))
        assert out.exists()
        content = out.read_text()
        assert "flops/step" in content and "Flops Profiler" in content

    def test_profiler_fires_under_offload(self, rng, tmp_path):
        out = tmp_path / "flops_off.txt"
        engine = build({"flops_profiler": {
            "enabled": True, "profile_step": 1, "output_file": str(out)},
            "zero_optimization": {"stage": 2,
                                  "offload_optimizer": {"device": "cpu"}}})
        engine.train_batch(mlp_batch(rng))
        assert out.exists() and "flops/step" in out.read_text()


class TestMonitor:
    def test_scalars_written(self, rng, tmp_path):
        engine = build({"tensorboard": {"enabled": True,
                                        "output_path": str(tmp_path),
                                        "job_name": "job1"}})
        for _ in range(3):
            engine.train_batch(mlp_batch(rng))
        logdir = tmp_path / "job1"
        files = os.listdir(logdir)
        assert files, "no event files written"
        if "scalars.jsonl" in files:  # fallback writer
            lines = [json.loads(l) for l in open(logdir / "scalars.jsonl")]
            tags = {l["tag"] for l in lines}
            assert "Train/Samples/train_loss" in tags

    def test_disabled_no_monitor(self, rng):
        engine = build({})
        assert engine.monitor is None


class TestPLD:
    def test_theta_schedule(self):
        from deepspeed_tpu.runtime.progressive_layer_drop import \
            ProgressiveLayerDrop

        pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
        assert pld.get_theta(0) == pytest.approx(1.0)
        assert pld.get_theta(10 ** 6) == pytest.approx(0.5)
        a, b = pld.get_theta(10), pld.get_theta(100)
        assert 0.5 < b < a < 1.0
        pld.update_state(50)
        assert pld.get_state()["pld_theta"] == pytest.approx(
            pld.get_theta(50))

    def test_engine_injects_theta_and_model_consumes(self, rng):
        """GPT-tiny with PLD: training works, and the drop actually changes
        the computed loss vs no-PLD at equal seeds (gates fire)."""
        from deepspeed_tpu.models import make_gpt

        model, cfg = make_gpt("tiny", dropout_rate=0.0, num_layers=4)
        ids = rng.integers(0, cfg.vocab_size, (2, 8, 16)).astype(np.int32)
        params = model.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)},
                            {"input_ids": ids[0]})["params"]

        def eng(pld_on):
            extra = {"progressive_layer_drop":
                     {"enabled": True, "theta": 0.1, "gamma": 0.0}} \
                if pld_on else {}
            e, _, _, _ = deepspeed_tpu.initialize(
                model=model, params=params,
                config={"train_micro_batch_size_per_gpu": 1,
                        "gradient_accumulation_steps": 2,
                        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                        "zero_optimization": {"stage": 0}, **extra})
            return e

        e_pld, e_plain = eng(True), eng(False)
        assert e_pld.progressive_layer_drop is not None
        batch = {"input_ids": ids}
        l_pld = float(e_pld.train_batch(batch))
        l_plain = float(e_plain.train_batch(batch))
        assert np.isfinite(l_pld) and np.isfinite(l_plain)
        # theta=0.1 drops most deep layers; losses must differ measurably
        assert abs(l_pld - l_plain) > 1e-6

    def test_pld_injected_on_forward_path(self, rng):
        """The reference-parity forward/backward/step loop must also see
        pld_theta (review regression: was train_batch-only)."""
        from deepspeed_tpu.models import make_gpt

        model, cfg = make_gpt("tiny", dropout_rate=0.0, num_layers=4)
        ids = rng.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32)
        params = model.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)},
                            {"input_ids": ids})["params"]

        def eng(pld_on, seed):
            extra = {"progressive_layer_drop":
                     {"enabled": True, "theta": 0.05, "gamma": 0.0}}                 if pld_on else {}
            e, _, _, _ = deepspeed_tpu.initialize(
                model=model, params=params, rng_seed=seed,
                config={"train_micro_batch_size_per_gpu": 1,
                        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                        "zero_optimization": {"stage": 0}, **extra})
            return e

        l_pld = float(eng(True, 0).forward({"input_ids": ids}))
        l_plain = float(eng(False, 0).forward({"input_ids": ids}))
        assert abs(l_pld - l_plain) > 1e-6

    def test_model_ignores_theta_when_deterministic(self, rng):
        from deepspeed_tpu.models import make_gpt

        model, cfg = make_gpt("tiny", dropout_rate=0.0, num_layers=2)
        ids = rng.integers(0, cfg.vocab_size, (2, 16)).astype(np.int32)
        params = model.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(1)},
                            {"input_ids": ids})["params"]
        a = model.apply({"params": params}, {"input_ids": ids},
                        deterministic=True)
        b = model.apply({"params": params},
                        {"input_ids": ids,
                         "pld_theta": jnp.float32(0.1)},
                        deterministic=True)
        np.testing.assert_array_equal(np.asarray(a["logits"]),
                                      np.asarray(b["logits"]))


class TestEigenvalue:
    def test_quadratic_eigenvalue_exact(self):
        """loss = 0.5 x^T A x has Hessian A; power iteration must find
        lambda_max(A)."""
        from deepspeed_tpu.runtime.eigenvalue import Eigenvalue

        evs = np.array([5.0, 2.0, 0.5], np.float32)
        A = np.diag(evs)

        def loss_fn(params, batch, rng):
            x = params["x"]
            return 0.5 * x @ jnp.asarray(A) @ x

        e = Eigenvalue(max_iter=200, tol=1e-4)
        out = e.compute_eigenvalue(loss_fn, {"x": jnp.ones((3,))},
                                   batch=None)
        assert out["x"] == pytest.approx(5.0, rel=1e-2)

    def test_per_layer_keys(self):
        from deepspeed_tpu.runtime.eigenvalue import Eigenvalue

        def loss_fn(params, batch, rng):
            return (jnp.sum(params["a"] ** 2) * 3.0
                    + jnp.sum(params["b"] ** 2) * 1.0)

        out = Eigenvalue(max_iter=100).compute_eigenvalue(
            loss_fn, {"a": jnp.ones((4,)), "b": jnp.ones((4,))}, None)
        assert set(out) == {"a", "b"}
        assert out["a"] == pytest.approx(6.0, rel=1e-2)   # H = 2*3 I
        assert out["b"] == pytest.approx(2.0, rel=1e-2)


class TestMoQ:
    def test_bits_schedule(self):
        from deepspeed_tpu.ops.quantizer import MoQConfig, MoQQuantizer

        q = MoQQuantizer(MoQConfig(start_bits=16, target_bits=8,
                                   quantize_period=10, schedule_offset=5))
        assert q.current_bits(0) == 16
        assert q.current_bits(5 + 9) == 16
        assert q.current_bits(5 + 10) == 15
        assert q.current_bits(5 + 10 + 20) == 14
        assert q.current_bits(10 ** 9) == 8   # floors at target

    def test_sim_quantize_grid(self):
        from deepspeed_tpu.ops.quantizer import sim_quantize

        w = jnp.asarray(np.random.default_rng(0).standard_normal((16, 8)),
                        jnp.float32)
        q8 = sim_quantize(w, 8, 4, True, False, jax.random.PRNGKey(0))
        q2 = sim_quantize(w, 2, 4, True, False, jax.random.PRNGKey(0))
        err8 = float(jnp.abs(w - q8).max())
        err2 = float(jnp.abs(w - q2).max())
        assert err8 < err2                      # more bits, less error
        assert err8 <= float(jnp.abs(w).max()) / 127 + 1e-6
        # asymmetric grid also reconstructs
        qa = sim_quantize(w, 8, 1, False, False, jax.random.PRNGKey(0))
        assert float(jnp.abs(w - qa).max()) < 0.05

    def test_engine_applies_moq(self, rng):
        engine = build({"quantize_training": {
            "enabled": True,
            "quantize_bits": {"start_bits": 4, "target_bits": 4},
            "quantize_schedule": {"quantize_period": 1,
                                  "schedule_offset": 0},
            "quantize_groups": 2}})
        assert engine.moq is not None
        engine.train_batch(mlp_batch(rng))
        w = np.asarray(engine.state.params["w1"], np.float64)
        # weights now sit on a 4-bit per-group grid: few distinct values
        per_group = w.reshape(2, -1)
        for g in range(2):
            assert len(np.unique(np.round(per_group[g], 6))) <= 16

    def test_unknown_keys_rejected(self):
        from deepspeed_tpu.ops.quantizer import MoQConfig

        with pytest.raises(ValueError, match="unknown quantize_training"):
            MoQConfig.from_dict({"enabled": True, "tyop": 1})


class TestSparseGradients:
    def test_engine_rejects_loudly(self):
        with pytest.raises(ConfigError, match="sparse_gradients"):
            build({"sparse_gradients": True})

    def test_csr_tensor_roundtrip(self):
        from deepspeed_tpu.runtime.sparse_tensor import CsrTensor

        dense = np.zeros((10, 4), np.float32)
        dense[2] = 1.0
        dense[7] = 2.0
        t = CsrTensor.from_dense(dense)
        assert t.nnz == 2 and t.sparsity == pytest.approx(0.8)
        np.testing.assert_array_equal(t.to_dense(), dense)
        s = t.add(t.scale(2.0)).coalesce()
        np.testing.assert_array_equal(s.to_dense(), dense * 3.0)
        assert s.nnz == 2


class TestActivationCheckpointing:
    def test_configure_and_policy(self):
        from deepspeed_tpu.runtime import activation_checkpointing as ac

        ac.reset()
        assert not ac.is_configured()
        ac.configure(partition_activations=True)
        assert ac.is_configured()
        assert ac.remat_policy() is jax.checkpoint_policies.nothing_saveable
        ac.reset()
        ac.configure()
        assert (ac.remat_policy()
                is jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        ac.reset()

    def test_checkpoint_wrapper_grad_parity(self):
        from deepspeed_tpu.runtime import activation_checkpointing as ac

        ac.reset()
        ac.configure(partition_activations=True)

        def f(w, x):
            return jnp.sum(jnp.tanh(x @ w) ** 2)

        w = jnp.asarray(np.random.default_rng(0).standard_normal((8, 8)),
                        jnp.float32)
        x = jnp.ones((4, 8), jnp.float32)
        g_plain = jax.grad(f)(w, x)
        g_ckpt = jax.grad(lambda w, x: ac.checkpoint(f, w, x))(w, x)
        np.testing.assert_allclose(np.asarray(g_plain), np.asarray(g_ckpt),
                                   rtol=1e-6)
        ac.reset()

    def test_engine_configures_from_config_block(self, rng):
        from deepspeed_tpu.runtime import activation_checkpointing as ac

        ac.reset()
        build({"activation_checkpointing": {"partition_activations": True}})
        assert ac.is_configured()
        assert ac.get_config().partition_activations
        ac.reset()


class TestReviewRegressions2:
    def test_moq_with_nvme_offload_rejected(self, tmp_path):
        with pytest.raises(ConfigError, match="nvme"):
            build({"quantize_training": {"enabled": True},
                   "zero_optimization": {"stage": 2,
                                         "offload_optimizer":
                                         {"device": "nvme",
                                          "nvme_path": str(tmp_path)}}})

    def test_later_engine_ac_block_wins(self, rng):
        from deepspeed_tpu.runtime import activation_checkpointing as ac

        ac.reset()
        build({})  # no block: must not configure globally
        assert not ac.is_configured()
        build({"activation_checkpointing": {"cpu_checkpointing": True}})
        assert ac.is_configured() and ac.get_config().cpu_checkpointing
        build({"activation_checkpointing": {"partition_activations": True}})
        assert ac.get_config().partition_activations  # later block wins
        ac.reset()

    def test_profiler_measure_survives_donating_fn(self):
        from deepspeed_tpu.profiling import FlopsProfiler

        donating = jax.jit(lambda a: a * 2.0, donate_argnums=(0,))
        x = jnp.ones((128, 128))
        r = FlopsProfiler().profile_callable(donating, x, measure=True,
                                             detailed=False)
        assert r["latency_s"] > 0  # timed the cold call, no crash

    def test_swapper_read_after_pending_write(self, tmp_path):
        from deepspeed_tpu.runtime.swap_tensor import AsyncTensorSwapper

        sw = AsyncTensorSwapper(str(tmp_path), num_threads=4)
        for i in range(20):
            a = np.full((4096,), float(i), np.float32)
            sw.swap_out("t", a)          # do NOT wait
            got = sw.swap_in("t").result()
            np.testing.assert_array_equal(got, a)
        sw.close(remove_files=True)


class TestSmallAdditions:
    def test_prefetch_loader_order_and_overlap(self):
        from deepspeed_tpu.runtime.dataloader import PrefetchLoader

        puts = []
        loader = [1, 2, 3, 4, 5]
        pl = PrefetchLoader(loader, put=lambda b: (puts.append(b), b * 10)[1],
                            prefetch=2)
        out = []
        for i, b in enumerate(pl):
            out.append(b)
            if i == 0:
                # two batches were placed before the first was consumed
                assert len(puts) >= 2
        assert out == [10, 20, 30, 40, 50]
        assert len(pl) == 5

    def test_checkpointing_alias(self):
        import deepspeed_tpu.checkpointing as ckpt

        ckpt.reset()
        ckpt.configure(partition_activations=True)
        assert ckpt.is_configured()
        import jax.numpy as jnp2
        y = ckpt.checkpoint(lambda a: a * 2, jnp2.ones((4,)))
        np.testing.assert_array_equal(np.asarray(y), 2 * np.ones(4))
        ckpt.reset()

    def test_moq_eigenvalue_stretches_period(self):
        from deepspeed_tpu.ops.quantizer import MoQConfig, MoQQuantizer

        cfg = MoQConfig(start_bits=16, target_bits=8, quantize_period=10,
                        schedule_offset=0)
        q = MoQQuantizer(cfg, layer_eigenvalues={"sharp": 4.0, "flat": 1.0})
        # flat layer drops at t=10; sharp layer's period is 4x longer
        assert q.current_bits(10, "flat") == 15
        assert q.current_bits(10, "sharp") == 16
        assert q.current_bits(40, "sharp") == 15
        # nonpositive estimates are clamped, not explosive
        q2 = MoQQuantizer(cfg, layer_eigenvalues={"flat": 0.0, "sharp": 4.0})
        assert q2.period_scale("sharp") <= 4.0 / 1e-6

    def test_moq_engine_eigenvalue_wiring(self, rng):
        """eigenvalue.enabled: the engine probes the Hessian once past the
        schedule offset and layers quantize at per-layer bit widths."""
        engine = build({"quantize_training": {
            "enabled": True,
            "quantize_bits": {"start_bits": 6, "target_bits": 4},
            "quantize_schedule": {"quantize_period": 1,
                                  "schedule_offset": 0},
            "quantize_groups": 1,
            "eigenvalue": {"enabled": True, "max_iter": 30}}})
        for _ in range(3):
            engine.train_batch(mlp_batch(rng))
        assert engine.moq.eigenvalues, "eigenvalues never computed"
        assert set(engine.moq.eigenvalues) == {"w1", "w2"}
        # per-layer schedules differ when eigenvalues differ
        b1 = engine.moq.current_bits(engine.global_steps, "w1")
        b2 = engine.moq.current_bits(engine.global_steps, "w2")
        assert 4 <= min(b1, b2) <= max(b1, b2) <= 6

    def test_moq_eigenvalue_under_cpu_offload(self, rng):
        """Regression (advisor r2): the offload train_batch path must stash
        the probe batch too, or eigenvalue modulation is silently inert."""
        engine = build({"quantize_training": {
            "enabled": True,
            "quantize_bits": {"start_bits": 6, "target_bits": 4},
            "quantize_schedule": {"quantize_period": 1,
                                  "schedule_offset": 0},
            "quantize_groups": 1,
            "eigenvalue": {"enabled": True, "max_iter": 30}},
            "zero_optimization": {
                "offload_optimizer": {"device": "cpu"}}})
        assert engine._train_step is None  # really on the offload tier
        for _ in range(3):
            engine.train_batch(mlp_batch(rng))
        assert engine.moq.eigenvalues, \
            "eigenvalues never computed on the offload path"

    def test_prefetch_put_error_not_swallowed(self):
        from deepspeed_tpu.runtime.dataloader import PrefetchLoader

        def bad_put(b):
            raise StopIteration  # user bug must surface, not end the epoch

        pl = PrefetchLoader([1, 2, 3], put=bad_put)
        with pytest.raises((StopIteration, RuntimeError)):
            list(pl)


class TestPLDWithOneBit:
    """PLD x 1-bit composition (round-3 VERDICT weak #5's last restriction):
    the local-grad shard_map now builds per-leaf batch specs at trace time,
    so the [gas] pld_theta vector rides replicated."""

    def test_trains_and_theta_decays(self, eight_devices):
        import deepspeed_tpu
        from deepspeed_tpu.models import make_gpt
        from deepspeed_tpu.parallel.mesh import build_mesh

        mesh = build_mesh(data=8)
        model, cfg = make_gpt("tiny", dtype=jnp.float32)
        rng = np.random.default_rng(0)
        gas, bs, seq = 2, 8, 32
        batches = {"input_ids": rng.integers(0, cfg.vocab_size,
                                             (gas, bs, seq),
                                             dtype=np.int32)}
        params = model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(1)},
            {"input_ids": batches["input_ids"][0]})["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params, mesh=mesh,
            config={
                "train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": gas,
                "optimizer": {"type": "OneBitAdam",
                              "params": {"lr": 1e-3, "freeze_step": 3}},
                "zero_optimization": {"stage": 1},
                "progressive_layer_drop": {"enabled": True,
                                           "theta": 0.5, "gamma": 0.01},
            })
        losses = [float(engine.train_batch(batches)) for _ in range(8)]
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0] - 0.2, losses
        assert engine.progressive_layer_drop is not None
        assert engine.progressive_layer_drop.current_theta < 1.0
