"""LR schedule tests (reference tests/unit/test_lr_schedulers.py)."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.lr_schedules import (LRRangeTest, OneCycle, WarmupLR,
                                                WarmupDecayLR, build_lr_schedule)


def test_warmup_lr():
    s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=10)
    assert float(s.lr_at(0)) == pytest.approx(0.0)
    assert float(s.lr_at(5)) == pytest.approx(0.05)
    assert float(s.lr_at(10)) == pytest.approx(0.1)
    assert float(s.lr_at(100)) == pytest.approx(0.1)


def test_warmup_decay_lr():
    s = WarmupDecayLR(total_num_steps=100, warmup_min_lr=0.0,
                      warmup_max_lr=0.1, warmup_num_steps=10)
    assert float(s.lr_at(10)) == pytest.approx(0.1)
    assert float(s.lr_at(55)) == pytest.approx(0.05)
    assert float(s.lr_at(100)) == pytest.approx(0.0)


def test_one_cycle_shape():
    s = OneCycle(cycle_min_lr=0.01, cycle_max_lr=0.1, cycle_first_step_size=10)
    assert float(s.lr_at(0)) == pytest.approx(0.01)
    assert float(s.lr_at(10)) == pytest.approx(0.1)
    assert float(s.lr_at(20)) == pytest.approx(0.01)
    # momentum cycles inversely
    assert float(s.momentum_at(0)) == pytest.approx(0.99)
    assert float(s.momentum_at(10)) == pytest.approx(0.85)


def test_lr_range_test():
    s = LRRangeTest(lr_range_test_min_lr=0.01, lr_range_test_step_size=10,
                    lr_range_test_step_rate=1.0)
    assert float(s.lr_at(0)) == pytest.approx(0.01)
    assert float(s.lr_at(10)) == pytest.approx(0.02)
    s2 = LRRangeTest(lr_range_test_min_lr=0.01, lr_range_test_step_size=10,
                     lr_range_test_step_rate=1.0, lr_range_test_staircase=True)
    assert float(s2.lr_at(5)) == pytest.approx(0.01)


def test_stateful_surface():
    s = WarmupLR(warmup_max_lr=0.1, warmup_num_steps=10)
    for _ in range(5):
        s.step()
    assert s.get_lr() == pytest.approx(0.05)
    sd = s.state_dict()
    s2 = WarmupLR(warmup_max_lr=0.1, warmup_num_steps=10)
    s2.load_state_dict(sd)
    assert s2.get_lr() == pytest.approx(s.get_lr())


def test_registry():
    s = build_lr_schedule("WarmupLR", {"warmup_num_steps": 5})
    assert s is not None
    with pytest.raises(ValueError):
        build_lr_schedule("Nope", {})
    assert build_lr_schedule(None, {}) is None


def test_monotone_warmup():
    s = WarmupLR(warmup_max_lr=0.1, warmup_num_steps=100)
    lrs = [float(s.lr_at(i)) for i in range(0, 100, 10)]
    assert all(b >= a for a, b in zip(lrs, lrs[1:]))
    assert not np.isnan(lrs).any()
