"""Launcher unit tests (reference tests/unit/test_dist.py-adjacent +
runner parsing behaviors): hostfile parsing, include/exclude filters,
world-info encoding, per-host env construction, env report."""

import os

import pytest

from deepspeed_tpu.env_report import collect_report
from deepspeed_tpu.launcher import launch, runner


def _write_hostfile(tmp_path, text):
    p = tmp_path / "hostfile"
    p.write_text(text)
    return str(p)


class TestHostfile:
    def test_parse(self, tmp_path):
        hf = _write_hostfile(tmp_path, """
# comment
worker-0 slots=4
worker-1 slots=8   # trailing comment
""")
        res = runner.fetch_hostfile(hf)
        assert res == {"worker-0": 4, "worker-1": 8}
        assert list(res) == ["worker-0", "worker-1"]  # order preserved

    def test_missing_returns_empty(self):
        assert runner.fetch_hostfile("/nonexistent") == {}

    def test_malformed_raises(self, tmp_path):
        hf = _write_hostfile(tmp_path, "worker-0 gpus=4\n")
        with pytest.raises(ValueError, match="malformed"):
            runner.fetch_hostfile(hf)

    def test_duplicate_raises(self, tmp_path):
        hf = _write_hostfile(tmp_path, "w0 slots=2\nw0 slots=2\n")
        with pytest.raises(ValueError, match="duplicates"):
            runner.fetch_hostfile(hf)


class TestFilters:
    def _resources(self):
        from collections import OrderedDict

        return OrderedDict([("w0", 4), ("w1", 4), ("w2", 4)])

    def test_no_filters(self):
        active = runner.parse_inclusion_exclusion(self._resources(), "", "")
        assert active == {"w0": [0, 1, 2, 3], "w1": [0, 1, 2, 3],
                          "w2": [0, 1, 2, 3]}

    def test_include_hosts_and_slots(self):
        active = runner.parse_inclusion_exclusion(
            self._resources(), "w0@w2:0,2", "")
        assert active == {"w0": [0, 1, 2, 3], "w2": [0, 2]}

    def test_exclude_host(self):
        active = runner.parse_inclusion_exclusion(self._resources(), "", "w1")
        assert list(active) == ["w0", "w2"]

    def test_exclude_slots(self):
        active = runner.parse_inclusion_exclusion(
            self._resources(), "", "w0:1,3")
        assert active["w0"] == [0, 2]

    def test_both_filters_raise(self):
        with pytest.raises(ValueError, match="only one"):
            runner.parse_inclusion_exclusion(self._resources(), "w0", "w1")

    def test_unknown_host_raises(self):
        with pytest.raises(ValueError, match="not in hostfile"):
            runner.parse_inclusion_exclusion(self._resources(), "wX", "")


class TestWorldInfo:
    def test_roundtrip(self):
        from collections import OrderedDict

        world = OrderedDict([("w0", [0, 1]), ("w1", [0, 1, 2])])
        blob = runner.encode_world_info(world)
        assert runner.decode_world_info(blob) == {"w0": [0, 1],
                                                  "w1": [0, 1, 2]}


class TestLaunchEnv:
    def test_build_env(self):
        from collections import OrderedDict

        blob = runner.encode_world_info(
            OrderedDict([("hostA", [0]), ("hostB", [0])]))
        env = launch.build_env(blob, 1, "hostA", 29501)
        assert env["DSTPU_COORDINATOR"] == "hostA:29501"
        assert env["DSTPU_NUM_PROCS"] == "2"
        assert env["DSTPU_RANK"] == "1"
        assert env["MASTER_ADDR"] == "hostA"
        assert env["WORLD_SIZE"] == "2"

    def test_bad_node_rank(self):
        from collections import OrderedDict

        blob = runner.encode_world_info(OrderedDict([("hostA", [0])]))
        with pytest.raises(ValueError, match="out of range"):
            launch.build_env(blob, 3, "hostA", 29500)


class TestEnvReport:
    def test_collect(self):
        report = collect_report()
        assert report["packages"]["jax"] is not None
        assert report["platform"] in ("cpu", "tpu")
        assert report["features"]["zero_stages_0_3"]
