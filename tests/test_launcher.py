"""Launcher unit tests (reference tests/unit/test_dist.py-adjacent +
runner parsing behaviors): hostfile parsing, include/exclude filters,
world-info encoding, per-host env construction, env report."""

import os

import pytest

from deepspeed_tpu.env_report import collect_report
from deepspeed_tpu.launcher import launch, runner


def _write_hostfile(tmp_path, text):
    p = tmp_path / "hostfile"
    p.write_text(text)
    return str(p)


class TestHostfile:
    def test_parse(self, tmp_path):
        hf = _write_hostfile(tmp_path, """
# comment
worker-0 slots=4
worker-1 slots=8   # trailing comment
""")
        res = runner.fetch_hostfile(hf)
        assert res == {"worker-0": 4, "worker-1": 8}
        assert list(res) == ["worker-0", "worker-1"]  # order preserved

    def test_missing_returns_empty(self):
        assert runner.fetch_hostfile("/nonexistent") == {}

    def test_malformed_raises(self, tmp_path):
        hf = _write_hostfile(tmp_path, "worker-0 gpus=4\n")
        with pytest.raises(ValueError, match="malformed"):
            runner.fetch_hostfile(hf)

    def test_duplicate_raises(self, tmp_path):
        hf = _write_hostfile(tmp_path, "w0 slots=2\nw0 slots=2\n")
        with pytest.raises(ValueError, match="duplicates"):
            runner.fetch_hostfile(hf)


class TestFilters:
    def _resources(self):
        from collections import OrderedDict

        return OrderedDict([("w0", 4), ("w1", 4), ("w2", 4)])

    def test_no_filters(self):
        active = runner.parse_inclusion_exclusion(self._resources(), "", "")
        assert active == {"w0": [0, 1, 2, 3], "w1": [0, 1, 2, 3],
                          "w2": [0, 1, 2, 3]}

    def test_include_hosts_and_slots(self):
        active = runner.parse_inclusion_exclusion(
            self._resources(), "w0@w2:0,2", "")
        assert active == {"w0": [0, 1, 2, 3], "w2": [0, 2]}

    def test_exclude_host(self):
        active = runner.parse_inclusion_exclusion(self._resources(), "", "w1")
        assert list(active) == ["w0", "w2"]

    def test_exclude_slots(self):
        active = runner.parse_inclusion_exclusion(
            self._resources(), "", "w0:1,3")
        assert active["w0"] == [0, 2]

    def test_both_filters_raise(self):
        with pytest.raises(ValueError, match="only one"):
            runner.parse_inclusion_exclusion(self._resources(), "w0", "w1")

    def test_unknown_host_raises(self):
        with pytest.raises(ValueError, match="not in hostfile"):
            runner.parse_inclusion_exclusion(self._resources(), "wX", "")


class TestWorldInfo:
    def test_roundtrip(self):
        from collections import OrderedDict

        world = OrderedDict([("w0", [0, 1]), ("w1", [0, 1, 2])])
        blob = runner.encode_world_info(world)
        assert runner.decode_world_info(blob) == {"w0": [0, 1],
                                                  "w1": [0, 1, 2]}


class TestLaunchEnv:
    def test_build_env(self):
        from collections import OrderedDict

        blob = runner.encode_world_info(
            OrderedDict([("hostA", [0]), ("hostB", [0])]))
        env = launch.build_env(blob, 1, "hostA", 29501)
        assert env["DSTPU_COORDINATOR"] == "hostA:29501"
        assert env["DSTPU_NUM_PROCS"] == "2"
        assert env["DSTPU_RANK"] == "1"
        assert env["MASTER_ADDR"] == "hostA"
        assert env["WORLD_SIZE"] == "2"

    def test_bad_node_rank(self):
        from collections import OrderedDict

        blob = runner.encode_world_info(OrderedDict([("hostA", [0])]))
        with pytest.raises(ValueError, match="out of range"):
            launch.build_env(blob, 3, "hostA", 29500)


class TestEnvReport:
    def test_collect(self):
        report = collect_report()
        assert report["packages"]["jax"] is not None
        assert report["platform"] in ("cpu", "tpu")
        assert report["features"]["zero_stages_0_3"]


class TestBabysit:
    def test_all_success(self):
        import subprocess
        import sys

        from deepspeed_tpu.launcher.runner import babysit

        procs = [subprocess.Popen([sys.executable, "-c", "pass"])
                 for _ in range(3)]
        assert babysit(procs, poll_interval=0.05) == 0

    def test_failure_kills_survivors(self):
        import subprocess
        import sys
        import time as _t

        from deepspeed_tpu.launcher.runner import babysit

        slow = subprocess.Popen([sys.executable, "-c",
                                 "import time; time.sleep(60)"])
        bad = subprocess.Popen([sys.executable, "-c",
                                "import sys; sys.exit(3)"])
        try:
            called = []
            t0 = _t.time()
            rc = babysit([slow, bad], poll_interval=0.05,
                         on_failure=lambda: called.append(1))
            assert rc == 3
            assert called == [1]
            assert _t.time() - t0 < 30, "survivor was not terminated"
            assert slow.poll() is not None
        finally:
            for p in (slow, bad):
                if p.poll() is None:
                    p.kill()
                p.wait()

    def test_sigterm_ignorer_gets_killed(self):
        import subprocess
        import sys
        import time as _t

        from deepspeed_tpu.launcher.runner import babysit

        stubborn = subprocess.Popen([sys.executable, "-c",
            "import signal, time; signal.signal(signal.SIGTERM, "
            "signal.SIG_IGN); time.sleep(120)"])
        bad = subprocess.Popen([sys.executable, "-c",
                                "import sys; sys.exit(5)"])
        try:
            _t.sleep(0.3)  # let the handler install
            t0 = _t.time()
            rc = babysit([stubborn, bad], poll_interval=0.05,
                         term_timeout=2.0)
            assert rc == 5
            assert _t.time() - t0 < 60, "SIGKILL escalation missing"
            assert stubborn.poll() is not None
        finally:
            for p in (stubborn, bad):
                if p.poll() is None:
                    p.kill()
                p.wait()


class TestDsSsh:
    def test_runs_command_on_hostfile_hosts(self, tmp_path, capsys):
        """ds-ssh-tpu (reference bin/ds_ssh): localhost entries run
        locally so the fan-out is testable without sshd."""
        from deepspeed_tpu.launcher.runner import ds_ssh_main

        hf = tmp_path / "hostfile"
        hf.write_text("localhost slots=4\n")
        rc = ds_ssh_main(["-H", str(hf), "echo", "hello-from-ds-ssh"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "[localhost] hello-from-ds-ssh" in out

    def test_nonzero_exit_propagates(self, tmp_path):
        from deepspeed_tpu.launcher.runner import ds_ssh_main

        hf = tmp_path / "hostfile"
        hf.write_text("localhost slots=4\n")
        rc = ds_ssh_main(["-H", str(hf), "false"])
        assert rc != 0
