"""Guardrails subsystem (guardrails/, docs/RESILIENCE.md "Guardrails"):
EWMA/z-score anomaly detection, in-memory rollback from a snapshot ring,
the step watchdog's diagnostics-dump + distinct-rc contract, the shared
jittered-backoff helper, and the zero-cost-when-disabled guarantee.

The two acceptance gates live here: a FaultPlan-injected NaN-loss window
triggers detection -> in-memory rollback -> replay past the bad window with
a trajectory bit-identical to a clean run of the post-window stream; and a
FaultPlan-injected hang trips the watchdog (diagnostics dump, distinct exit
rc) with supervisor auto-resume — all on CPU.
"""

import json
import os
import random
import sys
import textwrap

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deepspeed_tpu import initialize
from deepspeed_tpu.config.config import ConfigError, DeepSpeedTPUConfig
from deepspeed_tpu.config.constants import \
    GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT
from deepspeed_tpu.guardrails import (OK, SKIP, SPIKE, AnomalyDetector,
                                      EWMATracker, GuardrailsError,
                                      RollbackPolicy, SnapshotRing,
                                      StepWatchdog, backoff_delay,
                                      is_watchdog_exit, restore_snapshot,
                                      retry_call, take_snapshot)
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.resilience import FaultPlan, Supervisor, list_checkpoints
from deepspeed_tpu.runtime.dataloader import RepeatingLoader
from deepspeed_tpu.runtime.utils import has_inf_or_nan

from simple_model import mlp_params, mlp_loss_fn, random_batches

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def _make_engine(guardrails=None, fault_injection=None, extra=None, dp=8):
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 10_000,
    }
    if guardrails is not None:
        config["guardrails"] = guardrails
    if fault_injection is not None:
        config["resilience"] = {"fault_injection": fault_injection}
    config.update(extra or {})
    engine, _, _, _ = initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(), config=config,
        mesh=build_mesh(data=dp, devices=jax.devices()[:dp]), rng_seed=0)
    return engine


def _stream(n, seed=7, batch_size=16):
    rng = np.random.default_rng(seed)
    return [random_batches(rng, 1, batch_size=batch_size) for _ in range(n)]


def _params_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(a)),
                    jax.tree_util.tree_leaves(jax.device_get(b))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=0, atol=0)


def _params_finite(tree) -> bool:
    flags = jax.jit(lambda t: jnp.stack(
        [jnp.all(jnp.isfinite(x.astype(jnp.float32)))
         for x in jax.tree_util.tree_leaves(t)]))(tree)
    return bool(jnp.all(flags))


# ---------------------------------------------------------------------------
# Shared retry helper
# ---------------------------------------------------------------------------

class TestRetry:
    def test_exponential_schedule_no_jitter(self):
        delays = [backoff_delay(a, 0.5, jitter=0.0) for a in range(4)]
        assert delays == [0.5, 1.0, 2.0, 4.0]

    def test_cap_applies_before_jitter(self):
        rng = random.Random(0)
        for a in range(20):
            d = backoff_delay(a, 1.0, max_delay=5.0, jitter=0.25, rng=rng)
            assert d <= 5.0 * 1.25 + 1e-9
        # a huge attempt index must not overflow
        assert backoff_delay(10_000, 1.0, max_delay=5.0, jitter=0.0) == 5.0

    def test_jitter_bounds_and_determinism(self):
        d1 = backoff_delay(3, 1.0, jitter=0.25, rng=random.Random(42))
        d2 = backoff_delay(3, 1.0, jitter=0.25, rng=random.Random(42))
        assert d1 == d2
        assert 8.0 * 0.75 <= d1 <= 8.0 * 1.25

    def test_validation(self):
        with pytest.raises(ValueError):
            backoff_delay(-1, 1.0)
        with pytest.raises(ValueError):
            backoff_delay(0, 1.0, jitter=1.5)

    def test_retry_call_retries_then_succeeds(self):
        calls, slept = [], []
        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"
        out = retry_call(flaky, max_retries=3, base=0.01, jitter=0.0,
                         sleep=slept.append)
        assert out == "ok" and len(calls) == 3
        assert slept == [0.01, 0.02]

    def test_retry_call_terminal_raises(self):
        slept = []
        def always():
            raise OSError("permanent")
        with pytest.raises(OSError, match="permanent"):
            retry_call(always, max_retries=2, base=0.01, jitter=0.0,
                       sleep=slept.append)
        assert len(slept) == 2


# ---------------------------------------------------------------------------
# Anomaly detector
# ---------------------------------------------------------------------------

class TestDetector:
    def test_warmup_absorbs_descent(self):
        det = AnomalyDetector(zscore_threshold=3.0, warmup_steps=10)
        # steep early descent: would be wildly out-of-distribution if the
        # z-score gate were armed from step 1
        for i, loss in enumerate([10.0, 6.0, 4.0, 3.0, 2.5, 2.2, 2.0]):
            assert det.observe(i, loss).kind == OK

    def test_nonfinite_is_spike_even_in_warmup(self):
        det = AnomalyDetector(warmup_steps=100)
        v = det.observe(0, float("nan"))
        assert v.kind == SPIKE and v.reason == "nonfinite"
        v = det.observe(1, 1.0, grad_norm=float("inf"))
        assert v.kind == SPIKE and v.reason == "nonfinite"

    def test_zscore_spike_not_absorbed_into_baseline(self):
        det = AnomalyDetector(zscore_threshold=4.0, warmup_steps=5,
                              ewma_alpha=0.1)
        for i in range(20):
            assert det.observe(i, 1.0 + 0.01 * ((-1) ** i)).kind == OK
        mean_before = det.loss_tracker.mean
        v = det.observe(20, 50.0)
        assert v.kind == SPIKE and v.reason == "zscore" and v.loss_z > 4.0
        assert det.loss_tracker.mean == mean_before  # spike excluded
        # the same spike magnitude again is still a spike (no drift)
        assert det.observe(21, 50.0).kind == SPIKE

    def test_grad_norm_spike(self):
        det = AnomalyDetector(zscore_threshold=4.0, warmup_steps=5,
                              ewma_alpha=0.1)
        for i in range(10):
            det.observe(i, 1.0 + 0.01 * (i % 2), grad_norm=2.0 + 0.01 * (i % 2))
        v = det.observe(10, 1.0, grad_norm=100.0)
        assert v.kind == SPIKE and v.norm_z > 4.0

    def test_overflow_is_skip_and_not_learned(self):
        det = AnomalyDetector(warmup_steps=2)
        det.observe(0, 1.0)
        count = det.loss_tracker.count
        v = det.observe(1, float("nan"), overflow=True)
        assert v.kind == SKIP and v.reason == "overflow"
        assert det.loss_tracker.count == count
        assert det.stats[SKIP] == 1

    def test_tracker_state_roundtrip_and_sigma_floor(self):
        t = EWMATracker(alpha=0.1)
        for x in [1.0, 1.0, 1.0]:
            t.update(x)
        assert t.sigma() > 0  # floor keeps z finite on a flat signal
        t2 = EWMATracker(alpha=0.1)
        t2.load_state_dict(t.state_dict())
        assert t2.mean == t.mean and t2.var == t.var and t2.count == t.count

    def test_validation(self):
        with pytest.raises(ValueError):
            AnomalyDetector(zscore_threshold=0.0)
        with pytest.raises(ValueError):
            AnomalyDetector(warmup_steps=0)
        with pytest.raises(ValueError):
            EWMATracker(alpha=0.0)


# ---------------------------------------------------------------------------
# has_inf_or_nan: native-dtype check (satellite)
# ---------------------------------------------------------------------------

class TestHasInfOrNan:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16,
                                       jnp.float16])
    def test_dtype_coverage(self, dtype):
        clean = {"a": jnp.ones((4, 4), dtype), "b": jnp.zeros((3,), dtype)}
        assert not bool(has_inf_or_nan(clean))
        dirty = dict(clean, b=jnp.array([1.0, jnp.nan, 2.0], dtype))
        assert bool(has_inf_or_nan(dirty))
        inf_t = dict(clean, a=jnp.full((4, 4), jnp.inf, dtype))
        assert bool(has_inf_or_nan(inf_t))

    def test_int_leaves_skipped(self):
        tree = {"step": jnp.array(3, jnp.int32),
                "w": jnp.ones((2,), jnp.float32)}
        assert not bool(has_inf_or_nan(tree))
        assert not bool(has_inf_or_nan({"step": jnp.array(3, jnp.int32)}))

    def test_no_fp32_upcast_for_half_precision(self):
        """The satellite's point: the predicate reads bf16/fp16 leaves in
        native dtype — no convert_element_type widening in the jaxpr."""
        tree = {"a": jnp.ones((8, 8), jnp.bfloat16),
                "b": jnp.ones((8,), jnp.float16)}
        jaxpr = str(jax.make_jaxpr(has_inf_or_nan)(tree))
        assert "convert_element_type" not in jaxpr

    def test_empty_tree(self):
        assert not bool(has_inf_or_nan({}))

    def test_fp16_overflow_semantics_kept(self):
        # fp16 inf (overflowed grad) must still be flagged — the loss
        # scaler's skip decision rides on it.
        big = jnp.array([65504.0], jnp.float16) * 2  # -> inf in fp16
        assert bool(has_inf_or_nan({"g": big}))


# ---------------------------------------------------------------------------
# RepeatingLoader: replay + skip (satellite)
# ---------------------------------------------------------------------------

class _CountingSampler:
    def __init__(self):
        self.epoch = 0

    def set_epoch(self, e):
        self.epoch = e


class _ListLoader:
    """Epoch-aware toy loader: item values encode (epoch, position)."""

    def __init__(self, n):
        self.n = n
        self.sampler = _CountingSampler()

    def __iter__(self):
        base = self.sampler.epoch * 100
        return iter(range(base, base + self.n))


class TestRepeatingLoaderReplaySkip:
    def test_skip_batches_matches_consumption(self):
        a, b = RepeatingLoader(_ListLoader(5)), RepeatingLoader(_ListLoader(5))
        for _ in range(3):
            next(a)
        a.skip_batches(4)                   # crosses the epoch boundary
        for _ in range(7):
            next(b)
        assert a.state_dict() == b.state_dict()
        assert next(a) == next(b)           # identical continuation

    def test_state_roundtrip_with_rollback_skip(self):
        """The rollback shape: consume, checkpoint (state_dict), consume a
        bad window, restore (load_state_dict), skip past the window — the
        stream continues exactly where a clean run that never saw the
        window would be."""
        src = RepeatingLoader(_ListLoader(4))
        for _ in range(3):
            next(src)
        saved = src.state_dict()
        for _ in range(2):
            next(src)                        # the poisoned window

        resumed = RepeatingLoader(_ListLoader(4))
        resumed.load_state_dict(saved)       # replay to the checkpoint
        resumed.skip_batches(2)              # advance past the bad window
        assert resumed.state_dict() == src.state_dict()
        assert [next(resumed) for _ in range(5)] == \
               [next(src) for _ in range(5)]

    def test_skip_across_epoch_boundary_restarts_iterator(self):
        """The __next__ StopIteration-restart edge: a skip landing exactly
        on the boundary rolls the epoch and re-seeds the sampler."""
        src = RepeatingLoader(_ListLoader(3))
        src.skip_batches(3)                  # consumes exactly one epoch
        assert src.state_dict() == {"epoch": 0, "batch_in_epoch": 3}
        assert next(src) == 100              # epoch 1 content (sampler-seeded)
        assert src.state_dict() == {"epoch": 1, "batch_in_epoch": 1}

    def test_skip_validation_and_zero(self):
        src = RepeatingLoader(_ListLoader(3))
        assert src.skip_batches(0) == 0
        assert src.state_dict() == {"epoch": 0, "batch_in_epoch": 0}
        with pytest.raises(ValueError):
            src.skip_batches(-1)


# ---------------------------------------------------------------------------
# Supervisor backoff cap + jitter + watchdog rc (satellite)
# ---------------------------------------------------------------------------

class TestSupervisorBackoff:
    def _sleeps(self, monkeypatch):
        from deepspeed_tpu.resilience import supervisor as sup_mod
        rec = []
        monkeypatch.setattr(sup_mod.time, "sleep", rec.append)
        return rec

    def test_delay_is_capped(self, monkeypatch):
        rec = self._sleeps(monkeypatch)
        sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(3)"],
                         max_restarts=6, backoff=10.0, max_backoff=0.5,
                         jitter=0.25)
        assert sup.run() == 3
        assert len(rec) == 6
        assert all(d <= 0.5 * 1.25 + 1e-9 for d in rec)   # capped (pre-jitter)
        assert all(d > 0 for d in rec)

    def test_watchdog_rc_restarts_immediately(self, monkeypatch, tmp_path):
        rec = self._sleeps(monkeypatch)
        marker = tmp_path / "died_once"
        rc = GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT
        script = textwrap.dedent(f"""
            import os, sys
            marker = {str(marker)!r}
            if not os.path.exists(marker):
                open(marker, "w").close()
                sys.exit({rc})   # watchdog-style death
            sys.exit(0)
        """)
        sup = Supervisor([sys.executable, "-c", script], max_restarts=3,
                         backoff=10.0)
        assert sup.run() == 0
        assert sup.exit_codes == [rc, 0]
        assert sup.immediate_restarts == 1
        assert rec == []                      # no backoff sleep at all
        assert is_watchdog_exit(rc) and not is_watchdog_exit(0)

    def test_custom_immediate_rc(self, monkeypatch, tmp_path):
        """A config-overridden watchdog exit_code keeps the no-backoff
        contract when passed through immediate_restart_rcs."""
        rec = self._sleeps(monkeypatch)
        marker = tmp_path / "died_once"
        script = textwrap.dedent(f"""
            import os, sys
            marker = {str(marker)!r}
            if not os.path.exists(marker):
                open(marker, "w").close()
                sys.exit(77)
            sys.exit(0)
        """)
        sup = Supervisor([sys.executable, "-c", script], max_restarts=3,
                         backoff=10.0, immediate_restart_rcs={77})
        assert sup.run() == 0
        assert sup.immediate_restarts == 1 and rec == []


# ---------------------------------------------------------------------------
# Config block
# ---------------------------------------------------------------------------

class TestGuardrailsConfig:
    BASE = {"train_micro_batch_size_per_gpu": 1}

    def test_defaults_off(self):
        cfg = DeepSpeedTPUConfig(dict(self.BASE))
        assert cfg.guardrails.enabled is False
        assert cfg.guardrails.nonfinite_grad_check is False
        assert cfg.guardrails.watchdog.enabled is False

    def test_nonfinite_gate_needs_both_flags(self):
        on = DeepSpeedTPUConfig({**self.BASE, "guardrails": {
            "enabled": True, "detector": {"check_nonfinite_grads": True}}})
        assert on.guardrails.nonfinite_grad_check is True
        half = DeepSpeedTPUConfig({**self.BASE, "guardrails": {
            "enabled": False, "detector": {"check_nonfinite_grads": True}}})
        assert half.guardrails.nonfinite_grad_check is False

    @pytest.mark.parametrize("block,match", [
        ({"detector": {"zscore_threshold": 0}}, "zscore_threshold"),
        ({"detector": {"warmup_steps": 0}}, "warmup_steps"),
        ({"detector": {"ewma_alpha": 0}}, "ewma_alpha"),
        ({"rollback": {"ring_size": 0}}, "ring_size"),
        ({"rollback": {"consecutive_spikes": 0}}, "consecutive_spikes"),
        ({"rollback": {"snapshot_interval": 0}}, "snapshot_interval"),
        ({"rollback": {"lr_decay": 0}}, "lr_decay"),
        ({"rollback": {"max_rollbacks": 0}}, "max_rollbacks"),
        ({"watchdog": {"enabled": True, "step_timeout_seconds": 0}},
         "step_timeout_seconds"),
        ({"watchdog": {"poll_interval_seconds": -1}},
         "poll_interval_seconds"),
        ({"watchdog": {"exit_code": 0}}, "exit_code"),
    ])
    def test_validation(self, block, match):
        with pytest.raises(ConfigError, match=match):
            DeepSpeedTPUConfig({**self.BASE,
                                "guardrails": {"enabled": True, **block}})

    def test_fault_plan_new_keys(self, monkeypatch):
        plan = FaultPlan.resolve({"nan_loss_at_step": 4, "nan_loss_steps": 2,
                                  "hang_at_step": 7})
        assert not plan.should_nan_loss(3)
        assert plan.should_nan_loss(4) and plan.should_nan_loss(5)
        assert not plan.should_nan_loss(6)
        assert plan.should_hang(7) and not plan.should_hang(8)
        monkeypatch.setenv("DSTPU_FAULT_PLAN", '{"hang_at_step": 2}')
        assert FaultPlan.resolve({}).should_hang(2)

    def test_poison_batch_floats_only(self):
        plan = FaultPlan(nan_loss_at_step=1)
        out = plan.poison_batch({"x": np.ones((2, 2), np.float32),
                                 "ids": np.ones((2,), np.int32)})
        assert np.isnan(out["x"]).all()
        assert (out["ids"] == 1).all()


# ---------------------------------------------------------------------------
# Snapshot ring + rollback policy
# ---------------------------------------------------------------------------

class TestRollback:
    def test_ring_bounded_newest_wins(self):
        ring = SnapshotRing(capacity=2)
        for i in range(5):
            ring.push(i)
        assert len(ring) == 2 and ring.newest() == 4
        ring.drop_newest()
        assert ring.newest() == 3
        with pytest.raises(ValueError):
            SnapshotRing(0)

    def test_snapshot_restore_bit_exact(self):
        engine = _make_engine()
        for b in _stream(3):
            engine.train_batch(b)
        snap = take_snapshot(engine)
        params_at_3 = jax.device_get(engine.state.params)
        for b in _stream(2, seed=11):
            engine.train_batch(b)
        assert engine.global_steps == 5
        rewound = restore_snapshot(engine, snap)
        assert rewound == 2 and engine.global_steps == 3
        _params_equal(engine.state.params, params_at_3)
        # continuation after restore is bit-identical to a fresh engine
        # trained on the same prefix (rng/opt_state restored too)
        fresh = _make_engine()
        for b in _stream(3):
            fresh.train_batch(b)
        tail = _stream(2, seed=23)
        got = [repr(float(engine.train_batch(b))) for b in tail]
        want = [repr(float(fresh.train_batch(b))) for b in tail]
        assert got == want

    def test_policy_streak_and_budget(self):
        ring = SnapshotRing(2)
        pol = RollbackPolicy(ring, consecutive_spikes=3)
        assert not pol.note_spike() and not pol.note_spike()
        pol.note_ok()                       # streak resets
        assert not pol.note_spike() and not pol.note_spike()
        assert pol.note_spike()             # third consecutive

    def test_policy_exhausted_budget_raises(self):
        engine = _make_engine()
        ring = SnapshotRing(4)
        pol = RollbackPolicy(ring, consecutive_spikes=1, max_rollbacks=1,
                             skip_batches=0)
        engine.train_batch(_stream(1)[0])
        ring.push(take_snapshot(engine))
        ring.push(take_snapshot(engine))
        pol.rollback(engine)
        with pytest.raises(GuardrailsError, match="budget exhausted"):
            pol.rollback(engine)

    def test_empty_ring_without_disk_raises(self):
        engine = _make_engine()
        pol = RollbackPolicy(SnapshotRing(1), consecutive_spikes=1,
                             escalate_to_disk=False)
        with pytest.raises(GuardrailsError, match="no in-memory snapshot"):
            pol.rollback(engine)

    def test_empty_ring_escalates_to_disk(self, tmp_path):
        engine = _make_engine(extra={"resilience": {
            "enabled": True,
            "checkpoint": {"dir": str(tmp_path), "interval": 100,
                           "backoff_seconds": 0.01}}})
        for b in _stream(2):
            engine.train_batch(b)
        engine.save_checkpoint_async()
        engine.ckpt_manager.wait()
        params_at_2 = jax.device_get(engine.state.params)
        engine.train_batch(_stream(1, seed=9)[0])
        pol = RollbackPolicy(SnapshotRing(1), consecutive_spikes=1,
                             skip_batches=0)
        summary = pol.rollback(engine)
        assert summary["source"] == "disk"
        assert engine.global_steps == 2
        _params_equal(engine.state.params, params_at_2)
        engine.ckpt_manager.close()

    def test_lr_decay_applies_on_rollback(self):
        engine = _make_engine()
        engine.train_batch(_stream(1)[0])
        gr_ring = SnapshotRing(1)
        gr_ring.push(take_snapshot(engine))
        pol = RollbackPolicy(gr_ring, consecutive_spikes=1, lr_decay=0.5,
                             skip_batches=0)
        pol.rollback(engine)
        assert pol.lr_scale == 0.5


# ---------------------------------------------------------------------------
# bf16/fp32 skip-on-nonfinite (engine.py:548 satellite)
# ---------------------------------------------------------------------------

class TestNonfiniteGradSkip:
    def _poisoned_stream(self):
        s = _stream(4)
        bad = {k: v.copy() for k, v in s[1].items()}
        bad["x"][:] = np.nan
        s[1] = bad
        return s

    def test_gate_on_skips_step_params_stay_finite(self):
        engine = _make_engine(
            guardrails={"enabled": True,
                        "detector": {"check_nonfinite_grads": True},
                        "rollback": {"enabled": False}},
            extra={"bf16": {"enabled": True}})
        s = self._poisoned_stream()
        engine.train_batch(s[0])
        params_before = jax.device_get(engine.state.params)
        engine.train_batch(s[1])                       # poisoned
        assert engine.skipped_steps == 1
        assert int(engine.state.step) == 1             # update refused
        _params_equal(engine.state.params, params_before)
        assert engine.guardrails.last_verdict.kind == SKIP
        engine.train_batch(s[2])
        assert int(engine.state.step) == 2
        assert _params_finite(engine.state.params)

    def test_gate_off_nan_commits(self):
        engine = _make_engine(extra={"bf16": {"enabled": True}})
        s = self._poisoned_stream()
        engine.train_batch(s[0])
        engine.train_batch(s[1])                       # poisoned, no gate
        assert engine.skipped_steps == 0
        assert not _params_finite(engine.state.params)  # the failure mode


# ---------------------------------------------------------------------------
# Zero cost when disabled (acceptance)
# ---------------------------------------------------------------------------

class TestZeroCostDisabled:
    def test_no_syncs_no_fetches_no_snapshots(self, monkeypatch):
        """Guardrails fully disabled => zero guardrails-originated host
        fetches AND zero telemetry-originated device syncs over a 10-step
        loop (the same contract/counting style as PR 2's zero-sync test)."""
        import deepspeed_tpu.guardrails as gr_mod
        from deepspeed_tpu.utils import timer as timer_mod
        fetches, syncs = {"n": 0}, {"n": 0}
        orig_fetch = gr_mod._host_fetch
        monkeypatch.setattr(gr_mod, "_host_fetch",
                            lambda x: (fetches.__setitem__("n", fetches["n"] + 1),
                                       orig_fetch(x))[1])
        monkeypatch.setattr(timer_mod, "_device_synchronize",
                            lambda: syncs.__setitem__("n", syncs["n"] + 1))
        import deepspeed_tpu.resilience.checkpoint as ckpt_mod
        snaps = {"n": 0}
        orig_snap = ckpt_mod.snapshot_engine
        monkeypatch.setattr(
            ckpt_mod, "snapshot_engine",
            lambda *a, **k: (snaps.__setitem__("n", snaps["n"] + 1),
                             orig_snap(*a, **k))[1])

        engine = _make_engine()                        # default: all off
        assert engine.guardrails is None
        for b in _stream(10):
            engine.train_batch(b)
        jax.block_until_ready(engine.state.params)
        assert fetches["n"] == 0
        assert syncs["n"] == 0
        assert snaps["n"] == 0

    def test_offload_tier_feeds_grad_norm(self):
        """The ZeRO-offload step path must feed the detector the unscaled
        grad norm like the device tiers do (it was silently None)."""
        engine = _make_engine(
            guardrails={"enabled": True, "rollback": {"enabled": False}},
            extra={"zero_optimization": {
                "stage": 1, "offload_optimizer": {"device": "cpu"}}})
        for b in _stream(3):
            engine.train_batch(b)
        det = engine.guardrails.detector
        assert det.stats[OK] == 3
        assert det.norm_tracker.count == 3      # norm observed every step
        assert det.norm_tracker.mean > 0.0

    def test_enabled_fetches_are_counted(self, monkeypatch):
        import deepspeed_tpu.guardrails as gr_mod
        fetches = {"n": 0}
        orig_fetch = gr_mod._host_fetch
        monkeypatch.setattr(gr_mod, "_host_fetch",
                            lambda x: (fetches.__setitem__("n", fetches["n"] + 1),
                                       orig_fetch(x))[1])
        engine = _make_engine(guardrails={"enabled": True,
                                          "rollback": {"enabled": False}})
        for b in _stream(3):
            engine.train_batch(b)
        assert fetches["n"] > 0
        assert engine.guardrails.detector.stats[OK] == 3


# ---------------------------------------------------------------------------
# E2E: NaN-loss window -> detect -> in-memory rollback -> replay past it
# ---------------------------------------------------------------------------

class _StreamLoader:
    def __init__(self, stream):
        self.stream = stream

    def __iter__(self):
        return iter(self.stream)


class TestRollbackEndToEnd:
    def test_nan_window_rollback_bit_identical_tail(self):
        """Acceptance: FaultPlan NaN-poisons the batches for step attempts
        [k+1, k+2] (consecutive_spikes=2 -> rollback to the step-k ring
        snapshot, the poisoned positions already consumed). The guarded
        run's trajectory must then be BIT-IDENTICAL to a clean run fed the
        same stream with the poisoned window excised — detection, restore
        and replay cost exactly the bad window, nothing else."""
        k, total = 4, 10
        stream = _stream(total + 2)
        guarded = _make_engine(
            guardrails={"enabled": True,
                        # stat gate effectively off: only nonfinite trips
                        "detector": {"zscore_threshold": 1e9,
                                     "warmup_steps": 1},
                        "rollback": {"snapshot_interval": 1, "ring_size": 2,
                                     "consecutive_spikes": 2,
                                     "skip_batches": 0}},
            fault_injection={"nan_loss_at_step": k + 1, "nan_loss_steps": 2})
        loader = RepeatingLoader(_StreamLoader(stream))
        guarded.register_data_skip_fn(loader.skip_batches)
        guarded_losses = {}
        attempts = 0
        while guarded.global_steps < total:
            before = guarded.global_steps
            loss = guarded.train_batch(next(loader))
            if guarded.global_steps == before + 1:
                # committed step (a rollback iteration rewinds instead;
                # its loss belongs to no surviving step). Re-committed
                # steps overwrite their poisoned first attempt.
                guarded_losses[guarded.global_steps] = repr(float(loss))
            attempts += 1
            assert attempts < 50, "rollback did not converge"

        # exactly one rollback, at the configured streak
        assert guarded.guardrails.policy.rollbacks == 1
        assert guarded.guardrails.detector.stats[SPIKE] == 2
        assert _params_finite(guarded.state.params)
        # every COMMITTED step's loss is finite (the NaN attempts were
        # rolled back and re-keyed to the restored step numbers)
        assert all(np.isfinite(float(v.strip("'")))
                   for v in guarded_losses.values())

        # clean run: same stream minus the two poisoned positions (k, k+1)
        clean = _make_engine()
        clean_stream = stream[:k] + stream[k + 2:]
        clean_losses = {}
        for i in range(total):
            loss = clean.train_batch(clean_stream[i])
            clean_losses[clean.global_steps] = repr(float(loss))

        assert guarded_losses == clean_losses   # bit-identical, full run
        _params_equal(guarded.state.params, clean.state.params)

    def test_spike_steps_never_checkpointed(self, tmp_path):
        """The interval auto-save is verdict-gated: a spike-committed
        (NaN) state must never become the newest on-disk checkpoint —
        it is exactly what escalation and post-watchdog auto-resume
        would restore."""
        engine = _make_engine(
            guardrails={"enabled": True,
                        "detector": {"zscore_threshold": 1e9,
                                     "warmup_steps": 1},
                        "rollback": {"snapshot_interval": 1,
                                     "consecutive_spikes": 2,
                                     "skip_batches": 0}},
            fault_injection={"nan_loss_at_step": 3, "nan_loss_steps": 2},
            extra={"resilience": {
                "enabled": True,
                "fault_injection": {"nan_loss_at_step": 3,
                                    "nan_loss_steps": 2},
                "checkpoint": {"dir": str(tmp_path), "interval": 1,
                               "backoff_seconds": 0.01}}})
        stream = _stream(10)
        i = 0
        while engine.global_steps < 6:
            engine.train_batch(stream[i % len(stream)])
            i += 1
        engine.ckpt_manager.wait()
        from deepspeed_tpu.resilience import find_restorable
        # every committed checkpoint holds finite params — the two NaN
        # spike steps (attempts 3, 4 -> steps 3 and 4 pre-rollback) were
        # skipped by the verdict gate
        for step, path in list_checkpoints(str(tmp_path)):
            found = find_restorable(str(tmp_path))
            assert found is not None
        _, manifest, arrays, _ = find_restorable(str(tmp_path))
        for name, arr in arrays.items():
            if name.startswith("params"):
                assert np.isfinite(arr).all(), name
        assert engine.guardrails.policy.rollbacks == 1
        engine.ckpt_manager.close()

    def test_rollback_emits_telemetry(self, tmp_path):
        engine = _make_engine(
            guardrails={"enabled": True,
                        "detector": {"zscore_threshold": 1e9,
                                     "warmup_steps": 1},
                        "rollback": {"snapshot_interval": 1,
                                     "consecutive_spikes": 1,
                                     "skip_batches": 0}},
            fault_injection={"nan_loss_at_step": 3},
            extra={"telemetry": {"enabled": True, "dir": str(tmp_path),
                                 "trace": {"sync_spans": False}}})
        stream = _stream(8)
        i = 0
        while engine.global_steps < 5:
            engine.train_batch(stream[i % len(stream)])
            i += 1
        engine.telemetry.flush()
        rows = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
        tags = {r["tag"] for r in rows}
        assert "guardrails/steps_ok" in tags
        assert "guardrails/steps_spike" in tags
        assert "guardrails/rollbacks" in tags
        assert "guardrails/snapshots" in tags
        assert "guardrails/loss_zscore" in tags
        doc = json.load(open(tmp_path / "trace.json"))
        instants = {e["name"] for e in doc["traceEvents"]
                    if e.get("ph") == "i"}
        assert {"guardrails_spike", "guardrails_rollback"} <= instants


# ---------------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------------

class TestWatchdog:
    def test_trip_dumps_and_exits_with_rc(self, tmp_path):
        exits = []
        wd = StepWatchdog(timeout=0.15, crashdump_dir=str(tmp_path),
                          poll_interval=0.02, exit_fn=exits.append)
        wd.start()
        wd.step_begin(7, label="unit_test_step")
        import time
        time.sleep(0.6)
        wd.stop()
        assert wd.tripped and exits == [GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT]
        dumps = os.listdir(tmp_path)
        assert len(dumps) == 1 and dumps[0].startswith("watchdog_step7")
        ddir = tmp_path / dumps[0]
        info = json.load(open(ddir / "info.json"))
        assert info["step"] == 7 and info["label"] == "unit_test_step"
        assert info["elapsed_sec"] > 0.15
        stacks = open(ddir / "stacks.txt").read()
        assert "Thread" in stacks or "File" in stacks  # faulthandler output

    def test_idle_never_trips(self, tmp_path):
        exits = []
        wd = StepWatchdog(timeout=0.05, crashdump_dir=str(tmp_path),
                          poll_interval=0.01, exit_fn=exits.append)
        wd.start()
        import time
        time.sleep(0.3)           # never armed: between-step idle is fine
        wd.stop()
        assert not wd.tripped and exits == []

    def test_reentrant_brackets(self, tmp_path):
        exits = []
        wd = StepWatchdog(timeout=10.0, crashdump_dir=str(tmp_path),
                          exit_fn=exits.append)
        wd.step_begin(1, label="outer")
        wd.step_begin(1, label="inner")   # depth 2: must not re-arm
        assert wd._label == "outer"
        wd.step_end()
        assert wd._armed_at is not None   # still armed at depth 1
        wd.step_end()
        assert wd._armed_at is None

    def test_suspend_disarms_at_any_depth(self, tmp_path):
        """Rollback recovery calls suspend() from inside the (possibly
        nested pipe) bracket: fully disarmed, and the enclosing step_end
        finallys re-balance without going negative."""
        wd = StepWatchdog(timeout=10.0, crashdump_dir=str(tmp_path),
                          exit_fn=lambda rc: None)
        wd.step_begin(1, label="pipe_step")
        wd.step_begin(1)                  # nested base bracket
        wd.suspend()
        assert wd._armed_at is None and wd._depth == 0
        wd.step_end()
        wd.step_end()                     # clamped, no underflow
        assert wd._depth == 0
        wd.step_begin(2)                  # next step re-arms cleanly
        assert wd._armed_at is not None
        wd.step_end()

    def test_validation(self):
        with pytest.raises(ValueError):
            StepWatchdog(timeout=0)
        with pytest.raises(ValueError, match="poll_interval"):
            StepWatchdog(timeout=1.0, poll_interval=-0.5)


# ---------------------------------------------------------------------------
# E2E: injected hang -> watchdog dump + distinct rc -> supervisor resume
# ---------------------------------------------------------------------------

_HANG_SCRIPT = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, sys.argv[5])
    import numpy as np
    from deepspeed_tpu import initialize
    from deepspeed_tpu.parallel.mesh import build_mesh
    from simple_model import mlp_params, mlp_loss_fn, random_batches

    ckpt_dir, dump_dir, total, out = (sys.argv[1], sys.argv[2],
                                      int(sys.argv[3]), sys.argv[4])
    engine, _, _, _ = initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 1000,
            "resilience": {"enabled": True,
                           "checkpoint": {"dir": ckpt_dir, "interval": 1,
                                          "backoff_seconds": 0.01}},
            "guardrails": {"enabled": True,
                           "rollback": {"enabled": False},
                           "watchdog": {"enabled": True,
                                        "step_timeout_seconds": 1.0,
                                        "poll_interval_seconds": 0.05,
                                        "crashdump_dir": dump_dir}},
        },
        mesh=build_mesh(data=8), rng_seed=0)
    engine.auto_resume()
    rng = np.random.default_rng(7)
    stream = [random_batches(rng, 1, batch_size=16) for _ in range(total)]
    with open(out, "a", buffering=1) as f:
        for i in range(engine.global_steps, total):
            loss = float(engine.train_batch(stream[i]))
            f.write(json.dumps({"step": i + 1, "loss": repr(loss)}) + "\\n")
    engine.ckpt_manager.close()
""")


def test_hang_watchdog_supervisor_resume(tmp_path):
    """Acceptance: a FaultPlan-injected hang at step 3 trips the watchdog
    (diagnostics dump, distinct rc), the supervisor restarts IMMEDIATELY
    (no backoff) and the resumed incarnation finishes the run."""
    total = 6
    ckpt, dump = tmp_path / "ckpt", tmp_path / "dump"
    out = tmp_path / "losses.jsonl"
    sup = Supervisor(
        [sys.executable, "-c", _HANG_SCRIPT, str(ckpt), str(dump),
         str(total), str(out), TESTS_DIR],
        max_restarts=2, backoff=30.0,    # a backoff sleep would time out
        env={"JAX_PLATFORMS": "cpu",
             "DSTPU_FAULT_PLAN": json.dumps(
                 {"hang_at_step": 3, "hang_seconds": 120})})
    rc = sup.run()
    assert rc == 0
    assert sup.exit_codes[0] == GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT
    assert sup.immediate_restarts == 1 and sup.restarts == 1

    # the dump holds thread stacks naming the hang site
    dumps = [d for d in os.listdir(dump) if d.startswith("watchdog_")]
    assert len(dumps) == 1
    stacks = open(dump / dumps[0] / "stacks.txt").read()
    assert "hang" in stacks          # FaultPlan.hang's sleep frame
    info = json.load(open(dump / dumps[0] / "info.json"))
    assert info["exit_code"] == GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT

    # the run completed every step, resuming from a committed checkpoint
    steps = {json.loads(l)["step"] for l in open(out)}
    assert steps == set(range(1, total + 1))
    assert [s for s, _ in list_checkpoints(str(ckpt))][-1] == total
