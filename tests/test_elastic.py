"""Elasticity config math tests (reference ``tests/unit/test_elastic.py``)."""

import pytest

from deepspeed_tpu.config.config import DeepSpeedTPUConfig
from deepspeed_tpu.elasticity import (
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    highly_composite_numbers,
)
from deepspeed_tpu.version import __version__


def base_config():
    return {
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 10000,
            "micro_batch_sizes": [8, 12, 16, 17],
            "min_gpus": 32,
            "max_gpus": 1500,
            "min_time": 20,
            "version": 0.1,
        }
    }


def test_basic_10k():
    # The reference's canonical case (test_elastic.py:23): 9792 with 23
    # valid chip counts.
    batch, valid = compute_elastic_config(base_config(), __version__)
    assert batch == 9792
    assert len(valid) == 23
    micro_batches = base_config()["elasticity"]["micro_batch_sizes"]
    for w in valid:
        assert batch % w == 0
        assert any((batch // w) % mb == 0 for mb in micro_batches)


def test_hcn_generation_matches_known_sequence():
    # First entries of the true HCN sequence (the reference hardcodes these,
    # elasticity.py:21; we generate them).
    assert highly_composite_numbers(720720) == (
        1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840,
        1260, 1680, 2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720,
        45360, 50400, 55440, 83160, 110880, 166320, 221760, 277200,
        332640, 498960, 554400, 665280, 720720)


def test_old_version():
    with pytest.raises(ElasticityError):
        compute_elastic_config(base_config(), "0.0.1")


def test_disabled():
    cfg = base_config()
    cfg["elasticity"]["enabled"] = False
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(cfg, __version__)


def test_valid_world_size():
    batch, valid, micro = compute_elastic_config(
        base_config(), __version__, world_size=64)
    assert micro == 17


def test_invalid_world_size():
    with pytest.raises(ElasticityIncompatibleWorldSize):
        compute_elastic_config(base_config(), __version__, world_size=128)


def test_future_elastic_version():
    cfg = base_config()
    cfg["elasticity"]["version"] = "0.2"
    with pytest.raises(ElasticityError):
        compute_elastic_config(cfg, __version__)


def test_missing_max_batch():
    cfg = base_config()
    del cfg["elasticity"]["max_train_batch_size"]
    with pytest.raises(ElasticityError):
        compute_elastic_config(cfg, __version__)


def test_missing_micro_batch():
    cfg = base_config()
    del cfg["elasticity"]["micro_batch_sizes"]
    with pytest.raises(ElasticityError):
        compute_elastic_config(cfg, __version__)


def test_non_list_micro_batch():
    cfg = base_config()
    cfg["elasticity"]["micro_batch_sizes"] = 8
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(cfg, __version__)


def test_config_takes_over_batch_triple():
    # DeepSpeedTPUConfig with elasticity enabled at a valid world size
    # derives the batch triple from the elastic config.
    cfg = base_config()
    ds = DeepSpeedTPUConfig(cfg, world_size=64)
    assert ds.elasticity_enabled
    assert ds.train_batch_size == 9792
    assert ds.train_micro_batch_size_per_gpu == 17
    assert ds.gradient_accumulation_steps == 9792 // (17 * 64)
    assert 64 in ds.elastic_valid_world_sizes


def test_config_rejects_external_batch_info():
    cfg = base_config()
    cfg["train_batch_size"] = 1024
    with pytest.raises(ElasticityConfigError):
        DeepSpeedTPUConfig(cfg, world_size=64)
    cfg["elasticity"]["ignore_non_elastic_batch_info"] = True
    ds = DeepSpeedTPUConfig(cfg, world_size=64)
    assert ds.train_batch_size == 9792


def test_candidate_batch_never_exceeds_cap():
    """Regression: an lcm(micro_batches) larger than max_train_batch_size
    must not leak through as a candidate (it previously won with scale=1)."""
    from deepspeed_tpu.elasticity.elasticity import _best_batch

    batch, valid = _best_batch([7, 9, 11], 50, 1, 64, True)
    assert batch <= 50
    assert valid


def test_per_chip_alias_also_guarded():
    import pytest

    from deepspeed_tpu.config.config import DeepSpeedTPUConfig
    from deepspeed_tpu.elasticity import ElasticityConfigError

    cfg = {
        "train_micro_batch_size_per_chip": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "elasticity": {"enabled": True, "max_train_batch_size": 1024,
                       "micro_batch_sizes": [2, 4],
                       "min_gpus": 1, "max_gpus": 64, "version": 0.1},
    }
    with pytest.raises(ElasticityConfigError):
        DeepSpeedTPUConfig(cfg)
