"""Elasticity tests: the config math (reference
``tests/unit/test_elastic.py``) plus live elasticity — in-process
shrink/grow on a preemption advance warning, step-boundary rejoin, and
goodput-driven straggler eviction (resilience/elastic.py,
docs/RESILIENCE.md "Live elasticity")."""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.config.config import DeepSpeedTPUConfig
from deepspeed_tpu.elasticity import (
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    highly_composite_numbers,
    world_change_plan,
)
from deepspeed_tpu.version import __version__

from simple_model import mlp_loss_fn, mlp_params

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS_DIR)


def base_config():
    return {
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 10000,
            "micro_batch_sizes": [8, 12, 16, 17],
            "min_gpus": 32,
            "max_gpus": 1500,
            "min_time": 20,
            "version": 0.1,
        }
    }


def test_basic_10k():
    # The reference's canonical case (test_elastic.py:23): 9792 with 23
    # valid chip counts.
    batch, valid = compute_elastic_config(base_config(), __version__)
    assert batch == 9792
    assert len(valid) == 23
    micro_batches = base_config()["elasticity"]["micro_batch_sizes"]
    for w in valid:
        assert batch % w == 0
        assert any((batch // w) % mb == 0 for mb in micro_batches)


def test_hcn_generation_matches_known_sequence():
    # First entries of the true HCN sequence (the reference hardcodes these,
    # elasticity.py:21; we generate them).
    assert highly_composite_numbers(720720) == (
        1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840,
        1260, 1680, 2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720,
        45360, 50400, 55440, 83160, 110880, 166320, 221760, 277200,
        332640, 498960, 554400, 665280, 720720)


def test_old_version():
    with pytest.raises(ElasticityError):
        compute_elastic_config(base_config(), "0.0.1")


def test_disabled():
    cfg = base_config()
    cfg["elasticity"]["enabled"] = False
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(cfg, __version__)


def test_valid_world_size():
    batch, valid, micro = compute_elastic_config(
        base_config(), __version__, world_size=64)
    assert micro == 17


def test_invalid_world_size():
    with pytest.raises(ElasticityIncompatibleWorldSize):
        compute_elastic_config(base_config(), __version__, world_size=128)


def test_future_elastic_version():
    cfg = base_config()
    cfg["elasticity"]["version"] = "0.2"
    with pytest.raises(ElasticityError):
        compute_elastic_config(cfg, __version__)


def test_missing_max_batch():
    cfg = base_config()
    del cfg["elasticity"]["max_train_batch_size"]
    with pytest.raises(ElasticityError):
        compute_elastic_config(cfg, __version__)


def test_missing_micro_batch():
    cfg = base_config()
    del cfg["elasticity"]["micro_batch_sizes"]
    with pytest.raises(ElasticityError):
        compute_elastic_config(cfg, __version__)


def test_non_list_micro_batch():
    cfg = base_config()
    cfg["elasticity"]["micro_batch_sizes"] = 8
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(cfg, __version__)


def test_config_takes_over_batch_triple():
    # DeepSpeedTPUConfig with elasticity enabled at a valid world size
    # derives the batch triple from the elastic config.
    cfg = base_config()
    ds = DeepSpeedTPUConfig(cfg, world_size=64)
    assert ds.elasticity_enabled
    assert ds.train_batch_size == 9792
    assert ds.train_micro_batch_size_per_gpu == 17
    assert ds.gradient_accumulation_steps == 9792 // (17 * 64)
    assert 64 in ds.elastic_valid_world_sizes


def test_config_rejects_external_batch_info():
    cfg = base_config()
    cfg["train_batch_size"] = 1024
    with pytest.raises(ElasticityConfigError):
        DeepSpeedTPUConfig(cfg, world_size=64)
    cfg["elasticity"]["ignore_non_elastic_batch_info"] = True
    ds = DeepSpeedTPUConfig(cfg, world_size=64)
    assert ds.train_batch_size == 9792


def test_candidate_batch_never_exceeds_cap():
    """Regression: an lcm(micro_batches) larger than max_train_batch_size
    must not leak through as a candidate (it previously won with scale=1)."""
    from deepspeed_tpu.elasticity.elasticity import _best_batch

    batch, valid = _best_batch([7, 9, 11], 50, 1, 64, True)
    assert batch <= 50
    assert valid


def test_per_chip_alias_also_guarded():
    import pytest

    from deepspeed_tpu.config.config import DeepSpeedTPUConfig
    from deepspeed_tpu.elasticity import ElasticityConfigError

    cfg = {
        "train_micro_batch_size_per_chip": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "elasticity": {"enabled": True, "max_train_batch_size": 1024,
                       "micro_batch_sizes": [2, 4],
                       "min_gpus": 1, "max_gpus": 64, "version": 0.1},
    }
    with pytest.raises(ElasticityConfigError):
        DeepSpeedTPUConfig(cfg)


# ===========================================================================
# Live elasticity (resilience/elastic.py)
# ===========================================================================

GLOBAL_BATCH = 24   # ladder below: batch 24, worlds {1,2,3,4,6,8,12,24}
_LADDER = {
    "enabled": True,
    "max_train_batch_size": GLOBAL_BATCH,
    "micro_batch_sizes": [1, 2],
    "min_chips": 1, "max_chips": 64,
    "version": 0.1,
}


def _live_config(tmp_path, live=True, fault_injection=None, extra=None,
                 telemetry=True, live_extra=None):
    cfg = {
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "mesh": {"slices": 2},
        "steps_per_print": 1000,
        "elasticity": dict(_LADDER),
    }
    if live:
        cfg["elasticity"]["live"] = {"enabled": True, "grace_seconds": 30.0,
                                     "check_interval_steps": 1,
                                     **(live_extra or {})}
    if telemetry:
        cfg["telemetry"] = {"enabled": True, "dir": str(tmp_path),
                            "metrics": {"sinks": ["memory", "jsonl"]},
                            "trace": {"sync_spans": False}}
    if fault_injection:
        cfg["resilience"] = {"fault_injection": fault_injection}
    for k, v in (extra or {}).items():
        if isinstance(v, dict) and isinstance(cfg.get(k), dict):
            cfg[k] = {**cfg[k], **v}
        else:
            cfg[k] = v
    return cfg


def _live_engine(tmp_path, **kw):
    from deepspeed_tpu import initialize

    engine, _, _, _ = initialize(loss_fn=mlp_loss_fn, params=mlp_params(),
                                 config=_live_config(tmp_path, **kw),
                                 rng_seed=0)
    return engine


def _flat_stream(n, seed=7):
    rng = np.random.default_rng(seed)
    return [{"x": rng.standard_normal((GLOBAL_BATCH, 16)).astype(np.float32),
             "y": rng.standard_normal((GLOBAL_BATCH, 8)).astype(np.float32)}
            for _ in range(n)]


def _shaped(flat, engine):
    """Re-chunk one GLOBAL_BATCH-sized step batch for the engine's
    CURRENT (gas, micro×dp) split — what a ladder-aware dataloader does
    across a world change; the sample content/order never changes, so the
    trajectory is the same experiment."""
    gas = engine.gradient_accumulation_steps
    return {k: v.reshape(gas, GLOBAL_BATCH // gas, *v.shape[1:])
            for k, v in flat.items()}


class TestWorldChangePlan:
    def test_plan_preserves_global_batch_across_rungs(self):
        ds = {"elasticity": dict(_LADDER)}
        for chips in (24, 12, 8, 7, 6, 4, 3, 2, 1):
            world, micro, gas = world_change_plan(ds, chips)
            assert world <= chips
            assert micro * gas * world == GLOBAL_BATCH
        # shrink 8 -> 4 halves the world and re-splits, same global batch
        assert world_change_plan(ds, 8) == (8, 1, 3)
        assert world_change_plan(ds, 4) == (4, 2, 3)
        with pytest.raises(ElasticityIncompatibleWorldSize):
            world_change_plan({"elasticity": {**_LADDER, "min_chips": 2}}, 1)

    def test_eviction_cost_model(self):
        from deepspeed_tpu.resilience import evaluate_eviction

        # 0.5 s lost per step over 1000 steps = 500 s projected gain vs
        # 2x a 60 s reshard -> evict
        d = evaluate_eviction(0.5, 1000, 60.0, min_gain_factor=2.0)
        assert d["evict"] and d["projected_gain_sec"] == 500.0
        # marginal straggler: 0.05 s/step -> 50 s < 120 s -> keep
        d = evaluate_eviction(0.05, 1000, 60.0, min_gain_factor=2.0)
        assert not d["evict"]
        # degenerate inputs never flip the verdict to evict
        assert not evaluate_eviction(-1.0, 1000, 60.0)["evict"]
        assert not evaluate_eviction(0.0, 0, 0.0)["evict"]


class TestFaultPlanSliceEvents:
    def test_fields_resolve_and_validate(self, monkeypatch):
        from deepspeed_tpu.resilience import FAULT_PLAN_ENV, FaultPlan

        plan = FaultPlan.resolve({"slice_preempt_at_step": 3,
                                  "rejoin_after_steps": 2,
                                  "slice_preempt_slice": 1,
                                  "preempt_grace_seconds": 5.0})
        assert plan.should_slice_preempt(3)
        assert not plan.should_slice_preempt(4)
        assert plan.should_rejoin(5, 3) and not plan.should_rejoin(4, 3)
        assert not plan.should_rejoin(99, None)   # no shrink happened
        monkeypatch.setenv(FAULT_PLAN_ENV,
                           '{"slice_preempt_at_step": 7}')
        assert FaultPlan.resolve({}).slice_preempt_at_step == 7
        with pytest.raises(ValueError):
            FaultPlan(rejoin_after_steps=0)
        with pytest.raises(ValueError):
            FaultPlan(preempt_grace_seconds=0.0)


class TestLiveElasticityE2E:
    def test_slice_preempt_shrink_rejoin_matches_clean(
            self, eight_devices, tmp_path):
        """The acceptance gate: an injected slice preemption at step 3
        shrinks IN-PROCESS (same pid, no restart, no init_restore booked
        after the first step), the slice rejoins 3 steps later restoring
        the original world, and the whole trajectory matches an
        uninterrupted run within tolerance."""
        pid = os.getpid()
        handler_before = signal.getsignal(signal.SIGTERM)
        engine = _live_engine(
            tmp_path / "live",
            fault_injection={"slice_preempt_at_step": 3,
                             "slice_preempt_slice": 1,
                             "rejoin_after_steps": 3,
                             "preempt_grace_seconds": 30.0})
        try:
            assert engine.elastic is not None
            assert signal.getsignal(signal.SIGTERM) is not handler_before
            stream = _flat_stream(9)
            worlds, losses = [], []
            init_restore_after_first = None
            for i, b in enumerate(stream):
                losses.append(float(engine.train_batch(_shaped(b, engine))))
                worlds.append(engine.mesh.size)
                if i == 0:
                    init_restore_after_first = \
                        engine.goodput.totals()["init_restore"]
            # the warning fires during attempt 3 and the shrink lands at
            # that step's boundary, so the world reads 4 from the third
            # committed step on; rejoin_after_steps=3 grows back at the
            # step-6 boundary
            assert worlds == [8, 8, 4, 4, 4, 8, 8, 8, 8]
            assert os.getpid() == pid                 # same process
            assert engine.elastic.epoch == 2
            assert engine.elastic.reshards == 2
            totals = engine.goodput.totals()
            # no restart: init_restore froze after the first step, and
            # the reshard time landed in its OWN category
            assert totals["init_restore"] == init_restore_after_first
            assert totals["elastic_reshard"] > 0.0
            assert engine.recovery_count == 0

            clean = _live_engine(tmp_path / "clean", live=False,
                                 telemetry=False)
            assert clean.elastic is None
            clean_losses = [float(clean.train_batch(_shaped(b, clean)))
                            for b in _flat_stream(9)]
            # Same global batch + same sample order at every step (the
            # ladder's invariant): only the dp reduction grouping changes
            # post-shrink, so tight allclose — the documented tolerance.
            np.testing.assert_allclose(losses, clean_losses,
                                       rtol=1e-4, atol=1e-6)

            # manifest: world-change timeline stamped + epoch in
            # elastic/* gauges + instants in the trace
            manifest = engine.goodput.manifest()
            assert [e["world_size"] for e in manifest["elastic"]] == [4, 8]
            assert [e["cause"] for e in manifest["elastic"]] == \
                ["preemption", "rejoin"]
            engine.telemetry.flush()
            doc = json.load(open(tmp_path / "live" / "trace.json"))
            instants = {e["name"] for e in doc["traceEvents"]
                        if e.get("ph") == "i"}
            assert {"elastic/preempt_warned", "elastic/shrink",
                    "elastic/rejoin"} <= instants
            mem = next(s for s in engine.telemetry.registry.sinks
                       if hasattr(s, "tags"))
            assert {"elastic/world_size", "elastic/reshards",
                    "elastic/reshard_sec",
                    "elastic/evictions"} <= set(mem.tags())
        finally:
            engine.elastic.close()
        assert signal.getsignal(signal.SIGTERM) is handler_before

    def test_rejoin_rendezvous_checks_elastic_hash(self, eight_devices,
                                                   tmp_path):
        from deepspeed_tpu.resilience import (read_rejoin_request,
                                              request_rejoin)

        engine = _live_engine(tmp_path)
        try:
            stream = _flat_stream(6)
            engine.train_batch(_shaped(stream[0], engine))
            engine.elastic.request_shrink(1)
            engine.train_batch(_shaped(stream[1], engine))
            assert engine.mesh.size == 4
            # wrong hash: refused, request consumed, world unchanged
            request_rejoin(str(tmp_path), "ghost-host", 4,
                           elastic_config_hash="deadbeef")
            engine.train_batch(_shaped(stream[2], engine))
            assert engine.mesh.size == 4
            assert read_rejoin_request(str(tmp_path)) is None
            # MISSING hash: refused too — an external writer cannot
            # silently waive the batch-math check
            request_rejoin(str(tmp_path), "ghost-host", 4)
            engine.train_batch(_shaped(stream[3], engine))
            assert engine.mesh.size == 4
            assert read_rejoin_request(str(tmp_path)) is None
            # matching hash: admitted at the next boundary
            request_rejoin(str(tmp_path), "ghost-host", 4,
                           elastic_config_hash=engine.elastic_hash)
            engine.train_batch(_shaped(stream[4], engine))
            assert engine.mesh.size == 8
            assert read_rejoin_request(str(tmp_path)) is None
            engine.telemetry.flush()
            doc = json.load(open(tmp_path / "trace.json"))
            instants = {e["name"] for e in doc["traceEvents"]
                        if e.get("ph") == "i"}
            assert "elastic/rejoin_refused" in instants
        finally:
            engine.elastic.close()

    def test_shrink_grow_with_telemetry_off(self, eight_devices, tmp_path):
        """Live elasticity must not assume telemetry/goodput/fleet exist:
        the null-telemetry facade has no sinks and goodput is None, yet
        shrink and grow still work (only the observability is gone)."""
        engine = _live_engine(tmp_path, telemetry=False)
        try:
            assert engine.goodput is None and not engine.telemetry.enabled
            stream = _flat_stream(3)
            engine.train_batch(_shaped(stream[0], engine))
            engine.elastic.request_shrink(1)
            engine.train_batch(_shaped(stream[1], engine))
            assert engine.mesh.size == 4
            engine.elastic.request_rejoin_now()
            engine.train_batch(_shaped(stream[2], engine))
            assert engine.mesh.size == 8
        finally:
            engine.elastic.close()

    @pytest.mark.slow
    def test_preempt_rejoin_chaos_soak(self, eight_devices, tmp_path):
        """K preempt/rejoin cycles back to back: the engine must keep a
        finite, clean-run-matching trajectory through every world change
        (the repeated-rebuild leak/correctness soak)."""
        K = 3
        engine = _live_engine(tmp_path / "soak")
        clean = _live_engine(tmp_path / "soak_clean", live=False,
                             telemetry=False)
        try:
            # 4K+1 steps: the cycle pattern (shrink at i%4==1, rejoin at
            # i%4==3) fires exactly K of each; one more step would start
            # a K+1'th shrink
            stream = _flat_stream(4 * K + 1)
            losses, clean_losses = [], []
            for i, b in enumerate(stream):
                if i % 4 == 1:
                    engine.elastic.request_shrink(1)
                elif i % 4 == 3:
                    engine.elastic.request_rejoin_now()
                losses.append(float(engine.train_batch(_shaped(b, engine))))
                clean_losses.append(
                    float(clean.train_batch(_shaped(b, clean))))
            assert engine.elastic.reshards == 2 * K
            assert engine.mesh.size == 8
            np.testing.assert_allclose(losses, clean_losses,
                                       rtol=1e-4, atol=1e-6)
        finally:
            engine.elastic.close()


class TestStragglerEviction:
    def _flag_persistent_straggler(self, engine, host="slowhost"):
        """Drive the fleet aggregator with synthetic 4-host matrices until
        the straggler verdict goes persistent (the documented multi-host-
        without-multi-host seam: FleetAggregator.ingest)."""
        fleet = engine.fleet
        hosts = ["a", "b", "c", host]
        for step in range(1, 8):
            matrix = np.zeros((4, 7), np.float32)
            matrix[:, 0] = [1.0, 1.0, 1.0, 3.0]     # step_time_sec
            verdict = (fleet.ingest(step, matrix, hosts=hosts,
                                    steps_delta=5) or {}).get("straggler")
        assert verdict and verdict["persistent"], verdict
        assert verdict["host"] == host
        return verdict

    def test_eviction_decision_and_shrink(self, eight_devices, tmp_path):
        engine = _live_engine(
            tmp_path, live_extra={
                "eviction": {"enabled": True, "horizon_steps": 1000,
                             "min_gain_factor": 2.0,
                             "assumed_reshard_sec": 10.0}},
            extra={"telemetry": {"fleet": {"enabled": True, "persist": 2,
                                           "min_window": 3}}})
        try:
            engine.train_batch(_shaped(_flat_stream(1)[0], engine))
            verdict = self._flag_persistent_straggler(engine)
            # 2 s/step excess x 1000 steps >> 2 x 10 s: the model says
            # evict; the host maps to slice 1
            engine.elastic.host_slice_fn = lambda host: 1
            decision = engine.elastic.maybe_evict(engine)
            assert decision["evict"] and decision["host"] == "slowhost"
            assert decision["zscore"] >= 3.0
            assert engine.mesh.size == 4          # the shrink executed
            assert engine.elastic.evictions == 1
            # one decision per host per run — persistent verdicts repeat
            assert engine.elastic.maybe_evict(engine) is None
            manifest = engine.goodput.manifest()
            assert manifest["eviction_decisions"][0]["host"] == "slowhost"
            assert manifest["elastic"][0]["cause"] == "eviction"
            engine.telemetry.flush()
            doc = json.load(open(tmp_path / "trace.json"))
            ev = [e for e in doc["traceEvents"]
                  if e.get("ph") == "i" and e["name"] == "elastic/evict"]
            assert ev and ev[0]["args"]["host"] == "slowhost"
            assert ev[0]["args"]["evict"] is True
        finally:
            engine.elastic.close()

    def test_eviction_declined_when_reshard_too_expensive(
            self, eight_devices, tmp_path):
        engine = _live_engine(
            tmp_path, live_extra={
                "eviction": {"enabled": True, "horizon_steps": 10,
                             "min_gain_factor": 2.0,
                             "assumed_reshard_sec": 1e6}},
            extra={"telemetry": {"fleet": {"enabled": True, "persist": 2,
                                           "min_window": 3}}})
        try:
            engine.train_batch(_shaped(_flat_stream(1)[0], engine))
            self._flag_persistent_straggler(engine)
            engine.elastic.host_slice_fn = lambda host: 1
            decision = engine.elastic.maybe_evict(engine)
            # evidence says straggler, cost model says keep: decision is
            # recorded (manifest + instant) but NO shrink happens
            assert decision is not None and not decision["evict"]
            assert engine.mesh.size == 8
            assert engine.elastic.evictions == 0
            assert not engine.goodput.manifest()["eviction_decisions"][0][
                "evict"]
        finally:
            engine.elastic.close()

    def test_supervisor_stamps_eviction_decisions(self, tmp_path):
        """Post-mortem half of the loop: the supervisor reads the fleet
        breakdown evidence after an attempt and stamps goodput-costed
        decisions into the run manifests for tools/fleet_report.py."""
        from deepspeed_tpu.resilience import Supervisor

        run_dir = tmp_path / "run"
        run_dir.mkdir()
        (run_dir / "run_manifest.a0000.hostA.json").write_text(json.dumps({
            "format": 1, "run_id": "r", "attempt": 0, "host": "hostA",
            "categories": {}, "start_wall": 0.0, "wall_sec": 10.0}))
        (run_dir / "fleet_breakdown.json").write_text(json.dumps({
            "format": 1, "step": 50, "hosts": ["hostA", "hostB"],
            "fields": {}, "stats": {},
            "stragglers": {"hostB": {"count": 4, "persistent": True,
                                     "lost_sec": 400.0,
                                     "lost_sec_per_step": 2.0,
                                     "last_zscore": 5.1}},
            "window": 8, "zscore_threshold": 3.0}))
        sup = Supervisor([sys.executable, "-c", "pass"],
                         run_dir=str(run_dir))
        sup._note_stragglers(0)
        assert sup.straggler_hosts == ["hostB"]
        assert sup.eviction_decisions and \
            sup.eviction_decisions[0]["host"] == "hostB"
        doc = json.loads(
            (run_dir / "run_manifest.a0000.hostA.json").read_text())
        d = doc["eviction_decisions"][0]
        assert d["host"] == "hostB" and d["source"] == "supervisor"
        # the model runs on the PER-STEP rate (2 s/step x 1000 steps),
        # not the cumulative lost_sec — the two halves of the cost model
        # must agree on units
        assert d["projected_gain_sec"] == 2.0 * 1000
        assert d["evict"] is True
        assert d["zscore"] == 5.1

    def test_classify_exit_preemption_warned(self):
        from deepspeed_tpu.config.constants import \
            ELASTIC_PREEMPT_EXIT_CODE_DEFAULT as RC
        from deepspeed_tpu.telemetry.goodput import classify_exit

        assert classify_exit(RC, (113,), (114,), (RC,)) == \
            "preemption_warned"
        assert classify_exit(-15, (113,), (114,), (RC,)) == "preemption"
        assert classify_exit(113, (113,), (114,), (RC,)) == "watchdog"
        assert classify_exit(0, warned_rcs=(RC,)) == "clean"
        # default Supervisor wiring carries the warned set
        from deepspeed_tpu.resilience import Supervisor
        sup = Supervisor([sys.executable, "-c", "pass"], max_restarts=0)
        assert RC in sup.warned_rcs


class TestZeroOverheadOffContract:
    def test_disabled_installs_nothing(self, eight_devices, tmp_path):
        """elasticity.live off (absent OR explicit false): engine.elastic
        is None and the process's SIGTERM disposition is untouched."""
        before = signal.getsignal(signal.SIGTERM)
        e1 = _live_engine(tmp_path / "a", live=False, telemetry=False)
        assert e1.elastic is None
        e2 = _live_engine(tmp_path / "b", telemetry=False,
                          live_extra={"enabled": False})
        assert e2.elastic is None
        assert signal.getsignal(signal.SIGTERM) is before

    def test_lowered_step_bit_identical_when_off(self, eight_devices,
                                                 tmp_path):
        """live {"enabled": false}, a live-less elasticity block, and no
        elasticity at all (same explicit batch triple) must lower to the
        SAME step text — the coordinator never touches the jitted step."""
        batches = _flat_stream(1)[0]
        texts = {}
        for name, kw in (
                ("absent", dict(live=False)),
                ("disabled", dict(live_extra={"enabled": False}))):
            engine = _live_engine(tmp_path / name, telemetry=False, **kw)
            placed = engine.put_batch(_shaped(batches, engine),
                                      leading_gas_dim=True)
            texts[name] = engine._train_step.lower(
                engine.state, placed, jnp.float32(1e-2)).as_text()
        # no elasticity block at all, same triple pinned by hand
        from deepspeed_tpu import initialize
        engine, _, _, _ = initialize(
            loss_fn=mlp_loss_fn, params=mlp_params(),
            config={"optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                    "zero_optimization": {"stage": 1},
                    "mesh": {"slices": 2},
                    "train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 3},
            rng_seed=0)
        placed = engine.put_batch(_shaped(batches, engine),
                                  leading_gas_dim=True)
        texts["none"] = engine._train_step.lower(
            engine.state, placed, jnp.float32(1e-2)).as_text()
        assert texts["absent"] == texts["disabled"] == texts["none"]

    def test_live_walls_incompatible_tiers(self):
        from deepspeed_tpu.config.config import ConfigError

        live = {**_LADDER, "live": {"enabled": True}}
        with pytest.raises(ConfigError, match="ladder"):
            DeepSpeedTPUConfig({"train_micro_batch_size_per_gpu": 1,
                                "elasticity": {"enabled": False,
                                               "live": {"enabled": True}}})
        with pytest.raises(ConfigError, match="pipeline"):
            DeepSpeedTPUConfig({"elasticity": live, "mesh": {"pipe": 2}},
                               world_size=8)
        with pytest.raises(ConfigError, match="zeropp"):
            DeepSpeedTPUConfig({"elasticity": live,
                                "zero_optimization": {
                                    "stage": 2,
                                    "zeropp": {"quantized_weights": "int8"}}},
                               world_size=8)
        with pytest.raises(ConfigError, match="offload"):
            DeepSpeedTPUConfig({"elasticity": live,
                                "zero_optimization": {
                                    "stage": 2,
                                    "offload_optimizer": {"device": "cpu"}}},
                               world_size=8)
        with pytest.raises(ConfigError, match="1-bit"):
            DeepSpeedTPUConfig({"elasticity": live,
                                "optimizer": {"type": "onebitadam",
                                              "params": {"lr": 1e-3}}},
                               world_size=8)


class TestProbeElasticity:
    def test_probe_selftest_cli(self, eight_devices, tmp_path):
        """tools/probe_elasticity.py --selftest: measured in-process
        reshard vs cold supervisor restart, asserting in-process wins —
        the tier-1 wiring the issue asks for."""
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "probe_elasticity.py"),
             "--selftest"],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=570)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "selftest ok" in proc.stdout
        row = json.loads([l for l in proc.stdout.splitlines()
                          if l.startswith("{")][-1])
        assert row["in_process_total_sec"] < row["cold_restart_sec"]
