"""The bench evidence pipeline itself (round-4 postmortem: one transient
tunnel error zeroed the whole round's perf record — BENCH_r04.json rc=1).

These tests pin the hardened harness contract WITHOUT running any model:
sections are isolated, transient failures are retried once, and every
completed row is flushed to disk immediately, so a crash mid-run still
leaves a valid partial record. main() exits 0 with whatever rows
completed; a ZERO-row run exits 1 so total failure stays distinguishable
from success in the driver's rc log.
"""

import importlib.util
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _load_bench(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench"] = mod
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "PARTIAL_PATH", str(tmp_path / "partial.json"))
    return mod


class TestRunSection:
    def test_success_flushes_partial(self, tmp_path, monkeypatch):
        bench = _load_bench(tmp_path, monkeypatch)
        result = {"value": None}

        def section():
            result["value"] = 42.0

        ok = bench.run_section("s", section, result)
        assert ok
        on_disk = json.loads((tmp_path / "partial.json").read_text())
        assert on_disk["value"] == 42.0

    def test_transient_failure_retries_once(self, tmp_path, monkeypatch):
        bench = _load_bench(tmp_path, monkeypatch)
        result = {}
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError("remote_compile: read body: closed")
            result["row"] = 1.0

        ok = bench.run_section("flaky", flaky, result)
        assert ok and len(calls) == 2
        assert json.loads((tmp_path / "partial.json").read_text())["row"] == 1.0
        # the first attempt's error stays on the record
        assert "flaky" in result["errors"][0]

    def test_double_failure_moves_on(self, tmp_path, monkeypatch):
        bench = _load_bench(tmp_path, monkeypatch)
        result = {"value": 7.0}

        def dead():
            raise RuntimeError("tunnel connection reset")   # transient-class

        ok = bench.run_section("dead", dead, result)
        assert not ok
        assert len(result["errors"]) == 2
        # prior rows survive on disk even when a later section dies twice
        assert json.loads((tmp_path / "partial.json").read_text())["value"] == 7.0

    def test_deterministic_failure_not_retried(self, tmp_path, monkeypatch):
        bench = _load_bench(tmp_path, monkeypatch)
        result = {}
        calls = []

        def buggy():
            calls.append(1)
            raise ValueError("shape mismatch (8192, 768) vs (8192, 770)")

        ok = bench.run_section("buggy", buggy, result)
        # a deterministic bug pays ONE multi-minute compile, not two
        assert not ok and len(calls) == 1 and len(result["errors"]) == 1

    def test_partial_flush_failure_does_not_kill_section(self, tmp_path,
                                                         monkeypatch):
        bench = _load_bench(tmp_path, monkeypatch)
        monkeypatch.setattr(bench, "PARTIAL_PATH", "/nonexistent-dir/x.json")
        result = {}

        def section():
            result["row"] = 1.0

        assert bench.run_section("s", section, result)


class _FlakyEngine:
    """train_batch raises a transient tunnel error after N good calls."""

    def __init__(self, die_after):
        self.calls = 0
        self.die_after = die_after

    def train_batch(self, batches):
        self.calls += 1
        if self.calls > self.die_after:
            raise RuntimeError("remote_compile: read body: closed")
        return 0.5


class TestTransientMidWindowPartial:
    """The r04 hardening (ISSUE 11 satellite): a transient failure AFTER
    the first completed window keeps the evidence, stamps the row
    partial, and the section keeps rc=1 semantics; a failure BEFORE any
    window still propagates to the retry path."""

    def test_partial_windows_kept_and_row_stamped(self, tmp_path,
                                                  monkeypatch):
        bench = _load_bench(tmp_path, monkeypatch)
        # warmup(1) + fence + window1(2 steps) ok, dies in window2
        eng = _FlakyEngine(die_after=3)
        result = {}

        def section():
            dt, dt_med = bench.time_train_batches(eng, {}, steps=2,
                                                  warmup=1, windows=3)
            assert dt > 0 and dt_med > 0
            bench._section_rows(result, "s", samples_per_sec=1.0 / dt)

        ok = bench.run_section("s", section, result)
        row = result["sections"]["s"]
        assert row["partial"] == 1
        assert row["samples_per_sec"] > 0
        # evidence recorded, section NOT green (backend-init rc=1 style)
        assert not ok
        assert any("partial" in e for e in result["errors"])
        # flag consumed: the NEXT recorded row is clean
        bench._section_rows(result, "s2", x=1.0)
        assert "partial" not in result["sections"]["s2"]

    def test_failure_before_first_window_propagates(self, tmp_path,
                                                    monkeypatch):
        bench = _load_bench(tmp_path, monkeypatch)
        eng = _FlakyEngine(die_after=1)     # dies inside window 1
        result = {}

        def section():
            bench.time_train_batches(eng, {}, steps=2, warmup=1, windows=3)
            bench._section_rows(result, "s", samples_per_sec=1.0)

        ok = bench.run_section("s", section, result)
        assert not ok                        # transient, retried, dead twice
        assert "sections" not in result      # no row fabricated
        assert len(result["errors"]) == 2

    def test_stale_flag_does_not_leak_across_attempts(self, tmp_path,
                                                      monkeypatch):
        bench = _load_bench(tmp_path, monkeypatch)
        result = {}
        attempt = []

        def section():
            attempt.append(1)
            if len(attempt) == 1:
                # first attempt: timing goes partial, then the section
                # dies transiently BEFORE recording its row
                eng = _FlakyEngine(die_after=3)
                bench.time_train_batches(eng, {}, steps=2, warmup=1,
                                         windows=3)
                raise RuntimeError("tunnel connection reset")
            # retry completes cleanly — its row must NOT be stamped
            bench._section_rows(result, "s", samples_per_sec=2.0)

        ok = bench.run_section("s", section, result)
        assert ok
        assert "partial" not in result["sections"]["s"]

    def test_deterministic_midwindow_failure_still_raises(self, tmp_path,
                                                          monkeypatch):
        bench = _load_bench(tmp_path, monkeypatch)

        class Buggy:
            calls = 0

            def train_batch(self, batches):
                Buggy.calls += 1
                if Buggy.calls > 3:
                    raise ValueError("shape mismatch")   # deterministic
                return 0.5

        import pytest
        with pytest.raises(ValueError):
            bench.time_train_batches(Buggy(), {}, steps=2, warmup=1,
                                     windows=3)
