"""Sparse attention tests (reference tests/unit/test_sparse_attention.py):
layout properties per config family, and Pallas block-sparse kernel parity
against the dense-masked reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, SparseSelfAttention, VariableSparsityConfig,
    layout_kv_indices, layout_to_dense_mask, pad_to_block_size,
    sparse_attention)
from deepspeed_tpu.ops.transformer.attention import xla_attention


SEQ, BLOCK, HEADS = 256, 16, 4


def _configs():
    return [
        DenseSparsityConfig(HEADS, BLOCK),
        FixedSparsityConfig(HEADS, BLOCK, num_local_blocks=4,
                            num_global_blocks=1),
        FixedSparsityConfig(HEADS, BLOCK, num_local_blocks=4,
                            num_global_blocks=1, attention="unidirectional"),
        VariableSparsityConfig(HEADS, BLOCK, num_random_blocks=1,
                               local_window_blocks=[2, 4],
                               global_block_indices=[0, 7]),
        BigBirdSparsityConfig(HEADS, BLOCK, num_random_blocks=2,
                              num_sliding_window_blocks=3,
                              num_global_blocks=1),
        BSLongformerSparsityConfig(HEADS, BLOCK,
                                   num_sliding_window_blocks=3,
                                   global_block_indices=[0]),
    ]


class TestLayouts:
    @pytest.mark.parametrize("cfg", _configs(),
                             ids=lambda c: type(c).__name__)
    def test_shape_and_diagonal(self, cfg):
        layout = cfg.make_layout(SEQ)
        b = SEQ // BLOCK
        assert layout.shape == (HEADS, b, b)
        assert layout.min() >= 0 and layout.max() <= 1
        # every q block attends at least its own block's window: row nonzero
        assert (layout.sum(-1) > 0).all()
        # layouts are sparse (except Dense)
        if not isinstance(cfg, DenseSparsityConfig):
            assert layout.sum() < HEADS * b * b

    def test_fixed_unidirectional_is_lower_triangular(self):
        cfg = FixedSparsityConfig(HEADS, BLOCK, num_local_blocks=4,
                                  attention="unidirectional")
        layout = cfg.make_layout(SEQ)
        b = SEQ // BLOCK
        upper = np.triu(np.ones((b, b), np.int32), k=1)
        assert (layout * upper[None]).sum() == 0

    def test_bigbird_has_window_and_global(self):
        cfg = BigBirdSparsityConfig(HEADS, BLOCK, num_random_blocks=0,
                                    num_sliding_window_blocks=3,
                                    num_global_blocks=1)
        layout = cfg.make_layout(SEQ)
        b = SEQ // BLOCK
        for qi in range(1, b - 1):
            assert layout[0, qi, qi - 1] and layout[0, qi, qi]
        assert layout[0, :, 0].all()       # first block global col
        assert layout[0, 0, :].all()       # ...and row (bidirectional)

    def test_different_layout_per_head(self):
        cfg = BigBirdSparsityConfig(HEADS, BLOCK, num_random_blocks=2,
                                    different_layout_per_head=True)
        layout = cfg.make_layout(SEQ)
        assert any(not np.array_equal(layout[0], layout[h])
                   for h in range(1, HEADS))

    def test_kv_indices_roundtrip(self):
        cfg = FixedSparsityConfig(HEADS, BLOCK, num_local_blocks=4)
        layout = cfg.make_layout(SEQ)
        idx, max_active = layout_kv_indices(layout)
        b = SEQ // BLOCK
        for qi in range(b):
            cols = set(idx[0, qi][idx[0, qi] >= 0].tolist())
            assert cols == set(np.nonzero(layout[0, qi])[0].tolist())


class TestSparseExecution:
    def _qkv(self, rng, seq=SEQ):
        shape = (2, seq, HEADS, 32)
        return tuple(jnp.asarray(rng.standard_normal(shape), jnp.float32)
                     for _ in range(3))

    def test_dense_layout_matches_full_attention(self):
        rng = np.random.default_rng(0)
        q, k, v = self._qkv(rng)
        layout = DenseSparsityConfig(HEADS, BLOCK).make_layout(SEQ)
        out = sparse_attention(q, k, v, layout, BLOCK, impl="xla")
        ref = xla_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("cfg", _configs(),
                             ids=lambda c: type(c).__name__)
    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_matches_xla(self, cfg, causal):
        rng = np.random.default_rng(1)
        q, k, v = self._qkv(rng)
        layout = cfg.make_layout(SEQ)
        ref = sparse_attention(q, k, v, layout, BLOCK, causal=causal,
                               impl="xla")
        out = sparse_attention(q, k, v, layout, BLOCK, causal=causal,
                               impl="pallas", interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_gradients_flow(self):
        rng = np.random.default_rng(2)
        q, k, v = self._qkv(rng)
        layout = FixedSparsityConfig(HEADS, BLOCK).make_layout(SEQ)

        def loss(q, k, v):
            return jnp.sum(sparse_attention(q, k, v, layout, BLOCK,
                                            impl="xla") ** 2)

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        assert all(np.isfinite(np.asarray(g)).all() for g in grads)

    def test_sparse_self_attention_module(self):
        rng = np.random.default_rng(3)
        q, k, v = self._qkv(rng)
        ssa = SparseSelfAttention(
            FixedSparsityConfig(HEADS, BLOCK, num_local_blocks=4))
        out = ssa(q, k, v)
        assert out.shape == q.shape
        # layout cached per seq_len
        assert SEQ in ssa._layouts

    def test_pad_to_block_size(self):
        x = jnp.zeros((2, 100, 4, 8))
        padded, pad = pad_to_block_size(x, 16)
        assert pad == 12 and padded.shape[1] == 112
        x2, pad2 = pad_to_block_size(jnp.zeros((2, 96, 4, 8)), 16)
        assert pad2 == 0 and x2.shape[1] == 96

    def test_layout_seq_mismatch_raises(self):
        rng = np.random.default_rng(0)
        q, k, v = self._qkv(rng, seq=128)
        layout = DenseSparsityConfig(HEADS, BLOCK).make_layout(SEQ)
        with pytest.raises(ValueError, match="layout"):
            sparse_attention(q, k, v, layout, BLOCK)


class TestSparseBackward:
    """Grad parity of the Pallas sparse custom VJP against the xla oracle —
    the capability the reference's Triton backward modes provide
    (matmul.py:749 SDD/DSD/DDS, trsrc/softmax_bwd.tr). Round-2 VERDICT
    task 3."""

    def _qkv(self, rng, seq=SEQ):
        shape = (2, seq, HEADS, 32)
        return tuple(jnp.asarray(rng.standard_normal(shape), jnp.float32)
                     for _ in range(3))

    @pytest.mark.parametrize("cfg", _configs(),
                             ids=lambda c: type(c).__name__)
    @pytest.mark.parametrize("causal", [False, True])
    def test_grad_parity(self, cfg, causal):
        rng = np.random.default_rng(7)
        q, k, v = self._qkv(rng)
        layout = cfg.make_layout(SEQ)

        def loss(impl):
            def f(q, k, v):
                o = sparse_attention(q, k, v, layout, BLOCK, causal=causal,
                                     impl=impl, interpret=True)
                # weighted sum so every output position has a distinct
                # cotangent (catches transpose-layout mistakes)
                w = jnp.arange(o.size, dtype=jnp.float32).reshape(o.shape)
                return jnp.sum(o * w) / o.size
            return f

        g_ref = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
        g_pal = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("q k v".split(), g_ref, g_pal):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-3, rtol=2e-3,
                err_msg=f"d{name} mismatch ({type(cfg).__name__})")

    def test_training_step_through_pallas(self):
        """A toy training step through impl='pallas' must run and reduce
        loss (the round-2 gap: sparse training was impossible)."""
        rng = np.random.default_rng(8)
        q, k, v = self._qkv(rng)
        layout = FixedSparsityConfig(HEADS, BLOCK,
                                     num_local_blocks=4).make_layout(SEQ)
        w = jnp.ones((32, 32)) * 0.1
        target = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)

        def loss(w):
            o = sparse_attention(q @ w, k, v, layout, BLOCK, impl="pallas",
                                 interpret=True)
            return jnp.mean((o - target) ** 2)

        grad = jax.jit(jax.grad(loss))
        losses = []
        for _ in range(5):
            g = grad(w)
            w = w - 0.5 * g
            losses.append(float(loss(w)))
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()
