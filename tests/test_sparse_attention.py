"""Sparse attention tests (reference tests/unit/test_sparse_attention.py):
layout properties per config family, and Pallas block-sparse kernel parity
against the dense-masked reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, SparseSelfAttention, VariableSparsityConfig,
    layout_kv_indices, layout_to_dense_mask, pad_to_block_size,
    sparse_attention)
from deepspeed_tpu.ops.transformer.attention import xla_attention


SEQ, BLOCK, HEADS = 256, 16, 4


def _configs():
    return [
        DenseSparsityConfig(HEADS, BLOCK),
        FixedSparsityConfig(HEADS, BLOCK, num_local_blocks=4,
                            num_global_blocks=1),
        FixedSparsityConfig(HEADS, BLOCK, num_local_blocks=4,
                            num_global_blocks=1, attention="unidirectional"),
        VariableSparsityConfig(HEADS, BLOCK, num_random_blocks=1,
                               local_window_blocks=[2, 4],
                               global_block_indices=[0, 7]),
        BigBirdSparsityConfig(HEADS, BLOCK, num_random_blocks=2,
                              num_sliding_window_blocks=3,
                              num_global_blocks=1),
        BSLongformerSparsityConfig(HEADS, BLOCK,
                                   num_sliding_window_blocks=3,
                                   global_block_indices=[0]),
    ]


class TestLayouts:
    @pytest.mark.parametrize("cfg", _configs(),
                             ids=lambda c: type(c).__name__)
    def test_shape_and_diagonal(self, cfg):
        layout = cfg.make_layout(SEQ)
        b = SEQ // BLOCK
        assert layout.shape == (HEADS, b, b)
        assert layout.min() >= 0 and layout.max() <= 1
        # every q block attends at least its own block's window: row nonzero
        assert (layout.sum(-1) > 0).all()
        # layouts are sparse (except Dense)
        if not isinstance(cfg, DenseSparsityConfig):
            assert layout.sum() < HEADS * b * b

    def test_fixed_unidirectional_is_lower_triangular(self):
        cfg = FixedSparsityConfig(HEADS, BLOCK, num_local_blocks=4,
                                  attention="unidirectional")
        layout = cfg.make_layout(SEQ)
        b = SEQ // BLOCK
        upper = np.triu(np.ones((b, b), np.int32), k=1)
        assert (layout * upper[None]).sum() == 0

    def test_bigbird_has_window_and_global(self):
        cfg = BigBirdSparsityConfig(HEADS, BLOCK, num_random_blocks=0,
                                    num_sliding_window_blocks=3,
                                    num_global_blocks=1)
        layout = cfg.make_layout(SEQ)
        b = SEQ // BLOCK
        for qi in range(1, b - 1):
            assert layout[0, qi, qi - 1] and layout[0, qi, qi]
        assert layout[0, :, 0].all()       # first block global col
        assert layout[0, 0, :].all()       # ...and row (bidirectional)

    def test_different_layout_per_head(self):
        cfg = BigBirdSparsityConfig(HEADS, BLOCK, num_random_blocks=2,
                                    different_layout_per_head=True)
        layout = cfg.make_layout(SEQ)
        assert any(not np.array_equal(layout[0], layout[h])
                   for h in range(1, HEADS))

    def test_kv_indices_roundtrip(self):
        cfg = FixedSparsityConfig(HEADS, BLOCK, num_local_blocks=4)
        layout = cfg.make_layout(SEQ)
        idx, max_active = layout_kv_indices(layout)
        b = SEQ // BLOCK
        for qi in range(b):
            cols = set(idx[0, qi][idx[0, qi] >= 0].tolist())
            assert cols == set(np.nonzero(layout[0, qi])[0].tolist())


class TestSparseExecution:
    def _qkv(self, rng, seq=SEQ):
        shape = (2, seq, HEADS, 32)
        return tuple(jnp.asarray(rng.standard_normal(shape), jnp.float32)
                     for _ in range(3))

    def test_dense_layout_matches_full_attention(self):
        rng = np.random.default_rng(0)
        q, k, v = self._qkv(rng)
        layout = DenseSparsityConfig(HEADS, BLOCK).make_layout(SEQ)
        out = sparse_attention(q, k, v, layout, BLOCK, impl="xla")
        ref = xla_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("cfg", _configs(),
                             ids=lambda c: type(c).__name__)
    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_matches_xla(self, cfg, causal):
        rng = np.random.default_rng(1)
        q, k, v = self._qkv(rng)
        layout = cfg.make_layout(SEQ)
        ref = sparse_attention(q, k, v, layout, BLOCK, causal=causal,
                               impl="xla")
        out = sparse_attention(q, k, v, layout, BLOCK, causal=causal,
                               impl="pallas", interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_gradients_flow(self):
        rng = np.random.default_rng(2)
        q, k, v = self._qkv(rng)
        layout = FixedSparsityConfig(HEADS, BLOCK).make_layout(SEQ)

        def loss(q, k, v):
            return jnp.sum(sparse_attention(q, k, v, layout, BLOCK,
                                            impl="xla") ** 2)

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        assert all(np.isfinite(np.asarray(g)).all() for g in grads)

    def test_sparse_self_attention_module(self):
        rng = np.random.default_rng(3)
        q, k, v = self._qkv(rng)
        ssa = SparseSelfAttention(
            FixedSparsityConfig(HEADS, BLOCK, num_local_blocks=4))
        out = ssa(q, k, v)
        assert out.shape == q.shape
        # layout cached per seq_len
        assert SEQ in ssa._layouts

    def test_pad_to_block_size(self):
        x = jnp.zeros((2, 100, 4, 8))
        padded, pad = pad_to_block_size(x, 16)
        assert pad == 12 and padded.shape[1] == 112
        x2, pad2 = pad_to_block_size(jnp.zeros((2, 96, 4, 8)), 16)
        assert pad2 == 0 and x2.shape[1] == 96

    def test_layout_seq_mismatch_raises(self):
        rng = np.random.default_rng(0)
        q, k, v = self._qkv(rng, seq=128)
        layout = DenseSparsityConfig(HEADS, BLOCK).make_layout(SEQ)
        with pytest.raises(ValueError, match="layout"):
            sparse_attention(q, k, v, layout, BLOCK)


class TestSparseBackward:
    """Grad parity of the Pallas sparse custom VJP against the xla oracle —
    the capability the reference's Triton backward modes provide
    (matmul.py:749 SDD/DSD/DDS, trsrc/softmax_bwd.tr). Round-2 VERDICT
    task 3."""

    def _qkv(self, rng, seq=SEQ):
        shape = (2, seq, HEADS, 32)
        return tuple(jnp.asarray(rng.standard_normal(shape), jnp.float32)
                     for _ in range(3))

    @pytest.mark.parametrize("cfg", _configs(),
                             ids=lambda c: type(c).__name__)
    @pytest.mark.parametrize("causal", [False, True])
    def test_grad_parity(self, cfg, causal):
        rng = np.random.default_rng(7)
        q, k, v = self._qkv(rng)
        layout = cfg.make_layout(SEQ)

        def loss(impl):
            def f(q, k, v):
                o = sparse_attention(q, k, v, layout, BLOCK, causal=causal,
                                     impl=impl, interpret=True)
                # weighted sum so every output position has a distinct
                # cotangent (catches transpose-layout mistakes)
                w = jnp.arange(o.size, dtype=jnp.float32).reshape(o.shape)
                return jnp.sum(o * w) / o.size
            return f

        g_ref = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
        g_pal = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("q k v".split(), g_ref, g_pal):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-3, rtol=2e-3,
                err_msg=f"d{name} mismatch ({type(cfg).__name__})")

    def test_training_step_through_pallas(self):
        """A toy training step through impl='pallas' must run and reduce
        loss (the round-2 gap: sparse training was impossible)."""
        rng = np.random.default_rng(8)
        q, k, v = self._qkv(rng)
        layout = FixedSparsityConfig(HEADS, BLOCK,
                                     num_local_blocks=4).make_layout(SEQ)
        w = jnp.ones((32, 32)) * 0.1
        target = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)

        def loss(w):
            o = sparse_attention(q @ w, k, v, layout, BLOCK, impl="pallas",
                                 interpret=True)
            return jnp.mean((o - target) ** 2)

        grad = jax.jit(jax.grad(loss))
        losses = []
        for _ in range(5):
            g = grad(w)
            w = w - 0.5 * g
            losses.append(float(loss(w)))
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()


class TestConfigDrivenSparse:
    """sparse_attention config block -> model families -> training
    (round-3 VERDICT task 3: previously the block parsed but nothing
    consumed it; reference chain = runtime/config.py presets ->
    SparseAttentionUtils surgery -> BertSparseSelfAttention)."""

    SPARSE = {"mode": "bigbird", "block": 16, "num_random_blocks": 1,
              "num_sliding_window_blocks": 3, "num_global_blocks": 1,
              "attention": "unidirectional"}

    def test_initialize_injects_sparse_into_gpt(self, eight_devices):
        import deepspeed_tpu
        from deepspeed_tpu.models import make_gpt

        model, cfg = make_gpt("tiny", dropout_rate=0.0, dtype=jnp.float32)
        rng = np.random.default_rng(0)
        batches = {"input_ids": rng.integers(0, cfg.vocab_size, (2, 8, 64),
                                             dtype=np.int32)}
        params = model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(1)},
            {"input_ids": batches["input_ids"][0]})["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params,
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 0},
                    "sparse_attention": dict(self.SPARSE)})
        losses = [float(engine.train_batch(batches)) for _ in range(8)]
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0] - 0.3, losses

    def test_dense_mode_matches_dense_attention(self):
        """mode='dense' through the model must equal the stock xla path —
        the numerics oracle for the whole config chain."""
        from deepspeed_tpu.models import make_gpt
        from deepspeed_tpu.ops.sparse_attention import SparseAttentionUtils

        m_d, cfg = make_gpt("tiny", dropout_rate=0.0, dtype=jnp.float32,
                            attention_impl="xla")
        rng = np.random.default_rng(1)
        batch = {"input_ids": rng.integers(0, cfg.vocab_size, (4, 64),
                                           dtype=np.int32)}
        p = m_d.init({"params": jax.random.PRNGKey(0),
                      "dropout": jax.random.PRNGKey(1)}, batch)["params"]
        m_s = (SparseAttentionUtils.
               replace_model_self_attention_with_sparse_self_attention(
                   m_d, {"mode": "dense", "block": 16, "impl": "xla"}))
        ld = m_d.apply({"params": p}, batch, deterministic=True)["loss"]
        ls = m_s.apply({"params": p}, batch, deterministic=True)["loss"]
        np.testing.assert_allclose(float(ld), float(ls), rtol=2e-5)

    def test_bert_sparse_with_padding_mask(self):
        """BERT + bslongformer + key-padding mask: masked keys must not
        influence unmasked positions (reference key_padding_mask)."""
        from deepspeed_tpu.models import make_bert
        from deepspeed_tpu.ops.sparse_attention import SparseAttentionUtils

        m, cfg = make_bert("tiny", dropout_rate=0.0, dtype=jnp.float32)
        m = (SparseAttentionUtils.
             replace_model_self_attention_with_sparse_self_attention(
                 m, {"mode": "bslongformer", "block": 16,
                     "num_sliding_window_blocks": 3, "impl": "xla"}))
        rng = np.random.default_rng(2)
        ids = rng.integers(0, cfg.vocab_size, (2, 64), dtype=np.int32)
        mask = np.ones((2, 64), np.int32)
        mask[:, 48:] = 0
        labels = np.where(rng.random((2, 64)) < 0.15, ids,
                          -100).astype(np.int32)
        labels[:, 48:] = -100   # padded tail predicts nothing
        batch = {"input_ids": ids, "attention_mask": mask, "labels": labels}
        p = m.init({"params": jax.random.PRNGKey(0),
                    "dropout": jax.random.PRNGKey(1)}, batch)["params"]
        out1 = m.apply({"params": p}, batch, deterministic=True)
        # changing tokens in the masked tail must not change the loss
        ids2 = ids.copy()
        ids2[:, 48:] = (ids2[:, 48:] + 7) % cfg.vocab_size
        batch2 = dict(batch, input_ids=ids2)
        out2 = m.apply({"params": p}, batch2, deterministic=True)
        np.testing.assert_allclose(float(out1["loss"]), float(out2["loss"]),
                                   rtol=1e-6)

    def test_surgery_rejects_opaque_model(self):
        import flax.linen as nn

        from deepspeed_tpu.ops.sparse_attention import SparseAttentionUtils

        class Opaque(nn.Module):
            @nn.compact
            def __call__(self, batch):
                return jnp.mean(batch["x"])

        with pytest.raises(ValueError, match="in-tree"):
            (SparseAttentionUtils.
             replace_model_self_attention_with_sparse_self_attention(
                 Opaque(), {"mode": "dense"}))

    def test_config_presets_and_unknown_keys(self):
        from deepspeed_tpu.ops.sparse_attention import \
            sparsity_config_from_dict

        for mode in ("dense", "fixed", "variable", "bigbird",
                     "bslongformer"):
            sc = sparsity_config_from_dict({"mode": mode, "block": 16}, 4)
            assert sc.make_layout(64).shape == (4, 4, 4)
        with pytest.raises(ValueError, match="unknown sparse_attention"):
            sparsity_config_from_dict({"mode": "nope"}, 4)
        with pytest.raises(ValueError, match="invalid sparse_attention"):
            sparsity_config_from_dict({"mode": "fixed", "bogus": 1}, 4)

    def test_pad_and_unpad_utils(self):
        from deepspeed_tpu.ops.sparse_attention import SparseAttentionUtils

        ids = np.arange(2 * 50, dtype=np.int32).reshape(2, 50) % 7
        pad, batch = SparseAttentionUtils.pad_to_block_size(
            16, jnp.asarray(ids), pad_token_id=3)
        assert pad == 14 and batch["input_ids"].shape == (2, 64)
        assert int(batch["attention_mask"][0, 49]) == 1
        assert int(batch["attention_mask"][0, 50]) == 0
        out = SparseAttentionUtils.unpad_sequence_output(
            pad, jnp.zeros((2, 64, 8)))
        assert out.shape == (2, 50, 8)

    def test_extend_position_embedding(self):
        from deepspeed_tpu.ops.sparse_attention import SparseAttentionUtils

        params = {"wpe": jnp.asarray(np.random.default_rng(0)
                                     .standard_normal((64, 8)), jnp.float32)}
        new = SparseAttentionUtils.extend_position_embedding(params, 200)
        assert new["wpe"].shape == (200, 8)
        np.testing.assert_array_equal(np.asarray(new["wpe"][64:128]),
                                      np.asarray(new["wpe"][:64]))


def _mask_blk_seq():
    """Masked-pallas shapes per platform: Mosaic lane-slices the mask at
    col*block, admitted only for block % 128 == 0 — so the on-chip run
    uses the long-seq geometry (blk 128) while CPU-interpret keeps the
    small fast shapes."""
    if jax.devices()[0].platform == "tpu":
        return 128, 512
    return 16, 128


class TestPallasKeyMask:
    """Key-padding mask inside the Pallas sparse kernels (r4 review
    finding: auto used to silently fall back to the dense-materializing
    XLA executor whenever a mask was present — fatal at long seq)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_masked_pallas_matches_xla(self, causal):
        from deepspeed_tpu.ops.sparse_attention import (
            BigBirdSparsityConfig, sparse_attention)

        rng = np.random.default_rng(0)
        blk, s = _mask_blk_seq()
        b, h, d = 2, 4, 64
        sc = BigBirdSparsityConfig(num_heads=h, block=blk,
                                   num_random_blocks=1,
                                   num_sliding_window_blocks=3,
                                   num_global_blocks=1)
        layout = sc.make_layout(s)
        keep = s - 28
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32) * .1
        k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32) * .1
        v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32) * .1
        mask = np.ones((b, s), np.int32)
        mask[:, keep:] = 0
        mask = jnp.asarray(mask)
        ref = sparse_attention(q, k, v, layout, blk, causal=causal,
                               key_mask=mask, impl="xla")
        out = sparse_attention(q, k, v, layout, blk, causal=causal,
                               key_mask=mask, impl="pallas")
        np.testing.assert_allclose(np.asarray(out)[:, :keep],
                                   np.asarray(ref)[:, :keep],
                                   atol=2e-5, rtol=2e-5)

    def test_masked_small_block_rejected_on_mosaic(self):
        """block < 128 + key_mask cannot lane-slice on TPU — explicit
        pallas must raise BEFORE lowering (auto dispatches to xla)."""
        from deepspeed_tpu.ops.sparse_attention import (
            BigBirdSparsityConfig, sparse_attention)

        b, s, h, d, blk = 1, 128, 2, 64, 16
        sc = BigBirdSparsityConfig(num_heads=h, block=blk,
                                   num_random_blocks=1,
                                   num_sliding_window_blocks=3,
                                   num_global_blocks=1)
        layout = sc.make_layout(s)
        q = jnp.zeros((b, s, h, d), jnp.float32)
        mask = jnp.ones((b, s), jnp.int32)
        with pytest.raises(ValueError, match="block % 128"):
            sparse_attention(q, q, q, layout, blk, key_mask=mask,
                             impl="pallas", interpret=False)

    def test_masked_grads_match_xla(self):
        from deepspeed_tpu.ops.sparse_attention import (
            BSLongformerSparsityConfig, sparse_attention)

        rng = np.random.default_rng(1)
        blk, s = _mask_blk_seq()
        s = s // 2 if blk < 128 else s      # keep the CPU case tiny
        b, h, d = 1, 2, 64
        sc = BSLongformerSparsityConfig(num_heads=h, block=blk,
                                        num_sliding_window_blocks=3)
        layout = sc.make_layout(s)
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32) * .1
        mask = np.ones((b, s), np.int32)
        mask[:, s - 16:] = 0
        mask = jnp.asarray(mask)
        w = jnp.asarray(np.asarray(mask), jnp.float32)[:, :, None, None]

        def loss(impl):
            return lambda q, k, v: jnp.sum((sparse_attention(
                q, k, v, layout, blk, key_mask=mask, impl=impl) * w) ** 2)

        g_ref = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, q, q)
        g_pal = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, q, q)
        for a, r, name in zip(g_pal, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       atol=3e-5, rtol=3e-5,
                                       err_msg=f"d{name}")
