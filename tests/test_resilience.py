"""Resilience subsystem (resilience/, docs/RESILIENCE.md): async
double-buffered checkpointing with manifest digests, deterministic fault
injection, and the supervisor auto-resume contract — crash at step k,
restart, resume, and the loss trajectory is bit-identical to an
uninterrupted run."""

import json
import os
import pickle
import subprocess
import sys
import textwrap

import numpy as np
import pytest
import jax

from deepspeed_tpu import initialize
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.resilience import (AsyncCheckpointManager, FaultPlan,
                                      ResilienceError, Supervisor,
                                      find_restorable, list_checkpoints,
                                      restore)
from deepspeed_tpu.resilience.checkpoint import MANIFEST_FILE
from deepspeed_tpu.resilience.fault import (FAULT_PLAN_ENV,
                                            RESUME_ATTEMPT_ENV,
                                            corrupt_one_shard)
from deepspeed_tpu.runtime.dataloader import RepeatingLoader

from simple_model import mlp_params, mlp_loss_fn, random_batches

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def _make_engine(ckpt_dir, dp=8, micro_bs=2, zero_stage=1, interval=1,
                 keep_last=3, fault_injection=None, async_write=True,
                 extra=None):
    config = {
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": zero_stage},
        "resilience": {
            "enabled": True,
            "checkpoint": {"dir": str(ckpt_dir), "interval": interval,
                           "keep_last": keep_last, "async": async_write,
                           "backoff_seconds": 0.01},
            "fault_injection": fault_injection or {},
        },
    }
    config.update(extra or {})
    engine, _, _, _ = initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(), config=config,
        mesh=build_mesh(data=dp, devices=jax.devices()[:dp]), rng_seed=0)
    return engine


def _batch_stream(n, seed=7, batch_size=16):
    rng = np.random.default_rng(seed)
    return [random_batches(rng, 1, batch_size=batch_size) for _ in range(n)]


def _params_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(a)),
                    jax.tree_util.tree_leaves(jax.device_get(b))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=0, atol=0)


# ---------------------------------------------------------------------------
# Async manager: commit protocol, manifest, GC, double buffer, retries
# ---------------------------------------------------------------------------

def test_async_commit_manifest_and_roundtrip(tmp_path):
    e1 = _make_engine(tmp_path)
    for b in _batch_stream(3):
        e1.train_batch(b)
    e1.ckpt_manager.wait()
    ckpts = list_checkpoints(str(tmp_path))
    assert [s for s, _ in ckpts] == [1, 2, 3]
    manifest = json.load(open(os.path.join(ckpts[-1][1], MANIFEST_FILE)))
    assert manifest["step"] == 3
    assert manifest["dp_world_size"] == 8
    assert manifest["zero_stage"] == 1
    assert manifest["shards"]  # every leaf carries file + sha256
    for rec in manifest["shards"].values():
        assert set(rec) >= {"file", "sha256", "shape", "dtype"}

    e2 = _make_engine(tmp_path)
    path, _ = e2.auto_resume()
    assert path == ckpts[-1][1]
    assert e2.global_steps == 3
    _params_equal(e1.state.params, e2.state.params)
    _params_equal(e1.state.opt_state.exp_avg, e2.state.opt_state.exp_avg)
    e1.ckpt_manager.close()
    e2.ckpt_manager.close()


def test_bit_identical_continuation_after_resume(tmp_path):
    stream = _batch_stream(6)
    e1 = _make_engine(tmp_path)
    for b in stream[:3]:
        e1.train_batch(b)
    e1.ckpt_manager.wait()
    e2 = _make_engine(tmp_path)
    e2.auto_resume()
    cont1 = [repr(float(e1.train_batch(b))) for b in stream[3:]]
    cont2 = [repr(float(e2.train_batch(b))) for b in stream[3:]]
    assert cont1 == cont2  # bit-identical, not just allclose
    e1.ckpt_manager.close()
    e2.ckpt_manager.close()


def test_gc_keeps_last_n(tmp_path):
    e = _make_engine(tmp_path, keep_last=2)
    for b in _batch_stream(5):
        e.train_batch(b)
        e.ckpt_manager.wait()   # drain so every step commits (no drops)
    assert [s for s, _ in list_checkpoints(str(tmp_path))] == [4, 5]
    e.ckpt_manager.close()


def test_double_buffer_latest_wins(tmp_path):
    """While a write is held, newer snapshots replace the pending one —
    slow disk back-pressures to skipped intermediates, never a stall."""
    e = _make_engine(tmp_path)
    mgr = e.ckpt_manager
    mgr._unpaused.clear()       # hold the writer
    for b in _batch_stream(3):
        e.train_batch(b)        # 3 saves enqueued while writer is held
    assert mgr.stats["dropped"] >= 1
    mgr._unpaused.set()
    mgr.wait()
    steps = [s for s, _ in list_checkpoints(str(tmp_path))]
    assert steps[-1] == 3       # the newest snapshot always lands
    assert 2 not in steps       # the superseded intermediate was dropped
    mgr.close()


def test_injected_io_error_retries_then_commits(tmp_path):
    e = _make_engine(tmp_path, fault_injection={"ckpt_write_errors": 2})
    assert e.fault_plan is not None
    e.train_batch(_batch_stream(1)[0])
    e.ckpt_manager.wait()
    assert e.ckpt_manager.stats["retries"] == 2
    assert e.ckpt_manager.stats["saved"] == 1
    assert e.ckpt_manager.stats["failed"] == 0
    assert [s for s, _ in list_checkpoints(str(tmp_path))] == [1]
    e.ckpt_manager.close()


def test_write_failure_never_kills_training(tmp_path):
    e = _make_engine(tmp_path, fault_injection={"ckpt_write_errors": 99})
    e.ckpt_manager.max_retries = 1
    losses = [float(e.train_batch(b)) for b in _batch_stream(2)]
    e.ckpt_manager.wait()
    assert all(np.isfinite(losses))          # training survived
    assert e.ckpt_manager.stats["failed"] == 2
    assert list_checkpoints(str(tmp_path)) == []
    assert isinstance(e.ckpt_manager.last_error, OSError)
    e.ckpt_manager.close()


# ---------------------------------------------------------------------------
# Corruption: digest verification and fallback
# ---------------------------------------------------------------------------

def test_corrupt_shard_falls_back_to_previous(tmp_path):
    e1 = _make_engine(tmp_path)
    stream = _batch_stream(3)
    for b in stream[:2]:
        e1.train_batch(b)
    e1.ckpt_manager.wait()
    params_at_1 = None
    ckpts = list_checkpoints(str(tmp_path))
    assert [s for s, _ in ckpts] == [1, 2]
    # Torn write / bitrot on the newest checkpoint:
    manifest = json.load(open(os.path.join(ckpts[1][1], MANIFEST_FILE)))
    corrupt_one_shard(ckpts[1][1], manifest)

    path, found_manifest, _, _ = find_restorable(str(tmp_path))
    assert path == ckpts[0][1]              # fell back past the torn one
    assert found_manifest["step"] == 1

    e2 = _make_engine(tmp_path)
    rpath, _ = e2.auto_resume()
    assert rpath == ckpts[0][1]
    assert e2.global_steps == 1
    e1.ckpt_manager.close()
    e2.ckpt_manager.close()


def test_corrupt_injection_at_step(tmp_path):
    """FaultPlan.corrupt_shard_at_step corrupts after commit — the loader
    must skip it by digest."""
    e = _make_engine(tmp_path,
                     fault_injection={"corrupt_shard_at_step": 2})
    for b in _batch_stream(2):
        e.train_batch(b)
    e.ckpt_manager.wait()
    path, manifest, _, _ = find_restorable(str(tmp_path))
    assert manifest["step"] == 1
    e.ckpt_manager.close()


def test_tmp_dirs_and_junk_never_considered(tmp_path):
    e = _make_engine(tmp_path)
    e.train_batch(_batch_stream(1)[0])
    e.ckpt_manager.wait()
    os.makedirs(tmp_path / ".tmp-step_00000009")   # death mid-write residue
    os.makedirs(tmp_path / "step_notanumber")
    assert [s for s, _ in list_checkpoints(str(tmp_path))] == [1]
    e.ckpt_manager.close()


def test_no_checkpoint_means_fresh_start(tmp_path):
    e = _make_engine(tmp_path)
    path, client = e.auto_resume()
    assert path is None and client == {}
    assert e.global_steps == 0
    e.ckpt_manager.close()


# ---------------------------------------------------------------------------
# Elastic resume: different world size, reshard, hash pinning
# ---------------------------------------------------------------------------

def test_elastic_resume_reshards_zero1(tmp_path):
    """Save under dp=8, resume under dp=4 with the same global batch: the
    gathered shards are device_put against the new mesh's shardings (the
    reshard), and the trajectory matches the uninterrupted dp=8 run."""
    stream = _batch_stream(5)
    e1 = _make_engine(tmp_path, dp=8, micro_bs=2)   # global batch 16
    for b in stream[:3]:
        e1.train_batch(b)
    e1.ckpt_manager.wait()

    e2 = _make_engine(tmp_path, dp=4, micro_bs=4)   # same global batch 16
    path, _ = e2.auto_resume()
    assert path is not None
    assert e2.global_steps == 3
    _params_equal(e1.state.params, e2.state.params)
    # ZeRO-1 optimizer state landed sharded over the NEW data axis:
    leaf = jax.tree_util.tree_leaves(e2.state.opt_state.exp_avg)[0]
    assert leaf.sharding.mesh.shape["data"] == 4

    cont1 = [float(e1.train_batch(b)) for b in stream[3:]]
    cont2 = [float(e2.train_batch(b)) for b in stream[3:]]
    # Same math, different dp reduction grouping — exact up to fp
    # association, so tight allclose rather than bit-equal:
    np.testing.assert_allclose(cont1, cont2, rtol=1e-5, atol=1e-6)
    e1.ckpt_manager.close()
    e2.ckpt_manager.close()


def test_elastic_hash_mismatch_refuses_resume(tmp_path):
    e1 = _make_engine(tmp_path)
    e1.elastic_hash = "aaaa"     # pretend an elastic ladder pinned the run
    e1.train_batch(_batch_stream(1)[0])
    e1.ckpt_manager.wait()
    e2 = _make_engine(tmp_path)
    e2.elastic_hash = "bbbb"     # resumed under a different batch math
    with pytest.raises(ResilienceError, match="elastic config hash"):
        restore(e2, str(tmp_path))
    e1.ckpt_manager.close()
    e2.ckpt_manager.close()


def test_pick_preferred_world():
    from deepspeed_tpu.elasticity import (ElasticityIncompatibleWorldSize,
                                          compute_elastic_config,
                                          pick_preferred_world)
    ds_config = {"elasticity": {"enabled": True,
                                "max_train_batch_size": 10000,
                                "micro_batch_sizes": [8, 12, 16, 17],
                                "min_chips": 32, "max_chips": 1500,
                                "version": 0.1}}
    _, valid = compute_elastic_config(ds_config, "0.3.1")
    w = pick_preferred_world(ds_config, available_chips=max(valid))
    assert w == max(valid)
    smaller = pick_preferred_world(ds_config, available_chips=max(valid) - 1)
    assert smaller in valid and smaller < max(valid)
    with pytest.raises(ElasticityIncompatibleWorldSize):
        pick_preferred_world(ds_config, available_chips=min(valid) - 1)


def test_elastic_config_hash_stability():
    from deepspeed_tpu.elasticity import elastic_config_hash
    block = {"enabled": True, "max_train_batch_size": 1024,
             "micro_batch_sizes": [4, 8], "min_chips": 8, "max_chips": 64}
    h1 = elastic_config_hash(dict(block))
    h2 = elastic_config_hash({**block,
                              "micro_batch_sizes": [8, 4]})  # order-free
    assert h1 == h2 and h1
    assert elastic_config_hash({**block, "max_train_batch_size": 512}) != h1
    assert elastic_config_hash({"enabled": False}) == ""
    assert elastic_config_hash(None) == ""


# ---------------------------------------------------------------------------
# FaultPlan resolution and scoping
# ---------------------------------------------------------------------------

def test_fault_plan_env_override_and_unknown_keys(monkeypatch):
    monkeypatch.setenv(FAULT_PLAN_ENV, '{"preempt_at_step": 4}')
    plan = FaultPlan.resolve({"ckpt_write_errors": 1})
    assert plan.preempt_at_step == 4 and plan.ckpt_write_errors == 1
    monkeypatch.setenv(FAULT_PLAN_ENV, '{"not_a_fault": 1}')
    with pytest.raises(ValueError, match="unknown fault_injection keys"):
        FaultPlan.resolve({})
    monkeypatch.setenv(FAULT_PLAN_ENV, 'not json')
    with pytest.raises(ValueError, match="not a JSON object"):
        FaultPlan.resolve({})
    monkeypatch.delenv(FAULT_PLAN_ENV)
    assert FaultPlan.resolve({}) is None
    assert FaultPlan.resolve(None) is None


def test_fault_plan_inert_after_its_restart(monkeypatch):
    """The injected death must not re-fire in the incarnation it caused."""
    block = {"preempt_at_step": 2}
    assert FaultPlan.resolve(block).should_preempt(2)
    monkeypatch.setenv(RESUME_ATTEMPT_ENV, "1")
    assert FaultPlan.resolve(block) is None
    assert FaultPlan.resolve({**block, "max_attempt": 1}) is not None


def test_config_validation():
    from deepspeed_tpu.config.config import ConfigError, DeepSpeedTPUConfig
    base = {"train_micro_batch_size_per_gpu": 1}
    with pytest.raises(ConfigError, match="checkpoint.dir"):
        DeepSpeedTPUConfig({**base, "resilience": {"enabled": True}})
    with pytest.raises(ConfigError, match="interval"):
        DeepSpeedTPUConfig({**base, "resilience": {
            "enabled": True,
            "checkpoint": {"dir": "/tmp/x", "interval": 0}}})
    cfg = DeepSpeedTPUConfig({**base, "resilience": {
        "enabled": True, "checkpoint": {"dir": "/tmp/x", "interval": 5}}})
    assert cfg.resilience.checkpoint.interval == 5
    assert DeepSpeedTPUConfig(base).resilience.enabled is False


# ---------------------------------------------------------------------------
# Dataloader replay
# ---------------------------------------------------------------------------

class _CountingSampler:
    def __init__(self):
        self.epoch = 0

    def set_epoch(self, e):
        self.epoch = e


class _ListLoader:
    """Epoch-aware toy loader: item values depend on the sampler epoch the
    way a shuffling sampler's permutation does."""

    def __init__(self, n):
        self.n = n
        self.sampler = _CountingSampler()

    def __iter__(self):
        base = self.sampler.epoch * 100
        return iter(range(base, base + self.n))


def test_repeating_loader_replay_is_exact():
    src = RepeatingLoader(_ListLoader(4))
    consumed = [next(src) for _ in range(10)]   # crosses 2 epoch boundaries
    sd = src.state_dict()
    assert sd == {"epoch": 2, "batch_in_epoch": 2}

    resumed = RepeatingLoader(_ListLoader(4))
    resumed.load_state_dict(sd)
    tail = [next(resumed) for _ in range(5)]
    cont = [next(src) for _ in range(5)]
    assert tail == cont                          # identical post-resume stream
    # and the replayed prefix saw the same epochs the original did:
    assert resumed.state_dict() == src.state_dict()


def test_client_state_rides_checkpoints(tmp_path):
    e = _make_engine(tmp_path)
    loader = RepeatingLoader(_ListLoader(4))
    e.register_client_state_fn(lambda: {"loader": loader.state_dict()})
    for _ in range(3):
        next(loader)
        e.train_batch(_batch_stream(1)[0])
    e.ckpt_manager.wait()
    _, _, _, client = find_restorable(str(tmp_path))
    assert client["loader"] == {"epoch": 0, "batch_in_epoch": 3}
    e.ckpt_manager.close()


# ---------------------------------------------------------------------------
# Supervisor
# ---------------------------------------------------------------------------

def test_supervisor_restarts_until_success(tmp_path):
    marker = tmp_path / "died_once"
    script = textwrap.dedent(f"""
        import os, sys
        marker = {str(marker)!r}
        attempt = int(os.environ.get({RESUME_ATTEMPT_ENV!r}, "0"))
        with open({str(tmp_path / "attempts.log")!r}, "a") as f:
            f.write(str(attempt) + "\\n")
        if not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit(17)   # first incarnation dies
        sys.exit(0)
    """)
    sup = Supervisor([sys.executable, "-c", script], max_restarts=3,
                     backoff=0.01)
    assert sup.run() == 0
    assert sup.restarts == 1
    assert sup.exit_codes == [17, 0]
    attempts = open(tmp_path / "attempts.log").read().split()
    assert attempts == ["0", "1"]   # each incarnation saw its attempt index


def test_supervisor_gives_up_after_budget():
    sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(3)"],
                     max_restarts=2, backoff=0.01)
    assert sup.run() == 3
    assert sup.exit_codes == [3, 3, 3]


def test_supervisor_elastic_world_env(tmp_path):
    out = tmp_path / "world.log"
    script = (f"import os; open({str(out)!r}, 'a').write("
              f"os.environ.get('DSTPU_ELASTIC_WORLD', '?') + '\\n')")
    sup = Supervisor([sys.executable, "-c", script], max_restarts=0,
                     available_worlds=lambda attempt: 8 // (attempt + 1))
    assert sup.run() == 0
    assert open(out).read().split() == ["8"]


# ---------------------------------------------------------------------------
# End to end: SIGTERM at step k -> auto-resume -> bit-identical trajectory
# ---------------------------------------------------------------------------

_TRAIN_SCRIPT = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, sys.argv[4])
    import numpy as np
    from deepspeed_tpu import initialize
    from deepspeed_tpu.parallel.mesh import build_mesh
    from simple_model import mlp_params, mlp_loss_fn, random_batches

    ckpt_dir, total_steps, out = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    engine, _, _, _ = initialize(
        loss_fn=mlp_loss_fn, params=mlp_params(),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 1000,
            "resilience": {"enabled": True,
                           "checkpoint": {"dir": ckpt_dir, "interval": 1,
                                          "backoff_seconds": 0.01}},
        },
        mesh=build_mesh(data=8), rng_seed=0)
    engine.auto_resume()
    # Deterministic stream indexed by global step: the resumed incarnation
    # regenerates the SAME batches the dead one saw.
    rng = np.random.default_rng(7)
    stream = [random_batches(rng, 1, batch_size=16)
              for _ in range(total_steps)]
    with open(out, "a", buffering=1) as f:
        for i in range(engine.global_steps, total_steps):
            loss = float(engine.train_batch(stream[i]))
            f.write(json.dumps({"step": i + 1, "loss": repr(loss)}) + "\\n")
    engine.ckpt_manager.close()
""")


def _trajectory(path):
    """step -> loss repr, last write wins (re-executed steps supersede)."""
    out = {}
    with open(path) as f:
        for line in f:
            row = json.loads(line)
            out[row["step"]] = row["loss"]
    return out


@pytest.mark.parametrize("preempt_step", [3])
def test_sigterm_resume_bit_identical_trajectory(tmp_path, preempt_step):
    """The acceptance gate: SIGTERM injected after step k, the supervisor
    restarts the job, it auto-resumes from the newest complete manifest,
    and every step's loss — including k..k+3 — is bit-identical to an
    uninterrupted run of the same config/seed."""
    total = preempt_step + 4
    env = {"JAX_PLATFORMS": "cpu"}

    faulted = tmp_path / "faulted"
    faulted.mkdir()
    sup = Supervisor(
        [sys.executable, "-c", _TRAIN_SCRIPT, str(faulted / "ckpt"),
         str(total), str(faulted / "losses.jsonl"), TESTS_DIR],
        max_restarts=2, backoff=0.01,
        env={**env,
             FAULT_PLAN_ENV: json.dumps({"preempt_at_step": preempt_step})})
    assert sup.run() == 0
    assert sup.restarts == 1          # died exactly once, at step k
    assert sup.exit_codes[0] != 0 and sup.exit_codes[-1] == 0

    clean = tmp_path / "clean"
    clean.mkdir()
    rc = subprocess.run(
        [sys.executable, "-c", _TRAIN_SCRIPT, str(clean / "ckpt"),
         str(total), str(clean / "losses.jsonl"), TESTS_DIR],
        env={**os.environ, **env}).returncode
    assert rc == 0

    got = _trajectory(faulted / "losses.jsonl")
    want = _trajectory(clean / "losses.jsonl")
    assert set(got) == set(range(1, total + 1))
    assert got == want   # bit-identical: compared as float reprs
