"""Memory evidence: ZeRO sharding economy, pipeline activation scaling,
zero_init shard-at-construction.

VERDICT r1 asked for measured live-buffer peaks instead of assertions:
``compiled.memory_analysis()`` gives XLA's own accounting (argument bytes =
resident state, temp bytes = transient/activation peak) on the same virtual
8-device mesh the sharding tests use.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import build_mesh


def big_mlp_loss(params, batch, rng):
    h = batch["x"]
    for name in sorted(params):
        h = jnp.tanh(h @ params[name])
    return jnp.mean(h ** 2)


def big_mlp_params(d=256, layers=4):
    ks = jax.random.split(jax.random.PRNGKey(0), layers)
    return {f"w{i}": jax.random.normal(ks[i], (d, d)) * 0.05
            for i in range(layers)}


def engine_for_stage(stage, params):
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=big_mlp_loss, params=params,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": stage,
                 "stage3_param_persistence_threshold": 1024}})
    return engine


def compiled_step_stats(engine, batch):
    lowered = engine._train_step.lower(
        engine.state, engine.put_batch(batch, leading_gas_dim=True),
        jnp.float32(1e-3))
    return lowered.compile().memory_analysis()


class TestZeroMemory:
    def test_stage3_resident_state_smaller_than_stage1(self, eight_devices,
                                                       rng):
        """Per-device resident bytes (params + moments + grads as compiled
        arguments) must shrink as the stage rises: stage 3 shards the
        params themselves (partition_parameters.py economy)."""
        batch = {"x": rng.standard_normal((1, 8, 256)).astype(np.float32)}
        stats = {}
        for stage in (1, 3):
            e = engine_for_stage(stage, big_mlp_params())
            stats[stage] = compiled_step_stats(e, batch)
        # memory_analysis reports whole-program sizes; arguments are the
        # TrainState. Sharded leaves count shard bytes per device.
        assert stats[3].argument_size_in_bytes < \
            stats[1].argument_size_in_bytes
        # stage-3 transient re-gathers must not blow past one extra full
        # param copy over stage 1's transients.
        params_bytes = 4 * 256 * 256 * 4
        assert stats[3].temp_size_in_bytes <= \
            stats[1].temp_size_in_bytes + 2 * params_bytes

    def test_state_leaves_actually_sharded_per_stage(self, eight_devices):
        p = big_mlp_params()
        e1 = engine_for_stage(1, p)
        e3 = engine_for_stage(3, p)
        w_1 = e1.state.params["w0"]
        w_3 = e3.state.params["w0"]
        assert w_1.sharding.shard_shape(w_1.shape) == (256, 256)  # replicated
        assert np.prod(w_3.sharding.shard_shape(w_3.shape)) == \
            256 * 256 // 8                                        # sharded
        m_1 = e1.state.opt_state.exp_avg["w0"]
        assert np.prod(m_1.sharding.shard_shape(m_1.shape)) == 256 * 256 // 8


class TestPipelineMemory:
    def _stats_for(self, M, remat):
        from deepspeed_tpu.parallel.pipe.pipeline import (_PIPELINE_CACHE,
                                                          pipeline_apply,
                                                          stack_blocks)

        mesh = build_mesh(pipe=4, data=2)
        d, mb, L = 128, 4, 8

        def block_fn(p, h, a, k):
            return jnp.tanh(h @ p["w"])

        blocks = stack_blocks([{"w": jnp.eye(d) * 0.5} for _ in range(L)])

        def train(blocks, x):
            def loss(bp):
                out = pipeline_apply(block_fn, bp, x, mesh,
                                     remat_blocks=remat)
                return jnp.mean(out ** 2)

            return jax.value_and_grad(loss)(blocks)

        x = jnp.ones((M, mb, d), jnp.float32)
        with mesh:
            stats = jax.jit(train).lower(blocks, x).compile() \
                .memory_analysis()
        return stats

    def test_activation_peak_growth_is_boundary_only(self, eight_devices):
        """Fill-drain + per-block remat: the per-microbatch memory cost must
        be the stage-boundary activation (mb x d fp32 per tick), NOT the
        block-internal activations — the economy that makes the jitted
        fill-drain competitive with hand-scheduled 1F1B (whose buffer bound
        pays block internals x stage depth instead; see
        parallel/pipe/schedule.py for the tape we deliberately don't
        execute)."""
        s4 = self._stats_for(M=4, remat=True)
        s16 = self._stats_for(M=16, remat=True)
        d, mb = 128, 4
        boundary = mb * d * 4                      # one tick's carry, fp32
        per_m = (s16.temp_size_in_bytes - s4.temp_size_in_bytes) / 12.0
        # generous factor: fwd carry + ppermute buf + output + cotangents
        assert per_m <= 16 * boundary, \
            f"per-microbatch growth {per_m} suggests block internals leak " \
            f"into the saved set (boundary={boundary})"

    def test_remat_bounds_saved_internals(self, eight_devices):
        """Without remat the scan saves block internals for every tick —
        measurably more temp than the remat path at the same M."""
        with_remat = self._stats_for(M=8, remat=True)
        without = self._stats_for(M=8, remat=False)
        assert with_remat.temp_size_in_bytes <= without.temp_size_in_bytes


class TestZeroInit:
    def test_params_born_sharded(self, eight_devices):
        from deepspeed_tpu.models import make_gpt

        from deepspeed_tpu.runtime.zero.config import ZeroConfig

        model, cfg = make_gpt("tiny", dropout_rate=0.0)
        mesh = build_mesh(data=-1)
        zcfg = ZeroConfig()
        zcfg.stage = 3
        zcfg.param_persistence_threshold = 1024
        params, specs = deepspeed_tpu.zero_init(
            model, {"input_ids": np.zeros((2, 16), np.int32)}, mesh=mesh,
            zero_config=zcfg)
        wte = params["wte"]
        assert np.prod(wte.sharding.shard_shape(wte.shape)) == \
            wte.size // 8, "embedding not born sharded over data"
        # every shardable leaf holds only 1/8 of its bytes per device
        total = sum(l.size for l in jax.tree_util.tree_leaves(params))
        per_dev = 0
        for l in jax.tree_util.tree_leaves(params):
            per_dev += np.prod(l.sharding.shard_shape(l.shape))
        assert per_dev < 0.55 * total  # small leaves stay replicated

    def test_trains_from_zero_init(self, eight_devices, rng):
        from deepspeed_tpu.models import make_gpt

        model, cfg = make_gpt("tiny", dropout_rate=0.0)
        mesh = build_mesh(data=-1)
        params, _ = deepspeed_tpu.zero_init(
            model, {"input_ids": np.zeros((8, 16), np.int32)}, mesh=mesh)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, params=params, mesh=mesh,
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 3}})
        ids = rng.integers(0, cfg.vocab_size, (2, 8, 16)).astype(np.int32)
        loss = float(engine.train_batch({"input_ids": ids}))
        assert np.isfinite(loss)

    def test_no_host_full_tree(self, eight_devices):
        """The init program's own output buffers are the shards — XLA's
        memory analysis shows output bytes ~= sharded size, proving no
        device materializes the replicated tree."""
        from deepspeed_tpu.models import make_gpt
        from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner
        from deepspeed_tpu.runtime.zero.config import ZeroConfig
        from jax.sharding import NamedSharding

        model, cfg = make_gpt("tiny", dropout_rate=0.0)
        mesh = build_mesh(data=-1)
        rngs = {"params": jax.random.PRNGKey(0),
                "dropout": jax.random.PRNGKey(1)}
        batch = {"input_ids": np.zeros((2, 16), np.int32)}

        def init_fn(r):
            return model.init(r, batch)["params"]

        abstract = jax.eval_shape(init_fn, rngs)
        zcfg = ZeroConfig()
        zcfg.stage = 3
        zcfg.param_persistence_threshold = 1024
        specs = ZeroPartitioner(mesh, zcfg).param_specs(abstract)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), specs)
        with mesh:
            stats = jax.jit(init_fn, out_shardings=shardings) \
                .lower(rngs).compile().memory_analysis()
        total = sum(int(np.prod(l.shape)) * 4
                    for l in jax.tree_util.tree_leaves(abstract))
        # outputs are per-device shards: well under the full fp32 tree
        assert stats.output_size_in_bytes < 0.7 * total


class TestCollectiveBytes:
    """ZeRO collective-traffic evidence (round-2 VERDICT weak #7): count
    the bytes moved by all-gather / reduce-scatter / all-reduce in the
    compiled 8-device train step and pin them to the ZeRO model: stage 2
    moves O(param_bytes) per step (grad reduce-scatter + param gathers at
    use), not a multiple blow-up."""

    def _collective_bytes(self, engine, batch):
        import re

        lowered = engine._train_step.lower(
            engine.state, engine.put_batch(batch, leading_gas_dim=True),
            jnp.float32(1e-3))
        hlo = lowered.compile().as_text()
        dt_bytes = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
                    "pred": 1, "f64": 8, "s8": 1, "u8": 1}
        totals = {}
        for op in ("all-gather", "reduce-scatter", "all-reduce",
                   "all-to-all", "collective-permute"):
            total = 0
            for line in hlo.splitlines():
                if f" {op}(" not in line and f"{op}-start(" not in line:
                    continue
                m = re.search(r"=\s+(?:\()?([a-z0-9]+)\[([0-9,]*)\]", line)
                if not m:
                    continue
                dt, shape = m.groups()
                elems = 1
                for s in shape.split(","):
                    if s:
                        elems *= int(s)
                total += elems * dt_bytes.get(dt, 4)
            totals[op] = total
        return totals

    def test_stage2_collective_bytes_order_param_bytes(self, eight_devices):
        batch = {"x": np.zeros((2, 8, 256), np.float32)}
        e = engine_for_stage(2, big_mlp_params())
        n_bytes = 4 * sum(int(np.prod(p.shape))
                          for p in jax.tree_util.tree_leaves(
                              e.state.params))
        totals = self._collective_bytes(e, batch)
        moved = sum(totals.values())
        assert moved > 0, totals
        # stage 2: grads reduce-scatter + updated-param all-gather —
        # a small constant times the param bytes, not quadratic in dp.
        assert moved <= 4 * n_bytes, (totals, n_bytes)

    def test_stage0_uses_allreduce_not_gather(self, eight_devices):
        batch = {"x": np.zeros((2, 8, 256), np.float32)}
        e0 = engine_for_stage(0, big_mlp_params())
        t0 = self._collective_bytes(e0, batch)
        assert t0["all-reduce"] > 0, t0
        assert t0["all-gather"] == 0, t0


class TestMemoryEstimator:
    """estimate_zero_model_states_mem_needs vs hand-computed byte budgets
    (reference estimators: stage2.py:2005 16-bytes/param offload economy,
    stage3.py:3272 18-bytes/param with offload_params — round-3 VERDICT
    weak #7: the stage<3 / stage-3 offload arms must differ)."""

    def _est(self, **kw):
        from deepspeed_tpu.runtime.zero.partition import \
            estimate_zero_model_states_mem_needs
        return estimate_zero_model_states_mem_needs(**kw)

    def test_hand_computed_budgets_1b_8dev(self):
        gb = 1024**3
        p = 10**9
        # bf16 params 2p, bf16 grads 2p, fp32 master+2 moments 12p
        cases = {
            (0, False): (2 + 2 + 12, 0),
            (1, False): (2 + 2 + 12 / 8, 0),
            (2, False): (2 + (2 + 12) / 8, 0),
            (3, False): ((2 + 2 + 12) / 8, 0),
            # offload: master+optim -> host (full at stage 0, sharded >=1)
            (0, True): (2 + 2, 12),
            (1, True): (2 + 2, 12 / 8),
            (2, True): (2 + 2 / 8, 12 / 8),
            # stage-3 offload implies offload_param: the bf16 param
            # partition leaves HBM too (18-vs-16 bytes/param, ref stage3)
            (3, True): (2 / 8, (12 + 2) / 8),
        }
        for (stage, off), (hbm_p, host_p) in cases.items():
            got = self._est(total_params=p, num_devices=8, stage=stage,
                            cpu_offload=off)
            np.testing.assert_allclose(got["hbm_gb"], hbm_p * p / gb,
                                       rtol=1e-6, err_msg=f"{stage},{off}")
            np.testing.assert_allclose(got["host_gb"], host_p * p / gb,
                                       rtol=1e-6, err_msg=f"{stage},{off}")

    def test_stage3_offload_differs_from_stage2(self):
        e2 = self._est(total_params=10**9, num_devices=8, stage=2,
                       cpu_offload=True)
        e3 = self._est(total_params=10**9, num_devices=8, stage=3,
                       cpu_offload=True)
        assert e3["hbm_gb"] < e2["hbm_gb"]
        assert e3["host_gb"] > e2["host_gb"]

    def test_matches_reference_scaling(self):
        """Per-device host bytes under stage-2 offload scale as ~16p/N in
        the reference (fp32 master+moments+grad-staging); ours models the
        persistent 12p/N tier — check the 4/3 ratio stays exact so the
        estimates stay comparable."""
        p, n = 7_000_000_000, 64
        ours = self._est(total_params=p, num_devices=n, stage=2,
                         cpu_offload=True)["host_gb"]
        ref_per_device = 16 * p / n / 1024**3  # stage2.py:2016 per rank
        np.testing.assert_allclose(ref_per_device / ours, 16 / 12, rtol=1e-6)


class TestTiledLinear:
    """TiledLinear (round-3 VERDICT missing #6; reference zero/tiling.py):
    a huge single layer under ZeRO-3 gathers tile-by-tile — transient
    gathered bytes bound by numel/T, numerics identical to Dense."""

    def test_matches_dense_numerics(self, eight_devices):
        import flax.linen as nn

        from deepspeed_tpu.ops.tiled_linear import TiledLinear

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
        dense = nn.Dense(256)
        pd = dense.init(jax.random.PRNGKey(0), x)["params"]
        tiled = TiledLinear(features=256, out_splits=4)
        pt = {"kernel": jnp.stack(jnp.split(pd["kernel"], 4, axis=1)),
              "bias": pd["bias"]}
        yd = dense.apply({"params": pd}, x)
        yt = tiled.apply({"params": pt}, x)
        np.testing.assert_allclose(np.asarray(yt), np.asarray(yd),
                                   rtol=1e-5, atol=1e-6)
        # gradients too
        gd = jax.grad(lambda p: jnp.sum(
            dense.apply({"params": p}, x) ** 2))(pd)
        gt = jax.grad(lambda p: jnp.sum(
            tiled.apply({"params": p}, x) ** 2))(pt)
        np.testing.assert_allclose(
            np.asarray(gt["kernel"]).transpose(1, 0, 2).reshape(64, 256),
            np.asarray(gd["kernel"]), rtol=1e-5, atol=1e-5)

    def test_stage3_transient_bytes_bounded_by_tile(self, eight_devices):
        """Compiled peak temp bytes with 8 tiles ≪ with 1 tile: the scan
        gathers piecewise (the reference TiledLinear's whole point)."""
        from deepspeed_tpu.ops.tiled_linear import TiledLinear, \
            tiled_linear_spec

        d, out = 512, 4096

        def peak(splits):
            tiled = TiledLinear(features=out, out_splits=splits,
                                use_bias=False, remat_tiles=True)
            x = jnp.zeros((2, d), jnp.bfloat16)
            params = tiled.init(jax.random.PRNGKey(0), x)["params"]

            def loss_fn(p, b, r):
                return jnp.mean(tiled.apply({"params": p}, b["x"]) ** 2)

            engine, _, _, _ = deepspeed_tpu.initialize(
                loss_fn=loss_fn, params=params,
                param_partition_specs={"kernel": tiled_linear_spec()},
                config={"train_micro_batch_size_per_gpu": 2,
                        "optimizer": {"type": "Adam", "params": {}},
                        "zero_optimization": {
                            "stage": 3,
                            "stage3_param_persistence_threshold": 0},
                        "bf16": {"enabled": True}})
            batch = {"x": np.zeros((1, 16, d), np.float32)}
            lowered = engine._train_step.lower(
                engine.state, engine.put_batch(batch, leading_gas_dim=True),
                jnp.float32(1e-3))
            return lowered.compile().memory_analysis().temp_size_in_bytes

        p1, p8 = peak(1), peak(8)
        assert p8 < p1 * 0.55, (p1, p8)


class TestRowSparseAllreduce:
    """CSR embedding-grad exchange capability (round-3 VERDICT missing #7;
    reference engine.py:1530-1586 sparse_gradients): touched rows cross
    the wire, dense grad rebuilt locally — equals the dense allreduce."""

    def test_matches_dense_pmean(self, eight_devices):
        from deepspeed_tpu.comm.sparse import (row_sparse_allreduce_jit,
                                               scatter_rows)
        from deepspeed_tpu.parallel.mesh import build_mesh

        mesh = build_mesh(data=8)
        rng = np.random.default_rng(0)
        n, N, V, D = 8, 16, 1000, 8
        ids = rng.integers(0, V, (n, N)).astype(np.int32)
        rows = rng.standard_normal((n, N, D)).astype(np.float32)
        out = row_sparse_allreduce_jit(jnp.asarray(ids), jnp.asarray(rows),
                                       V, mesh)
        ref = np.zeros((V, D), np.float32)
        for r in range(n):
            ref += np.asarray(scatter_rows(jnp.asarray(ids[r]),
                                           jnp.asarray(rows[r]), V))
        ref /= n
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5,
                                   atol=1e-6)

    def test_wire_bytes_scale_with_rows_not_vocab(self):
        # documented contract: gathered payload is 2*n*N*D numbers
        n, N, D, V = 8, 16, 8, 1000
        gathered = n * N * (D + 1)
        dense = V * D
        assert gathered < dense  # the regime the op exists for
