"""Elastic-training config math (reference ``deepspeed/elasticity/elasticity.py``).

Given an elasticity block, compute a total train batch size plus the list of
chip counts the job can scale across *without* changing convergence — the
batch decomposes as ``micro_batch x grad_accum x world`` for every valid
world size (reference ``compute_elastic_config`` at elasticity.py:226,
``_get_compatible_gpus_v01`` at :124).

TPU-native notes: "GPUs" in the reference become chips here; on TPU the
realistic world sizes are slice shapes (multiples of 4/8), which the
``min_chips``/``max_chips`` bounds express. The highly-composite-number
ladder is *generated* (prime-exponent recursion) instead of hardcoded, so
arbitrary ``max_train_batch_size`` values are supported.
"""

import json
import os
from functools import lru_cache
from math import lcm
from typing import Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.version import __version__

ELASTICITY_KEY = "elasticity"
LATEST_ELASTICITY_VERSION = 0.1
# Elasticity semantics are stable since the first release of this framework.
MINIMUM_FRAMEWORK_VERSION = "0.1.0"
# Env var through which the resource scheduler pins the elastic config it
# scheduled against (reference constants.py DEEPSPEED_ELASTICITY_CONFIG).
ELASTICITY_ENV = "DEEPSPEED_ELASTICITY_CONFIG"


class ElasticityError(Exception):
    """Base exception for elasticity."""


class ElasticityConfigError(ElasticityError):
    """Malformed/missing elasticity configuration."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """World size not in the valid chip-count list of the elastic config."""


class ElasticityConfig:
    """Typed view of the ``elasticity`` config block (reference
    ``elasticity/config.py:30``). Accepts both the reference's ``*_gpus``
    keys and TPU-flavoured ``*_chips`` aliases."""

    def __init__(self, d: Dict):
        self.enabled = bool(d.get("enabled", False))
        if self.enabled:
            if "max_train_batch_size" not in d:
                raise ElasticityConfigError(
                    "elasticity config missing 'max_train_batch_size'")
            if "micro_batch_sizes" not in d:
                raise ElasticityConfigError(
                    "elasticity config missing 'micro_batch_sizes'")
        self.max_acceptable_batch_size = int(d.get("max_train_batch_size", 2000))
        self.micro_batches = d.get("micro_batch_sizes", [2, 4, 6])
        if not isinstance(self.micro_batches, (list, tuple)) or \
                not self.micro_batches:
            raise ElasticityConfigError(
                f"'micro_batch_sizes' must be a non-empty list, got "
                f"{self.micro_batches!r}")
        if not all(isinstance(m, int) and m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"'micro_batch_sizes' must be positive ints, got "
                f"{self.micro_batches!r}")
        self.micro_batches = list(self.micro_batches)
        self.min_chips = int(d.get("min_chips", d.get("min_gpus", 1)))
        self.max_chips = int(d.get("max_chips", d.get("max_gpus", 10000)))
        self.min_time = int(d.get("min_time", 0))
        self.prefer_larger_batch_size = bool(d.get("prefer_larger_batch", True))
        self.ignore_non_elastic_batch_info = bool(
            d.get("ignore_non_elastic_batch_info", False))
        self.version = float(d.get("version", LATEST_ELASTICITY_VERSION))

    def repr_dict(self) -> Dict:
        return {
            "enabled": self.enabled,
            "max_train_batch_size": self.max_acceptable_batch_size,
            "micro_batch_sizes": self.micro_batches,
            "min_chips": self.min_chips,
            "max_chips": self.max_chips,
            "version": self.version,
        }


@lru_cache(maxsize=None)
def highly_composite_numbers(limit: int) -> Tuple[int, ...]:
    """All highly composite numbers <= limit, generated.

    A HCN has a prime factorisation over the first k primes with
    non-increasing exponents; enumerate that (small) candidate set and keep
    the divisor-count records. Replaces the reference's 38-entry hardcoded
    table (elasticity.py:21) and extends past its 720720 ceiling.
    """
    primes = [2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37]
    candidates: List[Tuple[int, int]] = []  # (value, n_divisors)

    def rec(i: int, value: int, max_exp: int, ndiv: int):
        candidates.append((value, ndiv))
        if i >= len(primes):
            return
        p, v, e = primes[i], value, 0
        while e < max_exp:
            v *= p
            if v > limit:
                break
            e += 1
            rec(i + 1, v, e, ndiv * (e + 1))

    rec(0, 1, 64, 1)
    best = 0
    out = []
    for value, ndiv in sorted(candidates):
        if ndiv > best:
            best = ndiv
            out.append(value)
    return tuple(out)


def _scaled_candidates(bases: Sequence[int], max_batch: int) -> List[int]:
    """For each base batch, the largest ``base * hcn`` <= max_batch
    (reference get_candidate_batch_sizes, elasticity.py:64)."""
    hcns = highly_composite_numbers(max_batch)
    out = set()
    for base in bases:
        if base > max_batch:
            continue  # e.g. lcm(micro_batches) itself exceeds the cap
        scale = 1
        for h in hcns:
            if base * h > max_batch:
                break
            scale = h
        out.add(base * scale)
    return sorted(out)


def _valid_world_sizes(batch: int, micro_batches: Sequence[int],
                       lo: int, hi: int) -> List[int]:
    """Chip counts w in [lo, hi] such that batch = mb * gas * w exactly for
    some configured micro batch (reference get_valid_gpus, elasticity.py:79):
    every divisor of batch//mb is a valid world size."""
    valid = set()
    for mb in micro_batches:
        if batch % mb:
            continue
        q = batch // mb
        d = 1
        while d * d <= q:
            if q % d == 0:
                for w in (d, q // d):
                    if lo <= w <= hi:
                        valid.add(w)
            d += 1
    return sorted(valid)


def _best_batch(micro_batches: Sequence[int], max_batch: int,
                min_chips: int, max_chips: int,
                prefer_larger: bool) -> Tuple[int, List[int]]:
    """Pick the candidate batch with the most valid chip counts
    (reference _get_compatible_gpus_v01, elasticity.py:124)."""
    if any(mb > max_batch for mb in micro_batches):
        raise ElasticityConfigError(
            f"all micro batches must be <= max_train_batch_size={max_batch}")
    bases = list(micro_batches) + [lcm(*micro_batches)]
    best_batch, best_valid = min(micro_batches), []
    for cand in _scaled_candidates(bases, max_batch):
        valid = _valid_world_sizes(cand, micro_batches, min_chips, max_chips)
        better = len(valid) > len(best_valid) or (
            len(valid) == len(best_valid) and
            (cand > best_batch if prefer_larger else cand < best_batch))
        if better:
            best_batch, best_valid = cand, valid
    return best_batch, best_valid


def _version_tuple(v: str) -> Tuple[int, ...]:
    parts = []
    for tok in str(v).split("."):
        digits = "".join(ch for ch in tok if ch.isdigit())
        parts.append(int(digits) if digits else 0)
    return tuple(parts)


def elasticity_enabled(ds_config: Dict) -> bool:
    return bool(ds_config.get(ELASTICITY_KEY, {}).get("enabled", False))


def elastic_config_hash(elastic_block: Optional[Dict]) -> str:
    """Stable fingerprint of the convergence-relevant elastic keys.

    Recorded in every checkpoint manifest (resilience/checkpoint.py) and
    re-checked on auto-resume: two worlds may differ in chip count, but if
    they disagree on the batch math the resumed trajectory is a different
    experiment and the restore must refuse. Empty string when elasticity is
    off (nothing to pin — resume only requires matching state shapes)."""
    if not elastic_block or not elastic_block.get("enabled", False):
        return ""
    ecfg = ElasticityConfig(dict(elastic_block))
    canon = json.dumps({
        "max_train_batch_size": ecfg.max_acceptable_batch_size,
        "micro_batch_sizes": sorted(ecfg.micro_batches),
        "min_chips": ecfg.min_chips,
        "max_chips": ecfg.max_chips,
        "version": ecfg.version,
    }, sort_keys=True)
    import hashlib

    return hashlib.sha1(canon.encode()).hexdigest()


def pick_preferred_world(ds_config: Dict, available_chips: int,
                         target_version: str = __version__) -> int:
    """The largest valid elastic world size <= ``available_chips`` — the
    supervisor's restart-time world selection when chips were lost to
    preemption. Raises ElasticityIncompatibleWorldSize when no rung of the
    ladder fits the surviving capacity."""
    _, valid = compute_elastic_config(ds_config, target_version)
    fitting = [w for w in valid if w <= available_chips]
    if not fitting:
        raise ElasticityIncompatibleWorldSize(
            f"no valid elastic world size <= {available_chips} chips "
            f"(ladder: {valid})")
    return max(fitting)


def _splits_for(final_batch: int, micro_batches: Sequence[int],
                world_size: int) -> List[Tuple[int, int]]:
    """The ONE (micro, gas) split derivation: every configured micro
    batch dividing ``final_batch // world_size``, largest first."""
    per_world = final_batch // world_size
    return [(mb, per_world // mb)
            for mb in sorted(set(micro_batches), reverse=True)
            if per_world % mb == 0]


def valid_batch_splits(ds_config: Dict, world_size: int,
                       target_version: str = __version__
                       ) -> List[Tuple[int, int]]:
    """Every ``(micro_batch, gas)`` split the elastic ladder allows at
    ``world_size`` chips, largest micro batch first. The final train
    batch is a property of the ladder, so every pair returned satisfies
    ``micro x gas x world == final_batch`` — the invariant that keeps
    convergence unchanged across re-splits. This is the ONE micro/gas
    derivation in the tree: :func:`compute_elastic_config`'s
    ``world_size`` mode (and therefore :func:`world_change_plan`) picks
    its micro batch from the head of this list, and the autotuner's
    micro x gas search axis (autotuning/space.py) enumerates the whole
    list instead of re-deriving ladder math. Raises
    :class:`ElasticityIncompatibleWorldSize` when ``world_size`` is not a
    ladder rung."""
    final_batch, valid = compute_elastic_config(ds_config, target_version)
    if world_size not in valid:
        raise ElasticityIncompatibleWorldSize(
            f"world size {world_size} not in valid chip counts {valid}")
    ecfg = ElasticityConfig(dict(ds_config[ELASTICITY_KEY]))
    return _splits_for(final_batch, ecfg.micro_batches, world_size)


def world_change_plan(ds_config: Dict, available_chips: int,
                      target_version: str = __version__
                      ) -> Tuple[int, int, int]:
    """``(world, micro_batch, gas)`` for an in-process world change
    (resilience/elastic.py): the largest valid elastic world size fitting
    ``available_chips`` plus the micro-batch / grad-accumulation split the
    ladder prescribes for it. The final train batch is a property of the
    ladder, not of the world size, so every rung this returns preserves
    the global batch — and therefore the convergence trajectory — across
    shrink *and* rejoin. Raises :class:`ElasticityIncompatibleWorldSize`
    when no rung fits the surviving capacity (the coordinator then drains
    to disk and exits with the distinct preemption-warned rc)."""
    world = pick_preferred_world(ds_config, available_chips, target_version)
    final_batch, _, micro = compute_elastic_config(
        ds_config, target_version, world_size=world)
    return world, micro, final_batch // (micro * world)


def ensure_immutable_elastic_config(runtime_elastic_config_dict: Dict) -> None:
    """Cross-check the runtime elastic config against the one the resource
    scheduler used (env ``DEEPSPEED_ELASTICITY_CONFIG``); they must agree on
    batch math or scaling decisions are invalid (reference elasticity.py:193)."""
    if ELASTICITY_ENV not in os.environ:
        logger.warning(
            "%s not set: resource scheduler cannot be verified to scale this "
            "job with compatible chip counts", ELASTICITY_ENV)
        return
    sched = ElasticityConfig(json.loads(os.environ[ELASTICITY_ENV]))
    run = ElasticityConfig(runtime_elastic_config_dict)
    for field in ("max_acceptable_batch_size", "micro_batches", "version"):
        if getattr(sched, field) != getattr(run, field):
            raise ElasticityConfigError(
                f"elastic config mismatch between scheduler and runtime on "
                f"{field}: {getattr(sched, field)} != {getattr(run, field)}")


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str,
                           world_size: int = 0):
    """Compute (final_batch_size, valid_chip_counts[, micro_batch]) for an
    elastic job (reference compute_elastic_config, elasticity.py:226).

    ``world_size > 0`` additionally resolves the largest configured micro
    batch compatible with that world size and returns it as a third value.
    """
    if not isinstance(ds_config, dict):
        raise ValueError(f"expected dict config, got {type(ds_config)}")
    if ELASTICITY_KEY not in ds_config:
        raise ElasticityConfigError(
            f"'{ELASTICITY_KEY}' missing from config — add it to run elastic")
    ecfg = ElasticityConfig(ds_config[ELASTICITY_KEY])
    if not ecfg.enabled:
        raise ElasticityConfigError("elasticity is disabled in config")
    if ecfg.version > LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"elasticity version {ecfg.version} unsupported "
            f"(latest {LATEST_ELASTICITY_VERSION})")
    if _version_tuple(target_deepspeed_version) < _version_tuple(
            MINIMUM_FRAMEWORK_VERSION):
        raise ElasticityError(
            f"target version {target_deepspeed_version} < minimum "
            f"{MINIMUM_FRAMEWORK_VERSION} supporting elasticity "
            f"(current {__version__})")

    final_batch, valid = _best_batch(
        ecfg.micro_batches, ecfg.max_acceptable_batch_size,
        ecfg.min_chips, ecfg.max_chips, ecfg.prefer_larger_batch_size)

    if world_size > 0:
        if world_size not in valid:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not in valid chip counts {valid}")
        # One split derivation in the tree (_splits_for, largest-micro
        # first): this mode returns its head; valid_batch_splits — the
        # autotuner's re-split axis — returns the whole list.
        splits = _splits_for(final_batch, ecfg.micro_batches, world_size)
        if not splits:
            raise ElasticityError(
                f"no configured micro batch divides "
                f"{final_batch}//{world_size}")
        return final_batch, valid, splits[0][0]
    return final_batch, valid
