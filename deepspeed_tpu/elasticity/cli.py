"""``ds-elastic-tpu`` CLI — inspect an elastic config (reference ``bin/ds_elastic``).

Prints the computed total batch size and valid chip counts for a config
file, optionally resolving the micro batch for a given world size.
"""

import argparse
import json

from deepspeed_tpu.elasticity import compute_elastic_config
from deepspeed_tpu.version import __version__


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Inspect DeepSpeed-TPU elastic config batch math")
    parser.add_argument("-c", "--config", required=True,
                        help="DeepSpeed-TPU JSON config file")
    parser.add_argument("-w", "--world-size", type=int, default=0,
                        help="Resolve micro batch for this chip count")
    args = parser.parse_args(argv)

    with open(args.config) as f:
        ds_config = json.load(f)

    result = compute_elastic_config(ds_config, __version__,
                                    world_size=args.world_size)
    if args.world_size > 0:
        batch, valid, micro = result
        print(f"train_batch_size: {batch}")
        print(f"micro_batch_size @ world={args.world_size}: {micro}")
        print(f"gradient_accumulation_steps: "
              f"{batch // (args.world_size * micro)}")
    else:
        batch, valid = result
        print(f"train_batch_size: {batch}")
    print(f"valid chip counts ({len(valid)}): {valid}")


if __name__ == "__main__":
    main()
