"""Elastic training config math (reference ``deepspeed/elasticity/``)."""

from deepspeed_tpu.elasticity.elasticity import (
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    elastic_config_hash,
    elasticity_enabled,
    ensure_immutable_elastic_config,
    highly_composite_numbers,
    pick_preferred_world,
    valid_batch_splits,
    world_change_plan,
)

# Reference exposes errors under deepspeed.elasticity.config as well.
from deepspeed_tpu.elasticity import elasticity as config  # noqa: F401

__all__ = [
    "ElasticityConfig", "ElasticityConfigError", "ElasticityError",
    "ElasticityIncompatibleWorldSize", "compute_elastic_config",
    "elastic_config_hash", "elasticity_enabled",
    "ensure_immutable_elastic_config", "highly_composite_numbers",
    "pick_preferred_world", "valid_batch_splits", "world_change_plan",
    "config",
]
