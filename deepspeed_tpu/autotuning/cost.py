"""The projected-speed ranking behind trial selection.

Trials are the ground truth — this model only decides WHICH top-K
candidates earn one, so it is built from the pieces the tree already
trusts rather than a new estimator: the compute floor divides the default
step's XLA ``cost_analysis`` flops/bytes by the per-chip peak tables in
``profiling/flops_profiler`` (the same denominators every MFU in the tree
uses), and the wire term instantiates the REAL
``comm/grad_sync.GradSyncPlan`` / ``ParamGatherPlan`` on shape-only
templates and asks for their modeled exposed/wire seconds — one modeled
wire formula in the tree, not a copy. Host arithmetic only: no device
work, no compilation per candidate.
"""

from typing import Any, Dict, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger


def step_flops_bytes(engine, batches, lr) -> Dict[str, float]:
    """flops / bytes-accessed of the engine's CURRENT fused step, from
    the compiled executable's cost analysis (the XLA compilation cache
    dedupes the binary against the step the engine runs anyway)."""
    lowered = engine._train_step.lower(engine.state, batches, lr)
    cost = lowered.compile().cost_analysis() or {}
    if isinstance(cost, (list, tuple)):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    return {"flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0))}


def compute_floor_seconds(flops: float, bytes_accessed: float,
                          n_chips: int, device_kind: Optional[str],
                          dtype: str) -> float:
    """Roofline floor of the whole global step: the slower of the
    compute and HBM ceilings at the chip-kind peaks (flops_profiler's
    tables — the one source every MFU divides by)."""
    from deepspeed_tpu.profiling.flops_profiler import (peak_hbm_gbps,
                                                        peak_tflops)

    chips = max(int(n_chips), 1)
    f = (flops / (chips * peak_tflops(device_kind, dtype) * 1e12)
         if flops > 0 else 0.0)
    b = (bytes_accessed / (chips * peak_hbm_gbps(device_kind) * 1e9)
         if bytes_accessed > 0 else 0.0)
    return max(f, b)


def modeled_wire_seconds(cand_cfg, mesh, param_shapes, base_specs,
                         acc_dtype, comm_dtype, gas: int) -> float:
    """Exposed wire seconds of the candidate's explicit collectives —
    the grad-sync hop (GradSyncPlan.modeled_exposed_seconds: overlap-
    aware) plus the ZeRO++ param gather (fully exposed by construction,
    ParamGatherPlan.modeled_wire_seconds). Shape-only templates; 0.0
    when neither strategy engages (the implicit pjit path is modeled
    inside the step's own bytes)."""
    import jax

    from deepspeed_tpu.comm.grad_sync import (GradSyncPlan, ParamGatherPlan,
                                              resolve_hierarchical,
                                              resolve_overlap)
    from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner

    total = 0.0
    partitioner = ZeroPartitioner(mesh, cand_cfg.zero_config)

    def sds_tree(dtype):
        return jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(
                tuple(getattr(l, "shape", ()) or ()), dtype), param_shapes)

    try:
        on, _ = resolve_hierarchical(cand_cfg.comm, mesh,
                                     needs_local_grads=False,
                                     sparse_gradients=False, pipe_stages=1)
    except Exception:  # noqa: BLE001 — comm.hierarchical=on blockers
        on = False
    if on:
        try:
            template = sds_tree(acc_dtype)
            plan = GradSyncPlan(
                cand_cfg.comm, mesh, grad_template=template,
                grad_specs=partitioner.grad_specs(template, base_specs),
                acc_dtype=acc_dtype, ici_dtype=comm_dtype, gas=int(gas),
                overlap=resolve_overlap(cand_cfg.comm))
            total += float(plan.modeled_exposed_seconds())
        except Exception as e:  # noqa: BLE001 — ranking must never kill
            logger.warning("autotune cost model: grad-sync wire model "
                           "failed (%s) — candidate ranked compute-only", e)
    zpp = cand_cfg.zero_config.zeropp
    if getattr(zpp, "active", False) and cand_cfg.zero_config.stage >= 2:
        try:
            template = sds_tree(np.float32)
            plan = ParamGatherPlan(
                zpp, mesh, param_template=template,
                param_specs=partitioner.param_specs(template, base_specs))
            total += float(plan.modeled_wire_seconds(
                cand_cfg.comm.dcn_gbps, cand_cfg.comm.ici_gbps))
        except Exception as e:  # noqa: BLE001
            logger.warning("autotune cost model: param-gather wire model "
                           "failed (%s) — candidate ranked without it", e)
    return total


def modeled_candidate_cost(engine, cand_cfg, gas: int,
                           flops_bytes: Dict[str, float]) -> Dict[str, Any]:
    """Per-candidate modeled step seconds: shared compute floor + the
    candidate's own exposed wire term. Candidates that differ only in
    knobs the model cannot see (micro x gas on a one-chip mesh) tie and
    keep enumeration order — the measured trial breaks the tie."""
    import jax

    dev = jax.devices()[0]
    compute = compute_floor_seconds(
        flops_bytes.get("flops", 0.0),
        flops_bytes.get("bytes_accessed", 0.0),
        n_chips=engine.mesh.size,
        device_kind=getattr(dev, "device_kind", ""),
        dtype=engine.precision.name)
    wire = modeled_wire_seconds(
        cand_cfg, engine.mesh, engine.state.params, engine._base_specs,
        acc_dtype=engine.grad_accum_dtype,
        comm_dtype=engine._comm_dtype or engine.grad_accum_dtype,
        gas=gas)
    return {"compute_sec": compute, "wire_sec": wire,
            "modeled_sec": compute + wire}
