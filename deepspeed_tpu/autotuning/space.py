"""Candidate enumeration — the knob space of the startup config search.

The searched knobs are exactly the ones the observability stack proved
workload-dependent: ``zero_stage`` (capacity vs gather traffic, PR 7's
planner), the ``micro x gas`` re-split (same global batch, different
activation footprint and scan length — the elastic ladder owns the valid
splits), and the wire knobs ``bucket_mb`` / ``dcn_quant_bits`` /
``overlap_grad_sync`` / ``zeropp`` whose right values ZeRO++
(arXiv 2306.10209) and EQuARX (arXiv 2506.17615) show depend on model
and mesh shape. Every list in the ``autotuning`` config block overrides
the derived axis; empty lists derive from the runtime shape, and axes the
mesh gives no meaning (comm knobs on a single-slice mesh, zeropp below
stage 2) collapse to the base config's values instead of generating
dead duplicates.

A candidate is a plain record of knob values plus :func:`materialize`,
which turns it into a full raw config dict the normal
``DeepSpeedTPUConfig`` parse can validate — stage-1 pruning IS that
parse, so every ConfigError wall in the tree prunes candidates for free.
"""

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.config import constants as C

# Derived-axis defaults (used only where the mesh activates the axis).
DEFAULT_ZERO_STAGES = (0, 1, 2, 3)
DEFAULT_DCN_QUANT_BITS = (8, 32)
DEFAULT_ZEROPP_TIERS = ("off", "int8")
# Divisor re-splits of micro x gas are capped when elasticity is off
# (the ladder caps itself through micro_batch_sizes).
MAX_DERIVED_SPLITS = 4


@dataclass
class Candidate:
    """One point of the knob space. ``overrides`` records only the knobs
    that differ from the base config — the result JSON stores it so a
    reader sees what the candidate changed, not the whole config."""

    name: str
    zero_stage: int
    micro: int
    gas: int
    hierarchical: Optional[str] = None   # None => base value
    bucket_mb: Optional[float] = None
    dcn_quant_bits: Optional[int] = None
    overlap: Optional[str] = None
    zeropp: Optional[str] = None         # off | bf16 | int8
    # MoE axes (None => base value / moe disabled). moe_experts is a
    # PRUNE-ONLY axis: a different expert count changes the param tree
    # shapes, and a measured trial reinstalls the pre-search snapshot
    # arrays (search.py _apply_candidate) — so non-base expert counts
    # ride enumeration + config-parse pruning + the capacity projection
    # but are never trialed in-process (search.py records not_trialed).
    moe_experts: Optional[int] = None
    moe_capacity_factor: Optional[float] = None
    moe_dispatch: Optional[str] = None
    overrides: Dict[str, Any] = field(default_factory=dict)


def _divisor_splits(micro: int, gas: int) -> List[Tuple[int, int]]:
    """All (micro, gas) re-splits preserving the per-chip product —
    the non-elastic fallback axis, largest micro first."""
    product = int(micro) * int(gas)
    splits = [(m, product // m) for m in range(product, 0, -1)
              if product % m == 0]
    return splits


def batch_splits(config, world_size: int) -> List[Tuple[int, int]]:
    """The micro x gas axis: the elastic ladder's valid splits when the
    ladder is enabled (:func:`deepspeed_tpu.elasticity.valid_batch_splits`
    — ONE ladder implementation, not a copy), else ALL divisor re-splits
    of the configured per-chip product. Every pair preserves the global
    batch by construction; :func:`enumerate_candidates` caps the derived
    divisor axis (with a note — never silently)."""
    if config.elasticity_enabled:
        from deepspeed_tpu.elasticity import valid_batch_splits

        return valid_batch_splits({"elasticity": dict(config.elasticity)},
                                  world_size)
    return _divisor_splits(config.train_micro_batch_size_per_gpu,
                           config.gradient_accumulation_steps)


def enumerate_candidates(config, mesh_shape: Dict[str, int],
                         world_size: int) -> Tuple[List[Candidate],
                                                   List[str]]:
    """The candidate list (base config first) plus human-readable notes
    about every axis that was capped or collapsed — the no-silent-caps
    rule: a reader of the log/result must see what was NOT searched."""
    acfg = config.autotuning
    notes: List[str] = []
    dcn = int(mesh_shape.get("dcn", 1))
    data = int(mesh_shape.get("data", 1))
    base_comm = config.comm
    base_zpp = config.zero_config.zeropp

    stages = tuple(acfg.zero_stages) or DEFAULT_ZERO_STAGES
    if acfg.micro_gas:
        # Explicit pairs must still preserve the global batch — the
        # whole contract ("the tuner never changes convergence") dies
        # otherwise: a half-batch pair would trial ~2x "faster" and win.
        from deepspeed_tpu.config.config import ConfigError

        legal = set(batch_splits(config, world_size))
        bad = [list(p) for p in acfg.micro_gas if tuple(p) not in legal]
        if bad:
            raise ConfigError(
                f"autotuning.micro_gas pairs {bad} change the global "
                f"batch (valid splits at world {world_size}: "
                f"{sorted(legal, reverse=True)}) — the tuner only "
                f"re-splits, never re-sizes, the batch")
        splits = tuple(acfg.micro_gas)
    else:
        splits = tuple(batch_splits(config, world_size))
    if not acfg.micro_gas and len(splits) > MAX_DERIVED_SPLITS:
        # Cap the derived divisor axis, keeping the extremes + the
        # configured split — and SAY so (the no-silent-caps rule).
        base = (config.train_micro_batch_size_per_gpu,
                config.gradient_accumulation_steps)
        keep = {splits[0], splits[-1], base}
        mid = [s for s in splits if s not in keep]
        keep.update(mid[:max(0, MAX_DERIVED_SPLITS - len(keep))])
        dropped = [s for s in splits if s not in keep]
        splits = tuple(s for s in splits if s in keep)
        notes.append(
            f"micro x gas axis capped at {MAX_DERIVED_SPLITS} derived "
            f"splits (dropped {sorted(dropped)}; list them in "
            f"autotuning.micro_gas to search them)")

    # Comm axes exist only where a DCN hop exists for them to tune.
    if dcn > 1:
        hier_axis = ((base_comm.hierarchical,) if base_comm.hierarchical
                     in ("auto", "on") else ("off", "auto"))
        bits_axis = tuple(acfg.dcn_quant_bits) or DEFAULT_DCN_QUANT_BITS
        bucket_axis = tuple(acfg.bucket_mbs) or (base_comm.bucket_mb,)
        overlap_axis = tuple(acfg.overlap) or (base_comm.overlap_grad_sync,)
    else:
        hier_axis = (base_comm.hierarchical,)
        bits_axis = (base_comm.dcn_quant_bits,)
        bucket_axis = (base_comm.bucket_mb,)
        overlap_axis = (base_comm.overlap_grad_sync,)
        if acfg.dcn_quant_bits or acfg.bucket_mbs or acfg.overlap:
            notes.append("comm axes collapsed: single-slice mesh (dcn=1) "
                         "has no DCN hop to tune")

    zpp_axis = tuple(acfg.zeropp) or (
        DEFAULT_ZEROPP_TIERS if data > 1 else ("off",))
    if data <= 1 and acfg.zeropp:
        notes.append("zeropp axis collapsed: data axis is 1 — the "
                     "explicit param gather has nothing to gather")

    base_zpp_tier = (base_zpp.quantized_weights
                     if getattr(base_zpp, "active", False) else "off")

    # MoE axes exist only when the workload IS MoE (the moe block on);
    # moe_experts is prune-only — see the Candidate field comment.
    base_moe = getattr(config, "moe", None)
    moe_on = base_moe is not None and base_moe.enabled
    if moe_on:
        experts_axis = tuple(acfg.moe_experts) or (base_moe.num_experts,)
        cf_axis = (tuple(acfg.moe_capacity_factors)
                   or (base_moe.capacity_factor,))
        disp_axis = tuple(acfg.moe_dispatch) or (base_moe.dispatch,)
    else:
        experts_axis = (None,)
        cf_axis = (None,)
        disp_axis = (None,)
        if acfg.moe_experts or acfg.moe_capacity_factors or acfg.moe_dispatch:
            notes.append("moe axes collapsed: the moe block is disabled — "
                         "no expert layer to tune")

    def base_knobs(stage: int, micro: int, gas: int) -> Candidate:
        return Candidate(name="", zero_stage=stage, micro=micro, gas=gas,
                         hierarchical=base_comm.hierarchical,
                         bucket_mb=base_comm.bucket_mb,
                         dcn_quant_bits=base_comm.dcn_quant_bits,
                         overlap=base_comm.overlap_grad_sync,
                         zeropp=base_zpp_tier,
                         moe_experts=(base_moe.num_experts
                                      if moe_on else None),
                         moe_capacity_factor=(base_moe.capacity_factor
                                              if moe_on else None),
                         moe_dispatch=(base_moe.dispatch
                                       if moe_on else None))

    out: List[Candidate] = []
    seen = set()
    seen_names = set()

    def add(c: Candidate) -> None:
        # overlap "auto" and "on" resolve identically (grad_sync.
        # resolve_overlap) — normalize so behavioral duplicates dedupe;
        # hierarchical "auto"/"on" likewise once the mesh admits it.
        ov = "off" if c.overlap == "off" else "on"
        hi = ("off" if c.hierarchical == "off" else "on")
        key = (c.zero_stage, c.micro, c.gas, hi, c.bucket_mb,
               c.dcn_quant_bits, ov, c.zeropp,
               c.moe_experts, c.moe_capacity_factor, c.moe_dispatch)
        if key in seen:
            return
        seen.add(key)
        # search.py keys records/configs by name — collisions would
        # corrupt the evidence trail, so uniqueness is enforced here.
        if c.name in seen_names:
            n = 2
            while f"{c.name}~{n}" in seen_names:
                n += 1
            c.name = f"{c.name}~{n}"
        seen_names.add(c.name)
        out.append(c)

    # The base config is ALWAYS candidate 0 ("default"): the tuner's
    # never-regress story needs the incumbent measured next to the
    # challengers.
    default = base_knobs(config.zero_config.stage,
                         config.train_micro_batch_size_per_gpu,
                         config.gradient_accumulation_steps)
    default.name = "default"
    add(default)

    for stage in stages:
        for micro, gas in splits:
            for hier in hier_axis:
                comm_active = dcn > 1 and hier in ("auto", "on")
                for bits in (bits_axis if comm_active
                             else (base_comm.dcn_quant_bits,)):
                    for bucket in (bucket_axis if comm_active
                                   else (base_comm.bucket_mb,)):
                        for ov in (overlap_axis if comm_active
                                   else (base_comm.overlap_grad_sync,)):
                            for zpp in (zpp_axis if stage >= 2
                                        else ("off",)):
                                for ne in experts_axis:
                                    for cf in cf_axis:
                                        for disp in disp_axis:
                                            c = Candidate(
                                                name="",
                                                zero_stage=int(stage),
                                                micro=int(micro),
                                                gas=int(gas),
                                                hierarchical=hier,
                                                bucket_mb=float(bucket),
                                                dcn_quant_bits=int(bits),
                                                overlap=ov, zeropp=zpp,
                                                moe_experts=(
                                                    int(ne) if ne
                                                    is not None else None),
                                                moe_capacity_factor=(
                                                    float(cf) if cf
                                                    is not None else None),
                                                moe_dispatch=disp)
                                            c.name = _candidate_name(
                                                c, comm_active)
                                            add(c)

    if len(out) > acfg.max_candidates:
        notes.append(
            f"candidate space capped at autotuning.max_candidates="
            f"{acfg.max_candidates} (enumerated {len(out)}; raise the cap "
            f"or narrow the override lists to search the rest)")
        out = out[:acfg.max_candidates]
    return out, notes


def _candidate_name(c: Candidate, comm_active: bool) -> str:
    parts = [f"stage{c.zero_stage}", f"mb{c.micro}x{c.gas}"]
    if comm_active:
        parts.append(f"{'hier' if c.hierarchical != 'off' else 'nohier'}")
        if c.hierarchical != "off":
            parts.append(f"b{c.dcn_quant_bits}")
            parts.append(f"bk{c.bucket_mb:g}")
            if c.overlap == "off":
                parts.append("noovl")
    if c.zeropp and c.zeropp != "off":
        parts.append(f"zpp-{c.zeropp}")
    if c.moe_experts is not None:
        parts.append(f"e{c.moe_experts}")
        parts.append(f"cf{c.moe_capacity_factor:g}")
        parts.append(str(c.moe_dispatch))
    return "-".join(parts)


def materialize(base_param_dict: Dict[str, Any], cand: Candidate,
                config) -> Dict[str, Any]:
    """The candidate's full raw config dict: the base dict with the
    candidate's knobs written over it — parseable by the normal
    ``DeepSpeedTPUConfig``, so stage-1 pruning is the ordinary config
    validation. Autotuning is disabled in the product (a candidate must
    never recursively search), and the batch triple is written explicitly
    only when the elastic ladder is NOT in control (the ladder owns the
    batch keys; the trial rebuild passes micro/gas directly)."""
    d = copy.deepcopy(dict(base_param_dict or {}))
    # Keep the user's knob lists (a later explicit re-search must see the
    # same space), flip only the auto-run gate: a candidate — including
    # the adopted one — must never recursively search at initialize().
    d[C.AUTOTUNING] = {**dict(d.get(C.AUTOTUNING) or {}),
                       C.AUTOTUNING_ENABLED: False}

    zo = dict(d.get(C.ZERO_OPTIMIZATION) or {})
    zo["stage"] = int(cand.zero_stage)
    if cand.zeropp and cand.zeropp != "off":
        zpp = dict(zo.get("zeropp") or {})
        zpp["quantized_weights"] = cand.zeropp
        zpp.setdefault("quant_block_size",
                       int(config.zero_config.zeropp.quant_block_size))
        # hpZ only means something with a DCN axis to keep gathers off.
        zpp.setdefault("hpz", "on" if config.mesh.slices > 1 else "off")
        zo["zeropp"] = zpp
        # The explicit gather needs non-persistent leaves to serve;
        # keep the user's threshold when set, else gather everything.
        zo.setdefault("stage3_param_persistence_threshold", 0)
    else:
        zo.pop("zeropp", None)
    d[C.ZERO_OPTIMIZATION] = zo

    comm = dict(d.get(C.COMM) or {})
    if cand.hierarchical is not None:
        comm[C.COMM_HIERARCHICAL] = cand.hierarchical
    if cand.bucket_mb is not None:
        comm[C.COMM_BUCKET_MB] = float(cand.bucket_mb)
    if cand.dcn_quant_bits is not None:
        comm[C.COMM_DCN_QUANT_BITS] = int(cand.dcn_quant_bits)
    if cand.overlap is not None:
        comm[C.COMM_OVERLAP_GRAD_SYNC] = cand.overlap
    d[C.COMM] = comm

    if cand.moe_experts is not None:
        moe = dict(d.get(C.MOE) or {})
        moe[C.MOE_ENABLED] = True
        moe[C.MOE_NUM_EXPERTS] = int(cand.moe_experts)
        moe[C.MOE_CAPACITY_FACTOR] = float(cand.moe_capacity_factor)
        moe[C.MOE_DISPATCH] = cand.moe_dispatch
        d[C.MOE] = moe

    if not config.elasticity_enabled:
        dp = config.data_parallel_size
        d[C.TRAIN_MICRO_BATCH_SIZE_PER_GPU] = int(cand.micro)
        d[C.GRADIENT_ACCUMULATION_STEPS] = int(cand.gas)
        d[C.TRAIN_BATCH_SIZE] = int(cand.micro) * int(cand.gas) * dp
        d.pop(C.TRAIN_MICRO_BATCH_SIZE_PER_CHIP, None)

    cand.overrides = _diff_overrides(cand, config)
    return d


def _diff_overrides(cand: Candidate, config) -> Dict[str, Any]:
    """The knobs the candidate changes vs the base config (for the
    result record / report table)."""
    base_zpp = config.zero_config.zeropp
    base_tier = (base_zpp.quantized_weights
                 if getattr(base_zpp, "active", False) else "off")
    out: Dict[str, Any] = {}
    if cand.zero_stage != config.zero_config.stage:
        out["zero_stage"] = cand.zero_stage
    if (cand.micro, cand.gas) != (config.train_micro_batch_size_per_gpu,
                                  config.gradient_accumulation_steps):
        out["micro_gas"] = [cand.micro, cand.gas]
    if cand.hierarchical not in (None, config.comm.hierarchical):
        out["hierarchical"] = cand.hierarchical
    if cand.bucket_mb not in (None, config.comm.bucket_mb):
        out["bucket_mb"] = cand.bucket_mb
    if cand.dcn_quant_bits not in (None, config.comm.dcn_quant_bits):
        out["dcn_quant_bits"] = cand.dcn_quant_bits
    if cand.overlap not in (None, config.comm.overlap_grad_sync):
        out["overlap_grad_sync"] = cand.overlap
    if cand.zeropp not in (None, base_tier):
        out["zeropp"] = cand.zeropp
    base_moe = getattr(config, "moe", None)
    if base_moe is not None and base_moe.enabled:
        if cand.moe_experts not in (None, base_moe.num_experts):
            out["moe_experts"] = cand.moe_experts
        if cand.moe_capacity_factor not in (None, base_moe.capacity_factor):
            out["moe_capacity_factor"] = cand.moe_capacity_factor
        if cand.moe_dispatch not in (None, base_moe.dispatch):
            out["moe_dispatch"] = cand.moe_dispatch
    return out
