"""Observatory-driven autotuner (docs/PERFORMANCE.md "Autotuning").

Startup config search over {zero_stage, micro x gas, bucket_mb,
dcn_quant_bits, overlap, zeropp}: enumerate + prune (ConfigError walls +
engine-free capacity projection), rank with the modeled cost (flops/
bytes roofline + grad-sync/param-gather wire seconds), measure the top-K
with short in-process trials through the PR-13 ``_elastic_rebuild``
path, adopt the measured winner. Never imported unless the search runs
(the zero-overhead-off contract).
"""

from deepspeed_tpu.autotuning.cost import (compute_floor_seconds,
                                           modeled_candidate_cost,
                                           modeled_wire_seconds,
                                           step_flops_bytes)
from deepspeed_tpu.autotuning.search import (AUTOTUNE_METRIC_TAGS, TrialOOM,
                                             autotune, render_result_table)
from deepspeed_tpu.autotuning.space import (Candidate, batch_splits,
                                            enumerate_candidates,
                                            materialize)

__all__ = [
    "AUTOTUNE_METRIC_TAGS", "Candidate", "TrialOOM", "autotune",
    "batch_splits", "compute_floor_seconds", "enumerate_candidates",
    "materialize", "modeled_candidate_cost", "modeled_wire_seconds",
    "render_result_table", "step_flops_bytes",
]
