"""The startup config search — enumerate, prune, measure, adopt.

Three stages (docs/PERFORMANCE.md "Autotuning"):

1. **enumerate + prune** — :func:`~deepspeed_tpu.autotuning.space.
   enumerate_candidates` generates the knob space; each candidate is
   materialized into a full raw config dict and fed through the ordinary
   ``DeepSpeedTPUConfig`` parse, so every ConfigError wall in the tree
   prunes for free (``pruned_config``); survivors' HBM is projected
   through the engine-free ``plan_capacity_from_config``
   (telemetry/memory.py) and anything over ``headroom_frac`` x the HBM
   limit is pruned too (``pruned_capacity``). Every pruned candidate is
   logged — and recorded in the result JSON — with its reason.
2. **measured trials** — survivors are ranked by the modeled cost
   (autotuning/cost.py: flops/bytes roofline floor + the grad-sync /
   param-gather modeled wire seconds); the top-K (plus the incumbent
   ``default``, always) get a real in-process trial: the engine's config
   is swapped through the PR-13 ``_elastic_rebuild`` path (same process,
   same devices, state reinstalled from one pre-search snapshot every
   time, so trials are isolated and the search leaves the engine exactly
   where it found it), then compile + ``trial_steps`` timed steps.
   Successive halving drops candidates slower than ``halving_factor`` x
   the round's best before the longer confirmation round. A trial OOM
   prunes the candidate (``trial_oom``) — the engine's OOM forensics
   exit is suspended for the search, so a fat candidate can never kill
   the run it is trying to speed up.
3. **commit + report** — the measured winner's config is adopted (state
   restored from the snapshot: step counters, rng and schedule continue
   as if the search never ran), ``autotune_result.json`` persists the
   full ranking with every verdict, the ``autotune/*`` gauges and the
   ``autotune/adopted`` instant land in telemetry, and the whole window
   is booked to the ``autotune_search`` goodput category (the engine's
   goodput hooks are quiesced during trials, so trial steps can never
   masquerade as productive time).

Zero-overhead-off contract: nothing in this package is imported unless
the search actually runs (``deepspeed_tpu.initialize`` gates the import
on ``autotuning.enabled``), and the search never touches the step
builders — the adopted engine is bit-identical to one hand-built with
the winning config (tests/test_autotuning.py pins both).
"""

import contextlib
import os
import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.autotuning.cost import (modeled_candidate_cost,
                                           step_flops_bytes)
from deepspeed_tpu.autotuning.space import (Candidate, enumerate_candidates,
                                            materialize)
from deepspeed_tpu.config.config import ConfigError, DeepSpeedTPUConfig
from deepspeed_tpu.utils.logging import log_dist, logger

RESULT_FORMAT = 1

# Every metric tag this module can emit (gauges + the adoption instant) —
# pinned against docs/OBSERVABILITY.md in BOTH directions by
# tests/test_doc_lint.py, like GOODPUT/MEMORY_METRIC_TAGS.
AUTOTUNE_METRIC_TAGS = frozenset({
    "autotune/candidates",
    "autotune/pruned",
    "autotune/trials",
    "autotune/search_sec",
    "autotune/best_step_ms",
    "autotune/adopted",
})

# Engine subsystems quiesced for the search window: trial steps must not
# feed anomaly detectors, write interval checkpoints of trial states,
# trip the elastic coordinator, exit the process on a trial OOM, book
# goodput categories, feed fleet step-time estimates, or schedule
# profiler captures. Restored verbatim afterwards.
_QUIESCED_ATTRS = ("memory", "guardrails", "elastic", "ckpt_manager",
                   "goodput", "fleet", "devicetime")


class TrialOOM(RuntimeError):
    """A measured trial ran the device out of memory — prune, never kill."""


@contextlib.contextmanager
def _quiesced(engine):
    saved = {a: getattr(engine, a) for a in _QUIESCED_ATTRS}
    for a in saved:
        setattr(engine, a, None)
    # The numerics observatory cannot be nulled — the step BUILDERS
    # consult `engine.numerics` (the trial programs must match what the
    # adopted engine will run) — so only its EMISSION is silenced: trial
    # steps run under candidate configs and their per-group stats /
    # quant-error gauges must never land in the production time series.
    num_tel = None
    if engine.numerics is not None:
        num_tel = engine.numerics.telemetry
        engine.numerics.telemetry = None
    try:
        yield
    finally:
        for a, v in saved.items():
            setattr(engine, a, v)
        if engine.numerics is not None:
            engine.numerics.telemetry = num_tel


def _check_engine(engine) -> None:
    import jax

    from deepspeed_tpu.parallel.mesh import PIPE_AXIS

    if jax.process_count() > 1:
        # Trial timings are per-process wall clock: two hosts measuring
        # a near-tie would halve/adopt DIFFERENT configs and the rebuilt
        # step programs' collectives stop matching — a distributed hang,
        # not a slow pick. Until the measurements are agreed through a
        # collective, the search is single-process only (the
        # initialize() entry warns and skips instead of dying).
        raise ConfigError(
            "autotune: measured trials are not coordinated across "
            "processes yet — per-host timings could adopt diverging "
            "configs (mismatched collectives). Run the search on a "
            "single-process mesh and ship the adopted config, or wait "
            "for the cross-host agreement collective")
    if engine.mesh.shape.get(PIPE_AXIS, 1) > 1:
        raise ConfigError(
            "autotune: the pipeline engine compiles its own schedule — "
            "the in-process trial rebuild only re-places the fused "
            "data-parallel tiers")
    if hasattr(engine, "offloader") or engine._train_step is None:
        # The explicit offload blocks are walled at config parse; the
        # host-IMPLIED tier (optimizer.type "cpuadam" / any host_resident
        # optimizer object) resolves only at engine level.
        raise ConfigError(
            "autotune cannot compose with the host optimizer tier "
            "(offload_optimizer, or a host-resident optimizer such as "
            "'cpuadam'): trial rebuilds only re-place device state")
    if getattr(engine.optimizer, "needs_local_grads", False):
        raise ConfigError(
            "autotune cannot compose with 1-bit optimizers: rank-local "
            "error-feedback buffers do not survive a trial rebuild")


def _apply_candidate(engine, parsed_cfg, cand: Candidate, snapshot,
                     devices) -> None:
    """Swap the engine onto a candidate config in-process: replace the
    parsed config, rebuild mesh/placement/step-fns through the one PR-13
    world-change path (same devices, same world), and reinstall the
    pre-search snapshot so every trial starts from identical state."""
    engine.config = parsed_cfg
    _apply_moe_knobs(engine, parsed_cfg)
    engine._elastic_rebuild(
        devices=devices, slices=engine.dcn_size,
        micro_batch=cand.micro, gas=cand.gas,
        arrays=dict(snapshot.arrays), meta=snapshot.meta)


def _apply_moe_knobs(engine, parsed_cfg) -> None:
    """moe capacity-factor/dispatch trials change the LOWERED step, not
    the param shapes — re-derive the module-backed loss_fn with the
    candidate's knobs so the rebuild below traces them (the adapter
    publishes ``loss_fn.module`` for exactly this). No-op for bare
    loss_fn entries (nothing to re-derive) and when the knobs already
    match. moe_experts never reaches a trial (prune-only axis): a
    different expert count changes the param tree the snapshot reinstall
    assumes."""
    moe = getattr(parsed_cfg, "moe", None)
    if moe is None or not moe.enabled:
        return
    module = getattr(engine.loss_fn, "module", None)
    mcfg = getattr(module, "cfg", None)
    if mcfg is None or not hasattr(mcfg, "moe_dispatch"):
        return
    if (mcfg.moe_capacity_factor == moe.capacity_factor
            and mcfg.moe_dispatch == moe.dispatch):
        return
    from dataclasses import replace as _dc_replace

    from deepspeed_tpu.models.adapter import flax_module_loss_fn

    new_module = type(module)(cfg=_dc_replace(
        mcfg, moe_capacity_factor=moe.capacity_factor,
        moe_dispatch=moe.dispatch))
    engine.loss_fn, _ = flax_module_loss_fn(new_module,
                                            params=engine.state.params)


def _run_trial(engine, cand: Candidate, make_batches: Callable,
               steps: int, warmup: int) -> float:
    """Compile + a few timed steps of the CURRENT engine config. Returns
    measured seconds per optimizer step (a scalar loss fetch closes the
    window — block_until_ready alone does not fence remote dispatch)."""
    from deepspeed_tpu.telemetry.memory import is_resource_exhausted

    batches = make_batches(cand.micro * engine.dp_size, cand.gas)
    try:
        loss = None
        for _ in range(max(warmup, 1)):   # >=1: the compile must be paid
            loss = engine.train_batch(batches)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batches)
        float(loss)
        return (time.perf_counter() - t0) / max(steps, 1)
    except Exception as e:  # noqa: BLE001 — screened below
        if is_resource_exhausted(e):
            raise TrialOOM(str(e)[:500]) from e
        raise


def autotune(engine, make_batches: Callable[[int, int], Any], *,
             result_dir: Optional[str] = None) -> Dict[str, Any]:
    """Run the three-stage search on a live engine and adopt the winner.

    ``make_batches(global_micro_batch, gas)`` must return a training
    batch pytree whose leaves carry ``[gas, global_micro_batch, ...]``
    leading dims — the ``train_batch`` shape for the candidate's batch
    split (the global micro batch is per-chip micro x dp world). The
    returned dict is the persisted ``autotune_result.json`` document.
    """
    g = engine.goodput
    if g is not None:
        # Close the preceding interval first so the one autotune_search
        # mark at the end books exactly the search window — marked in a
        # finally so a failed search (every trial errored, no fitting
        # candidate) can never leak its wall time into the NEXT
        # category's mark (the exact-partition contract).
        g.mark_gap()
    try:
        return _autotune_inner(engine, make_batches, result_dir=result_dir)
    finally:
        if g is not None:
            g.mark("autotune_search")


def _autotune_inner(engine, make_batches: Callable[[int, int], Any], *,
                    result_dir: Optional[str]) -> Dict[str, Any]:
    acfg = engine.config.autotuning
    _check_engine(engine)
    base_cfg = engine.config
    base_dict = dict(getattr(base_cfg, "_param_dict", {}) or {})
    mesh_shape = {str(k): int(v) for k, v in dict(engine.mesh.shape).items()}
    devices = list(engine.mesh.devices.ravel())
    t_start = time.monotonic()

    candidates, notes = enumerate_candidates(base_cfg, mesh_shape,
                                             engine.mesh.size)
    for n in notes:
        logger.warning("autotune: %s", n)
    records: List[Dict[str, Any]] = []
    parsed: Dict[str, DeepSpeedTPUConfig] = {}
    dicts: Dict[str, Dict[str, Any]] = {}

    # -- stage 1a: materialize + parse (the ConfigError walls) ----------
    survivors: List[Candidate] = []
    for cand in candidates:
        rec: Dict[str, Any] = {"name": cand.name, "overrides": {},
                               "status": "enumerated", "reason": None,
                               "projected_device_bytes": None,
                               "projected_headroom_bytes": None,
                               "modeled_sec": None, "rank": None,
                               "measured_step_ms": None}
        records.append(rec)
        try:
            d = materialize(base_dict, cand, base_cfg)
            rec["overrides"] = dict(cand.overrides)
            parsed[cand.name] = DeepSpeedTPUConfig(
                d, world_size=base_cfg.world_size)
            dicts[cand.name] = d
            survivors.append(cand)
        except (ConfigError, ValueError) as e:
            rec["status"] = "pruned_config"
            rec["reason"] = f"config: {e}"
            logger.info("autotune: pruned %s — %s", cand.name, e)

    # -- stage 1b: capacity projection (engine-free) --------------------
    from deepspeed_tpu.telemetry.memory import plan_capacity_from_config

    limit = _hbm_limit_bytes(engine, acfg)
    fitting: List[Candidate] = []
    for cand in survivors:
        rec = _rec(records, cand.name)
        try:
            plan = plan_capacity_from_config(
                parsed[cand.name], engine.state.params,
                num_shards=mesh_shape.get("data", 1),
                microbatch=cand.micro,
                act_bytes_per_sample=acfg.activation_bytes_per_sample,
                hbm_limit_bytes=limit)
            chosen = next(r for r in plan["rows"] if r["chosen"])
            dev_bytes = chosen["device_bytes"]
            rec["projected_device_bytes"] = int(dev_bytes)
            if limit:
                rec["projected_headroom_bytes"] = int(limit - dev_bytes)
                budget = acfg.headroom_frac * limit
                if dev_bytes > budget:
                    rec["status"] = "pruned_capacity"
                    rec["reason"] = (
                        f"capacity: projects {dev_bytes / 1024**3:.2f} GB "
                        f"per device > {acfg.headroom_frac:.0%} of the "
                        f"{limit / 1024**3:.2f} GB HBM limit")
                    logger.info("autotune: pruned %s — %s", cand.name,
                                rec["reason"])
                    continue
        except Exception as e:  # noqa: BLE001 — projection is advisory
            logger.warning("autotune: capacity projection failed for %s "
                           "(%s) — candidate kept", cand.name, e)
        fitting.append(cand)

    if not fitting:
        raise ConfigError(
            "autotune: every candidate was pruned (see the log / result "
            "records) — the base config itself projects over the HBM "
            "budget; raise autotuning.headroom_frac or fix the config")

    search_sec = 0.0
    result: Dict[str, Any] = {}
    with _quiesced(engine):
        # -- stage 2a: modeled ranking ----------------------------------
        try:
            batches = engine.put_batch(
                make_batches(engine.train_micro_batch_size_per_gpu
                             * engine.dp_size,
                             engine.gradient_accumulation_steps),
                leading_gas_dim=True)
            fb = step_flops_bytes(engine, batches, engine._current_lr())
        except Exception as e:  # noqa: BLE001 — ranking only
            logger.warning("autotune: default-step cost analysis failed "
                           "(%s) — ranking on wire model alone", e)
            fb = {"flops": 0.0, "bytes_accessed": 0.0}
        for cand in fitting:
            rec = _rec(records, cand.name)
            cost = modeled_candidate_cost(engine, parsed[cand.name],
                                          cand.gas, fb)
            rec["modeled_sec"] = cost["modeled_sec"]
        ranked = sorted(fitting,
                        key=lambda c: _rec(records, c.name)["modeled_sec"])
        for i, cand in enumerate(ranked):
            _rec(records, cand.name)["rank"] = i + 1
        # MoE trialability: a different expert count changes the param
        # tree shapes, and every trial reinstalls the pre-search snapshot
        # arrays — moe_experts is prune-only (enumerated, config-parse
        # pruned, capacity-projected, never measured). Capacity-factor/
        # dispatch trials additionally need the module handle the adapter
        # publishes to re-derive the loss — bare loss_fn entries cannot
        # retrace the knobs, so those candidates are not trialed either
        # (measuring an unchanged program would be a lie).
        base_moe = getattr(base_cfg, "moe", None)
        untrialable = []
        if base_moe is not None and base_moe.enabled:
            has_module = getattr(engine.loss_fn, "module", None) is not None
            for cand in ranked:
                if cand.moe_experts not in (None, base_moe.num_experts):
                    untrialable.append(
                        (cand, "moe_experts is a prune-only axis: a "
                         "different expert count changes the param tree "
                         "shapes the in-process trial's snapshot "
                         "reinstall assumes (modeled + capacity ranking "
                         "only)"))
                elif (not has_module
                      and (cand.moe_capacity_factor
                           not in (None, base_moe.capacity_factor)
                           or cand.moe_dispatch
                           not in (None, base_moe.dispatch))):
                    untrialable.append(
                        (cand, "moe capacity/dispatch knobs need a "
                         "module-backed loss_fn to retrace — this engine "
                         "was built from a bare loss_fn"))
        for cand, reason in untrialable:
            rec = _rec(records, cand.name)
            if rec["status"] == "enumerated":
                rec["status"] = "not_trialed"
                rec["reason"] = reason
        skip = {id(c) for c, _ in untrialable}
        trialable = [c for c in ranked if id(c) not in skip]
        trial_list = trialable[:acfg.top_k]
        if not any(c.name == "default" for c in trial_list):
            # The incumbent is ALWAYS measured: "the winner beat the
            # default" must be a measured statement, never a modeled one.
            # Unless it was itself capacity-pruned — the tuner's prime
            # scenario (the hand-picked config projects over HBM), in
            # which case the comparison is vacuous and the search simply
            # picks the fastest FITTING candidate.
            incumbent = next((c for c in ranked if c.name == "default"),
                             None)
            if incumbent is not None:
                trial_list.append(incumbent)
        for cand in trialable[acfg.top_k:]:
            rec = _rec(records, cand.name)
            if rec["status"] == "enumerated" and cand not in trial_list:
                rec["status"] = "not_trialed"
                rec["reason"] = (f"ranked {rec['rank']} > top_k "
                                 f"{acfg.top_k} by the modeled cost")

        # -- stage 2b: measured trials + successive halving -------------
        from deepspeed_tpu.resilience.checkpoint import snapshot_engine

        snapshot = snapshot_engine(engine)
        measured: Dict[str, float] = {}
        for cand in trial_list:
            rec = _rec(records, cand.name)
            try:
                _apply_candidate(engine, parsed[cand.name], cand,
                                 snapshot, devices)
                sec = _run_trial(engine, cand, make_batches,
                                 acfg.trial_steps, acfg.trial_warmup)
                measured[cand.name] = sec
                rec["status"] = "trialed"
                rec["measured_step_ms"] = round(sec * 1e3, 3)
            except TrialOOM as e:
                rec["status"] = "trial_oom"
                rec["reason"] = f"trial OOM: {e}"
                logger.warning("autotune: %s pruned — trial OOM", cand.name)
                _recover(engine, parsed, candidates, snapshot, devices)
            except Exception as e:  # noqa: BLE001 — a broken candidate
                # must not kill the search (the default always completes:
                # its config is the one the engine already ran)
                rec["status"] = "trial_error"
                rec["reason"] = f"trial failed: {type(e).__name__}: {e}"
                logger.warning("autotune: %s pruned — %s", cand.name,
                               rec["reason"])
                _recover(engine, parsed, candidates, snapshot, devices)
        if not measured:
            raise ConfigError(
                "autotune: every measured trial failed (see the result "
                "records) — not adopting anything")

        best = min(measured.values())
        finalists = [c for c in trial_list
                     if measured.get(c.name) is not None
                     and measured[c.name] <= best * acfg.halving_factor]
        for cand in trial_list:
            sec = measured.get(cand.name)
            if sec is not None and cand not in finalists:
                rec = _rec(records, cand.name)
                rec["status"] = "eliminated"
                rec["reason"] = (
                    f"successive halving: {sec * 1e3:.2f} ms/step > "
                    f"{acfg.halving_factor:g} x best "
                    f"{best * 1e3:.2f} ms/step")
        if len(finalists) > 1:
            # Confirmation round: longer windows for the close calls.
            for cand in finalists:
                rec = _rec(records, cand.name)
                try:
                    _apply_candidate(engine, parsed[cand.name], cand,
                                     snapshot, devices)
                    sec = _run_trial(engine, cand, make_batches,
                                     acfg.trial_steps * 2,
                                     acfg.trial_warmup)
                    measured[cand.name] = sec
                    rec["measured_step_ms"] = round(sec * 1e3, 3)
                except TrialOOM as e:
                    # The longer window raised live activation pressure:
                    # same verdict class as a round-1 OOM.
                    rec["status"] = "trial_oom"
                    rec["reason"] = f"trial OOM: {e}"
                    measured.pop(cand.name, None)
                    _recover(engine, parsed, candidates, snapshot, devices)
                except Exception as e:  # noqa: BLE001
                    rec["status"] = "trial_error"
                    rec["reason"] = (f"confirmation trial failed: "
                                     f"{type(e).__name__}: {e}")
                    measured.pop(cand.name, None)
                    _recover(engine, parsed, candidates, snapshot, devices)
            finalists = [c for c in finalists if c.name in measured]
        if not finalists:
            raise ConfigError(
                "autotune: every finalist failed its confirmation trial "
                "(see the result records) — not adopting anything")

        winner = min(finalists, key=lambda c: measured[c.name])
        wrec = _rec(records, winner.name)
        wrec["status"] = "adopted"

        # -- stage 3: commit -------------------------------------------
        _apply_candidate(engine, parsed[winner.name], winner, snapshot,
                         devices)
        search_sec = time.monotonic() - t_start

    # Quiesced subsystems are live again: re-arm the per-config caches
    # the rebuilds skipped while they were None. (The autotune_search
    # goodput mark lives in autotune()'s finally.)
    if engine.goodput is not None:
        engine.goodput.reset_flops()
    if engine.memory is not None:
        engine.memory.on_engine_init(engine)

    from deepspeed_tpu.telemetry.goodput import config_hash
    pruned = sum(1 for r in records
                 if r["status"].startswith(("pruned", "trial_oom",
                                            "trial_error")))
    if base_cfg.elasticity_enabled:
        # The ladder owns the batch keys, so the adopted config dict
        # cannot pin the winning split — record it (and fold it into the
        # hash so two splits never alias); re-initializing from the
        # adopted config yields the ladder's HEAD split unless the
        # adopted batch_triple is applied through the elastic machinery.
        notes = notes + [
            "elasticity owns the batch keys: the adopted config "
            "re-derives the ladder's head (micro, gas) at initialize(); "
            "the measured winner's split is recorded as "
            "adopted.batch_triple"]
    result = {
        "format": RESULT_FORMAT,
        "world_size": int(engine.mesh.size),
        "mesh": mesh_shape,
        "hbm_limit_bytes": (int(limit) if limit else None),
        "headroom_frac": acfg.headroom_frac,
        "top_k": acfg.top_k,
        "search_sec": round(search_sec, 3),
        "notes": notes,
        "adopted": {
            "name": winner.name,
            "overrides": dict(winner.overrides),
            # The triple rides the hash too: under the elastic ladder two
            # batch splits materialize byte-identical config dicts, and
            # two distinct candidates must never share a hash.
            "batch_triple": [winner.micro, winner.gas,
                             int(engine.dp_size)],
            "config_hash": config_hash(
                {**dicts[winner.name],
                 "_autotune_batch_triple": [winner.micro, winner.gas]}),
            "config": dicts[winner.name],
            "measured_step_ms": wrec["measured_step_ms"],
        },
        "default_measured_step_ms": _rec(records,
                                         "default")["measured_step_ms"],
        "candidates": records,
    }
    log_dist("autotune result:\n" + render_result_table(result), ranks=[0])
    _emit(engine, result, pruned=pruned,
          # every candidate that RAN a trial — OOM'd/errored ones
          # included (they paid trial time; docs define the gauge so)
          trials=sum(1 for r in records
                     if r["status"] in ("trialed", "eliminated", "adopted",
                                        "trial_oom", "trial_error")))
    _write_result(engine, acfg, result, result_dir)
    return result


def _rec(records: List[Dict[str, Any]], name: str) -> Dict[str, Any]:
    return next(r for r in records if r["name"] == name)


def _recover(engine, parsed, candidates, snapshot, devices) -> None:
    """A failed candidate rebuild/trial may leave the engine mid-swap:
    re-apply the incumbent so the next trial starts from a sane world."""
    default = next(c for c in candidates if c.name == "default")
    try:
        _apply_candidate(engine, parsed["default"], default, snapshot,
                         devices)
    except Exception as e:  # noqa: BLE001 — now it IS fatal
        raise RuntimeError(
            "autotune: could not restore the default config after a "
            f"failed trial: {e}") from e


def _hbm_limit_bytes(engine, acfg) -> Optional[int]:
    """Config override first (autotuning.hbm_limit_gb, then the memory
    observatory's), else the tightest local device's ``bytes_limit``
    (None on CPU — capacity pruning then reports verdict unknown and
    prunes nothing)."""
    if acfg.hbm_limit_gb:
        return int(acfg.hbm_limit_gb * 1024**3)
    mcfg = engine.config.telemetry.memory
    if getattr(mcfg, "hbm_limit_gb", None):
        return int(mcfg.hbm_limit_gb * 1024**3)
    from deepspeed_tpu.telemetry.memory import collect_memory_snapshot

    snap = collect_memory_snapshot()
    limits = [d["stats"]["bytes_limit"] for d in snap["devices"]
              if d.get("stats") and d["stats"].get("bytes_limit")]
    return int(min(limits)) if limits else None


def _emit(engine, result: Dict[str, Any], *, pruned: int,
          trials: int) -> None:
    tel = engine.telemetry
    if tel is None or not getattr(tel, "enabled", False):
        return
    reg = tel.registry
    step = int(engine.global_steps)
    reg.gauge("autotune/candidates").set(len(result["candidates"]),
                                         step=step)
    reg.gauge("autotune/pruned").set(pruned, step=step)
    reg.gauge("autotune/trials").set(trials, step=step)
    reg.gauge("autotune/search_sec").set(result["search_sec"], step=step)
    if result["adopted"]["measured_step_ms"] is not None:
        reg.gauge("autotune/best_step_ms").set(
            result["adopted"]["measured_step_ms"], step=step)
    tel.instant("autotune/adopted", candidate=result["adopted"]["name"],
                config_hash=result["adopted"]["config_hash"],
                measured_step_ms=result["adopted"]["measured_step_ms"],
                search_sec=result["search_sec"])
    tel.flush()


def _write_result(engine, acfg, result: Dict[str, Any],
                  result_dir: Optional[str]) -> None:
    tcfg = engine.config.telemetry
    out_dir = result_dir or (tcfg.dir if getattr(tcfg, "enabled", False)
                             else None)
    if not out_dir:
        return
    from deepspeed_tpu.telemetry.fleet import (host_scoped_path,
                                               telemetry_host_component)
    from deepspeed_tpu.telemetry.goodput import _atomic_write_json

    try:
        path = os.path.join(out_dir, host_scoped_path(
            acfg.result_file, telemetry_host_component()))
        _atomic_write_json(path, result)
        result["result_path"] = path
    except (OSError, TypeError, ValueError) as e:
        logger.warning("autotune: result write failed: %s", e)


def render_result_table(result: Dict[str, Any]) -> str:
    """The startup ranking table (also rendered, stdlib-side, by
    tools/autotune_report.py from the persisted JSON)."""
    lines = [
        f"autotune: world {result['world_size']}, "
        f"{len(result['candidates'])} candidates, adopted "
        f"'{result['adopted']['name']}' in {result['search_sec']:.1f}s",
        f"{'candidate':<28} {'status':<16} {'proj GB':>8} "
        f"{'model ms':>9} {'meas ms':>8}  reason",
    ]
    lines.append("-" * len(lines[-1]))
    for r in result["candidates"]:
        proj = (f"{r['projected_device_bytes'] / 1024**3:8.3f}"
                if r.get("projected_device_bytes") is not None else "     n/a")
        model = (f"{r['modeled_sec'] * 1e3:9.3f}"
                 if r.get("modeled_sec") is not None else "      n/a")
        meas = (f"{r['measured_step_ms']:8.2f}"
                if r.get("measured_step_ms") is not None else "     n/a")
        lines.append(f"{r['name']:<28} {r['status']:<16} {proj} {model} "
                     f"{meas}  {r.get('reason') or ''}")
    return "\n".join(lines)
