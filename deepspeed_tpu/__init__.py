"""deepspeed_tpu — a TPU-native distributed training framework.

Public API parity with the reference ``deepspeed/__init__.py``:
``initialize()`` (:58) returns ``(engine, optimizer, dataloader, lr_scheduler)``,
``add_config_arguments()`` (:211) wires argparse, ``init_inference()`` (:227)
builds the inference engine. The engine is TPU-first: jitted sharded train
steps over a jax device mesh (see runtime/engine.py).
"""

from typing import Any, Callable, Optional

from deepspeed_tpu.version import __version__
from deepspeed_tpu.config.config import DeepSpeedTPUConfig
from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.parallel.mesh import build_mesh, init_distributed
from deepspeed_tpu.parallel.topology import (PipeDataParallelTopology,
                                             PipeModelDataParallelTopology,
                                             ProcessTopology)
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader, RepeatingLoader
from deepspeed_tpu.runtime.engine import TPUEngine, TrainState
from deepspeed_tpu.runtime.zero import zero_init
from deepspeed_tpu.runtime.lr_schedules import add_tuning_arguments
from deepspeed_tpu.utils.logging import log_dist, logger


def initialize(args=None,
               loss_fn: Optional[Callable] = None,
               params: Any = None,
               model=None,
               optimizer=None,
               lr_scheduler=None,
               mesh=None,
               config: Any = None,
               config_params: Any = None,
               training_data=None,
               collate_fn=None,
               param_partition_specs=None,
               dist_init_required: Optional[bool] = None,
               rng_seed: int = 0,
               autotune_batches: Optional[Callable] = None,
               **kwargs):
    """Build the training engine (reference deepspeed/__init__.py:58).

    Two entry styles:
    - functional (TPU-native): pass ``loss_fn(params, batch, rng)`` + ``params``;
    - module: pass a flax ``model`` (``flax.linen.Module``) — it is adapted to
      a loss_fn via ``deepspeed_tpu.models.adapter`` (the model's ``__call__``
      must return the scalar loss).

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)``.
    """
    cfg = config if config is not None else config_params
    if cfg is None and args is not None and hasattr(args, "deepspeed_config"):
        cfg = args.deepspeed_config
    if not isinstance(cfg, DeepSpeedTPUConfig):
        cfg = DeepSpeedTPUConfig(cfg)

    if dist_init_required:
        init_distributed()

    if cfg.sparse_attention:
        # Config-driven sparse-attention surgery (reference applies
        # BertSparseSelfAttention via SparseAttentionUtils; here the
        # in-tree families route attention by config, so the swap is a
        # frozen-dataclass replace — parameter-free).
        if model is not None and hasattr(model, "cfg") \
                and hasattr(model.cfg, "sparse_attention"):
            if getattr(model.cfg, "sparse_attention") != cfg.sparse_attention:
                from deepspeed_tpu.ops.sparse_attention.utils import \
                    SparseAttentionUtils
                model = (SparseAttentionUtils.
                         replace_model_self_attention_with_sparse_self_attention(
                             model, cfg.sparse_attention))
                from deepspeed_tpu.utils.logging import log_dist
                log_dist(f"sparse_attention: routed {type(model).__name__} "
                         f"attention through mode="
                         f"{cfg.sparse_attention.get('mode', 'fixed')}",
                         ranks=[0])
        else:
            # Custom module or loss_fn entry: no surgery possible — same
            # contract for both entry styles (the user's code must route
            # attention through ops.sparse_attention.SparseSelfAttention).
            from deepspeed_tpu.utils.logging import logger
            logger.warning(
                "sparse_attention config block with a custom model/loss_fn:"
                " no surgery applied — route attention through "
                "ops.sparse_attention.SparseSelfAttention yourself "
                "(see ops/sparse_attention/utils.py)")

    sparse_grads_handled = False
    if cfg.sparse_gradients_enabled and model is not None \
            and loss_fn is None \
            and hasattr(model, "cfg") \
            and hasattr(model.cfg, "sparse_embedding_grad"):
        # Config-driven sparse-gradient surgery (reference engine.py:1530:
        # `sparse_gradients: true` makes embedding grads travel as CSR —
        # here the family's embedding_lookup VJP exchanges touched rows
        # over the data axes instead; frozen-dataclass replace, like the
        # sparse_attention surgery above). The ENGINE's mesh is resolved
        # here and baked in as (mesh, axes): binding to the ambient
        # default mesh instead would pick up whatever unrelated engine
        # registered one first (multi-engine processes — the test suite —
        # hit exactly that).
        from dataclasses import replace as _dc_replace

        from jax.sharding import Mesh as _Mesh

        from deepspeed_tpu.parallel.mesh import build_mesh, data_like_axes

        if mesh is None:
            mesh = build_mesh(data=-1, model=cfg.mesh.model,
                              pipe=cfg.mesh.pipe,
                              sequence=cfg.mesh.sequence,
                              expert=cfg.mesh.expert,
                              slices=cfg.mesh.slices)
        current = model.cfg.sparse_embedding_grad
        already_pinned = (isinstance(current, tuple) and len(current) == 2
                          and isinstance(current[0], _Mesh))
        if not already_pinned:
            # Re-pin True / bare-axes values too: a cfg built with
            # sparse_embedding_grad=True would otherwise resolve against
            # the AMBIENT mesh at trace time — the multi-engine footgun.
            axes = (tuple(current) if isinstance(current, tuple)
                    and current else data_like_axes(mesh))
            model = type(model)(cfg=_dc_replace(
                model.cfg, sparse_embedding_grad=(mesh, axes)))
        sparse_grads_handled = True
        from deepspeed_tpu.utils.logging import log_dist
        log_dist("sparse_gradients: embedding grads exchange touched rows "
                 "over the data axes (ops/embedding.py row-sparse VJP)",
                 ranks=[0])

    if cfg.moe.enabled and model is not None and loss_fn is None \
            and hasattr(model, "cfg") \
            and hasattr(model.cfg, "moe_experts"):
        # Config-driven MoE surgery (the `moe` block; docs/MOE.md): route
        # every moe.layer_freq-th block's FFN through the GShard MoE
        # layer with the block's knobs — frozen-dataclass replace, like
        # the sparse_attention/sparse_gradients surgeries above. The
        # ENGINE's mesh is resolved here and pinned into cfg.moe_mesh so
        # the all-to-all dispatch region never binds to whatever ambient
        # mesh an unrelated engine registered (the multi-engine footgun
        # the sparse_gradients pinning exists for). moe_stats follows
        # telemetry.enabled: the stat scalars only ride the step when an
        # engine-side flush point (telemetry/moe.py) will consume them.
        from dataclasses import replace as _dc_replace

        from deepspeed_tpu.parallel.mesh import build_mesh as _build_mesh
        from deepspeed_tpu.utils.logging import log_dist

        if mesh is None:
            mesh = _build_mesh(data=-1, model=cfg.mesh.model,
                               pipe=cfg.mesh.pipe,
                               sequence=cfg.mesh.sequence,
                               expert=cfg.mesh.expert,
                               slices=cfg.mesh.slices)
        # The config-parse wall only sees a `mesh` config block; a mesh
        # OBJECT handed to initialize() resolves its expert axis here.
        _e_axis = mesh.shape.get("expert", 1)
        if _e_axis > 1 and cfg.moe.num_experts % _e_axis != 0:
            from deepspeed_tpu.config.config import ConfigError
            raise ConfigError(
                f"moe.num_experts ({cfg.moe.num_experts}) must divide "
                f"evenly over the mesh expert axis ({_e_axis})")
        model = type(model)(cfg=_dc_replace(
            model.cfg,
            moe_experts=cfg.moe.num_experts,
            moe_k=cfg.moe.k,
            moe_layer_freq=cfg.moe.layer_freq,
            moe_capacity_factor=cfg.moe.capacity_factor,
            moe_eval_capacity_factor=cfg.moe.eval_capacity_factor,
            moe_min_capacity=cfg.moe.min_capacity,
            moe_router_jitter=cfg.moe.router_jitter,
            moe_dispatch=cfg.moe.dispatch,
            moe_mesh=mesh,
            moe_stats=cfg.telemetry.enabled))
        log_dist(
            f"moe: {cfg.moe.num_experts} experts (k={cfg.moe.k}, "
            f"dispatch={cfg.moe.dispatch}, capacity_factor="
            f"{cfg.moe.capacity_factor}) every {cfg.moe.layer_freq} "
            f"blocks; expert axis size {mesh.shape.get('expert', 1)}",
            ranks=[0])

    if cfg.zero_config.offload_param.enabled and loss_fn is not None:
        raise ValueError(
            "offload_param cannot stream an opaque loss_fn (no per-block "
            "fetch points): pass model= (a PipeModel or an in-tree GPT) and "
            "let initialize() build the streamed loss, or — if your loss_fn "
            "already fetches from host memory itself — construct TPUEngine "
            "directly")
    if cfg.zero_config.offload_param.enabled and loss_fn is None:
        # ZeRO-Infinity param tier: the step streams blocks from host
        # memory, which needs per-block fetch points — a block-structured
        # PipeModel, not an opaque module (the reference likewise needs
        # nn.Module boundaries for its fetch hooks, stage3.py:1084).
        from deepspeed_tpu.parallel.pipe.module import (PipeModel,
                                                        gpt_pipe_model)
        from deepspeed_tpu.runtime.zero.param_offload import \
            build_streamed_loss

        import jax as _jax

        # Init + pack on the HOST device: the params live in host memory
        # anyway, and materialising the full fp32 tree (plus the packing
        # copy) on the accelerator would OOM exactly the models this tier
        # exists for (a 1.6B GPT already exceeds one v5e's HBM here).
        with _jax.default_device(_jax.local_devices(backend="cpu")[0]):
            if isinstance(model, PipeModel):
                pm = model
            else:
                from deepspeed_tpu.models.gpt import GPT

                if isinstance(model, GPT):
                    pm = gpt_pipe_model(model.cfg)
                else:
                    raise ValueError(
                        "offload_param needs a block-structured model: "
                        "pass a PipeModel (parallel.pipe.module) or an "
                        "in-tree GPT; opaque modules/loss_fns have no "
                        "per-block fetch points")
            # `params` (if given) may be pipe layout OR an already-packed
            # tree restored from an offload checkpoint. With an explicit
            # mesh whose model axis > 1, TP specs are derived from the
            # in-tree partition rules and the packing becomes shard-aligned
            # (ZeRO-Infinity x MP; runtime/zero/param_offload.pack_blocks_tp).
            tp_specs = None
            if mesh is not None and any(
                    mesh.shape.get(a, 1) > 1
                    for a in ("model", "sequence")):
                from deepspeed_tpu.models import (build_specs,
                                                  gpt_partition_rules)

                one_block = _jax.tree_util.tree_map(
                    lambda x: x[0], pm.params["blocks"])
                tp_specs = build_specs(one_block, gpt_partition_rules(),
                                       mesh_axes=dict(mesh.shape))
            loss_fn, params = build_streamed_loss(pm, params=params,
                                                  tp_specs=tp_specs,
                                                  mesh=mesh)
    if loss_fn is None:
        if model is None:
            raise ValueError("initialize() needs either loss_fn+params or model")
        from deepspeed_tpu.models.adapter import flax_module_loss_fn

        loss_fn, params = flax_module_loss_fn(model, params)
    if params is None:
        raise ValueError("initialize() requires the initial parameter pytree")

    engine = TPUEngine(loss_fn=loss_fn, params=params, config=cfg, mesh=mesh,
                       param_partition_specs=param_partition_specs,
                       optimizer=optimizer, lr_scheduler=lr_scheduler,
                       rng_seed=rng_seed,
                       sparse_gradients_handled=sparse_grads_handled,
                       **kwargs)

    if cfg.autotuning.enabled:
        # Startup config search (autotuning/; docs/PERFORMANCE.md
        # "Autotuning"). Imported ONLY here — a default config never
        # loads the package (the zero-overhead-off contract). The search
        # needs a batch source shaped like the candidate's split:
        # `autotune_batches(global_micro_batch, gas) -> batches pytree`.
        import jax as _jax
        if autotune_batches is not None and _jax.process_count() > 1:
            # The explicit autotune() entry raises here (diverging
            # per-host trial decisions => mismatched collectives); the
            # automatic entry must not kill a multi-node job the user
            # launched with --autotune — skip loudly instead.
            from deepspeed_tpu.utils.logging import logger as _logger

            _logger.warning(
                "autotuning: measured trials are not coordinated across "
                "processes yet — skipping the search on this %d-process "
                "run (tune on a single-process mesh and ship the "
                "adopted config)", _jax.process_count())
        elif autotune_batches is not None:
            from deepspeed_tpu.autotuning import autotune as _autotune

            _autotune(engine, autotune_batches)
        else:
            from deepspeed_tpu.utils.logging import logger as _logger

            _logger.warning(
                "autotuning.enabled but no batch source: pass "
                "initialize(autotune_batches=fn) with fn(global_micro, "
                "gas) -> batches, or call deepspeed_tpu.autotune(engine, "
                "make_batches) yourself — skipping the search")

    dataloader = None
    if training_data is not None:
        import jax

        dataloader = DeepSpeedDataLoader(
            training_data,
            batch_size=cfg.train_micro_batch_size_per_gpu *
            max(engine.dp_size // max(jax.process_count(), 1), 1),
            data_parallel_world_size=jax.process_count(),
            data_parallel_rank=jax.process_index(),
            collate_fn=collate_fn)

    return engine, engine.optimizer, dataloader, engine.lr_scheduler


def add_config_arguments(parser):
    """Argparse integration (reference deepspeed/__init__.py:211)."""
    group = parser.add_argument_group("DeepSpeed-TPU", "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag for scripts)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the DeepSpeed-TPU JSON config")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse_suppress())
    group.add_argument("--local_rank", type=int, default=-1,
                       help="Local rank set by the launcher")
    return parser


def argparse_suppress():
    import argparse

    return argparse.SUPPRESS


def autotune(engine, make_batches, **kwargs):
    """Run the observatory-driven startup config search on a live engine
    and adopt the measured winner (autotuning/; docs/PERFORMANCE.md
    "Autotuning"). ``make_batches(global_micro_batch, gas)`` must return
    a training batch pytree with ``[gas, global_micro_batch, ...]``
    leading dims. Reads the knob space from the engine's ``autotuning``
    config block (an explicit call works with the block's defaults even
    when ``enabled`` is false — enabled gates only the automatic run
    inside :func:`initialize`). Returns the ``autotune_result.json``
    document."""
    from deepspeed_tpu.autotuning import autotune as _autotune

    return _autotune(engine, make_batches, **kwargs)


def init_inference(model=None, **kwargs):
    """Inference engine entry (reference deepspeed/__init__.py:227)."""
    from deepspeed_tpu.inference.engine import InferenceEngine

    return InferenceEngine(model, **kwargs)


def init_serving(model=None, config=None, **kwargs):
    """Serving engine entry — continuous batching over ``init_inference``.

    ``config``: a dict (or JSON path) whose ``serving`` block configures
    the engine (``config/config.py ServingConfig`` keys) and whose
    ``telemetry`` block, when enabled, wires the SLO metrics/trace sinks
    (docs/SERVING.md). All other kwargs go to ``init_inference`` (params,
    checkpoint, mp_size, quantize, dtype, ...).

    Returns a step-driven :class:`deepspeed_tpu.serving.ServeEngine`:
    ``submit()`` requests, ``step()`` / ``run_until_complete()`` /
    ``serve_forever()`` to drive it.
    """
    import json as _json

    from deepspeed_tpu.config.config import ServingConfig, TelemetryConfig
    from deepspeed_tpu.serving.engine import ServeEngine
    from deepspeed_tpu.telemetry import build_requests, build_telemetry

    if isinstance(config, str):
        with open(config) as f:
            config = _json.load(f)
    config = dict(config or {})
    scfg = ServingConfig.from_dict(config.get("serving"))
    tcfg = TelemetryConfig.from_dict(config.get("telemetry"))
    tel = build_telemetry(tcfg)
    engine = init_inference(model, tracer=tel.tracer, **kwargs)
    # Serving chaos rides the SAME resilience.fault_injection block (and
    # DSTPU_FAULT_PLAN env override) as the training loop — the serve_*
    # FaultPlan fields drive serving/resilience.py's recovery paths.
    fault_plan = None
    rblock = dict(config.get("resilience") or {})
    if rblock.get("fault_injection"):
        from deepspeed_tpu.resilience import FaultPlan
        fault_plan = FaultPlan.resolve(rblock["fault_injection"])
    # telemetry.numerics opt-in gates the per-prefill int8 KV-cache
    # round-trip-error gauge (docs/OBSERVABILITY.md "Numerics
    # observatory"); telemetry.requests gates the per-request SLO
    # accountant (docs/OBSERVABILITY.md "Request observatory") —
    # telemetry-only deployments pay nothing extra for either.
    return ServeEngine(engine, config=scfg, telemetry=tel,
                       measure_kv_quant_error=tcfg.numerics.enabled,
                       request_accountant=build_requests(tcfg, tel),
                       fault_plan=fault_plan)


__all__ = [
    "initialize", "init_inference", "init_serving", "autotune",
    "add_config_arguments",
    "init_distributed", "zero_init",
    "build_mesh", "TPUEngine", "TrainState", "DeepSpeedTPUConfig",
    "DeepSpeedDataLoader", "RepeatingLoader", "ProcessTopology",
    "PipeDataParallelTopology", "PipeModelDataParallelTopology",
    "add_tuning_arguments", "log_dist", "logger", "__version__",
]
