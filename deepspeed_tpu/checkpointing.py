"""``deepspeed_tpu.checkpointing`` — activation-checkpointing module alias.

API parity with ``deepspeed.checkpointing`` (reference
``runtime/activation_checkpointing/checkpointing.py`` re-exported at
package level): ``configure``, ``is_configured``, ``checkpoint``.
"""

from deepspeed_tpu.runtime.activation_checkpointing import (  # noqa: F401
    checkpoint, configure, get_config, is_configured, remat_policy, reset)

__all__ = ["configure", "is_configured", "checkpoint", "get_config",
           "remat_policy", "reset"]
