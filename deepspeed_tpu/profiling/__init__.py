"""Profiling subsystem (reference deepspeed/profiling/flops_profiler)."""

from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler

__all__ = ["FlopsProfiler"]
