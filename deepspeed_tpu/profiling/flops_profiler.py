"""FLOPs profiler — compiler-derived, not monkey-patched.

The reference's ``FlopsProfiler`` (``deepspeed/profiling/flops_profiler/
profiler.py:11``) wraps every ``torch.nn.functional`` op to count MACs as
they execute. On TPU the compiled program already knows its own cost: XLA's
``cost_analysis`` reports exact post-fusion FLOPs and bytes for the whole
step, and the jaxpr gives the pre-fusion per-primitive breakdown. This is
both cheaper (no per-op Python hooks in the hot path) and more truthful
(it counts what actually runs after fusion/remat).

``profile_callable`` profiles any jittable ``fn(*args)``; the engine's
``_maybe_profile`` hook calls it (measure=False) at
``flops_profiler.profile_step`` when the config block enables it
(reference engine hook parity). CAUTION: with measure=True a donating fn
consumes its args — the first (cold) call's timing is reported and the
inputs are gone afterwards.
"""

import sys
import time
from collections import defaultdict
from typing import Any, Dict, Optional

import jax
import numpy as np

# Peak matmul throughput per chip kind and dtype (TFLOP/s). bf16 numbers
# are the published MXU peaks; fp32 runs the MXU in multi-pass mode at
# half rate. fp16 inputs go through the same bf16 MXU path on TPU. The
# table is the ONE source every MFU in the tree divides by —
# bench.py, engine/mfu (telemetry/goodput.py) and tools/goodput_report
# all route through :func:`mfu` below.
TPU_PEAK_TFLOPS = {
    "TPU v4": {"bfloat16": 275.0, "float32": 137.5},
    "TPU v5 lite": {"bfloat16": 197.0, "float32": 98.5},
    "TPU v5p": {"bfloat16": 459.0, "float32": 229.5},
    "TPU v6 lite": {"bfloat16": 918.0, "float32": 459.0},
    "TPU v6e": {"bfloat16": 918.0, "float32": 459.0},
}
DEFAULT_PEAK_TFLOPS = 197.0  # v5e-class bf16 — the conservative fallback

# Peak HBM bandwidth per chip kind (GB/s) — the denominator of the
# roofline ridge point (telemetry/devicetime.py): ridge [flop/byte] =
# peak_flops / peak_bytes_per_sec. Published chip numbers; the fallback
# is v5e-class like DEFAULT_PEAK_TFLOPS.
TPU_PEAK_HBM_GBPS = {
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1638.0,
    "TPU v6e": 1638.0,
}
DEFAULT_PEAK_HBM_GBPS = 819.0


def peak_hbm_gbps(device_kind: Optional[str] = None) -> float:
    """Per-chip peak HBM bandwidth (GB/s) with the conservative
    v5e-class default for unknown kinds (CPU test meshes, future
    chips)."""
    return TPU_PEAK_HBM_GBPS.get(device_kind or "", DEFAULT_PEAK_HBM_GBPS)

_DTYPE_ALIASES = {
    "bf16": "bfloat16", "bfloat16": "bfloat16",
    "fp32": "float32", "float32": "float32",
    # fp16 inputs ride the bf16 MXU path on TPU
    "fp16": "bfloat16", "float16": "bfloat16",
}


def peak_tflops(device_kind: Optional[str] = None,
                dtype: str = "bfloat16") -> float:
    """Per-chip peak TFLOP/s for a device kind + compute dtype, with the
    conservative v5e-class default for unknown kinds (CPU test meshes,
    future chips)."""
    dtype = _DTYPE_ALIASES.get(str(dtype).lower(), "bfloat16")
    kinds = TPU_PEAK_TFLOPS.get(device_kind or "")
    if kinds is None:
        base = DEFAULT_PEAK_TFLOPS
        return base / 2.0 if dtype == "float32" else base
    return kinds.get(dtype, kinds["bfloat16"])


def mfu(flops_per_step: Optional[float], step_time_s: float,
        n_chips: int = 1, peak_tflops_per_chip: Optional[float] = None,
        device_kind: Optional[str] = None,
        dtype: str = "bfloat16") -> float:
    """Model FLOPs utilisation: ``flops_per_step`` (the WHOLE global
    step's FLOPs, across all chips) / (step time × chips × per-chip
    peak). Pass ``peak_tflops_per_chip`` explicitly or let the
    device-kind/dtype table supply it. Returns 0.0 for degenerate
    inputs (no FLOPs, non-positive time) rather than raising — MFU is a
    report field, not a control signal."""
    if not flops_per_step or flops_per_step <= 0 or step_time_s <= 0:
        return 0.0
    if peak_tflops_per_chip is None:
        peak_tflops_per_chip = peak_tflops(device_kind, dtype)
    denom = step_time_s * max(int(n_chips), 1) * peak_tflops_per_chip * 1e12
    return float(flops_per_step) / denom


def _count_params(tree: Any) -> int:
    return sum(int(np.prod(x.shape))
               for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "shape"))


def _jaxpr_breakdown(closed_jaxpr) -> Dict[str, float]:
    """Pre-fusion FLOPs per primitive family from the jaxpr (the analogue of
    the reference's per-module table at module_depth granularity)."""
    flops = defaultdict(float)

    def visit(jaxpr):
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "dot_general":
                dims = eqn.params["dimension_numbers"]
                lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
                (lc, rc), (lb, rb) = dims
                m = np.prod([d for i, d in enumerate(lhs.shape)
                             if i not in set(lc) | set(lb)], dtype=float)
                n = np.prod([d for i, d in enumerate(rhs.shape)
                             if i not in set(rc) | set(rb)], dtype=float)
                k = np.prod([lhs.shape[i] for i in lc], dtype=float)
                b = np.prod([lhs.shape[i] for i in lb], dtype=float)
                flops["matmul"] += 2.0 * b * m * n * k
            elif prim in ("conv_general_dilated",):
                flops["conv"] += 0.0  # counted by XLA total; rare in-tree
            elif prim in ("exp", "log", "tanh", "logistic", "erf", "rsqrt",
                          "sqrt", "sin", "cos", "pow"):
                flops["transcendental"] += float(
                    np.prod(eqn.outvars[0].aval.shape, dtype=float))
            elif prim in ("add", "mul", "sub", "div", "max", "min",
                          "integer_pow"):
                flops["elementwise"] += float(
                    np.prod(eqn.outvars[0].aval.shape, dtype=float))
            elif prim in ("reduce_sum", "reduce_max", "reduce_min",
                          "argmax", "argmin"):
                flops["reduction"] += float(
                    np.prod(eqn.invars[0].aval.shape, dtype=float))
            # recurse into sub-jaxprs (scan/cond/while/pjit/remat bodies)
            for v in eqn.params.values():
                if hasattr(v, "jaxpr"):          # ClosedJaxpr
                    visit(v.jaxpr)
                elif hasattr(v, "eqns"):         # raw Jaxpr
                    visit(v)
                elif isinstance(v, (list, tuple)):
                    for u in v:
                        if hasattr(u, "jaxpr"):
                            visit(u.jaxpr)
                        elif hasattr(u, "eqns"):
                            visit(u)

    visit(closed_jaxpr.jaxpr)
    return dict(flops)


class FlopsProfiler:
    """Profile a jitted callable: compiled-cost totals + jaxpr breakdown +
    measured wall clock → achieved FLOP/s.

    Reference surface: ``get_model_profile``/``print_model_profile``
    (profiler.py:735,602).
    """

    def __init__(self, config=None):
        self.config = config
        self.last: Optional[Dict[str, Any]] = None

    def profile_callable(self, fn, *args, params: Any = None,
                         detailed: bool = True,
                         measure: bool = True) -> Dict[str, Any]:
        jfn = fn if isinstance(fn, jax.stages.Wrapped) else jax.jit(fn)
        lowered = jfn.lower(*args)
        compiled = lowered.compile()
        cost = compiled.cost_analysis() or {}
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        result: Dict[str, Any] = {
            "flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
            "params": _count_params(params) if params is not None else None,
        }
        if detailed:
            try:
                result["breakdown"] = _jaxpr_breakdown(
                    jax.make_jaxpr(fn)(*args))
            except Exception:  # jaxpr walking is best-effort diagnostics
                result["breakdown"] = {}
        if measure:
            # Warm-up, then a timed call — but a donating fn deletes its
            # inputs on the first call, so fall back to timing that first
            # (cold) call rather than crashing or re-running on corpses.
            t0 = time.perf_counter()
            out = compiled(*args)
            jax.block_until_ready(out)
            cold = time.perf_counter() - t0
            deleted = any(isinstance(a, jax.Array) and a.is_deleted()
                          for a in jax.tree_util.tree_leaves(args))
            if deleted:
                dt = cold
            else:
                t0 = time.perf_counter()
                out = compiled(*args)
                jax.block_until_ready(out)
                dt = time.perf_counter() - t0
            result["latency_s"] = dt
            result["achieved_tflops"] = result["flops"] / dt / 1e12
        self.last = result
        return result

    # ------------------------------------------------------------------
    def mfu(self, step_time_s: float,
            peak_tflops_per_chip: Optional[float] = None,
            n_chips: int = 1, flops: Optional[float] = None,
            device_kind: Optional[str] = None,
            dtype: str = "bfloat16") -> float:
        """MFU of the last profiled callable (or explicit ``flops``) at a
        measured step time — delegates to the module-level :func:`mfu`,
        the single MFU formula in the tree."""
        if flops is None:
            flops = (self.last or {}).get("flops")
        return mfu(flops, step_time_s, n_chips=n_chips,
                   peak_tflops_per_chip=peak_tflops_per_chip,
                   device_kind=device_kind, dtype=dtype)

    # ------------------------------------------------------------------
    def print_profile(self, result: Optional[Dict[str, Any]] = None,
                      file=None) -> str:
        r = result or self.last
        if r is None:
            return ""
        lines = ["-" * 60, "DeepSpeed-TPU Flops Profiler (XLA cost analysis)"]
        if r.get("params") is not None:
            lines.append(f"params:               {r['params'] / 1e6:.2f} M")
        lines.append(f"fwd+bwd flops/step:   {r['flops'] / 1e9:.2f} G")
        lines.append(f"HBM bytes/step:       {r['bytes_accessed'] / 1e9:.3f} GB")
        if r["flops"] and r["bytes_accessed"]:
            lines.append(f"arithmetic intensity: "
                         f"{r['flops'] / max(r['bytes_accessed'], 1):.1f} flop/B")
        if "latency_s" in r:
            lines.append(f"step latency:         {r['latency_s'] * 1e3:.2f} ms")
            lines.append(f"achieved:             {r['achieved_tflops']:.2f} TFLOP/s")
        for k, v in sorted((r.get("breakdown") or {}).items(),
                           key=lambda kv: -kv[1]):
            lines.append(f"  {k:<18} {v / 1e9:10.2f} GFLOP (pre-fusion)")
        lines.append("-" * 60)
        text = "\n".join(lines)
        out = file if file is not None else sys.stderr
        print(text, file=out, flush=True)
        return text
